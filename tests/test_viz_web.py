"""Web viz tests: cluster building for every registered protocol, the
JSON API surface (state/deliver/op/partition), and a full drive of a
write through the browser API."""

import pytest

from frankenpaxos_tpu.mains.registry import REGISTRY
from frankenpaxos_tpu.viz import Stepper
from frankenpaxos_tpu.viz.web import VizServer, build_cluster


@pytest.mark.parametrize("protocol", sorted(REGISTRY))
def test_build_cluster_every_protocol(protocol):
    transport, client, issue = build_cluster(protocol)
    viz = VizServer(protocol, Stepper(transport), client, issue)
    snap = viz.snapshot()
    assert len(snap["actors"]) >= 1
    assert snap["protocol"] == protocol
    # States are inspectable for every actor.
    assert set(snap["states"]) == {a["name"] for a in snap["actors"]}


def test_viz_api_drives_a_write_to_completion():
    transport, client, issue = build_cluster("paxos")
    viz = VizServer("paxos", Stepper(transport), client, issue)
    assert viz.handle("op", {}) == {"ok": True}
    snap = viz.snapshot()
    assert snap["messages"], "client op produced no messages"
    # Deliver one specific message by its stable token, then the rest.
    tok = snap["messages"][0]["tok"]
    assert viz.handle("deliver", {"tok": tok}) == {"ok": True}
    # The token is now stale: acting on it reports an error instead of
    # hitting whatever message shifted into its position.
    import pytest as _pytest

    with _pytest.raises(KeyError):
        viz.handle("deliver", {"tok": tok})
    viz.handle("deliver_all", {})
    assert client.chosen is not None
    # Message descriptions decode to readable message types.
    assert "ProposeRequest" in snap["messages"][0]["desc"]


def test_viz_api_partition_and_errors():
    transport, client, issue = build_cluster("paxos")
    viz = VizServer("paxos", Stepper(transport), client, issue)
    name = viz.snapshot()["actors"][0]["name"]
    viz.handle("partition", {"addr": name})
    assert viz.snapshot()["actors"][0]["partitioned"]
    viz.handle("unpartition", {"addr": name})
    assert not viz.snapshot()["actors"][0]["partitioned"]
    assert viz.handle("nonsense", {}) is None
