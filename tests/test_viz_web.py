"""Web viz tests: cluster building for every registered protocol, the
JSON API surface (state/deliver/op/partition), and a full drive of a
write through the browser API."""

import pytest

from frankenpaxos_tpu.mains.registry import REGISTRY
from frankenpaxos_tpu.viz import Stepper
from frankenpaxos_tpu.viz.web import VizServer, build_cluster


@pytest.mark.parametrize("protocol", sorted(REGISTRY))
def test_build_cluster_every_protocol(protocol):
    transport, client, issue = build_cluster(protocol)
    viz = VizServer(protocol, Stepper(transport), client, issue)
    snap = viz.snapshot()
    assert len(snap["actors"]) >= 1
    assert snap["protocol"] == protocol
    # States are inspectable for every actor.
    assert set(snap["states"]) == {a["name"] for a in snap["actors"]}


def test_viz_api_drives_a_write_to_completion():
    transport, client, issue = build_cluster("paxos")
    viz = VizServer("paxos", Stepper(transport), client, issue)
    assert viz.handle("op", {}) == {"ok": True}
    snap = viz.snapshot()
    assert snap["messages"], "client op produced no messages"
    # Deliver one specific message by its stable token, then the rest.
    tok = snap["messages"][0]["tok"]
    assert viz.handle("deliver", {"tok": tok}) == {"ok": True}
    # The token is now stale: acting on it reports an error instead of
    # hitting whatever message shifted into its position.
    import pytest as _pytest

    with _pytest.raises(KeyError):
        viz.handle("deliver", {"tok": tok})
    viz.handle("deliver_all", {})
    assert client.chosen is not None
    # Message descriptions decode to readable message types.
    assert "ProposeRequest" in snap["messages"][0]["desc"]


def test_viz_api_partition_and_errors():
    transport, client, issue = build_cluster("paxos")
    viz = VizServer("paxos", Stepper(transport), client, issue)
    name = viz.snapshot()["actors"][0]["name"]
    viz.handle("partition", {"addr": name})
    assert viz.snapshot()["actors"][0]["partitioned"]
    viz.handle("unpartition", {"addr": name})
    assert not viz.snapshot()["actors"][0]["partitioned"]
    assert viz.handle("nonsense", {}) is None


def test_export_as_test_is_runnable():
    """The browser's 'export as test' emits a self-contained pytest
    function (JsTransport.scala:260-298 parity): exec'ing and calling it
    replays the recorded session against a freshly built cluster."""
    transport, client, issue = build_cluster("paxos")
    viz = VizServer("paxos", Stepper(transport), client, issue)
    viz.handle("op", {})
    tok = viz.snapshot()["messages"][0]["tok"]
    viz.handle("deliver", {"tok": tok})
    viz.handle("deliver_all", {})
    assert client.chosen is not None
    out = viz.handle("export", {"name": "test_replayed_session"})
    code = out["code"]
    assert code.startswith("def test_replayed_session():")
    assert "build_cluster('paxos')" in code
    assert "deliver_message" in code
    assert "issue(client, 0, 0)" in code
    # The exported test must RUN: replaying against a fresh cluster
    # reproduces the same outcome.
    ns = {}
    exec(code, ns)  # noqa: S102 - exercising the generated test
    ns["test_replayed_session"]()


def test_export_records_partitions_and_timers():
    transport, client, issue = build_cluster("paxos")
    viz = VizServer("paxos", Stepper(transport), client, issue)
    name = viz.snapshot()["actors"][0]["name"]
    viz.handle("partition", {"addr": name})
    viz.handle("unpartition", {"addr": name})
    code = viz.handle("export", {})["code"]
    assert "t.partition_actor(" in code
    assert "t.unpartition_actor(" in code
    ns = {}
    exec(code, ns)
    ns["test_replay"]()


def test_fire_targets_the_displayed_timer_instance():
    """Two running timers with the SAME (address, name): firing the
    second token must run the SECOND timer's callback (advisor round 2:
    name-only resolution fired the first match)."""
    from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
    from frankenpaxos_tpu.core.actor import Actor
    from frankenpaxos_tpu.viz import Stepper

    t = SimTransport(FakeLogger())

    class Two(Actor):
        def __init__(self, address, transport):
            super().__init__(address, transport, FakeLogger())
            self.fired = []
            for k in (0, 1):
                timer = self.timer("retry", 10.0, lambda k=k: self.fired.append(k))
                timer.start()

        def receive(self, src, msg):
            pass

    actor = Two(SimAddress("a"), t)
    stepper = Stepper(t)
    assert len(t.running_timers()) == 2
    stepper.fire(1)
    assert actor.fired == [1], actor.fired
    # And the transport-level occurrence API directly:
    t.trigger_timer(SimAddress("a"), "retry", occurrence=0)
    assert actor.fired == [1, 0]
