"""Sim tests for Fast Paxos and CRAQ."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import (
    DeliverMessage,
    FakeLogger,
    SimAddress,
    SimTransport,
    TriggerTimer,
)
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import craq as cq
from frankenpaxos_tpu.protocols import fastpaxos as fp
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)


def drain(t, max_steps=50000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


# -- Fast Paxos ---------------------------------------------------------------


def make_fp(f=1, num_clients=2):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    config = fp.FastPaxosConfig(
        f=f,
        leader_addresses=tuple(SimAddress(f"leader{i}") for i in range(f + 1)),
        acceptor_addresses=tuple(
            SimAddress(f"acceptor{i}") for i in range(2 * f + 1)
        ),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [fp.FpLeader(a, t, log(), config) for a in config.leader_addresses]
    acceptors = [
        fp.FpAcceptor(a, t, log(), config) for a in config.acceptor_addresses
    ]
    clients = [
        fp.FpClient(SimAddress(f"client{i}"), t, log(), config)
        for i in range(num_clients)
    ]
    return t, config, leaders, acceptors, clients


def test_fastpaxos_fast_path():
    """A single uncontended proposal is chosen on the fast path (round 0,
    no leader involvement)."""
    t, config, leaders, acceptors, clients = make_fp()
    p = clients[0].propose("apple")
    drain(t)
    assert p.done and p.result() == "apple"
    # The leader never acted: all leaders still idle.
    assert all(l.status == fp.FpLeader.IDLE for l in leaders)


def test_fastpaxos_conflict_falls_back_to_classic():
    """Two clients collide on the fast path; the classic path recovers."""
    t, config, leaders, acceptors, clients = make_fp()
    p1 = clients[0].propose("a")
    p2 = clients[1].propose("b")
    # Adversarial interleaving of fast-path messages.
    rng = random.Random(1)
    for _ in range(200):
        cmd = t.generate_command(rng)
        if cmd is None:
            break
        t.run_command(cmd, record=False)
    # Force the classic fallback via the repropose timers.
    for c in clients:
        if c.chosen_value is None:
            t.trigger_timer(c.address, "reproposeTimer")
    drain(t)
    chosen = {c.chosen_value for c in clients if c.chosen_value is not None}
    assert len(chosen) == 1


@dataclasses.dataclass(frozen=True)
class FpPropose:
    client_index: int


class SimulatedFastPaxos(SimulatedSystem):
    def __init__(self, f=1):
        self.f = f

    def new_system(self, seed):
        return make_fp(self.f)

    def get_state(self, system):
        t, config, leaders, acceptors, clients = system
        return tuple(c.chosen_value for c in clients) + tuple(
            l.chosen_value for l in leaders
        )

    def generate_command(self, system, rng):
        t, config, leaders, acceptors, clients = system
        ops = [
            (1, FpPropose(i))
            for i, c in enumerate(clients)
            if c.proposed_value is None and c.chosen_value is None
        ]
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, config, leaders, acceptors, clients = system
        if isinstance(command, FpPropose):
            clients[command.client_index].propose(f"v{command.client_index}")
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        chosen = {v for v in state if v is not None}
        if len(chosen) > 1:
            return f"multiple values chosen: {chosen}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if o is not None and n != o:
                return f"chosen value changed: {o!r} -> {n!r}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_fastpaxos_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedFastPaxos(f), run_length=120, num_runs=25, seed=f
    )
    assert bad is None, f"\n{bad}"


# -- CRAQ ---------------------------------------------------------------------


def make_craq(n=3, num_clients=2, seed=0):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    config = cq.CraqConfig(
        f=1,
        chain_node_addresses=tuple(SimAddress(f"node{i}") for i in range(n)),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    nodes = [
        cq.ChainNode(a, t, log(), config, seed=seed + i)
        for i, a in enumerate(config.chain_node_addresses)
    ]
    clients = [
        cq.CraqClient(SimAddress(f"client{i}"), t, log(), config, seed=seed + 10 + i)
        for i in range(num_clients)
    ]
    return t, config, nodes, clients


def test_craq_write_then_read():
    t, config, nodes, clients = make_craq()
    w = clients[0].write(0, "x", "1")
    drain(t)
    assert w.done
    # All nodes applied after the ack wave.
    assert all(n.state_machine.get("x") == "1" for n in nodes)
    r = clients[0].read(0, "x")
    drain(t)
    assert r.result() == "1"
    r2 = clients[0].read(0, "nope")
    drain(t)
    assert r2.result() == cq.DEFAULT


def test_craq_dirty_read_goes_to_tail():
    """A read at a mid-chain node with a pending write for that key must be
    served by the tail (apportioned queries)."""
    t, config, nodes, clients = make_craq()
    clients[0].write(0, "x", "1")
    drain(t)
    # Start a second write but deliver it only to the head (it stays dirty).
    clients[0].write(0, "x", "2")
    head_msgs = [m for m in t.messages if m.dst == config.chain_node_addresses[0]]
    for m in head_msgs:
        t.deliver_message(m)
    assert nodes[0].pending_writes  # dirty at head
    # Read at the head: must NOT be answered from its local (stale) state.
    class _Head:
        def randrange(self, n):
            return 0

    clients[1].rng = _Head()
    r = clients[1].read(0, "x")
    # Deliver the read to the head.
    for m in [m for m in t.messages if m.dst == config.chain_node_addresses[0]]:
        t.deliver_message(m)
    # The head forwarded to the tail rather than replying.
    assert any(m.dst == config.chain_node_addresses[-1] for m in t.messages)
    drain(t)
    assert r.done
    # Tail serves its own committed version; with the second write still
    # propagating it's either value, but never a lost update.
    assert r.result() in ("1", "2")


class SimulatedCraq(SimulatedSystem):
    """Invariant: committed (acked) prefixes of the chain agree — every
    node's state machine entry for a key, once the key is clean chain-wide,
    matches the tail's."""

    def new_system(self, seed):
        return make_craq(seed=seed)

    def get_state(self, system):
        t, config, nodes, clients = system
        return tuple(
            (tuple(sorted(n.state_machine.items())), len(n.pending_writes))
            for n in nodes
        )

    def generate_command(self, system, rng):
        t, config, nodes, clients = system
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append((1, ("write", i, pseudonym,
                                    f"k{rng.randrange(3)}", f"v{rng.randrange(50)}")))
                    ops.append((1, ("read", i, pseudonym, f"k{rng.randrange(3)}")))
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, config, nodes, clients = system
        if isinstance(command, tuple) and command[0] == "write":
            _, i, pseudonym, key, value = command
            clients[i].write(pseudonym, key, value)
        elif isinstance(command, tuple) and command[0] == "read":
            _, i, pseudonym, key = command
            clients[i].read(pseudonym, key)
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        # When NO node has pending writes, all state machines must agree.
        if all(npending == 0 for _, npending in state):
            sms = {sm for sm, _ in state}
            if len(sms) > 1:
                return f"quiescent chain disagrees: {sms}"
        return None


def test_craq_safety_randomized():
    bad = simulate_and_minimize(
        SimulatedCraq(), run_length=150, num_runs=20, seed=0
    )
    assert bad is None, f"\n{bad}"
