"""Faster Paxos sim tests: delegate fast path without the leader,
noop back-filling, noop-vs-command races, leader change on delegate
death, hole recovery, and randomized safety."""

import dataclasses

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport, wire
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import fasterpaxos as fpr
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import ReadableAppendLog


class Cluster:
    def __init__(self, seed=0, f=1, num_clients=2, options=None):
        self.transport = SimTransport(FakeLogger(LogLevel.FATAL))
        t = self.transport
        n = 2 * f + 1
        self.config = fpr.FasterPaxosConfig(
            f=f,
            server_addresses=tuple(
                SimAddress(f"server{i}") for i in range(n)
            ),
            heartbeat_addresses=tuple(
                SimAddress(f"heartbeat{i}") for i in range(n)
            ),
        )
        log = lambda: FakeLogger(LogLevel.FATAL)
        self.servers = [
            fpr.FprServer(a, t, log(), self.config, ReadableAppendLog(),
                          options or fpr.FprServerOptions(), seed=seed + i)
            for i, a in enumerate(self.config.server_addresses)
        ]
        self.clients = [
            fpr.FprClient(SimAddress(f"client{i}"), t, log(), self.config,
                          seed=seed + 50 + i)
            for i in range(num_clients)
        ]

    def drain(self, max_steps=300000):
        steps = 0
        t = self.transport
        while t.messages and steps < max_steps:
            t.deliver_message(t.messages[0])
            steps += 1
        assert steps < max_steps

    def pump(self, rounds=8, skip=lambda timer: False):
        infra = set(self.config.heartbeat_addresses)
        self.drain()
        for _ in range(rounds):
            for timer in list(self.transport.running_timers()):
                if (
                    timer.address not in infra
                    and timer.name() != "leaderChange"
                    and not skip(timer)
                ):
                    self.transport.trigger_timer(timer.address, timer.name())
            self.drain()


def test_fpr_single_command():
    cluster = Cluster()
    cluster.drain()  # round 0 phase 1 + Phase2aAny
    p = cluster.clients[0].propose(0, b"hello")
    cluster.drain()
    assert p.done
    for s in cluster.servers:
        assert s.state_machine.log == [b"hello"]


def test_fpr_delegate_commits_without_leader():
    """A client command sent to a non-leader delegate commits with NO
    message through the leader (the delegate proposes in its own slot)."""
    cluster = Cluster(seed=3)
    cluster.drain()
    # Delegates in round 0 are servers {0, 1}; 0 is the leader. Pin the
    # client to delegate 1.
    class _Pick1:
        def randrange(self, n):
            return 1

    cluster.clients[0].rng = _Pick1()
    leader = cluster.config.server_addresses[0]
    p = cluster.clients[0].propose(0, b"direct")
    t = cluster.transport
    leader_got_proposal_traffic = False
    while t.messages:
        m = t.messages[0]
        decoded = wire.decode(m.data)
        if m.dst == leader and isinstance(
            decoded, (fpr.FprClientRequest, fpr.FprPhase2a)
        ):
            # The delegate DOES send the leader a Phase2a: the leader is
            # also a delegate and must vote. What we check below is that
            # the client never talked to the leader.
            if isinstance(decoded, fpr.FprClientRequest):
                leader_got_proposal_traffic = True
        t.deliver_message(m)
    assert p.done
    assert not leader_got_proposal_traffic


def test_fpr_interleaved_delegates_noop_fill():
    """Two delegates own alternating slots; a command through one
    delegate noop-fills the other's skipped slots so execution never
    blocks."""
    cluster = Cluster(seed=5)
    cluster.drain()

    class _Pick(int):
        def randrange(self, n):
            return int(self)

    for i in range(6):
        cluster.clients[0].rng = _Pick(i % 2)
        p = cluster.clients[0].propose(i, f"c{i}".encode())
        cluster.drain()
        assert p.done, i
    logs = {tuple(s.state_machine.log) for s in cluster.servers}
    assert len(logs) == 1
    assert sorted(next(iter(logs))) == [f"c{i}".encode() for i in range(6)]


def test_fpr_noop_command_race_resolves_to_command():
    """Delegate A noop-fills a slot owned by B at the same time B
    proposes a command there: ack_noops_with_commands makes A adopt the
    command, and the command (not the noop) is chosen."""
    cluster = Cluster(seed=7)
    cluster.drain()
    t = cluster.transport

    class _Pick(int):
        def randrange(self, n):
            return int(self)

    # Client 0 -> delegate 1 (owns slot 1 in round 0's suffix); hold the
    # messages. Client 1 -> delegate 0 proposes later, noop-filling.
    cluster.clients[0].rng = _Pick(1)
    cluster.clients[1].rng = _Pick(0)
    p1 = cluster.clients[0].propose(0, b"cmd-b")
    p2 = cluster.clients[1].propose(0, b"cmd-a")
    # Random-ish interleaving via FIFO drain is enough: both proposals
    # are in flight before any Phase2a lands.
    cluster.pump(rounds=6)
    assert p1.done and p2.done
    logs = {tuple(s.state_machine.log) for s in cluster.servers}
    assert len(logs) == 1
    assert sorted(next(iter(logs))) == [b"cmd-a", b"cmd-b"]


def test_fpr_leader_change_on_delegate_death():
    """Killing a delegate and firing another server's leaderChange timer
    moves the system to a new round with live delegates."""
    cluster = Cluster(seed=9)
    cluster.drain()
    p = cluster.clients[0].propose(0, b"before")
    cluster.drain()
    assert p.done
    # Server 1 (a delegate) dies.
    dead = cluster.config.server_addresses[1]
    cluster.transport.partition_actor(dead)
    cluster.transport.partition_actor(cluster.config.heartbeat_addresses[1])
    # Server 2 notices: mark the delegate dead in its heartbeat view and
    # fire its leaderChange timer.
    cluster.servers[2].heartbeat.alive.discard(
        cluster.config.heartbeat_addresses[1]
    )
    cluster.servers[2].check_delegates_alive()
    cluster.pump(rounds=8, skip=lambda tm: tm.address == dead)
    server2 = cluster.servers[2]
    round, delegates = server2._round_info()
    assert round > 0
    assert 1 not in delegates
    p2 = cluster.clients[1].propose(0, b"after")
    cluster.pump(rounds=8, skip=lambda tm: tm.address == dead)
    assert p2.done
    assert cluster.servers[2].state_machine.log[-1] == b"after"


def test_fpr_client_round_catchup_via_round_info():
    cluster = Cluster(seed=11)
    cluster.drain()
    # Move the system to a higher round.
    cluster.servers[1].start_phase1(
        cluster.servers[1].round_system.next_classic_round(1, 0),
        (1, 2),
    )
    cluster.drain()
    # A client stuck in round 0 proposes; servers answer RoundInfo and
    # the client reroutes to the new delegates.
    p = cluster.clients[0].propose(0, b"catchup")
    cluster.pump(rounds=6)
    assert p.done
    assert cluster.clients[0].round > 0
    assert set(cluster.clients[0].delegates) == {1, 2}


def test_fpr_hole_recovery():
    """A server whose Phase3a was lost recovers the chosen value from
    the other servers via Recover."""
    cluster = Cluster(seed=13)
    cluster.drain()
    t = cluster.transport
    victim = cluster.config.server_addresses[2]
    p = cluster.clients[0].propose(0, b"lost")
    while t.messages:
        m = t.messages[0]
        if m.dst == victim and isinstance(wire.decode(m.data), fpr.FprPhase3a):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert p.done
    assert cluster.servers[2].state_machine.log == []
    p2 = cluster.clients[0].propose(0, b"next")
    cluster.pump(rounds=6)
    assert p2.done
    assert cluster.servers[2].state_machine.log == [b"lost", b"next"]


def test_fpr_recover_on_voted_but_not_proposed_slot():
    """Regression: a server can OWN a slot it only voted in (another
    delegate noop-filled it). Recovery of that slot must re-propose a
    noop over the existing pending entry, not crash on the proposer-path
    assertion that the log is empty."""
    cluster = Cluster(
        seed=15, f=2,
        options=fpr.FprServerOptions(use_f1_optimization=False),
    )
    cluster.drain()

    class _P2:
        def randrange(self, n):
            return 2

    cluster.clients[0].rng = _P2()
    p = cluster.clients[0].propose(0, b"cmd")
    t = cluster.transport
    proposer = cluster.config.server_addresses[2]
    while t.messages:
        m = t.messages[0]
        if m.dst == proposer and isinstance(
            wire.decode(m.data), fpr.FprPhase2b
        ):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert not p.done
    # Server 0 voted for delegate 2's noop-fill at slot 0 without being
    # its proposer.
    assert cluster.servers[0].log.get(0)[0] == "pending"
    assert 0 not in cluster.servers[0].state.pending_values
    cluster.servers[0].receive(
        cluster.config.server_addresses[1], fpr.FprRecover(slot=0)
    )
    cluster.pump(rounds=8)
    assert p.done
    logs = {tuple(s.state_machine.log) for s in cluster.servers}
    assert logs == {(b"cmd",)}


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    value: str


class SimulatedFpr(SimulatedSystem):
    def __init__(self, f=1, ack_noops=True):
        self.f = f
        self.ack_noops = ack_noops

    def new_system(self, seed):
        cluster = Cluster(
            seed=seed, f=self.f,
            options=fpr.FprServerOptions(
                ack_noops_with_commands=self.ack_noops,
                use_f1_optimization=(self.f == 1),
            ),
        )
        cluster.drain()
        return cluster

    def get_state(self, system):
        return tuple(
            tuple(s.state_machine.log) for s in system.servers
        )

    def generate_command(self, system, rng):
        ops = []
        for i, c in enumerate(system.clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (1, Propose(i, pseudonym, f"v{rng.randrange(100)}"))
                    )
        return mixed_command(rng, system.transport, ops)

    def run_command(self, system, command):
        if isinstance(command, Propose):
            system.clients[command.client_index].propose(
                command.pseudonym, command.value.encode()
            )
        else:
            system.transport.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        for i in range(len(state)):
            for j in range(i + 1, len(state)):
                a, b = state[i], state[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return f"server logs diverge: {a!r} vs {b!r}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if n[: len(o)] != o:
                return f"server log rewrote history: {o!r} -> {n!r}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_fpr_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedFpr(f), run_length=150, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_fpr_safety_randomized_no_ack_noops():
    bad = simulate_and_minimize(
        SimulatedFpr(1, ack_noops=False), run_length=120, num_runs=5, seed=41
    )
    assert bad is None, f"\n{bad}"
