"""Bit-packed hot planes (tpu/packing.py) + the trace-driven open-loop
workload mode (PR 16).

The load-bearing guarantees, in order:

  * The codec is exact: pack/unpack round-trips every value for every
    registered plane width (tpu/common.PACKED_PLANES), the occupancy
    bitmap's set/clear/get agree with the boolean view, and the
    arrival-trace delta codec round-trips (including the host-side
    range guards).
  * Packing is a PURE STORAGE transform: a ``pack_planes=True`` run is
    bit-identical to its unpacked twin on BOTH adopting backends
    (flagship multipaxos + compartmentalized), 3 seeds, with the fault
    plane, the workload engine, and the full lifecycle (rotation +
    sessions + TTL + resubmits) engaged — every protocol leaf equal,
    and the session table equal under ``canonical_sessions`` (the
    packed table keeps stale payload words under dead occupancy bits;
    canonicalization is the equality the exactly-once contract needs).
  * TTL expiry composes with window rotation: sessions expiring ACROSS
    a rotation boundary keep the ``lifecycle_ok`` conservation books
    exact, 3 seeds, packed and unpacked.
  * The trace arrival source replays a recorded schedule exactly once:
    every event fires on (or FIFO-deferred after) its recorded tick,
    chunk overflow defers without loss, the cursor pins at exhaustion,
    and swapping traces is a pure state swap (zero recompiles).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.tpu import compartmentalized_batched as cz
from frankenpaxos_tpu.tpu import lifecycle as lc_mod
from frankenpaxos_tpu.tpu import multipaxos_batched as mp
from frankenpaxos_tpu.tpu import packing
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.common import PACKED_PLANES
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan


def _run(mod, cfg, ticks, seed, state=None, t=None):
    state = mod.init_state(cfg) if state is None else state
    t = jnp.zeros((), jnp.int32) if t is None else t
    return mod.run_ticks(cfg, state, t, ticks, jax.random.PRNGKey(seed))


def _assert_invariants(mod, cfg, state, t):
    bad = {
        k: bool(v)
        for k, v in mod.check_invariants(cfg, state, t).items()
        if not bool(v)
    }
    assert not bad, bad


# ---------------------------------------------------------------------------
# Codec units
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bits", sorted(set(PACKED_PLANES.values())))
@pytest.mark.parametrize("size", [1, 16, 31, 32, 33, 100])
def test_pack_plane_round_trip(bits, size):
    rng = np.random.default_rng(bits * 100 + size)
    x = jnp.asarray(
        rng.integers(0, 1 << bits, size=(3, size)), jnp.int32
    )
    words = packing.pack_plane(x, bits)
    assert words.dtype == jnp.int32
    assert words.shape == (3, packing.words_for(size, bits))
    back = packing.unpack_plane(words, bits, size, jnp.int32)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_pack_status_masks_to_width():
    # Codes wider than the plane's registered width are masked, not
    # smeared into neighbor fields.
    x = jnp.asarray([[7, 1, 2, 3]], jnp.int8)
    w = packing.pack_status(x)
    back = packing.unpack_status(w, 4)
    np.testing.assert_array_equal(
        np.asarray(back), [[7 & 3, 1, 2, 3]]
    )


def test_occ_set_clear_get_agree_with_bool_view():
    rng = np.random.default_rng(7)
    L, S = 4, 70
    occ = packing.make_occ(L, S)
    ref = np.zeros((L, S), bool)
    idx = jnp.asarray(rng.integers(0, S, size=(L,)), jnp.int32)
    wrote = jnp.asarray(rng.random((L,)) < 0.8)
    occ = packing.occ_set(occ, jnp.where(wrote, idx, -1) * 0 + idx * wrote)
    for i in range(L):
        if bool(wrote[i]):
            ref[i, int(idx[i])] = True
    # occ_set writes only where the mask fires: re-derive via the
    # boolean view.
    occ2 = packing.make_occ(L, S)
    mask = np.zeros((L, S), bool)
    for i in range(L):
        if bool(wrote[i]):
            mask[i, int(idx[i])] = True
    occ2 = packing.occ_set(occ2, jnp.asarray(mask))
    np.testing.assert_array_equal(
        np.asarray(packing.occ_unpack(occ2, S)), mask
    )
    got = packing.occ_get(occ2, idx)
    np.testing.assert_array_equal(
        np.asarray(got), mask[np.arange(L), np.asarray(idx)]
    )
    # Clear is exact and only touches the cleared bits.
    occ3 = packing.occ_clear(occ2, jnp.asarray(mask))
    assert not np.asarray(packing.occ_unpack(occ3, S)).any()


def test_trace_codec_round_trip():
    rng = np.random.default_rng(11)
    ticks = np.sort(rng.integers(0, 500, size=200)).astype(np.int64)
    lanes = rng.integers(0, 4, size=200).astype(np.int64)
    words = packing.encode_trace(ticks, lanes)
    assert words.dtype == np.int32 and words.shape == (200,)
    dts, back_lanes = packing.decode_trace(jnp.asarray(words))
    np.testing.assert_array_equal(np.asarray(back_lanes), lanes)
    np.testing.assert_array_equal(
        np.cumsum(np.asarray(dts)) + int(ticks[0]) - int(dts[0]),
        ticks,
    )
    assert packing.trace_first_time(words) == int(ticks[0])
    with pytest.raises(AssertionError):
        packing.encode_trace(np.array([5, 3]), np.array([0, 0]))


# ---------------------------------------------------------------------------
# Packed == unpacked twin (the whole point): both adopting backends,
# 3 seeds, faults + workload + full lifecycle engaged.
# ---------------------------------------------------------------------------

_TWIN_LIFECYCLE = LifecyclePlan(
    rotate_every=32, sessions=8, resubmit_rate=0.15, session_ttl=24
)
_TWIN_FAULTS = FaultPlan(drop_rate=0.05, dup_rate=0.05, jitter=2)
# Bursty arrivals: the inter-burst troughs idle the session table past
# the TTL, so expiry (and its packed occ-clear path) actually runs.
_TWIN_WORKLOAD = WorkloadPlan(
    arrival="bursty", rate=0.5, burst_every=48, burst_len=8,
    burst_mult=6.0, zipf_s=0.8,
)


def _twin_pair(mod, seed, ticks=280):
    cfg = mod.analysis_config(
        faults=_TWIN_FAULTS,
        workload=_TWIN_WORKLOAD,
        lifecycle=_TWIN_LIFECYCLE,
    )
    cfg_p = dataclasses.replace(cfg, pack_planes=True)
    su, tu = _run(mod, cfg, ticks, seed)
    sp, tp = _run(mod, cfg_p, ticks, seed)
    assert int(tu) == int(tp)
    return cfg, cfg_p, su, sp, tu


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("mod", [mp, cz], ids=["multipaxos", "compart"])
def test_packed_bit_identical_to_unpacked_twin(mod, seed):
    cfg, cfg_p, su, sp, t = _twin_pair(mod, seed)
    W = cfg.window
    for f in dataclasses.fields(su):
        if f.name in ("status", "rb_status", "lifecycle"):
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(su, f.name)),
            jax.tree_util.tree_leaves(getattr(sp, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    # The packed planes decode to the twin's exact int8 planes.
    np.testing.assert_array_equal(
        np.asarray(mp_unpack(mod, cfg_p, sp.status, W)),
        np.asarray(su.status),
    )
    rb = getattr(su, "rb_status", None)  # multipaxos read ring only
    if rb is not None and rb.size:
        np.testing.assert_array_equal(
            np.asarray(
                mp_unpack(mod, cfg_p, sp.rb_status, rb.shape[-1])
            ),
            np.asarray(rb),
        )
    # Session tables agree under canonicalization (dead packed cells
    # retain stale words; the -1 mask is the client-visible view), and
    # the distinct-live counts agree.
    plan = cfg.lifecycle
    cu = lc_mod.canonical_sessions(plan, su.lifecycle)
    cp = lc_mod.canonical_sessions(plan, sp.lifecycle)
    for name in ("sess_last", "sess_res", "sess_total"):
        np.testing.assert_array_equal(
            np.asarray(getattr(cu, name)),
            np.asarray(getattr(cp, name)),
            err_msg=name,
        )
    assert int(lc_mod.live_sessions(plan, su.lifecycle)) == int(
        lc_mod.live_sessions(plan, sp.lifecycle)
    )
    # The books are identical outright.
    for name in ("sess_total", "resubmits", "cache_hits", "expired"):
        np.testing.assert_array_equal(
            np.asarray(getattr(su.lifecycle, name)),
            np.asarray(getattr(sp.lifecycle, name)),
        )
    _assert_invariants(mod, cfg_p, sp, t)
    # The run actually exercised what it claims: rotations happened,
    # the cache answered, TTL expired someone, and packing shrank the
    # status plane 4x.
    assert int(su.lifecycle.rot_count) >= 1
    assert int(su.lifecycle.cache_hits) > 0
    assert int(su.lifecycle.expired) > 0
    assert sp.status.nbytes * 4 == su.status.nbytes


def mp_unpack(mod, cfg, words, size):
    return mod._unpack_status(cfg, words, size)


# ---------------------------------------------------------------------------
# TTL expiry x rotation boundary: conservation stays exact.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("packed", [False, True], ids=["unpacked", "packed"])
def test_session_ttl_across_rotation_keeps_books_exact(seed, packed):
    """Expiry across >= 2 rotation boundaries: ``lifecycle_ok`` (the
    in-graph conservation predicate) holds at every probe point, the
    expiry counter moved, and the re-submission cache still answers
    AFTER the expiring rotations (an expired slot re-admits as a fresh
    session rather than double-serving)."""
    cfg = mp.analysis_config(
        workload=WorkloadPlan(
            arrival="bursty", rate=0.5, burst_every=48, burst_len=8,
            burst_mult=6.0,
        ),
        lifecycle=LifecyclePlan(
            rotate_every=32, sessions=4, resubmit_rate=0.2,
            session_ttl=16,
        ),
    )
    if packed:
        cfg = dataclasses.replace(cfg, pack_planes=True)
    st, t = _run(mp, cfg, 100, seed)
    _assert_invariants(mp, cfg, st, t)
    first_rot = int(st.lifecycle.rot_count)
    assert first_rot >= 1
    first_hits = int(st.lifecycle.cache_hits)
    for _ in range(2):  # segment boundaries probe conservation too
        st, t = _run(mp, cfg, 60, seed + 10, state=st, t=t)
        _assert_invariants(mp, cfg, st, t)
    assert int(st.lifecycle.rot_count) > first_rot
    assert int(st.lifecycle.expired) > 0
    assert int(st.lifecycle.cache_hits) > first_hits
    assert int(jnp.sum(st.lifecycle.sess_total)) == int(st.committed)


# ---------------------------------------------------------------------------
# Trace-driven open-loop arrivals
# ---------------------------------------------------------------------------


def _trace_cfg(n_events, chunk=8):
    return mp.analysis_config(
        workload=WorkloadPlan(
            arrival="trace", trace_len=n_events, trace_chunk=chunk
        ),
    )


def test_trace_replays_exactly_once_with_burst_deferral():
    """A recorded schedule with a burst wider than the decode chunk:
    every event admits exactly once (offered == trace_len), burst
    overflow defers FIFO to following ticks, and the cursor pins at
    exhaustion."""
    L = 4
    ticks = np.concatenate(
        [np.arange(10), np.full(20, 12), np.arange(14, 24)]
    )
    lanes = (np.arange(ticks.size) % L).astype(np.int64)
    words = packing.encode_trace(np.sort(ticks), lanes)
    cfg = _trace_cfg(words.size, chunk=8)
    st = mp.init_state(cfg)
    st = dataclasses.replace(
        st, workload=workload_mod.load_trace(st.workload, words)
    )
    st, t = _run(mp, cfg, 80, 0, state=st)
    _assert_invariants(mp, cfg, st, t)
    assert int(st.workload.trace_cursor) == words.size
    assert int(st.workload.offered) == words.size
    # Exactly-once end to end: everything offered was admitted and
    # eventually committed (80 ticks drains the burst).
    assert int(jnp.sum(st.workload.adm_total)) == words.size
    # The cursor is STABLE at exhaustion: more ticks change nothing.
    st2, _ = _run(mp, cfg, 20, 1, state=st, t=t)
    assert int(st2.workload.trace_cursor) == words.size
    assert int(st2.workload.offered) == words.size


def test_trace_swap_is_a_pure_state_swap():
    """Serving a different recorded trace reuses the compiled brick:
    load_trace replaces state leaves only — zero recompiles — and the
    second trace replays exactly."""
    L = 4
    n = 40
    rng = np.random.default_rng(3)

    def make(seed_ticks):
        t = np.sort(seed_ticks.astype(np.int64))
        return packing.encode_trace(
            t, rng.integers(0, L, size=t.size).astype(np.int64)
        )

    cfg = _trace_cfg(n)
    words_a = make(rng.integers(0, 30, size=n))
    words_b = make(rng.integers(0, 30, size=n))
    st = mp.init_state(cfg)
    st = dataclasses.replace(
        st, workload=workload_mod.load_trace(st.workload, words_a)
    )
    st, _ = _run(mp, cfg, 50, 0, state=st)
    assert int(st.workload.trace_cursor) == n
    before = mp.run_ticks._cache_size()
    st_b = mp.init_state(cfg)
    st_b = dataclasses.replace(
        st_b, workload=workload_mod.load_trace(st_b.workload, words_b)
    )
    st_b, tb = _run(mp, cfg, 50, 0, state=st_b)
    assert mp.run_ticks._cache_size() == before
    assert int(st_b.workload.trace_cursor) == n
    _assert_invariants(mp, cfg, st_b, tb)


def test_trace_plan_validation_guards():
    with pytest.raises(AssertionError, match="trace_len > 0"):
        WorkloadPlan(arrival="trace").validate()
    with pytest.raises(AssertionError, match="open-loop"):
        WorkloadPlan(
            arrival="trace", trace_len=4, closed_window=2
        ).validate()
    # Length mismatch is a host-side install error, not a device one.
    cfg = _trace_cfg(8)
    st = mp.init_state(cfg)
    words = packing.encode_trace(np.arange(4), np.zeros(4, np.int64))
    with pytest.raises(AssertionError, match="trace_len=8"):
        workload_mod.load_trace(st.workload, words)


def test_read_mix_rejection_names_read_backends():
    """PR 9 follow-up: asking for a read mix on a backend with no
    device read path fails with a structured error that NAMES the
    backends that do support one."""
    plan = WorkloadPlan(
        arrival="constant", rate=1.0, read_fraction=0.3
    )
    with pytest.raises(AssertionError) as exc:
        plan.validate(reads_supported=False)
    msg = str(exc.value)
    for name in workload_mod.READ_BACKENDS:
        assert name in msg, msg
    assert "read_fraction=0" in msg
    # The same plan is fine where reads exist.
    plan.validate(reads_supported=True)
