"""The static-analysis engine's own tests: per-rule positive/negative
synthetic fixtures (``tests/fixtures/analysis/{clean,dirty}/``),
allowlist application + stale-entry rejection, and the CLI contract
(exit code = finding count, ``--json`` schema, ``--list``).

The fixture trees are PARSED, never imported — they are mini package
roots with a ``tpu/`` directory, so every AST rule runs against them
exactly as it runs against ``frankenpaxos_tpu/``.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from frankenpaxos_tpu import analysis
from frankenpaxos_tpu.analysis import allowlists, core

pytestmark = pytest.mark.lint

FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures" / "analysis"

# Every pure-AST rule (the registry-introspection kernel rules and the
# trace layer need an importable real tree and are covered by their own
# wrappers/tests).
FIXTURE_RULES = [
    "donation-jit",
    "telemetry-state-carry",
    "telemetry-tick-records",
    "host-sync-purity",
    "fault-config-field",
    "fault-validate",
    "fault-apply",
    "fault-rate-validated",
    "workload-config-field",
    "workload-validate",
    "workload-apply",
    "workload-rate-validated",
    "kernel-pallas-containment",
    "packing-containment",
]


def run_on(root: str, rule_ids, min_backends: int = 1) -> core.Report:
    ctx = core.Context(
        root=FIXTURES / root,
        repo=FIXTURES,
        min_backends=min_backends,
        importable=False,
    )
    return core.run(rule_ids=rule_ids, ctx=ctx)


# ---------------------------------------------------------------------------
# Fixture coverage: every rule passes on clean, fires on dirty
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule_id", FIXTURE_RULES)
def test_rule_negative_on_clean_fixture(rule_id):
    report = run_on("clean", [rule_id])
    assert not report.findings, "\n" + report.format()


@pytest.mark.parametrize("rule_id", FIXTURE_RULES)
def test_rule_positive_on_dirty_fixture(rule_id):
    report = run_on("dirty", [rule_id])
    assert report.findings, f"rule {rule_id} has no teeth on dirty tree"
    assert all(f.rule == rule_id for f in report.findings)


def test_dirty_fixture_expected_keys():
    """The dirty tree produces exactly the violations it documents —
    pinned by key so a matcher regression (missing OR spurious
    findings) is visible."""
    report = run_on("dirty", FIXTURE_RULES)
    keys = {(f.rule, f.key) for f in report.findings}
    expected = {
        ("donation-jit", "toy_batched.py:run_ticks"),
        ("telemetry-state-carry", "toy_batched.py:ToyState"),
        ("telemetry-tick-records", "toy_batched.py"),
        ("host-sync-purity", "toy_batched.py:_inline_sync:device_get"),
        ("host-sync-purity", "helpers.py:pull:block_until_ready"),
        ("host-sync-purity", "helpers.py:pull:asarray"),
        ("host-sync-purity", "toy_batched.py:run_ticks:asarray"),
        (
            "host-sync-purity",
            "toy_batched.py:method_sync:block_until_ready",
        ),
        ("host-sync-purity", "toy_batched.py:_table_sync:item"),
        ("fault-config-field", "toy_batched.py:ToyConfig"),
        ("fault-validate", "toy_batched.py:ToyConfig"),
        ("fault-apply", "toy_batched.py"),
        ("fault-rate-validated", "toy_batched.py:ToyConfig:loss_rate"),
        ("workload-config-field", "toy_batched.py:ToyConfig"),
        ("workload-validate", "toy_batched.py:ToyConfig"),
        ("workload-apply", "toy_batched.py"),
        ("workload-rate-validated", "workload.py:ToyWorkloadPlan:bad_fraction"),
        ("kernel-pallas-containment", "tpu/toy_batched.py"),
        ("packing-containment", "tpu/toy_batched.py"),
    }
    assert keys == expected, keys.symmetric_difference(expected)


def test_transitive_host_sync_is_the_new_coverage():
    """The smuggled-through-a-helper syncs (same-module helper and a
    cross-module helpers.py call) are exactly what the old inline-only
    lint could not see."""
    report = run_on("dirty", ["host-sync-purity"])
    keys = {f.key for f in report.findings}
    assert "toy_batched.py:_inline_sync:device_get" in keys
    assert "helpers.py:pull:block_until_ready" in keys


def test_method_and_switch_table_sync_coverage():
    """The PR 5 (b) depth extension: syncs reached only through a
    METHOD call (driver.method_sync) or a dict SWITCH TABLE
    (_HANDLERS[...]) are found, and the clean tree's traced method +
    table dispatch stay finding-free (no false positives)."""
    report = run_on("dirty", ["host-sync-purity"])
    keys = {f.key for f in report.findings}
    assert "toy_batched.py:method_sync:block_until_ready" in keys
    assert "toy_batched.py:_table_sync:item" in keys
    assert not run_on("clean", ["host-sync-purity"]).findings


def test_backend_inventory_floor():
    assert not run_on("clean", ["backend-inventory"]).findings
    report = run_on("clean", ["backend-inventory"], min_backends=2)
    assert [f.key for f in report.findings] == ["count"]


# ---------------------------------------------------------------------------
# Allowlist semantics
# ---------------------------------------------------------------------------


def test_allowlist_suppresses_by_key(monkeypatch):
    monkeypatch.setitem(
        allowlists.SUPPRESS,
        "donation-jit",
        {"toy_batched.py:run_ticks": "fixture exercise"},
    )
    report = run_on("dirty", ["donation-jit"])
    assert not report.findings
    assert [s["key"] for s in report.allowlisted] == [
        "toy_batched.py:run_ticks"
    ]
    assert report.allowlisted[0]["reason"] == "fixture exercise"


def test_stale_allowlist_entry_is_a_finding(monkeypatch):
    """A typo'd/outdated allowlist key silently exempts nothing — the
    engine turns it into an `allowlist-stale` finding."""
    monkeypatch.setitem(
        allowlists.SUPPRESS,
        "donation-jit",
        {"gone_batched.py:no_such_fn": "stale reason"},
    )
    report = run_on("clean", ["donation-jit"])
    assert [f.rule for f in report.findings] == [core.STALE_RULE]
    assert "gone_batched.py:no_such_fn" in report.findings[0].message


def test_suppress_block_for_unknown_rule_id_is_a_finding(monkeypatch):
    """A SUPPRESS block keyed by a rule id that is not registered
    (typo, renamed rule) would never be examined by any rule's
    suppression pass — the engine flags the block itself."""
    monkeypatch.setitem(
        allowlists.SUPPRESS,
        "donation_jit",  # underscore typo for donation-jit
        {"toy_batched.py:run_ticks": "misrouted exemption"},
    )
    report = run_on("clean", ["donation-jit"])
    assert [f.rule for f in report.findings] == [core.STALE_RULE]
    assert report.findings[0].key == "donation_jit:<unknown-rule>"


def test_stale_dataflow_allowlist_entry_is_a_finding(monkeypatch):
    """The stale-rejection hygiene covers the dataflow layer too: a
    suppression key no dataflow rule currently raises is itself a
    finding, even though dataflow rules derive keys from traced jaxprs
    rather than source locations."""
    import importlib.util
    import sys

    path = FIXTURES / "dataflow" / "clean_toy.py"
    spec = importlib.util.spec_from_file_location("clean_toy", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["clean_toy"] = mod
    spec.loader.exec_module(mod)

    monkeypatch.setitem(
        allowlists.SUPPRESS,
        "donation-hazard",
        {"gone_backend:gone_leaf": "stale reason"},
    )
    ctx = core.Context(dataflow_targets=[("clean_toy", mod)])
    report = core.run(rule_ids=["donation-hazard"], ctx=ctx)
    assert [f.rule for f in report.findings] == [core.STALE_RULE]
    assert [f.key for f in report.findings] == [
        "donation-hazard:gone_backend:gone_leaf"
    ]


def test_dtype_pin_for_unknown_backend_is_a_finding(monkeypatch):
    """A DTYPE_WIDENING pin naming a nonexistent backend can never
    match a trace — it is a typo/rename leftover and must be flagged
    even on runs that trace no backends at all."""
    monkeypatch.setitem(
        allowlists.DTYPE_WIDENING,
        ("fasterpaxo", "int16->int32"),  # typo for fasterpaxos
        (5, "typo'd pin"),
    )
    ctx = core.Context(backends=())  # stale-pin scan only, no compiles
    report = core.run(rule_ids=["trace-dtype-policy"], ctx=ctx)
    assert [f.key for f in report.findings] == [
        "fasterpaxo:int16->int32:unknown-backend"
    ]


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError, match="no-such-rule"):
        analysis.run(rule_ids=["no-such-rule"])


def test_rule_registry_shape():
    n = analysis.rule_count()
    assert n >= 44, sorted(core.RULES)
    layers = {r.layer for r in core.RULES.values()}
    assert layers == {"ast", "trace", "dataflow"}
    assert all(r.doc for r in core.RULES.values())


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def _cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "frankenpaxos_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=str(pathlib.Path(__file__).resolve().parent.parent),
        timeout=600,
    )


def test_cli_ast_layer_json_smoke():
    """`--layer ast --json`: exit 0 on the clean repo, structured
    report on stdout."""
    proc = _cli("--layer", "ast", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["version"] == analysis.ANALYSIS_VERSION
    assert report["finding_count"] == 0
    assert report["findings"] == []
    assert set(report["rules_run"]) >= set(FIXTURE_RULES)
    for entry in report["allowlisted"]:
        assert {"rule", "path", "line", "message", "key", "reason"} <= set(
            entry
        )


def test_cli_single_rule_and_list():
    proc = _cli("--rule", "donation-jit")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    listing = _cli("--list")
    assert listing.returncode == 0
    for rid in ("donation-jit", "trace-dtype-policy", "host-sync-purity"):
        assert rid in listing.stdout

    bogus = _cli("--rule", "no-such-rule")
    assert bogus.returncode == 2  # usage error, not a finding count
