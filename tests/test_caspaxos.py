"""CASPaxos sim tests (the analog of shared/src/test/scala/caspaxos)."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import (
    DeliverMessage,
    FakeLogger,
    SimAddress,
    SimTransport,
    TriggerTimer,
)
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols.caspaxos import (
    CasAcceptor,
    CasClient,
    CasLeader,
    CasPaxosConfig,
)
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)


def make(f=1, seed=0, num_clients=2):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    config = CasPaxosConfig(
        f=f,
        leader_addresses=tuple(SimAddress(f"leader{i}") for i in range(f + 1)),
        acceptor_addresses=tuple(
            SimAddress(f"acceptor{i}") for i in range(2 * f + 1)
        ),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [
        CasLeader(a, t, log(), config, seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    acceptors = [CasAcceptor(a, t, log(), config) for a in config.acceptor_addresses]
    clients = [
        CasClient(SimAddress(f"client{i}"), t, log(), config, seed=seed + 50 + i)
        for i in range(num_clients)
    ]
    return t, config, leaders, acceptors, clients


def drain(t, max_steps=50000):
    """Deliver all messages; when the network is quiet, fire recover/resend
    timers (nacked leaders back off on a timer) until nothing is left."""
    steps = 0
    for _ in range(50):
        while t.messages and steps < max_steps:
            t.deliver_message(t.messages[0])
            steps += 1
        assert steps < max_steps
        recover = [x for x in t.running_timers() if x.name() == "recover"]
        if not recover:
            return
        t.trigger_timer(recover[0].address, "recover")


def test_caspaxos_single_proposal():
    t, config, leaders, acceptors, clients = make()
    p = clients[0].propose({1, 2})
    drain(t)
    assert p.done and p.result() == frozenset({1, 2})


def test_caspaxos_sequential_unions():
    t, config, leaders, acceptors, clients = make()
    p1 = clients[0].propose({1})
    drain(t)
    p2 = clients[0].propose({2})
    drain(t)
    p3 = clients[1].propose({3})
    drain(t)
    assert p1.result() == frozenset({1})
    assert p2.result() == frozenset({1, 2})
    assert p3.result() == frozenset({1, 2, 3})


def test_caspaxos_contending_leaders_converge():
    """Two clients hit two different leaders; nack/backoff resolves it."""
    t, config, leaders, acceptors, clients = make(seed=3)
    p1 = clients[0].propose({1})
    p2 = clients[1].propose({2})
    rng = random.Random(0)
    for _ in range(3000):
        cmd = t.generate_command(rng)
        if cmd is None:
            break
        t.run_command(cmd, record=False)
    assert p1.done and p2.done
    # Both results contain the client's own element; the later one contains
    # both (register grows monotonically).
    assert 1 in p1.result() and 2 in p2.result()
    union = p1.result() | p2.result()
    assert union == frozenset({1, 2})


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    x: int


class SimulatedCasPaxos(SimulatedSystem):
    """Linearizability of the union register, real-time fragment: if
    operation B is INVOKED after operation A COMPLETED, then B's result
    must contain everything A's result contained (overlapping operations
    may linearize in either order, so only non-overlapping pairs are
    constrained)."""

    def __init__(self, f=1):
        self.f = f
        self.violation = None
        self.completed_union = frozenset()
        self.n_completed = 0

    def new_system(self, seed):
        self.violation = None
        self.completed_union = frozenset()
        self.n_completed = 0
        system = make(self.f, seed)
        self._next_x = iter(range(1, 10_000))
        return system

    def get_state(self, system):
        return (self.n_completed, self.violation)

    def generate_command(self, system, rng):
        t, config, leaders, acceptors, clients = system
        ops = [
            (1, Propose(i, next(self._next_x)))
            for i, c in enumerate(clients)
            if c.pending is None
        ]
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, config, leaders, acceptors, clients = system
        if isinstance(command, Propose):
            promise = clients[command.client_index].propose({command.x})
            # Snapshot what was already completed when this op was invoked.
            seen_at_invocation = self.completed_union

            def on_done(p):
                if p.exception is not None:
                    return
                if not seen_at_invocation <= p.value:
                    self.violation = (
                        f"op invoked after {sorted(seen_at_invocation)} "
                        f"completed, but returned {sorted(p.value)}"
                    )
                self.completed_union = self.completed_union | p.value
                self.n_completed += 1

            promise.on_complete(on_done)
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        return state[1]


@pytest.mark.parametrize("f", [1, 2])
def test_caspaxos_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedCasPaxos(f), run_length=150, num_runs=15, seed=f
    )
    assert bad is None, f"\n{bad}"
