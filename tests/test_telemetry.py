"""The device-side telemetry subsystem (tpu/telemetry.py): ring
semantics, the repo-wide dtype bit-identity contract, window-size
invariance, the coalesced transport pulls, and the exposition layer."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.tpu import telemetry as T
from frankenpaxos_tpu.tpu.common import widen_state
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    BatchedMultiPaxosConfig,
    init_state,
    run_ticks,
)
from frankenpaxos_tpu.tpu.transport import TpuSimTransport

SEEDS = [0, 1, 2]


def _with_window(state, window):
    return dataclasses.replace(state, telemetry=T.make_telemetry(window))


def _flagship_cfg(**kw):
    base = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=0.05, retry_timeout=8,
    )
    base.update(kw)
    return BatchedMultiPaxosConfig(**base)


# -- Ring mechanics -----------------------------------------------------------


def test_record_zero_window_is_noop_except_ticks():
    tel = T.make_telemetry(0)
    tel = T.record(tel, commits=5, queue_depth=3, queue_capacity=10)
    assert int(tel.ticks) == 1
    assert tel.counters.shape == (0, T.NUM_COLS)
    assert int(tel.totals.sum()) == 0
    assert int(tel.queue_hist.sum()) == 0


def test_series_unrolls_ring_in_time_order():
    tel = T.make_telemetry(4)
    for i in range(7):  # wraps: keeps ticks 3..6
        tel = T.record(tel, commits=i)
    s = T.series(tel)
    np.testing.assert_array_equal(s["tick"], [3, 4, 5, 6])
    np.testing.assert_array_equal(s["commits"], [3, 4, 5, 6])
    assert T.summary(tel)["commits_total"] == sum(range(7))


def test_series_partial_ring():
    tel = T.make_telemetry(8)
    for i in range(3):
        tel = T.record(tel, proposals=10 + i)
    s = T.series(tel)
    np.testing.assert_array_equal(s["tick"], [0, 1, 2])
    np.testing.assert_array_equal(s["proposals"], [10, 11, 12])


def test_queue_histogram_bins_by_occupancy_fraction():
    tel = T.make_telemetry(4)
    tel = T.record(tel, queue_depth=0, queue_capacity=64)
    tel = T.record(tel, queue_depth=63, queue_capacity=64)
    qh = np.asarray(tel.queue_hist)
    assert qh[0] == 1 and qh[-1] == 1 and qh.sum() == 2


# -- The repo-wide contracts on a real backend --------------------------------


def test_telemetry_counters_reconcile_with_state():
    cfg = _flagship_cfg()
    st, t = run_ticks(
        cfg, init_state(cfg), jnp.zeros((), jnp.int32), 60,
        jax.random.PRNGKey(0),
    )
    s = T.summary(st.telemetry)
    assert s["ticks"] == 60
    assert s["commits_total"] == int(st.committed)
    assert s["executes_total"] == int(st.retired)
    # The telemetry latency histogram IS the commit-latency histogram.
    np.testing.assert_array_equal(
        np.asarray(st.telemetry.lat_hist), np.asarray(st.lat_hist)
    )
    # 60 < default window: the full commit series is retained and sums
    # to the cumulative counter.
    assert int(T.series(st.telemetry)["commits"].sum()) == int(st.committed)


@pytest.mark.parametrize("seed", SEEDS)
def test_telemetry_bit_identical_between_narrow_and_widened(seed):
    """Satellite contract: telemetry counters are bit-identical between
    a narrowed backend run and its widen_state() int32 reference run —
    the ring must never observe the dtype policy."""
    cfg = _flagship_cfg()
    key = jax.random.PRNGKey(seed)
    t0 = jnp.zeros((), jnp.int32)
    narrow, _ = run_ticks(cfg, init_state(cfg), t0, 80, key)
    wide, _ = run_ticks(cfg, widen_state(init_state(cfg)), t0, 80, key)
    la = jax.tree_util.tree_leaves(narrow.telemetry)
    lb = jax.tree_util.tree_leaves(wide.telemetry)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert a.dtype == b.dtype  # int32 on both paths: never narrowed
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("seed", SEEDS)
def test_ring_contents_invariant_to_window_size(seed):
    """Where two ring windows overlap, they record identical values:
    the window is a VIEW of the same per-tick series, never an input to
    the simulation."""
    cfg = _flagship_cfg()
    key = jax.random.PRNGKey(seed)
    t0 = jnp.zeros((), jnp.int32)
    ticks = 50
    small, _ = run_ticks(
        cfg, _with_window(init_state(cfg), 16), t0, ticks, key
    )
    big, _ = run_ticks(
        cfg, _with_window(init_state(cfg), 64), t0, ticks, key
    )
    s_small = T.series(small.telemetry)
    s_big = T.series(big.telemetry)
    n = len(s_small["tick"])  # 16 retained ticks
    assert n == 16
    for name in ("tick",) + T.COUNTER_FIELDS:
        np.testing.assert_array_equal(
            s_small[name], s_big[name][-n:], err_msg=name
        )
    # Cumulative views are window-independent outright.
    np.testing.assert_array_equal(
        np.asarray(small.telemetry.totals), np.asarray(big.telemetry.totals)
    )
    np.testing.assert_array_equal(
        np.asarray(small.telemetry.lat_hist),
        np.asarray(big.telemetry.lat_hist),
    )


def test_disabled_telemetry_does_not_change_simulation():
    """The zero-width ring variant must be a pure observer removal: the
    simulation state itself stays bit-identical."""
    cfg = _flagship_cfg()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)
    on, _ = run_ticks(cfg, init_state(cfg), t0, 40, key)
    off, _ = run_ticks(cfg, _with_window(init_state(cfg), 0), t0, 40, key)
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        # Pytree-valued fields (the workload shaping state) compare
        # leaf-by-leaf; array fields directly.
        on_leaves = jax.tree_util.tree_leaves(getattr(on, f.name))
        off_leaves = jax.tree_util.tree_leaves(getattr(off, f.name))
        assert len(on_leaves) == len(off_leaves), f.name
        for a, b in zip(on_leaves, off_leaves):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )


# -- Transport integration ----------------------------------------------------


def test_transport_telemetry_and_trace_spans():
    sim = TpuSimTransport(_flagship_cfg(), seed=0)
    sim.run(30)
    sim.block_until_ready()
    tel = sim.telemetry()
    assert int(tel.ticks) == 30
    summary = sim.telemetry_summary()
    assert summary["commits_total"] == sim.stats()["committed"]
    d = sim.telemetry_dict()
    json.dumps(d)  # must be JSON-serializable as-is
    assert d["ticks"] == 30
    assert len(d["series"]["commits"]) == 30
    # Host-side spans: the first dispatch compiles; wait and transfer
    # spans carry wall-clock stamps.
    names = [s["name"] for s in sim.trace()]
    assert "dispatch" in names and "wait" in names and "transfer" in names
    first_dispatch = next(s for s in sim.trace() if s["name"] == "dispatch")
    assert first_dispatch["compile"] is True
    assert all(s["start_unix"] > 0 and s["duration_s"] >= 0 for s in sim.trace())
    # A second run of the same length is not a fresh compile.
    sim.run(30)
    assert [s for s in sim.trace() if s["name"] == "dispatch"][-1][
        "compile"
    ] is False


def test_transport_stats_is_one_coalesced_pull(monkeypatch):
    """The satellite fix: stats() must issue exactly ONE jax.device_get,
    regardless of which optional subsystems are live."""
    cfg = _flagship_cfg(
        fail_rate=0.02, revive_rate=0.2, heartbeat_timeout=4,
        reconfigure_every=25, state_machine="kv", kv_keys=16,
        num_clients=4, dup_rate=0.05, read_rate=2, read_window=8,
    )
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(30)
    sim.block_until_ready()
    calls = []
    real = jax.device_get

    def counting(x):
        calls.append(1)
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    stats = sim.stats()
    assert len(calls) == 1, f"stats() issued {len(calls)} device pulls"
    # Every optional block made it into the single pull.
    for key in ("elections", "reconfigurations", "sm_applied", "reads_done"):
        assert key in stats


# -- Exposition + dashboard ---------------------------------------------------


def test_exposition_lines_parse_and_match_totals():
    from frankenpaxos_tpu.monitoring.scrape import parse_exposition

    cfg = _flagship_cfg()
    st, _ = run_ticks(
        cfg, init_state(cfg), jnp.zeros((), jnp.int32), 40,
        jax.random.PRNGKey(1),
    )
    text = "\n".join(
        T.exposition_lines(st.telemetry, labels={"backend": "multipaxos"})
    )
    samples = parse_exposition(text)
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    assert by_name["fpx_device_ticks_total"][0][1] == 40.0
    (labels, commits) = by_name["fpx_device_commits_total"][0]
    assert ("backend", "multipaxos") in labels
    assert commits == float(st.committed)
    # Histogram buckets are cumulative and end at the total count.
    buckets = by_name["fpx_device_commit_latency_ticks_bucket"]
    values = [v for _, v in buckets]
    assert values == sorted(values)
    assert values[-1] == float(np.asarray(st.lat_hist).sum())


def test_device_samples_roundtrip_into_metrics_capture(tmp_path):
    from frankenpaxos_tpu.monitoring.scrape import (
        MetricsCapture,
        append_device_samples,
        append_host_spans,
    )

    sim = TpuSimTransport(_flagship_cfg(), seed=0)
    csv_path = str(tmp_path / "metrics.csv")
    for _ in range(3):
        sim.run(20)
        sim.block_until_ready()
        append_device_samples(csv_path, sim.telemetry())
    append_host_spans(csv_path, sim.trace())
    cap = MetricsCapture(csv_path)
    assert "fpx_device_commits_total" in cap.names()
    assert "fpx_host_span_seconds" in cap.names()
    # The counter is monotone across scrapes and totals to the state.
    wide = cap.query("fpx_device_commits_total")
    col = wide.iloc[:, 0].dropna()
    assert list(col) == sorted(col)
    assert cap.total("fpx_device_commits_total") == float(
        sim.stats()["committed"]
    )


def test_dashboard_renders_telemetry_panels(tmp_path):
    pytest.importorskip("matplotlib")
    from frankenpaxos_tpu.monitoring.dashboard import (
        _load_telemetry_capture,
        render_telemetry_dashboard,
    )

    sim = TpuSimTransport(_flagship_cfg(), seed=0)
    sim.run(40)
    sim.block_until_ready()
    capture_path = tmp_path / "telemetry.json"
    capture_path.write_text(json.dumps({"telemetry": sim.telemetry_dict()}))
    loaded = _load_telemetry_capture(str(capture_path))
    assert loaded is not None and loaded["ticks"] == 40
    out = render_telemetry_dashboard(
        loaded, str(tmp_path / "dashboard.png")
    )
    assert out is not None and os.path.getsize(out) > 0


# -- The microbench hook ------------------------------------------------------


@pytest.mark.slow
def test_microbench_telemetry_reports_phase_breakdown(capsys):
    from frankenpaxos_tpu.harness.microbench import bench_telemetry

    rows = bench_telemetry(
        num_groups=16, window=16, slots_per_tick=2, ticks=40
    )
    cases = {r["case"]: r for r in rows}
    assert set(cases) == {"ring_off", "ring_on"}
    on = cases["ring_on"]
    assert "overhead_ratio" in on and on["overhead_ratio"] > 0
    assert on["commits_per_sec"] > 0
    assert any(
        line.startswith("TELEM_JSON ")
        for line in capsys.readouterr().out.splitlines()
    )
