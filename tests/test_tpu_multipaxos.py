"""Tests of the batched TPU simulation backend (CPU backend, 8 virtual
devices via conftest)."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from frankenpaxos_tpu.parallel import (
    make_mesh,
    run_ticks_sharded,
    shard_state,
)
from frankenpaxos_tpu.tpu import (
    BatchedMultiPaxosConfig,
    TpuSimTransport,
    check_invariants,
    init_state,
    leader_change,
    run_ticks,
    tick,
)
from frankenpaxos_tpu.tpu.common import INF16


def make(drop=0.0, **kw):
    defaults = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, drop_rate=drop,
    )
    defaults.update(kw)
    return BatchedMultiPaxosConfig(**defaults)


def test_happy_path_commits_and_executes():
    sim = TpuSimTransport(make(), seed=0)
    sim.run(60)
    stats = sim.stats()
    # Steady state: K slots per group per tick commit; pipeline depth only
    # affects the warmup.
    max_possible = 4 * 2 * 60
    assert stats["committed"] > max_possible * 0.8
    assert 0 < stats["executed"] <= stats["committed"]
    assert stats["commit_latency_p50_ticks"] >= 2  # two message hops minimum
    assert all(sim.check_invariants().values())


def test_progress_is_monotone_and_window_bounded():
    sim = TpuSimTransport(make(), seed=1)
    prev_committed, prev_executed = 0, 0
    for _ in range(5):
        sim.run(20)
        s = sim.stats()
        assert s["committed"] >= prev_committed
        assert s["executed"] >= prev_executed
        prev_committed, prev_executed = s["committed"], s["executed"]
        assert all(sim.check_invariants().values())


def test_drops_recovered_by_retries():
    cfg = make(drop=0.3, retry_timeout=8)
    sim = TpuSimTransport(cfg, seed=2)
    sim.run(400)
    stats1 = sim.stats()
    assert stats1["committed"] > 0
    assert stats1["executed"] > 0
    # Progress must be SUSTAINED: retries re-send to the full group,
    # including already-voted acceptors whose Phase2b may have been the
    # dropped message, so no slot can deadlock and stall its window.
    sim.run(400)
    stats2 = sim.stats()
    assert stats2["committed"] > stats1["committed"] + 100, (
        "commit progress stalled under loss: windows deadlocked"
    )
    assert stats2["executed"] > stats1["executed"] + 100
    # (Windows may well be full here — that is backpressure behind a slow
    # head slot, not deadlock; sustained executed growth is the liveness
    # signal.)
    assert all(sim.check_invariants().values())
    # Latency under loss must exceed the lossless latency.
    lossless = TpuSimTransport(make(), seed=2)
    lossless.run(400)
    assert (
        stats2["commit_latency_mean_ticks"]
        > lossless.stats()["commit_latency_mean_ticks"]
    )


def test_thrifty_vs_full_broadcast():
    thrifty = TpuSimTransport(make(thrifty=True), seed=3)
    full = TpuSimTransport(make(thrifty=False), seed=3)
    thrifty.run(100)
    full.run(100)
    assert thrifty.stats()["committed"] > 0
    assert full.stats()["committed"] > 0
    assert all(thrifty.check_invariants().values())
    assert all(full.check_invariants().values())


def test_leader_change_keeps_safety_and_liveness():
    sim = TpuSimTransport(make(), seed=4)
    sim.run(30)
    before = sim.stats()["committed"]
    sim.leader_change()
    sim.run(60)
    stats = sim.stats()
    assert stats["round"] == 1
    assert stats["committed"] > before  # in-flight slots repaired + new ones
    assert all(sim.check_invariants().values())


def test_leader_change_under_loss():
    sim = TpuSimTransport(make(drop=0.2, retry_timeout=6), seed=5)
    sim.run(50)
    sim.leader_change()
    sim.run(200)
    stats = sim.stats()
    assert stats["executed"] > 0
    assert all(sim.check_invariants().values())


def test_stale_round_votes_not_counted():
    """After a leader change, votes from the old round must not form
    quorums in the new round (ballot safety)."""
    cfg = make(lat_min=3, lat_max=3)  # long latency: votes in flight
    sim = TpuSimTransport(cfg, seed=6)
    sim.run(4)  # phase2as in flight, few votes landed
    sim.leader_change()
    sim.run(100)
    assert all(sim.check_invariants().values())


def test_vmap_over_seeds():
    """Massively parallel property testing: S independent simulations with
    different PRNG schedules as one vmapped program."""
    cfg = make(drop=0.1, retry_timeout=6)
    S = 8
    states = jax.vmap(lambda _: init_state(cfg))(jnp.arange(S))
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(S))

    def run_one(state, key):
        def step(carry, i):
            st, t = carry
            st = tick(cfg, st, t, jax.random.fold_in(key, i))
            return (st, t + 1), ()

        (state, t), _ = jax.lax.scan(
            step, (state, jnp.zeros((), jnp.int32)), jnp.arange(200)
        )
        return state, t

    states, ts = jax.vmap(run_one)(states, keys)
    committed = jax.device_get(states.committed)
    assert (committed > 0).all()
    # Different seeds → different schedules → (almost surely) different
    # commit counts under loss.
    assert len(set(committed.tolist())) > 1
    for s in range(S):
        one = jax.tree.map(lambda x: x[s], states)
        inv = check_invariants(cfg, one, ts[s])
        assert all(bool(v) for v in inv.values()), (s, inv)


def test_sharded_run_matches_unsharded():
    """The same simulation, sharded over an 8-device CPU mesh along the
    group axis, produces the exact same results."""
    cfg = make(num_groups=8, drop=0.1, retry_timeout=6)
    key = jax.random.PRNGKey(7)
    t0 = jnp.zeros((), jnp.int32)

    plain_state, plain_t = run_ticks(cfg, init_state(cfg), t0, 150, key)

    mesh = make_mesh()
    assert mesh.devices.size == 8
    sharded0 = shard_state(init_state(cfg), mesh)
    sharded_state, sharded_t = run_ticks_sharded(cfg, mesh, sharded0, t0, 150, key)

    assert int(plain_t) == int(sharded_t)
    for field in (
        "committed", "retired", "lat_sum", "next_slot", "head", "executed",
    ):
        a = jax.device_get(getattr(plain_state, field))
        b = jax.device_get(getattr(sharded_state, field))
        assert (a == b).all(), field
    assert (
        jax.device_get(plain_state.lat_hist)
        == jax.device_get(sharded_state.lat_hist)
    ).all()


def test_transport_with_mesh():
    cfg = make(num_groups=8)
    sim = TpuSimTransport(cfg, seed=8, mesh=make_mesh())
    sim.run(50)
    assert sim.stats()["committed"] > 0
    assert all(sim.check_invariants().values())


def test_invariant_checker_has_teeth():
    """Corrupt the state (a chosen slot without quorum) and the checker
    must flag it."""
    cfg = make()
    state = init_state(cfg)
    state, t = run_ticks(cfg, state, jnp.zeros((), jnp.int32), 30, jax.random.PRNGKey(9))
    bad = dataclasses.replace(
        state, status=state.status.at[0, 0].set(2),  # CHOSEN
        # Offset clocks: INF16 = "never arrives" (no vote counted).
        p2b_arrival=jnp.full_like(state.p2b_arrival, INF16),
    )
    inv = check_invariants(cfg, bad, t)
    assert not bool(inv["quorum_ok"])


def test_reconfiguration_churn_preserves_safety_and_values():
    """Matchmaker-style reconfiguration (BASELINE config 4): periodic
    acceptor-set swaps preserve all invariants, and an in-flight slot
    with a vote in the old configuration keeps its value through the
    reconfiguration (the phase-1-against-old-configs guarantee)."""
    import dataclasses as dc

    import numpy as np

    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        INF16,
        NOOP_VALUE,
        BatchedMultiPaxosConfig,
        check_invariants,
        init_state,
        reconfigure,
        tick,
    )

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=2, window=8, slots_per_tick=2,
        lat_min=1, lat_max=1, thrifty=False, retry_timeout=100,
        max_slots_per_group=2,
    )
    key = jax.random.PRNGKey(5)
    state = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    # Let exactly one acceptor of group 0 slot 0 vote; block the rest.
    # Layout: [A, G, W].
    p2a = np.asarray(state.p2a_arrival).copy()
    p2a[1:, :, :] = INF16  # acceptors 1.. never hear the Phase2a
    p2a[:, 1, :] = INF16  # group 1 blocked entirely
    p2a[:, 0, 1] = INF16  # group 0 slot 1 blocked
    state = dc.replace(state, p2a_arrival=jnp.asarray(p2a))
    state = tick(cfg, state, jnp.int32(1), jax.random.fold_in(key, 1))
    assert int(state.committed) == 0
    voted_value = int(np.asarray(state.vote_value)[0, 0, 0])
    assert voted_value >= 0

    # Reconfigure: new acceptor set; the voted slot must keep its value,
    # unvoted in-flight slots become noops.
    state = reconfigure(cfg, state, jnp.int32(2), jax.random.fold_in(key, 99))
    slot_value = np.asarray(state.slot_value)
    assert int(slot_value[0, 0]) == voted_value
    assert int(slot_value[0, 1]) == NOOP_VALUE
    assert int(slot_value[1, 0]) == NOOP_VALUE
    # Fresh acceptors: no votes, no pending phase2bs for in-flight slots.
    assert (np.asarray(state.vote_round) == -1).all()
    # Run to completion: everything commits in the new configuration.
    t = 2
    for _ in range(20):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    inv = check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.retired) == 4
    # The chosen value for the voted slot survived the configuration swap.


def test_reconfiguration_under_load_invariants():
    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

    cfg = BatchedMultiPaxosConfig(
        f=2, num_groups=4, window=32, slots_per_tick=4,
        lat_min=1, lat_max=3, drop_rate=0.1,
    )
    sim = TpuSimTransport(cfg, seed=11)
    for _ in range(4):
        sim.run(50)
        sim.reconfigure()
    sim.run(100)
    inv = sim.check_invariants()
    assert all(inv.values()), inv
    assert sim.stats()["round"] == 4
    assert sim.committed() > 500


def test_baseline_configs_runner():
    """The five tracked BASELINE configurations run and report sane
    results at test sizes."""
    from frankenpaxos_tpu.tpu import baseline_configs as bc

    r1 = bc.config1_multipaxos_smoke(full=False)
    assert r1["committed"] > 0 and r1["invariants_ok"]
    r4 = bc.config4_matchmaker_churn(full=False)
    # Device-side churn: every group reconfigures on each 100-tick wave.
    assert r4["with_churn"]["reconfigurations"] >= 4 * 16
    assert r4["with_churn"]["old_configs_gcd"] > 0
    assert r4["throughput_retained"] > 0.8  # churn must not crater it
    # The timeline carries the dip/recovery signature.
    tl = r4["with_churn"]["timeline_committed_per_segment"]
    assert min(tl) < max(tl)
    r5 = bc.config5_flexible_sweep(full=False)
    modes = {(p["mode"], p["acceptors"]) for p in r5["points"]}
    assert ("grid", 6) in modes and ("majority", 6) in modes


def test_tpu_profile_writes_trace(tmp_path):
    """TpuSimTransport.profile captures a jax.profiler trace of a run
    segment (the perf_util.py flame-graph capability, device-side)."""
    import os

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

    cfg = BatchedMultiPaxosConfig(f=1, num_groups=4, window=16, slots_per_tick=2)
    sim = TpuSimTransport(cfg, seed=0)
    trace_dir = str(tmp_path / "trace")
    sim.profile(20, trace_dir)
    assert sim.committed() > 0
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found += files
    assert found, "profiler wrote no trace files"
