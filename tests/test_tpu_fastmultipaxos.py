"""Tests of the batched Fast MultiPaxos backend
(tpu/fastmultipaxos_batched.py): per-acceptor log-structured fast
rounds (fastmultipaxos/Acceptor.scala:183-238), O4 conflict recovery,
the fast-committed ledger, and client-retry dups."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu import fastmultipaxos_batched as fm


def run_random(cfg, seed, ticks):
    key = jax.random.PRNGKey(seed)
    state, t = fm.run_ticks(cfg, fm.init_state(cfg), jnp.int32(0), ticks, key)
    return state, t


def test_no_jitter_is_all_fast_path():
    """Identical arrival order at every acceptor: every slot gets a
    unanimous vote census and chooses on the fast path."""
    cfg = fm.BatchedFastMultiPaxosConfig(
        f=1, num_groups=4, window=32, cmd_window=16, cmds_per_tick=2,
        lat_min=2, lat_max=2, jitter=0,
    )
    state, t = run_random(cfg, seed=0, ticks=150)
    s = fm.stats(cfg, state, t)
    assert s["cmds_done"] > 4 * 100
    assert s["fast_fraction"] > 0.99
    assert s["recoveries"] == 0
    assert s["dups"] == 0
    assert s["safety_violations"] == 0
    inv = fm.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_jitter_creates_conflicts_and_recoveries():
    """Arrival-order divergence is the conflict source: with jitter the
    fast fraction drops and classic recoveries appear — yet every
    command still completes and the ledger stays clean."""
    # Fixed latency isolates jitter as the only divergence source.
    base = dict(
        f=1, num_groups=8, window=32, cmd_window=16, cmds_per_tick=2,
        lat_min=2, lat_max=2,
    )
    out = {}
    for jitter in (0, 2):
        cfg = fm.BatchedFastMultiPaxosConfig(jitter=jitter, **base)
        state, t = run_random(cfg, seed=1, ticks=200)
        s = fm.stats(cfg, state, t)
        assert s["safety_violations"] == 0
        assert s["cmds_done"] > 500
        inv = fm.check_invariants(cfg, state, t)
        assert all(bool(v) for v in inv.values()), inv
        out[jitter] = s
    assert out[0]["fast_fraction"] > out[2]["fast_fraction"]
    assert out[2]["recoveries"] > out[0]["recoveries"]


def test_recovery_discovers_unobserved_fast_quorum():
    """All acceptors voted the same command into a slot but the leader's
    visibility lags: a timeout/census recovery must choose THAT command
    (the ledger asserts it), never a competitor."""
    cfg = fm.BatchedFastMultiPaxosConfig(
        f=1, num_groups=2, window=16, cmd_window=8, cmds_per_tick=1,
        lat_min=1, lat_max=1, jitter=0, recovery_timeout=4,
    )
    key = jax.random.PRNGKey(2)
    state = fm.init_state(cfg)
    t = 0
    for _ in range(3):
        state = fm.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    # Votes exist for slot 0 in every group; delay the leader's
    # visibility far beyond the recovery timeout.
    assert bool((np.asarray(state.vote_value)[:, :, 0] >= 0).all())
    committed0 = np.asarray(state.fast_committed)[:, 0].copy()
    state = dataclasses.replace(
        state,
        vote_seen=jnp.where(
            state.vote_seen < fm.INF, state.vote_seen + 20, state.vote_seen
        ),
    )
    for _ in range(40):
        state = fm.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    s = fm.stats(cfg, state, jnp.int32(t))
    assert s["safety_violations"] == 0
    assert s["cmds_done"] > 0
    inv = fm.check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv


def test_retry_can_dup_but_never_violates():
    """Aggressive retries under heavy jitter: commands may be chosen in
    two slots (the execution layer dedups — counted, not a violation),
    but the per-slot ledger stays clean."""
    cfg = fm.BatchedFastMultiPaxosConfig(
        f=1, num_groups=8, window=32, cmd_window=16, cmds_per_tick=2,
        lat_min=1, lat_max=3, jitter=3, recovery_timeout=12,
        retry_timeout=8,
    )
    state, t = run_random(cfg, seed=3, ticks=300)
    s = fm.stats(cfg, state, t)
    assert s["dups"] > 0  # retries got double-chosen somewhere
    assert s["safety_violations"] == 0
    assert s["cmds_done"] > 1000
    inv = fm.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_dense_acceptor_logs():
    """Every slot below an acceptor's nextSlot carries its vote (the
    log-structured append is dense)."""
    cfg = fm.BatchedFastMultiPaxosConfig(
        f=1, num_groups=4, window=32, cmd_window=16, cmds_per_tick=2,
        lat_min=1, lat_max=2, jitter=1,
    )
    state, t = run_random(cfg, seed=4, ticks=100)
    vote = np.asarray(state.vote_value)
    head = np.asarray(state.head)
    nxt = np.asarray(state.acc_next)
    W = cfg.window
    for a in range(cfg.n):
        for g in range(cfg.num_groups):
            for s_ in range(int(head[g]), int(nxt[a, g])):
                assert vote[a, g, s_ % W] >= 0, (a, g, s_)


# ---------------------------------------------------------------------------
# Proposer crash semantics (PR 3 follow-up (b)): crash gates proposing,
# revival triggers the recovery election (instant re-broadcast of every
# pending command).
# ---------------------------------------------------------------------------


def _crash_cfg(**fault_kw):
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    return fm.BatchedFastMultiPaxosConfig(
        f=1, num_groups=4, window=16, cmd_window=16, cmds_per_tick=2,
        lat_min=1, lat_max=2, jitter=1, recovery_timeout=10,
        retry_timeout=6, faults=FaultPlan(**fault_kw),
    )


def test_dead_proposers_stall_and_manual_revival_resumes():
    """Every proposer dead: in-flight work drains, then commits STOP
    (no new commands, no re-broadcasts); reviving the proposers
    restores commit progress via the retry timers — the
    liveness-after-revive contract a crashed sequencer must honor.
    Deaths/revivals are forced by editing prop_alive (revive_rate=0
    keeps the PRNG process from resurrecting anyone mid-stall)."""
    cfg = _crash_cfg(crash_rate=0.001, revive_rate=0.0)
    key = jax.random.PRNGKey(2)
    state, t = fm.run_ticks(cfg, fm.init_state(cfg), jnp.int32(0), 30, key)
    assert int(state.committed_slots) > 0

    # Kill every proposer; the pipeline drains, then progress stops
    # (revive_rate=0: nobody comes back until we say so).
    state = dataclasses.replace(
        state, prop_alive=jnp.zeros((cfg.num_groups,), bool)
    )
    state, t = fm.run_ticks(cfg, state, t, 30, jax.random.fold_in(key, 1))
    c_drained = int(state.committed_slots)
    state, t = fm.run_ticks(cfg, state, t, 25, jax.random.fold_in(key, 2))
    assert int(state.committed_slots) == c_drained  # fully stalled
    assert not bool(np.asarray(state.prop_alive).any())

    # Revive: pending commands re-broadcast on the retry timers and
    # commits resume (the low crash_rate may fell an odd proposer
    # again; the cluster as a whole must still progress).
    state = dataclasses.replace(
        state, prop_alive=jnp.ones((cfg.num_groups,), bool)
    )
    state, t = fm.run_ticks(cfg, state, t, 40, jax.random.fold_in(key, 3))
    assert int(state.committed_slots) > c_drained
    inv = fm.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_revival_triggers_recovery_election_rebroadcast():
    """High revive_rate: the tick after the proposers are killed, the
    crash/revive process brings (almost surely all of) them back, and
    each revival transition re-broadcasts EVERY pending command of its
    group at once (cmd_last_send stamps to the revival tick, ahead of
    the retry timers) and records a recovery election as a telemetry
    leader change."""
    from frankenpaxos_tpu.tpu.telemetry import COL

    cfg = _crash_cfg(crash_rate=0.001, revive_rate=0.99)
    key = jax.random.PRNGKey(2)
    state, t = fm.run_ticks(cfg, fm.init_state(cfg), jnp.int32(0), 20, key)
    lc0 = int(state.telemetry.totals[COL["leader_changes"]])
    assert int(jnp.sum(state.cmd_status == 1)) > 0

    state = dataclasses.replace(
        state, prop_alive=jnp.zeros((cfg.num_groups,), bool)
    )
    # ONE tick: the revive draw fires per group with p=0.99.
    state, t = fm.run_ticks(cfg, state, t, 1, jax.random.fold_in(key, 1))
    alive = np.asarray(state.prop_alive)
    assert alive.any()  # p(all four stay dead) = 1e-8
    lc1 = int(state.telemetry.totals[COL["leader_changes"]])
    assert lc1 - lc0 == int(alive.sum())  # one election per revival
    # Every pending command of a revived group was re-stamped at the
    # revival tick.
    ls = np.asarray(state.cmd_last_send)
    pending = np.asarray(state.cmd_status) == 1
    mask = pending & alive[:, None]
    assert mask.any()
    assert (ls[mask] == int(t) - 1).all()


def test_crash_plan_randomized_schedules_hold_invariants():
    """The simtest axis the satellite adds: randomized crash/revive
    schedules over the proposer plane keep every invariant and make
    progress (liveness after revival — revive_rate keeps dead windows
    finite)."""
    from frankenpaxos_tpu.harness import simtest
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    spec = simtest.SPECS["fastmultipaxos"]
    assert spec.crash_ok  # the crash axis is enabled for this backend
    plan = FaultPlan(crash_rate=0.05, revive_rate=0.3)
    out = simtest.run_many_seeds(spec, plan, seeds=(0, 1, 2, 3), ticks=80)
    assert out["ok"], out
    assert all(p > 0 for p in out["progress"])  # commits despite crashes
