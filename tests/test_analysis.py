"""L6 analysis-layer tests (pd_util / plot_latency_and_throughput
analogs): rolling throughput and latency math, outlier pruning, counter
rates, plotting, and the one-command benchmark-dir analyzer."""

import os

import numpy as np
import pandas as pd
import pytest

from frankenpaxos_tpu.harness import analysis


def make_recorder_csv(path, n=200, base=1_700_000_000.0, spacing=0.01):
    with open(path, "w") as f:
        f.write("start,stop,latency_nanos,label\n")
        for i in range(n):
            start = base + i * spacing
            latency = 0.002 if i % 50 else 0.050  # periodic slow outlier
            f.write(f"{start},{start + latency},{int(latency * 1e9)},op\n")
    return path


def test_read_and_summarize(tmp_path):
    path = make_recorder_csv(str(tmp_path / "recorder.csv"))
    df = analysis.read_recorder_csvs([path])
    assert len(df) == 200
    s = analysis.summarize(df)
    assert s["count"] == 200
    # 200 ops over ~199 * 10ms ~= 2 seconds -> ~100/s.
    assert 90 <= s["throughput_per_s"] <= 110
    assert s["latency_p50_ms"] == pytest.approx(2.0, abs=0.5)
    assert s["latency_max_ms"] == pytest.approx(50.0, abs=1.0)
    # Dropping the first second halves the count (approximately).
    s2 = analysis.summarize(df, drop_seconds=1.0)
    assert 90 <= s2["count"] <= 110


def test_rolling_throughput_constant_rate(tmp_path):
    path = make_recorder_csv(str(tmp_path / "recorder.csv"))
    df = analysis.read_recorder_csvs([path])
    tp = analysis.rolling_throughput(df["start"], window_ms=1000.0)
    # Steady 100/s arrival: full windows must report ~100.
    assert tp.iloc[-1] == pytest.approx(100.0, rel=0.05)
    # Trimming removed the partial first window.
    assert tp.index[0] >= df.index[0] + pd.Timedelta(seconds=1)


def test_outliers_and_quantiles(tmp_path):
    path = make_recorder_csv(str(tmp_path / "recorder.csv"))
    df = analysis.read_recorder_csvs([path])
    mask = analysis.outliers(df["latency_ms"], 3.0)
    assert int(mask.sum()) == 4  # the periodic 50ms spikes
    qs = analysis.rolling_latency_quantiles(df, window_ms=500.0)
    assert set(qs) == {0.5, 0.9, 0.99}
    assert float(qs[0.5].iloc[-1]) == pytest.approx(2.0, abs=0.5)


def test_counter_rate():
    idx = pd.to_datetime(
        [1_700_000_000.0 + i * 0.25 for i in range(9)], unit="s"
    )
    counter = pd.Series([i * 10.0 for i in range(9)], index=idx)
    r = analysis.rate(counter, window_ms=1000.0)
    # 10 per 0.25s -> 40/s within every full window.
    assert float(r.iloc[-1]) == pytest.approx(40.0, rel=0.01)
    assert np.isnan(r.iloc[0])  # single-point window has no rate


def test_weighted_throughput():
    idx = pd.to_datetime([1_700_000_000.0 + i for i in range(5)], unit="s")
    counts = pd.Series([10.0] * 5, index=idx)
    tp = analysis.weighted_throughput(counts, window_ms=2000.0)
    assert float(tp.iloc[-1]) == pytest.approx(10.0, rel=0.01)


def test_plot_and_analyze_dir(tmp_path):
    bench = tmp_path / "bench"
    bench.mkdir()
    make_recorder_csv(str(bench / "recorder.csv"))
    summary = analysis.analyze_benchmark_dir(str(bench))
    assert summary["count"] == 200
    assert os.path.exists(summary["plot"])
    assert os.path.getsize(summary["plot"]) > 1000  # a real image


def test_suite_results_roundtrip(tmp_path):
    (tmp_path / "results.csv").write_text(
        "input.x,output.throughput_per_s\n1,100.0\n2,180.0\n"
    )
    df = analysis.suite_results(str(tmp_path))
    assert list(df["input.x"]) == [1, 2]


def test_lt_sweep_suite(tmp_path):
    """The sweep driver end-to-end on the fastest protocol: two points,
    real deployments, a results.csv with per-point summaries."""
    from frankenpaxos_tpu.harness.analysis import suite_results
    from frankenpaxos_tpu.harness.lt_sweep import LtSweepSuite

    suite = LtSweepSuite("unreplicated", [1, 2], duration=1.5)
    suite_dir = suite.run_suite(str(tmp_path), "lt_unreplicated")
    df = suite_results(suite_dir.path)
    assert len(df) == 2
    assert (df["output.count"] > 0).all()
    assert (df["output.throughput_per_s"] > 0).all()
