import pytest

from frankenpaxos_tpu.roundsystem import (
    ClassicRoundRobin,
    ClassicStutteredRoundRobin,
    MixedRoundRobin,
    RenamedRoundSystem,
    RotatedClassicRoundRobin,
    RotatedRoundZeroFast,
    RoundType,
    RoundZeroFast,
)

ALL = [
    ClassicRoundRobin(3),
    ClassicStutteredRoundRobin(3, 2),
    ClassicStutteredRoundRobin(3, 3),
    RoundZeroFast(3),
    MixedRoundRobin(3),
    RotatedClassicRoundRobin(3, 1),
    RotatedRoundZeroFast(3, 2),
    RenamedRoundSystem(ClassicRoundRobin(3), {0: 0, 1: 2, 2: 1}),
]


def test_classic_round_robin_table():
    rs = ClassicRoundRobin(3)
    assert [rs.leader(r) for r in range(7)] == [0, 1, 2, 0, 1, 2, 0]
    assert all(rs.round_type(r) == RoundType.CLASSIC for r in range(7))
    assert rs.next_classic_round(1, -1) == 1
    assert rs.next_classic_round(0, 0) == 3
    assert rs.next_classic_round(2, 0) == 2
    assert rs.next_classic_round(2, 2) == 5


def test_stuttered_table():
    rs = ClassicStutteredRoundRobin(3, 2)
    assert [rs.leader(r) for r in range(7)] == [0, 0, 1, 1, 2, 2, 0]
    rs3 = ClassicStutteredRoundRobin(3, 3)
    assert [rs3.leader(r) for r in range(7)] == [0, 0, 0, 1, 1, 1, 2]
    assert rs.next_classic_round(0, -5) == 0
    assert rs.next_classic_round(1, -5) == 2
    assert rs.next_classic_round(0, 0) == 1  # still own next round
    assert rs.next_classic_round(0, 1) == 6
    assert rs.next_classic_round(2, 1) == 4


def test_round_zero_fast_table():
    rs = RoundZeroFast(3)
    assert [rs.leader(r) for r in range(7)] == [0, 0, 1, 2, 0, 1, 2]
    assert rs.round_type(0) == RoundType.FAST
    assert rs.round_type(1) == RoundType.CLASSIC
    assert rs.next_fast_round(0, -1) == 0
    assert rs.next_fast_round(0, 0) is None
    assert rs.next_fast_round(1, -1) is None


def test_mixed_round_robin_table():
    rs = MixedRoundRobin(3)
    assert [rs.leader(r) for r in range(10)] == [0, 0, 1, 1, 2, 2, 0, 0, 1, 1]
    assert [rs.round_type(r) for r in range(4)] == [
        RoundType.FAST,
        RoundType.CLASSIC,
        RoundType.FAST,
        RoundType.CLASSIC,
    ]
    assert rs.next_fast_round(0, -1) == 0
    assert rs.next_classic_round(0, 0) == 1
    assert rs.next_classic_round(1, 0) == 3


def test_rotated_tables():
    rs = RotatedClassicRoundRobin(3, 1)
    assert [rs.leader(r) for r in range(7)] == [1, 2, 0, 1, 2, 0, 1]
    rs2 = RotatedClassicRoundRobin(3, 2)
    assert [rs2.leader(r) for r in range(7)] == [2, 0, 1, 2, 0, 1, 2]
    rz = RotatedRoundZeroFast(3, 1)
    assert [rz.leader(r) for r in range(7)] == [1, 1, 2, 0, 1, 2, 0]
    assert rz.round_type(0) == RoundType.FAST


@pytest.mark.parametrize("rs", ALL, ids=repr)
def test_next_classic_round_properties(rs):
    """next_classic_round(l, r) is the smallest classic round of l > r."""
    for leader in range(rs.num_leaders()):
        for r in range(-2, 30):
            nxt = rs.next_classic_round(leader, r)
            assert nxt > r or r < 0
            assert rs.leader(nxt) == leader
            assert rs.round_type(nxt) == RoundType.CLASSIC
            lo = 0 if r < 0 else r + 1
            for between in range(lo, nxt):
                assert not (
                    rs.leader(between) == leader
                    and rs.round_type(between) == RoundType.CLASSIC
                ), f"{rs!r}: {between} is an earlier classic round of {leader}"


@pytest.mark.parametrize("rs", ALL, ids=repr)
def test_next_fast_round_properties(rs):
    for leader in range(rs.num_leaders()):
        for r in range(-2, 20):
            nxt = rs.next_fast_round(leader, r)
            if nxt is None:
                continue
            assert nxt > r or r < 0
            assert rs.leader(nxt) == leader
            assert rs.round_type(nxt) == RoundType.FAST
