"""Tests of the batched CASPaxos backend (caspaxos_batched.py): the
register chain-inclusion safety property under leader contention with
nack/backoff dances, cross-validated against the per-actor protocol
(protocols/caspaxos.py; caspaxos/Leader.scala state machine)."""

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu import caspaxos_batched as cpb


def run_random(cfg, seed, ticks):
    key = jax.random.PRNGKey(seed)
    state, t = cpb.run_ticks(
        cfg, cpb.init_state(cfg), jnp.int32(0), ticks, key
    )
    return state, t


def test_progress_and_chain_safety_under_contention():
    cfg = cpb.BatchedCasPaxosConfig(
        f=1, num_registers=16, num_leaders=2, op_rate=0.3,
        lat_min=1, lat_max=3, backoff_min=2, backoff_max=8,
    )
    state, t = run_random(cfg, seed=0, ticks=400)
    inv = cpb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    s = cpb.stats(cfg, state, t)
    assert s["commits"] > 16 * 3
    assert s["bits_chosen"] > 0
    # Two leaders per register MUST collide sometimes: the nack/backoff
    # dance (WaitingToRecover) is exercised.
    assert s["nacks"] > 0 and s["backoffs"] > 0
    assert s["chain_violations"] == 0


def test_single_leader_no_contention():
    cfg = cpb.BatchedCasPaxosConfig(
        f=1, num_registers=8, num_leaders=1, op_rate=0.5,
        lat_min=1, lat_max=2,
    )
    state, t = run_random(cfg, seed=1, ticks=300)
    s = cpb.stats(cfg, state, t)
    inv = cpb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    # One leader never nacks itself.
    assert s["nacks"] == 0 and s["backoffs"] == 0
    assert s["commits"] > 0
    # Everything issued long enough ago is chosen: the register is the
    # union of issued bits (set union change function).
    done_frac = s["bits_chosen"] / max(1, s["bits_issued"])
    assert done_frac > 0.7


def test_register_is_union_of_issued_bits_when_quiescent():
    """Run with a finite op burst, then let the system quiesce: the final
    register must be EXACTLY the union of every issued bit — no lost
    updates, no invented ones (the CASPaxos linearizable-union result
    the per-actor test_caspaxos_sequential_unions asserts)."""
    cfg = cpb.BatchedCasPaxosConfig(
        f=1, num_registers=8, num_leaders=2, op_rate=0.4,
        lat_min=1, lat_max=3, backoff_min=2, backoff_max=6,
    )
    key = jax.random.PRNGKey(5)
    state, t = cpb.run_ticks(
        cfg, cpb.init_state(cfg), jnp.int32(0), 150, key
    )
    # Quiesce: no new ops, let every pending bit commit.
    quiet = cpb.BatchedCasPaxosConfig(
        **{**cfg.__dict__, "op_rate": 0.0}
    )
    state, t = cpb.run_ticks(quiet, state, t, 150, jax.random.fold_in(key, 1))
    inv = cpb.check_invariants(quiet, state, t)
    assert all(bool(v) for v in inv.values()), inv
    issued = np.asarray(state.bit_issue) < int(cpb.INF)  # [G, NBITS]
    reg = np.asarray(state.last_chosen)  # [G] uint32
    bitmat = (reg[:, None] >> np.arange(32)[None, :].astype(np.uint32)) & 1
    assert np.array_equal(bitmat.astype(bool), issued), (
        "register != union of issued bits"
    )
    pend = np.asarray(state.l_pending)
    assert not pend.any(), "pending bits survived quiescence"


def test_cross_validation_caspaxos_union():
    """Aligned scenario against the per-actor protocol: clients propose
    singleton sets through contending leaders; after the dust settles
    BOTH executions hold the union of all proposals, chosen values
    having formed an inclusion chain throughout."""
    from test_caspaxos import drain, make

    t, config, leaders, acceptors, clients = make(f=1, num_clients=2)
    p1 = clients[0].propose(frozenset({1}))
    drain(t)
    p2 = clients[1].propose(frozenset({2}))
    drain(t)
    p3 = clients[0].propose(frozenset({3}))
    drain(t)
    assert p1.done and p2.done and p3.done
    final = p3.result()
    assert final == frozenset({1, 2, 3})
    # Acceptor vote values chain: the highest-round vote contains all.
    votes = sorted(
        ((a.vote_round, a.vote_value) for a in acceptors if a.vote_value),
        key=lambda rv: rv[0],
    )
    for (_, lo), (_, hi) in zip(votes, votes[1:]):
        assert lo.issubset(hi)

    # Batched: sequential single-leader ops on one register; the final
    # register equals the union and the chain counter is clean — the
    # same linearizable-union outcome.
    cfg = cpb.BatchedCasPaxosConfig(
        f=1, num_registers=1, num_leaders=1, op_rate=0.0,
        lat_min=1, lat_max=1,
    )
    state = cpb.init_state(cfg)
    key = jax.random.PRNGKey(0)
    tt = 0
    import dataclasses as dc

    for bit in (1, 2, 3):
        state = dc.replace(
            state,
            l_pending=state.l_pending | jnp.uint32(1 << bit),
            bit_issue=state.bit_issue.at[0, bit].set(tt),
        )
        for _ in range(12):
            state = cpb.tick(
                cfg, state, jnp.int32(tt), jax.random.fold_in(key, tt)
            )
            tt += 1
    assert int(state.last_chosen[0]) == (1 << 1) | (1 << 2) | (1 << 3)
    inv = cpb.check_invariants(cfg, state, jnp.int32(tt))
    assert all(bool(v) for v in inv.values()), inv


def test_wide_latency_out_of_order_commits():
    """lat_max >> lat_min: a slow quorum can complete a LOWER round after
    a higher round already advanced the register. The register must not
    regress and the chain counter must not false-alarm (the late value
    is contained in the newer one by quorum intersection)."""
    cfg = cpb.BatchedCasPaxosConfig(
        f=1, num_registers=24, num_leaders=3, op_rate=0.5,
        lat_min=1, lat_max=10, backoff_min=1, backoff_max=4,
    )
    key = jax.random.PRNGKey(9)
    state, t = cpb.run_ticks(cfg, cpb.init_state(cfg), jnp.int32(0), 600, key)
    inv = cpb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    s = cpb.stats(cfg, state, t)
    assert s["chain_violations"] == 0
    assert s["nacks"] > 0 and s["commits"] > 0
    # Quiesce and require exact union (no bit lost to a register
    # regression).
    quiet = cpb.BatchedCasPaxosConfig(**{**cfg.__dict__, "op_rate": 0.0})
    state, t = cpb.run_ticks(quiet, state, t, 400, jax.random.fold_in(key, 1))
    issued = np.asarray(state.bit_issue) < int(cpb.INF)
    reg = np.asarray(state.last_chosen)
    bitmat = (reg[:, None] >> np.arange(32)[None, :].astype(np.uint32)) & 1
    assert np.array_equal(bitmat.astype(bool), issued)
    inv = cpb.check_invariants(quiet, state, t)
    assert all(bool(v) for v in inv.values()), inv
