"""Kernel-suite tests (interpret mode on CPU): every fused Pallas
kernel must match its pure-jnp reference twin bit for bit on random
dtype-policy states, and the vote/quorum reference must match the live
tick's vote phase."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops import (
    INF,
    INF16,
    fused_craq_chain,
    fused_fmp_vote,
    fused_horizontal_vote,
    fused_mencius_vote,
    fused_mp_dispatch,
    fused_p1_promise,
    fused_scalog_cut_commit,
    fused_tick,
    fused_vote_quorum,
    reference_craq_chain,
    reference_fmp_vote,
    reference_fused_tick,
    reference_horizontal_vote,
    reference_mencius_vote,
    reference_mp_dispatch,
    reference_p1_promise,
    reference_scalog_cut_commit,
    reference_vote_quorum,
)

I16 = jnp.int16
I8 = jnp.int8


def _assert_trees_equal(ref, got, names=None):
    ref = jax.tree_util.tree_leaves(ref)
    got = jax.tree_util.tree_leaves(got)
    assert len(ref) == len(got)
    names = names or [str(i) for i in range(len(ref))]
    for name, r, g in zip(names, ref, got):
        r, g = np.asarray(r), np.asarray(g)
        assert r.dtype == g.dtype, f"{name}: {r.dtype} != {g.dtype}"
        np.testing.assert_array_equal(r, g, err_msg=name)


def _clock(key, shape, p=0.3):
    """Random offset clock: INF16 = never, else an offset in [-1, 5)."""
    ks = jax.random.split(key, 2)
    return jnp.where(
        jax.random.uniform(ks[0], shape) < p,
        jax.random.randint(ks[1], shape, -1, 5),
        INF16,
    ).astype(I16)


def vote_quorum_args(key, A=3, G=8, W=16):
    ks = jax.random.split(key, 10)
    p2a = _clock(ks[0], (A, G, W))
    acc_round = jax.random.randint(ks[1], (A, G), 0, 3).astype(I16)
    leader_round = jax.random.randint(ks[2], (G,), 0, 3).astype(I16)
    slot_value = jax.random.randint(ks[3], (G, W), 0, 1000)
    vote_round = jax.random.randint(ks[4], (A, G, W), -1, 3).astype(I16)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(ks[5], (A, G, W), 0, 1000), -1
    )
    p2b = jnp.where(vote_round >= 0, _clock(ks[6], (A, G, W), p=0.7), INF16)
    lat = jax.random.randint(ks[7], (A, G, W), 1, 4).astype(I16)
    delivered = jax.random.uniform(ks[8], (A, G, W)) < 0.9
    head = jax.random.randint(ks[9], (G,), 0, 100)
    return (
        p2a, acc_round, leader_round, slot_value,
        vote_round, vote_value, p2b, lat, delivered, head,
    )


VOTE_QUORUM_OUTS = [
    "vote_round", "vote_value", "p2b", "acc_round", "nvotes", "nsends",
    "max_ord",
]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 4, 32)])
def test_fused_vote_quorum_matches_reference(seed, shape):
    A, G, W = shape
    args = vote_quorum_args(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    ref = reference_vote_quorum(*args)
    got = fused_vote_quorum(*args, block=max(G // 2, 1), interpret=True)
    _assert_trees_equal(ref, got, VOTE_QUORUM_OUTS)


def p1_promise_args(key, A=3, G=8, W=16):
    ks = jax.random.split(key, 12)
    status = jax.random.randint(ks[0], (G, W), 0, 3).astype(I8)
    vote_round = jax.random.randint(ks[1], (A, G, W), -1, 3).astype(I16)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(ks[2], (A, G, W), 0, 1000), -1
    )
    slot_value = jax.random.randint(ks[3], (G, W), 0, 1000)
    p2a = _clock(ks[4], (A, G, W))
    p2b = _clock(ks[5], (A, G, W))
    last_send = jax.random.randint(ks[6], (G, W), 0, 50)
    mask = jax.random.uniform(ks[7], (G,)) < 0.6
    learned = jax.random.uniform(ks[8], (A, G)) < 0.7
    lat = jax.random.randint(ks[9], (A, G, W), 1, 4).astype(I16)
    return (
        status, vote_round, vote_value, slot_value, p2a, p2b,
        last_send, mask, learned, lat, jnp.int32(33),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 6, 32)])
def test_fused_p1_promise_matches_reference(seed, shape):
    A, G, W = shape
    args = p1_promise_args(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    ref = reference_p1_promise(*args)
    got = fused_p1_promise(*args, block=max(G // 2, 1), interpret=True)
    _assert_trees_equal(
        ref, got, ["slot_value", "p2a", "p2b", "last_send"]
    )


def mp_dispatch_args(key, A=3, G=8, W=16):
    ks = jax.random.split(key, 20)
    status = jax.random.randint(ks[0], (G, W), 0, 3).astype(I8)
    slot_value = jnp.where(
        status > 0, jax.random.randint(ks[1], (G, W), 0, 1000), -1
    )
    propose_tick = jnp.where(
        status > 0, jax.random.randint(ks[2], (G, W), 0, 30), INF
    )
    last_send = jnp.where(
        status > 0, jax.random.randint(ks[3], (G, W), 0, 33), INF
    )
    chosen_tick = jnp.where(
        status == 2, jax.random.randint(ks[4], (G, W), 0, 33), INF
    )
    chosen_round = jnp.where(
        status == 2, jax.random.randint(ks[5], (G, W), 0, 3), -1
    ).astype(I16)
    chosen_value = jnp.where(status == 2, slot_value, -1)
    replica_arrival = jnp.where(
        status == 2, jax.random.randint(ks[6], (G, W), 30, 40), INF
    )
    p2a = _clock(ks[7], (A, G, W))
    p2b = _clock(ks[8], (A, G, W))
    vote_round = jax.random.randint(ks[9], (A, G, W), -1, 3).astype(I16)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(ks[10], (A, G, W), 0, 1000), -1
    )
    nvotes = jax.random.randint(ks[11], (G, W), 0, A + 1)
    head = jax.random.randint(ks[12], (G,), 0, 100)
    next_slot = head + jax.random.randint(ks[13], (G,), 0, W + 1)
    leader_round = jax.random.randint(ks[14], (G,), 0, 3).astype(I16)
    cap = jax.random.randint(ks[15], (G,), 0, 5)
    retry_ok = jax.random.uniform(ks[16], (G,)) < 0.8
    send_ok = jax.random.uniform(ks[17], (A, G, W)) < 0.6
    retry_deliv = jax.random.uniform(ks[18], (A, G, W)) < 0.9
    kl = jax.random.split(ks[19], 3)
    p2a_lat = jax.random.randint(kl[0], (A, G, W), 1, 4).astype(I16)
    retry_lat = jax.random.randint(kl[1], (A, G, W), 1, 4).astype(I16)
    rep_lat = jax.random.randint(kl[2], (G, W), 1, 4)
    return (
        status, slot_value, propose_tick, last_send,
        chosen_tick, chosen_round, chosen_value, replica_arrival,
        p2a, p2b, vote_round, vote_value,
        nvotes, head, next_slot, leader_round, cap, retry_ok,
        send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat,
        jnp.arange(G, dtype=jnp.int32), jnp.int32(33),
    )


MP_DISPATCH_OUTS = [
    "status", "slot_value", "propose_tick", "last_send",
    "chosen_tick", "chosen_round", "chosen_value", "replica_arrival",
    "p2a", "p2b", "vote_round", "vote_value",
    "head", "next_slot", "count", "n_retire",
    "newly_chosen", "retire_mask", "is_new", "timed_out", "latency",
]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 6, 32)])
def test_fused_mp_dispatch_matches_reference(seed, shape):
    A, G, W = shape
    args = mp_dispatch_args(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    statics = dict(f=1, retry_timeout=8, num_groups=G)
    ref = reference_mp_dispatch(*args, **statics)
    got = fused_mp_dispatch(
        *args, block=max(G // 2, 1), interpret=True, **statics
    )
    _assert_trees_equal(ref, got, MP_DISPATCH_OUTS)


def fused_tick_args(key, A=3, G=8, W=16, aged=True):
    """Megakernel inputs = the vote-plane args + the dispatch-only args
    (same distributions as the per-plane helpers). ``aged=False`` draws
    clocks one tick earlier so the in-kernel aging path has arrivals to
    consume."""
    kv, kd = jax.random.split(key)
    (p2a, acc_round, leader_round, slot_value, vote_round, vote_value,
     p2b, p2b_lat, delivered, _head) = vote_quorum_args(kv, A=A, G=G, W=W)
    if not aged:
        # Pre-aged clocks: +1 so that one in-kernel aging step lands the
        # same arrivals (0 stays "arrives now" after the kernel's age).
        p2a = jnp.where(p2a == INF16, INF16, p2a + 1).astype(p2a.dtype)
        p2b = jnp.where(p2b == INF16, INF16, p2b + 1).astype(p2b.dtype)
    d = mp_dispatch_args(kd, A=A, G=G, W=W)
    (status, d_slot_value, propose_tick, last_send, chosen_tick,
     chosen_round, chosen_value, replica_arrival, _p2a, _p2b, _vr, _vv,
     _nvotes, head, next_slot, d_leader_round, cap, retry_ok,
     send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t) = d
    del d_slot_value, d_leader_round
    return (
        p2a, acc_round, leader_round, slot_value, vote_round, vote_value,
        p2b, p2b_lat, delivered, head,
        status, propose_tick, last_send, chosen_tick, chosen_round,
        chosen_value, replica_arrival, next_slot, cap, retry_ok,
        send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t,
    )


FUSED_TICK_OUTS = MP_DISPATCH_OUTS + ["acc_round", "nsends", "max_ord"]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("age", [True, False])
# Padding edges: G not a multiple of the block, odd A, W untouched by
# blocking (the grid tiles G only).
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 7, 32)])
def test_fused_tick_matches_reference(seed, age, shape):
    """The megakernel vs its composition reference (aging + vote/quorum
    + dispatch), both aging modes, padding-edge shapes."""
    A, G, W = shape
    args = fused_tick_args(
        jax.random.PRNGKey(seed), A=A, G=G, W=W, aged=not age
    )
    statics = dict(f=1, retry_timeout=8, num_groups=G, age=age)
    ref = reference_fused_tick(*args, **statics)
    got = fused_tick(*args, block=max(G // 2, 1), interpret=True, **statics)
    _assert_trees_equal(ref, got, FUSED_TICK_OUTS)


def test_fused_tick_composition_equals_planes():
    """reference_fused_tick(age=True) IS age_clock + vote plane +
    dispatch plane: the megakernel's reference twin reproduces the exact
    multi-plane program, so kernel-vs-reference bit-identity doubles as
    megakernel-vs-multi-plane bit-identity."""
    from frankenpaxos_tpu.tpu.common import age_clock

    A, G, W = 3, 6, 16
    args = fused_tick_args(jax.random.PRNGKey(9), A=A, G=G, W=W, aged=False)
    (p2a, acc_round, leader_round, slot_value, vote_round, vote_value,
     p2b, p2b_lat, delivered, head,
     status, propose_tick, last_send, chosen_tick, chosen_round,
     chosen_value, replica_arrival, next_slot, cap, retry_ok,
     send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t) = args
    fused = reference_fused_tick(
        *args, f=1, retry_timeout=8, num_groups=G, age=True
    )
    p2a_aged, p2b_aged = age_clock(p2a), age_clock(p2b)
    vr, vv, p2b2, accr, nvotes, nsends, max_ord = reference_vote_quorum(
        p2a_aged, acc_round, leader_round, slot_value, vote_round,
        vote_value, p2b_aged, p2b_lat, delivered, head,
    )
    planes = reference_mp_dispatch(
        status, slot_value, propose_tick, last_send, chosen_tick,
        chosen_round, chosen_value, replica_arrival, p2a_aged, p2b2,
        vr, vv, nvotes, head, next_slot, leader_round, cap, retry_ok,
        send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t,
        f=1, retry_timeout=8, num_groups=G,
    )
    _assert_trees_equal(
        (*planes, accr, nsends, max_ord), fused, FUSED_TICK_OUTS
    )


def fmp_vote_args(key, A=3, G=8, W=16, t=20):
    ks = jax.random.split(key, 14)
    vote_value = jnp.where(
        jax.random.uniform(ks[0], (A, G, W)) < 0.6,
        jax.random.randint(ks[1], (A, G, W), 0, 6),  # few values: conflicts
        -1,
    )
    vote_seen = jnp.where(
        vote_value >= 0, jax.random.randint(ks[2], (A, G, W), 0, t + 4), INF
    )
    status = jax.random.randint(ks[3], (G, W), 0, 3).astype(I8)
    open_tick = jnp.where(
        status > 0, jax.random.randint(ks[4], (G, W), 0, t), INF
    )
    fast_committed = jnp.where(
        jax.random.uniform(ks[5], (G, W)) < 0.2,
        jax.random.randint(ks[6], (G, W), 0, 6),
        -1,
    )
    rv_value = jnp.where(
        status == 1, jax.random.randint(ks[7], (G, W), 0, 6), -1
    )
    rv_p2a = jnp.where(
        (status == 1)[None] & (jax.random.uniform(ks[8], (A, G, W)) < 0.5),
        jax.random.randint(ks[9], (A, G, W), t - 1, t + 3),
        INF,
    )
    rv_voted = (status == 1)[None] & (
        jax.random.uniform(ks[10], (A, G, W)) < 0.4
    )
    rv_p2b = jnp.where(
        rv_voted, jax.random.randint(ks[11], (A, G, W), t - 2, t + 3), INF
    )
    chosen_value = jnp.where(status == 2, 1, -1)
    replica_arrival = jnp.where(
        status == 2, jax.random.randint(ks[12], (G, W), t, t + 5), INF
    )
    kl = jax.random.split(ks[13], 2)
    rv_lat = jax.random.randint(kl[0], (G, W), 1, 4)
    reply_lat = jax.random.randint(kl[1], (G, W), 1, 4)
    return (
        vote_value, vote_seen, status, open_tick, fast_committed,
        rv_value, rv_p2a, rv_p2b, rv_voted, chosen_value,
        replica_arrival, rv_lat, reply_lat, jnp.int32(t),
    )


FMP_VOTE_OUTS = [
    "status", "open_tick", "fast_committed", "rv_value",
    "rv_p2a", "rv_p2b", "rv_voted", "chosen_value", "replica_arrival",
    "newly_chosen", "fast_ok", "start_rec", "safety",
]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 7, 32)])
def test_fused_fmp_vote_matches_reference(seed, shape):
    A, G, W = shape
    args = fmp_vote_args(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    statics = dict(fq=2 if A == 3 else 4, f=(A - 1) // 2,
                   recovery_timeout=8)
    ref = reference_fmp_vote(*args, **statics)
    got = fused_fmp_vote(
        *args, block=max(G // 2, 1), interpret=True, **statics
    )
    _assert_trees_equal(ref, got, FMP_VOTE_OUTS)


def horizontal_vote_args(key, P=6, G=8, W=16, t=20):
    ks = jax.random.split(key, 10)
    status = jax.random.randint(ks[0], (G, W), 0, 3).astype(I8)
    slot_epoch = jnp.where(
        status > 0, jax.random.randint(ks[1], (G, W), 0, 4), -1
    ).astype(I16)
    propose_tick = jnp.where(
        status > 0, jax.random.randint(ks[2], (G, W), 0, t), INF
    )
    p2a = jnp.where(
        (status == 1)[None] & (jax.random.uniform(ks[3], (P, G, W)) < 0.5),
        jax.random.randint(ks[4], (P, G, W), t - 1, t + 3),
        INF,
    )
    voted = (status > 0)[None] & (
        jax.random.uniform(ks[5], (P, G, W)) < 0.4
    )
    vote_epoch = jnp.where(voted, slot_epoch[None], -1).astype(I16)
    p2b = jnp.where(
        voted, jax.random.randint(ks[6], (P, G, W), t - 2, t + 3), INF
    )
    p2b_lat = jax.random.randint(ks[7], (P, G, W), 1, 4)
    delivered = jax.random.uniform(ks[8], (P, G, W)) < 0.9
    return (
        slot_epoch, status, propose_tick, p2a, p2b, voted, vote_epoch,
        p2b_lat, delivered, jnp.int32(t),
    )


HORIZONTAL_VOTE_OUTS = [
    "status", "p2a", "p2b", "voted", "vote_epoch",
    "newly_chosen", "lat", "viol",
]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("dims", [(6, 8, 16), (6, 7, 32)])
def test_fused_horizontal_vote_matches_reference(seed, dims):
    P, G, W = dims
    args = horizontal_vote_args(jax.random.PRNGKey(seed), P=P, G=G, W=W)
    statics = dict(n=P // 2, quorum=P // 4 + 1)
    ref = reference_horizontal_vote(*args, **statics)
    got = fused_horizontal_vote(
        *args, block=max(G // 2, 1), interpret=True, **statics
    )
    _assert_trees_equal(ref, got, HORIZONTAL_VOTE_OUTS)


def scalog_args(key, P=8, S=16, t=30):
    ks = jax.random.split(key, 6)
    committed_cuts = jnp.int32(5)
    live_n = int(jax.random.randint(ks[0], (), 0, P + 1))
    next_cut = committed_cuts + live_n
    # Monotone live cut vectors (cuts dominate their predecessors).
    grow = jax.random.randint(ks[1], (P, S), 0, 5)
    base = jax.random.randint(ks[2], (S,), 0, 20)
    # Issue-order rows mapped back onto ring slots.
    ids = committed_cuts + jnp.arange(P)
    vec_asc = base[None, :] + jnp.cumsum(grow, axis=0)
    cut_vec = jnp.zeros((P, S), jnp.int32).at[ids % P].set(vec_asc)
    cut_commit_tick = jnp.full((P,), INF, jnp.int32).at[ids % P].set(
        jnp.where(
            jnp.arange(P) < live_n,
            jax.random.randint(ks[3], (P,), t - 3, t + 4),
            INF,
        )
    )
    cut_snap_tick = jnp.full((P,), INF, jnp.int32).at[ids % P].set(
        jnp.where(
            jnp.arange(P) < live_n,
            jax.random.randint(ks[4], (P,), t - 10, t - 3),
            INF,
        )
    )
    cut_prev_snap = jnp.maximum(cut_snap_tick - 2, 0)
    last_committed = base
    return (
        cut_vec, cut_commit_tick, cut_snap_tick, cut_prev_snap,
        last_committed, committed_cuts, next_cut, jnp.int32(t),
    )


SCALOG_OUTS = [
    "new_cut", "committed_now", "recs", "lag", "slot_committed",
    "commit_tick", "snap_tick",
]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("dims", [(8, 16), (4, 23)])
def test_fused_scalog_cut_commit_matches_reference(seed, dims):
    P, S = dims
    args = scalog_args(jax.random.PRNGKey(seed), P=P, S=S)
    ref = reference_scalog_cut_commit(*args)
    got = fused_scalog_cut_commit(
        *args, block=max(S // 2, 1), interpret=True
    )
    _assert_trees_equal(ref, got, SCALOG_OUTS)


def mencius_args(key, L=8, W=16, A=3, t=9):
    ks = jax.random.split(key, 6)
    p2a = jnp.where(
        jax.random.uniform(ks[0], (L, W, A)) < 0.3,
        jax.random.randint(ks[1], (L, W, A), t - 2, t + 3),
        INF,
    )
    voted = jax.random.uniform(ks[2], (L, W, A)) < 0.3
    p2b = jnp.where(
        voted, jax.random.randint(ks[3], (L, W, A), t - 3, t + 4), INF
    )
    lat = jax.random.randint(ks[4], (L, W, A), 1, 4)
    delivered = jax.random.uniform(ks[5], (L, W, A)) < 0.9
    return p2a, voted, p2b, lat, delivered, jnp.int32(t)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(8, 16, 3), (6, 32, 5)])
def test_fused_mencius_vote_matches_reference(seed, shape):
    L, W, A = shape
    args = mencius_args(jax.random.PRNGKey(seed), L=L, W=W, A=A)
    ref = reference_mencius_vote(*args)
    got = fused_mencius_vote(*args, block=max(L // 2, 1), interpret=True)
    _assert_trees_equal(ref, got, ["voted", "p2b", "nvotes"])


def craq_args(key, N=8, L=3, KV=4, W=8, t=9):
    tail = L - 1
    ks = jax.random.split(key, 8)
    w_status = jax.random.randint(ks[0], (N, W), 0, 3).astype(I8)
    w_key = jax.random.randint(ks[1], (N, W), 0, KV)
    w_version = jax.random.randint(ks[2], (N, W), 0, 50)
    w_node = jnp.where(
        w_status == 2,  # UP acks live on nodes [0, tail)
        jax.random.randint(ks[3], (N, W), 0, max(tail, 1)),
        jax.random.randint(ks[3], (N, W), 0, tail + 1),
    )
    w_arrival = jnp.where(
        w_status > 0, jax.random.randint(ks[4], (N, W), t - 1, t + 3), INF
    )
    w_issue = jax.random.randint(ks[5], (N, W), 0, t)
    dirty = jax.random.randint(ks[6], (N, L * KV), 0, 3)
    version = jax.random.randint(ks[7], (N, L * KV), -1, 40)
    hop_lat = jax.random.randint(jax.random.fold_in(key, 9), (N, W), 1, 4)
    return (
        w_status, w_key, w_version, w_node, w_arrival, w_issue,
        dirty, version, hop_lat, jnp.int32(t),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("dims", [(8, 3, 4, 8), (6, 4, 3, 16)])
def test_fused_craq_chain_matches_reference(seed, dims):
    N, L, KV, W = dims
    args = craq_args(jax.random.PRNGKey(seed), N=N, L=L, KV=KV, W=W)
    statics = dict(tail=L - 1, num_keys=KV)
    ref = reference_craq_chain(*args, **statics)
    got = fused_craq_chain(
        *args, block=max(N // 2, 1), interpret=True, **statics
    )
    _assert_trees_equal(
        ref, got,
        ["w_status", "w_node", "w_arrival", "dirty", "version",
         "at_tail", "wlat"],
    )


def test_reference_matches_tick_phase():
    """The vote/quorum spec equals the tick's own vote phase, replicating
    the tick's bit-derived latency and drop samples AND its clock aging
    (offsets age once at tick start, so the spec sees aged clocks)."""
    from frankenpaxos_tpu.tpu.common import age_clock, bit_delivered, bit_latency
    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        CHOSEN,
        PROPOSED,
        BatchedMultiPaxosConfig,
        init_state,
        tick,
    )

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=8, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=0.2, thrifty=False,
    )
    key = jax.random.PRNGKey(2)
    state = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    # Recompute the tick's own per-message samples for t=1 (same key
    # derivation as multipaxos_batched.tick steps 0-1). Split into FIVE
    # like tick does: threefry split derives key i from counters
    # (i, num+i), so split(key, 3)[0] != split(key, 5)[0].
    tkey = jax.random.fold_in(key, 1)
    k3, k2, k_extra, k_read, k_fail = jax.random.split(tkey, 5)
    G, W, A = cfg.num_groups, cfg.window, cfg.group_size
    bits3 = jax.random.bits(k3, (A, G, W))
    p2b_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max).astype(
        state.p2b_arrival.dtype
    )
    p2b_delivered = bit_delivered(bits3, 24, cfg.drop_rate)

    vr, vv, p2b, accr, nvotes, nsends, max_ord = reference_vote_quorum(
        age_clock(state.p2a_arrival),
        state.acc_round,
        state.leader_round,
        state.slot_value,
        state.vote_round,
        state.vote_value,
        age_clock(state.p2b_arrival),
        p2b_lat,
        p2b_delivered,
        state.head,
    )
    after = tick(cfg, state, jnp.int32(1), tkey)
    np.testing.assert_array_equal(np.asarray(vr), np.asarray(after.vote_round))
    np.testing.assert_array_equal(np.asarray(vv), np.asarray(after.vote_value))
    np.testing.assert_array_equal(
        np.asarray(p2b), np.asarray(after.p2b_arrival)
    )
    np.testing.assert_array_equal(
        np.asarray(accr), np.asarray(after.acc_round)
    )
    # nvotes drives chosen-ness: slots the spec counts to quorum are
    # exactly the slots the tick marked CHOSEN this tick (no prior
    # chosen at t=1; status is PROPOSED or CHOSEN only).
    chosen = np.asarray(after.status) == CHOSEN
    proposed_before = np.asarray(state.status) == PROPOSED
    expect_chosen = proposed_before & (np.asarray(nvotes) >= cfg.f + 1)
    np.testing.assert_array_equal(expect_chosen, chosen)


@pytest.mark.parametrize("drop", [0.0, 0.2])
def test_tick_with_use_pallas_is_bit_identical(drop):
    """The whole simulation with the hot planes routed through the fused
    kernels (interpret mode on CPU via the legacy use_pallas knob, which
    folds into KernelPolicy(mode='on')) equals the reference path bit
    for bit — state arrays, stats, and invariants."""
    import dataclasses as dc

    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        BatchedMultiPaxosConfig,
        check_invariants,
        init_state,
        run_ticks,
    )

    # num_groups NOT divisible by pallas_block_g exercises the padding.
    base = dict(
        f=1, num_groups=3, window=8, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=drop, retry_timeout=6,
        pallas_block_g=2,
    )
    key = jax.random.PRNGKey(5)
    t0 = jnp.zeros((), jnp.int32)
    cfg_x = BatchedMultiPaxosConfig(**base, use_pallas=False)
    cfg_p = BatchedMultiPaxosConfig(**base, use_pallas=True)
    sx, tx = run_ticks(cfg_x, init_state(cfg_x), t0, 40, key)
    sp, tp = run_ticks(cfg_p, init_state(cfg_p), t0, 40, key)
    assert int(sx.committed) > 0
    for field in dc.fields(sx):
        # Nested pytree fields (the Telemetry ring) compare leaf-wise;
        # the per-tick counters must also match across kernel paths.
        la = jax.tree_util.tree_leaves(getattr(sx, field.name))
        lb = jax.tree_util.tree_leaves(getattr(sp, field.name))
        assert len(la) == len(lb), field.name
        for a, b in zip(la, lb):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=field.name
            )
    inv = check_invariants(cfg_p, sp, tp)
    assert all(bool(v) for v in inv.values()), inv


# ---------------------------------------------------------------------------
# Dependency-graph execution plane (ops/depgraph.py)
# ---------------------------------------------------------------------------

from frankenpaxos_tpu.ops import depgraph as dg  # noqa: E402


def depgraph_args(key, B=5, V=24, density=0.12):
    """Random windowed dependency graphs: a sparse digraph packed to
    words, a forced directed CYCLE through the first six vertices (so
    the SCC condensation always has multi-vertex components to
    collapse), GARBAGE in the packed padding lanes above V (the
    padding-edge contract: tail bits must never leak into results),
    and random committed/active masks."""
    ks = jax.random.split(key, 6)
    ids = jnp.arange(V)
    bits = jax.random.uniform(ks[0], (B, V, V)) < density
    ring = (ids[None, :] == (ids[:, None] + 1) % 6) & (ids[:, None] < 6)
    adj = dg.pack_mask(bits | ring[None])
    valid = dg.pack_mask(jnp.ones((V,), bool))  # low-V-bits words
    junk = (
        jax.random.randint(ks[1], adj.shape, 0, 1 << 16).astype(jnp.uint32)
        << 16
    ) | jax.random.randint(ks[2], adj.shape, 0, 1 << 16).astype(jnp.uint32)
    adj = adj | (junk & ~valid)
    committed = jax.random.uniform(ks[3], (B, V)) < 0.45
    active = jax.random.uniform(ks[4], (B, V)) < 0.8
    return adj, committed, active


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(5, 24), (3, 40), (4, 64)])
def test_depgraph_reference_matches_tarjan_oracle(seed, shape):
    """The batched bitmask closure equals the sequential iterative-
    Tarjan pointer walk (TarjanDependencyGraph.scala's control flow)
    graph for graph — eligibility, execution rank, and SCC roots —
    on random cyclic windowed graphs with garbage padding bits."""
    B, V = shape
    adj, committed, active = depgraph_args(jax.random.PRNGKey(seed), B, V)
    elig, order, root = dg.reference_depgraph_execute(
        adj, committed, active
    )
    for b in range(B):
        oe, oo, orr = dg.oracle_execute(adj[b], committed[b], active[b])
        np.testing.assert_array_equal(
            np.asarray(elig[b]), oe, err_msg=f"eligible[{b}]"
        )
        np.testing.assert_array_equal(
            np.asarray(order[b]), oo, err_msg=f"order[{b}]"
        )
        np.testing.assert_array_equal(
            np.asarray(root[b]), orr, err_msg=f"scc_root[{b}]"
        )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fused_depgraph_sha256_bit_identity(seed):
    """Kernel-vs-reference digest equality (interpret mode on CPU):
    the fused grid at a block that does NOT divide the batch (padding
    row edge) hashes to the same sha256 as the pure-jnp reference —
    dtype, shape, and every byte."""
    import hashlib

    adj, committed, active = depgraph_args(
        jax.random.PRNGKey(seed), B=8, V=40
    )
    ref = dg.reference_depgraph_execute(adj, committed, active)
    got = dg.fused_depgraph_execute(
        adj, committed, active, block=3, interpret=True
    )

    def digest(tree):
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(tree):
            arr = np.asarray(leaf)
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        return h.hexdigest()

    assert digest(ref) == digest(got)


def test_depgraph_mask_helpers_round_trip():
    """pack/unpack invert each other off word boundaries, and
    clear_vertices drops BOTH the rows and the columns of the cleared
    vertices (rows_subset is the checkable witness)."""
    bits = jax.random.uniform(jax.random.PRNGKey(9), (3, 37)) < 0.5
    words = dg.pack_mask(bits)
    assert words.shape == (3, 2) and words.dtype == jnp.uint32
    np.testing.assert_array_equal(
        np.asarray(dg.unpack_mask(words, 37)), np.asarray(bits)
    )
    adj = dg.pack_mask(
        jax.random.uniform(jax.random.PRNGKey(10), (37, 37)) < 0.3
    )
    drop = jax.random.uniform(jax.random.PRNGKey(11), (37,)) < 0.5
    cleared = dg.clear_vertices(adj, drop)
    assert bool(jnp.all(dg.rows_subset(cleared, dg.pack_mask(~drop))))
    assert bool(
        jnp.all(jnp.where(drop[:, None], cleared, jnp.uint32(0)) == 0)
    )
