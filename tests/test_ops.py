"""Pallas kernel tests (interpret mode on CPU): the fused acceptor-step
kernel must match its pure-jnp specification bit for bit, and the spec
must match the live tick's vote/quorum phase."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops import (
    INF,
    fused_vote_quorum,
    reference_vote_quorum,
)


def random_state(key, A=3, G=8, W=16, t=7):
    ks = jax.random.split(key, 8)
    p2a = jnp.where(
        jax.random.uniform(ks[0], (A, G, W)) < 0.3,
        jax.random.randint(ks[1], (A, G, W), t - 2, t + 3),
        INF,
    )
    acc_round = jax.random.randint(ks[2], (A, G), 0, 3)
    leader_round = jax.random.randint(ks[3], (G,), 0, 3)
    slot_value = jax.random.randint(ks[4], (G, W), 0, 1000)
    vote_round = jax.random.randint(ks[5], (A, G, W), -1, 3)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(ks[6], (A, G, W), 0, 1000), -1
    )
    p2b = jnp.where(
        vote_round >= 0,
        jax.random.randint(ks[7], (A, G, W), t - 3, t + 4),
        INF,
    )
    lat = jax.random.randint(jax.random.fold_in(key, 9), (A, G, W), 1, 4)
    delivered = jax.random.uniform(jax.random.fold_in(key, 10), (A, G, W)) < 0.9
    return (
        p2a, acc_round, leader_round, slot_value,
        vote_round, vote_value, p2b, lat, delivered, jnp.int32(t),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 4, 32)])
def test_fused_vote_quorum_matches_reference(seed, shape):
    A, G, W = shape
    args = random_state(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    ref = reference_vote_quorum(*args)
    got = fused_vote_quorum(*args, block_g=G // 2, interpret=True)
    names = [
        "vote_round", "vote_value", "p2b_arrival", "acc_round", "nvotes",
        "nsends",
    ]
    assert len(ref) == len(got) == len(names)
    for name, r, g in zip(names, ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g), err_msg=name)


def test_reference_matches_tick_phase():
    """The spec equals the tick's vote/count phase (both acceptor-major),
    replicating the tick's OWN bit-derived latency and drop samples so
    every spec output (votes, phase2b schedule, promised rounds, quorum
    counts) is compared."""
    from frankenpaxos_tpu.tpu.common import bit_delivered, bit_latency
    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        CHOSEN,
        PROPOSED,
        BatchedMultiPaxosConfig,
        init_state,
        tick,
    )

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=8, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=0.2, thrifty=False,
    )
    key = jax.random.PRNGKey(2)
    state = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    # Recompute the tick's own per-message samples for t=1 (same key
    # derivation as multipaxos_batched.tick steps 0-1).
    tkey = jax.random.fold_in(key, 1)
    # Split into FIVE like tick does: threefry split derives key i from
    # counters (i, num+i), so split(key, 3)[0] != split(key, 5)[0] — a
    # 3-way split here would replay different latency/drop bits than the
    # tick actually used.
    k3, k2, k_extra, k_read, k_fail = jax.random.split(tkey, 5)
    G, W, A = cfg.num_groups, cfg.window, cfg.group_size
    bits3 = jax.random.bits(k3, (A, G, W))
    p2b_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    p2b_delivered = bit_delivered(bits3, 24, cfg.drop_rate)

    vr, vv, p2b, accr, nvotes, nsends = reference_vote_quorum(
        state.p2a_arrival,
        state.acc_round,
        state.leader_round,
        state.slot_value,
        state.vote_round,
        state.vote_value,
        state.p2b_arrival,
        p2b_lat,
        p2b_delivered,
        jnp.int32(1),
    )
    after = tick(cfg, state, jnp.int32(1), tkey)
    np.testing.assert_array_equal(np.asarray(vr), np.asarray(after.vote_round))
    np.testing.assert_array_equal(np.asarray(vv), np.asarray(after.vote_value))
    np.testing.assert_array_equal(
        np.asarray(p2b), np.asarray(after.p2b_arrival)
    )
    np.testing.assert_array_equal(
        np.asarray(accr), np.asarray(after.acc_round)
    )
    # nvotes drives chosen-ness: slots the spec counts to quorum are
    # exactly the slots the tick marked CHOSEN this tick (no prior
    # chosen at t=1; status is PROPOSED or CHOSEN only).
    chosen = np.asarray(after.status) == CHOSEN
    proposed_before = np.asarray(state.status) == PROPOSED
    expect_chosen = proposed_before & (np.asarray(nvotes) >= cfg.f + 1)
    np.testing.assert_array_equal(expect_chosen, chosen)


@pytest.mark.parametrize("drop", [0.0, 0.2])
def test_tick_with_use_pallas_is_bit_identical(drop):
    """The whole simulation with tick steps 1-2 routed through the fused
    Pallas kernel (interpret mode on CPU) equals the XLA path bit for bit
    — state arrays, stats, and invariants."""
    import dataclasses as dc

    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        BatchedMultiPaxosConfig,
        check_invariants,
        init_state,
        run_ticks,
    )

    # num_groups NOT divisible by pallas_block_g exercises the padding.
    base = dict(
        f=1, num_groups=3, window=8, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=drop, retry_timeout=6,
        pallas_block_g=2,
    )
    key = jax.random.PRNGKey(5)
    t0 = jnp.zeros((), jnp.int32)
    cfg_x = BatchedMultiPaxosConfig(**base, use_pallas=False)
    cfg_p = BatchedMultiPaxosConfig(**base, use_pallas=True)
    sx, tx = run_ticks(cfg_x, init_state(cfg_x), t0, 40, key)
    sp, tp = run_ticks(cfg_p, init_state(cfg_p), t0, 40, key)
    assert int(sx.committed) > 0
    for field in dc.fields(sx):
        # Nested pytree fields (the Telemetry ring) compare leaf-wise;
        # the per-tick counters must also match across kernel paths.
        la = jax.tree_util.tree_leaves(getattr(sx, field.name))
        lb = jax.tree_util.tree_leaves(getattr(sp, field.name))
        assert len(la) == len(lb), field.name
        for a, b in zip(la, lb):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=field.name
            )
    inv = check_invariants(cfg_p, sp, tp)
    assert all(bool(v) for v in inv.values()), inv
