"""Kernel-suite tests (interpret mode on CPU): every fused Pallas
kernel must match its pure-jnp reference twin bit for bit on random
dtype-policy states, and the vote/quorum reference must match the live
tick's vote phase."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops import (
    INF,
    INF16,
    fused_craq_chain,
    fused_mencius_vote,
    fused_mp_dispatch,
    fused_p1_promise,
    fused_vote_quorum,
    reference_craq_chain,
    reference_mencius_vote,
    reference_mp_dispatch,
    reference_p1_promise,
    reference_vote_quorum,
)

I16 = jnp.int16
I8 = jnp.int8


def _assert_trees_equal(ref, got, names=None):
    ref = jax.tree_util.tree_leaves(ref)
    got = jax.tree_util.tree_leaves(got)
    assert len(ref) == len(got)
    names = names or [str(i) for i in range(len(ref))]
    for name, r, g in zip(names, ref, got):
        r, g = np.asarray(r), np.asarray(g)
        assert r.dtype == g.dtype, f"{name}: {r.dtype} != {g.dtype}"
        np.testing.assert_array_equal(r, g, err_msg=name)


def _clock(key, shape, p=0.3):
    """Random offset clock: INF16 = never, else an offset in [-1, 5)."""
    ks = jax.random.split(key, 2)
    return jnp.where(
        jax.random.uniform(ks[0], shape) < p,
        jax.random.randint(ks[1], shape, -1, 5),
        INF16,
    ).astype(I16)


def vote_quorum_args(key, A=3, G=8, W=16):
    ks = jax.random.split(key, 10)
    p2a = _clock(ks[0], (A, G, W))
    acc_round = jax.random.randint(ks[1], (A, G), 0, 3).astype(I16)
    leader_round = jax.random.randint(ks[2], (G,), 0, 3).astype(I16)
    slot_value = jax.random.randint(ks[3], (G, W), 0, 1000)
    vote_round = jax.random.randint(ks[4], (A, G, W), -1, 3).astype(I16)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(ks[5], (A, G, W), 0, 1000), -1
    )
    p2b = jnp.where(vote_round >= 0, _clock(ks[6], (A, G, W), p=0.7), INF16)
    lat = jax.random.randint(ks[7], (A, G, W), 1, 4).astype(I16)
    delivered = jax.random.uniform(ks[8], (A, G, W)) < 0.9
    return (
        p2a, acc_round, leader_round, slot_value,
        vote_round, vote_value, p2b, lat, delivered,
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 4, 32)])
def test_fused_vote_quorum_matches_reference(seed, shape):
    A, G, W = shape
    args = vote_quorum_args(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    ref = reference_vote_quorum(*args)
    got = fused_vote_quorum(*args, block=max(G // 2, 1), interpret=True)
    _assert_trees_equal(
        ref, got,
        ["vote_round", "vote_value", "p2b", "acc_round", "nvotes", "nsends"],
    )


def p1_promise_args(key, A=3, G=8, W=16):
    ks = jax.random.split(key, 12)
    status = jax.random.randint(ks[0], (G, W), 0, 3).astype(I8)
    vote_round = jax.random.randint(ks[1], (A, G, W), -1, 3).astype(I16)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(ks[2], (A, G, W), 0, 1000), -1
    )
    slot_value = jax.random.randint(ks[3], (G, W), 0, 1000)
    p2a = _clock(ks[4], (A, G, W))
    p2b = _clock(ks[5], (A, G, W))
    last_send = jax.random.randint(ks[6], (G, W), 0, 50)
    mask = jax.random.uniform(ks[7], (G,)) < 0.6
    learned = jax.random.uniform(ks[8], (A, G)) < 0.7
    lat = jax.random.randint(ks[9], (A, G, W), 1, 4).astype(I16)
    return (
        status, vote_round, vote_value, slot_value, p2a, p2b,
        last_send, mask, learned, lat, jnp.int32(33),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 6, 32)])
def test_fused_p1_promise_matches_reference(seed, shape):
    A, G, W = shape
    args = p1_promise_args(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    ref = reference_p1_promise(*args)
    got = fused_p1_promise(*args, block=max(G // 2, 1), interpret=True)
    _assert_trees_equal(
        ref, got, ["slot_value", "p2a", "p2b", "last_send"]
    )


def mp_dispatch_args(key, A=3, G=8, W=16):
    ks = jax.random.split(key, 20)
    status = jax.random.randint(ks[0], (G, W), 0, 3).astype(I8)
    slot_value = jnp.where(
        status > 0, jax.random.randint(ks[1], (G, W), 0, 1000), -1
    )
    propose_tick = jnp.where(
        status > 0, jax.random.randint(ks[2], (G, W), 0, 30), INF
    )
    last_send = jnp.where(
        status > 0, jax.random.randint(ks[3], (G, W), 0, 33), INF
    )
    chosen_tick = jnp.where(
        status == 2, jax.random.randint(ks[4], (G, W), 0, 33), INF
    )
    chosen_round = jnp.where(
        status == 2, jax.random.randint(ks[5], (G, W), 0, 3), -1
    ).astype(I16)
    chosen_value = jnp.where(status == 2, slot_value, -1)
    replica_arrival = jnp.where(
        status == 2, jax.random.randint(ks[6], (G, W), 30, 40), INF
    )
    p2a = _clock(ks[7], (A, G, W))
    p2b = _clock(ks[8], (A, G, W))
    vote_round = jax.random.randint(ks[9], (A, G, W), -1, 3).astype(I16)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(ks[10], (A, G, W), 0, 1000), -1
    )
    nvotes = jax.random.randint(ks[11], (G, W), 0, A + 1)
    head = jax.random.randint(ks[12], (G,), 0, 100)
    next_slot = head + jax.random.randint(ks[13], (G,), 0, W + 1)
    leader_round = jax.random.randint(ks[14], (G,), 0, 3).astype(I16)
    cap = jax.random.randint(ks[15], (G,), 0, 5)
    retry_ok = jax.random.uniform(ks[16], (G,)) < 0.8
    send_ok = jax.random.uniform(ks[17], (A, G, W)) < 0.6
    retry_deliv = jax.random.uniform(ks[18], (A, G, W)) < 0.9
    kl = jax.random.split(ks[19], 3)
    p2a_lat = jax.random.randint(kl[0], (A, G, W), 1, 4).astype(I16)
    retry_lat = jax.random.randint(kl[1], (A, G, W), 1, 4).astype(I16)
    rep_lat = jax.random.randint(kl[2], (G, W), 1, 4)
    return (
        status, slot_value, propose_tick, last_send,
        chosen_tick, chosen_round, chosen_value, replica_arrival,
        p2a, p2b, vote_round, vote_value,
        nvotes, head, next_slot, leader_round, cap, retry_ok,
        send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, jnp.int32(33),
    )


MP_DISPATCH_OUTS = [
    "status", "slot_value", "propose_tick", "last_send",
    "chosen_tick", "chosen_round", "chosen_value", "replica_arrival",
    "p2a", "p2b", "vote_round", "vote_value",
    "head", "next_slot", "count", "n_retire",
    "newly_chosen", "retire_mask", "is_new", "timed_out", "latency",
]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(3, 8, 16), (5, 6, 32)])
def test_fused_mp_dispatch_matches_reference(seed, shape):
    A, G, W = shape
    args = mp_dispatch_args(jax.random.PRNGKey(seed), A=A, G=G, W=W)
    statics = dict(f=1, retry_timeout=8, num_groups=G)
    ref = reference_mp_dispatch(*args, **statics)
    got = fused_mp_dispatch(
        *args, block=max(G // 2, 1), interpret=True, **statics
    )
    _assert_trees_equal(ref, got, MP_DISPATCH_OUTS)


def mencius_args(key, L=8, W=16, A=3, t=9):
    ks = jax.random.split(key, 6)
    p2a = jnp.where(
        jax.random.uniform(ks[0], (L, W, A)) < 0.3,
        jax.random.randint(ks[1], (L, W, A), t - 2, t + 3),
        INF,
    )
    voted = jax.random.uniform(ks[2], (L, W, A)) < 0.3
    p2b = jnp.where(
        voted, jax.random.randint(ks[3], (L, W, A), t - 3, t + 4), INF
    )
    lat = jax.random.randint(ks[4], (L, W, A), 1, 4)
    delivered = jax.random.uniform(ks[5], (L, W, A)) < 0.9
    return p2a, voted, p2b, lat, delivered, jnp.int32(t)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(8, 16, 3), (6, 32, 5)])
def test_fused_mencius_vote_matches_reference(seed, shape):
    L, W, A = shape
    args = mencius_args(jax.random.PRNGKey(seed), L=L, W=W, A=A)
    ref = reference_mencius_vote(*args)
    got = fused_mencius_vote(*args, block=max(L // 2, 1), interpret=True)
    _assert_trees_equal(ref, got, ["voted", "p2b", "nvotes"])


def craq_args(key, N=8, L=3, KV=4, W=8, t=9):
    tail = L - 1
    ks = jax.random.split(key, 8)
    w_status = jax.random.randint(ks[0], (N, W), 0, 3).astype(I8)
    w_key = jax.random.randint(ks[1], (N, W), 0, KV)
    w_version = jax.random.randint(ks[2], (N, W), 0, 50)
    w_node = jnp.where(
        w_status == 2,  # UP acks live on nodes [0, tail)
        jax.random.randint(ks[3], (N, W), 0, max(tail, 1)),
        jax.random.randint(ks[3], (N, W), 0, tail + 1),
    )
    w_arrival = jnp.where(
        w_status > 0, jax.random.randint(ks[4], (N, W), t - 1, t + 3), INF
    )
    w_issue = jax.random.randint(ks[5], (N, W), 0, t)
    dirty = jax.random.randint(ks[6], (N, L * KV), 0, 3)
    version = jax.random.randint(ks[7], (N, L * KV), -1, 40)
    hop_lat = jax.random.randint(jax.random.fold_in(key, 9), (N, W), 1, 4)
    return (
        w_status, w_key, w_version, w_node, w_arrival, w_issue,
        dirty, version, hop_lat, jnp.int32(t),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("dims", [(8, 3, 4, 8), (6, 4, 3, 16)])
def test_fused_craq_chain_matches_reference(seed, dims):
    N, L, KV, W = dims
    args = craq_args(jax.random.PRNGKey(seed), N=N, L=L, KV=KV, W=W)
    statics = dict(tail=L - 1, num_keys=KV)
    ref = reference_craq_chain(*args, **statics)
    got = fused_craq_chain(
        *args, block=max(N // 2, 1), interpret=True, **statics
    )
    _assert_trees_equal(
        ref, got,
        ["w_status", "w_node", "w_arrival", "dirty", "version",
         "at_tail", "wlat"],
    )


def test_reference_matches_tick_phase():
    """The vote/quorum spec equals the tick's own vote phase, replicating
    the tick's bit-derived latency and drop samples AND its clock aging
    (offsets age once at tick start, so the spec sees aged clocks)."""
    from frankenpaxos_tpu.tpu.common import age_clock, bit_delivered, bit_latency
    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        CHOSEN,
        PROPOSED,
        BatchedMultiPaxosConfig,
        init_state,
        tick,
    )

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=8, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=0.2, thrifty=False,
    )
    key = jax.random.PRNGKey(2)
    state = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    # Recompute the tick's own per-message samples for t=1 (same key
    # derivation as multipaxos_batched.tick steps 0-1). Split into FIVE
    # like tick does: threefry split derives key i from counters
    # (i, num+i), so split(key, 3)[0] != split(key, 5)[0].
    tkey = jax.random.fold_in(key, 1)
    k3, k2, k_extra, k_read, k_fail = jax.random.split(tkey, 5)
    G, W, A = cfg.num_groups, cfg.window, cfg.group_size
    bits3 = jax.random.bits(k3, (A, G, W))
    p2b_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max).astype(
        state.p2b_arrival.dtype
    )
    p2b_delivered = bit_delivered(bits3, 24, cfg.drop_rate)

    vr, vv, p2b, accr, nvotes, nsends = reference_vote_quorum(
        age_clock(state.p2a_arrival),
        state.acc_round,
        state.leader_round,
        state.slot_value,
        state.vote_round,
        state.vote_value,
        age_clock(state.p2b_arrival),
        p2b_lat,
        p2b_delivered,
    )
    after = tick(cfg, state, jnp.int32(1), tkey)
    np.testing.assert_array_equal(np.asarray(vr), np.asarray(after.vote_round))
    np.testing.assert_array_equal(np.asarray(vv), np.asarray(after.vote_value))
    np.testing.assert_array_equal(
        np.asarray(p2b), np.asarray(after.p2b_arrival)
    )
    np.testing.assert_array_equal(
        np.asarray(accr), np.asarray(after.acc_round)
    )
    # nvotes drives chosen-ness: slots the spec counts to quorum are
    # exactly the slots the tick marked CHOSEN this tick (no prior
    # chosen at t=1; status is PROPOSED or CHOSEN only).
    chosen = np.asarray(after.status) == CHOSEN
    proposed_before = np.asarray(state.status) == PROPOSED
    expect_chosen = proposed_before & (np.asarray(nvotes) >= cfg.f + 1)
    np.testing.assert_array_equal(expect_chosen, chosen)


@pytest.mark.parametrize("drop", [0.0, 0.2])
def test_tick_with_use_pallas_is_bit_identical(drop):
    """The whole simulation with the hot planes routed through the fused
    kernels (interpret mode on CPU via the legacy use_pallas knob, which
    folds into KernelPolicy(mode='on')) equals the reference path bit
    for bit — state arrays, stats, and invariants."""
    import dataclasses as dc

    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        BatchedMultiPaxosConfig,
        check_invariants,
        init_state,
        run_ticks,
    )

    # num_groups NOT divisible by pallas_block_g exercises the padding.
    base = dict(
        f=1, num_groups=3, window=8, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=drop, retry_timeout=6,
        pallas_block_g=2,
    )
    key = jax.random.PRNGKey(5)
    t0 = jnp.zeros((), jnp.int32)
    cfg_x = BatchedMultiPaxosConfig(**base, use_pallas=False)
    cfg_p = BatchedMultiPaxosConfig(**base, use_pallas=True)
    sx, tx = run_ticks(cfg_x, init_state(cfg_x), t0, 40, key)
    sp, tp = run_ticks(cfg_p, init_state(cfg_p), t0, 40, key)
    assert int(sx.committed) > 0
    for field in dc.fields(sx):
        # Nested pytree fields (the Telemetry ring) compare leaf-wise;
        # the per-tick counters must also match across kernel paths.
        la = jax.tree_util.tree_leaves(getattr(sx, field.name))
        lb = jax.tree_util.tree_leaves(getattr(sp, field.name))
        assert len(la) == len(lb), field.name
        for a, b in zip(la, lb):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=field.name
            )
    inv = check_invariants(cfg_p, sp, tp)
    assert all(bool(v) for v in inv.values()), inv
