"""Fault-injection contract (thin wrapper): every batched *Config
accepts a ``faults: FaultPlan`` field, validates it in
``__post_init__``, applies it in ``tick``, and range-checks every float
``*_rate`` knob.

The checkers are the ``fault-*`` rules in ``frankenpaxos_tpu/analysis``;
synthetic positive/negative fixtures for them live in
``test_analysis_engine.py``. Intentional exceptions go in
``analysis/allowlists.py`` with a reason.
"""

import pytest

from frankenpaxos_tpu import analysis

pytestmark = pytest.mark.lint


@pytest.mark.parametrize(
    "rule_id",
    [
        "fault-config-field",
        "fault-validate",
        "fault-apply",
        "fault-rate-validated",
    ],
)
def test_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()
