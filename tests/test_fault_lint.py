"""AST lint: the fault-injection contract across every batched backend
(the tpu/faults.py repo-wide contract, sibling of the telemetry lint in
test_telemetry_lint.py and the donation lint in test_donation_lint.py).

Three clauses, enforced for every ``tpu/*_batched.py``:

 1. The backend's ``*Config`` dataclass accepts a ``faults`` field
    (annotated ``FaultPlan``), so every backend can run under a fault
    schedule — and ``FaultPlan.none()`` as the default keeps ordinary
    runs bit-identical.
 2. Its ``__post_init__`` validates the plan (``self.faults.validate``
    with the backend's partition axis), so malformed rates/masks fail
    at config time, not as silent mis-simulation.
 3. Its ``tick`` actually APPLIES the plan: the body references
    ``faults`` (via ``cfg.faults`` or a ``faults_mod``/``faults``
    helper call), so a new backend can't accept a plan and ignore it.

Intentional exceptions go in the ALLOWLISTs with a reason.
"""

import ast
import pathlib

TPU_DIR = (
    pathlib.Path(__file__).resolve().parent.parent
    / "frankenpaxos_tpu"
    / "tpu"
)

# Files exempt from a clause, with reasons.
CONFIG_ALLOWLIST = {
    # Nothing is currently exempt.
}
VALIDATE_ALLOWLIST = {
    # Nothing is currently exempt.
}
APPLY_ALLOWLIST = {
    # Nothing is currently exempt.
}


def _batched_files():
    files = sorted(TPU_DIR.glob("*_batched.py"))
    assert len(files) >= 13, [f.name for f in files]
    return files


def _config_classes(tree):
    return [
        node
        for node in ast.walk(tree)
        if isinstance(node, ast.ClassDef) and node.name.endswith("Config")
    ]


def _ann_fields(cls):
    return {
        stmt.target.id: ast.unparse(stmt.annotation)
        for stmt in cls.body
        if isinstance(stmt, ast.AnnAssign)
        and isinstance(stmt.target, ast.Name)
    }


def test_every_batched_config_accepts_a_fault_plan():
    offenders = []
    for path in _batched_files():
        if path.name in CONFIG_ALLOWLIST:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        classes = _config_classes(tree)
        assert classes, f"{path.name}: no *Config dataclass found"
        for cls in classes:
            ann = _ann_fields(cls).get("faults")
            if ann is None or "FaultPlan" not in ann:
                offenders.append((path.name, cls.name))
    assert not offenders, (
        "batched *Config dataclasses without a `faults: FaultPlan` "
        f"field (the tpu/faults.py contract): {offenders}"
    )


def test_every_post_init_validates_the_fault_plan():
    """__post_init__ must call ``self.faults.validate(...)`` — and every
    fault-rate field must thereby be range-checked at config time."""
    offenders = []
    for path in _batched_files():
        if path.name in VALIDATE_ALLOWLIST:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for cls in _config_classes(tree):
            post = [
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
                and n.name == "__post_init__"
            ]
            if not post:
                offenders.append((path.name, cls.name, "no __post_init__"))
                continue
            calls_validate = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "validate"
                and "faults" in ast.unparse(n.func.value)
                for n in ast.walk(post[0])
            )
            if not calls_validate:
                offenders.append(
                    (path.name, cls.name, "no faults.validate call")
                )
    assert not offenders, (
        "batched configs whose __post_init__ never validates the fault "
        f"plan: {offenders}"
    )


def _tick_applies_faults(func: ast.FunctionDef) -> bool:
    for node in ast.walk(func):
        # cfg.faults (any attribute path ending in .faults).
        if isinstance(node, ast.Attribute) and node.attr == "faults":
            return True
        # faults_mod.<helper>(...) / faults.<helper>(...).
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("faults_mod", "faults")
        ):
            return True
    return False


def test_every_tick_applies_the_fault_plan():
    offenders = []
    for path in _batched_files():
        if path.name in APPLY_ALLOWLIST:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        ticks = [
            n
            for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name == "tick"
        ]
        assert ticks, f"{path.name}: no tick function"
        for func in ticks:
            if not _tick_applies_faults(func):
                offenders.append(path.name)
    assert not offenders, (
        "tick functions that accept a FaultPlan via config but never "
        f"apply it: {offenders}"
    )


def test_lint_detects_a_violation():
    """Teeth: a tick that never touches faults must be flagged."""
    src = (
        "def tick(cfg, state, t, key):\n"
        "    x = cfg.drop_rate\n"
        "    return state\n"
    )
    func = ast.parse(src).body[0]
    assert not _tick_applies_faults(func)
    src2 = (
        "def tick(cfg, state, t, key):\n"
        "    fp = cfg.faults\n"
        "    return state\n"
    )
    assert _tick_applies_faults(ast.parse(src2).body[0])


def test_fault_rate_fields_are_validated_everywhere():
    """Every *_rate field on a batched config must be range-checked in
    __post_init__ (an assert mentioning the field) — rates silently out
    of range would simulate a different protocol regime. The FaultPlan's
    own rates are covered by validate() (clause 2)."""
    offenders = []
    for path in _batched_files():
        tree = ast.parse(path.read_text(), filename=str(path))
        for cls in _config_classes(tree):
            rate_fields = [
                name
                for name, ann in _ann_fields(cls).items()
                if name.endswith("_rate") and "float" in ann
            ]
            post = [
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
                and n.name == "__post_init__"
            ]
            body_src = ast.unparse(post[0]) if post else ""
            for name in rate_fields:
                if f"self.{name}" not in body_src:
                    offenders.append((path.name, cls.name, name))
    assert not offenders, (
        f"unvalidated *_rate config fields: {offenders}"
    )


def test_allowlists_reference_existing_code():
    for allow in (CONFIG_ALLOWLIST, VALIDATE_ALLOWLIST, APPLY_ALLOWLIST):
        for fname in allow:
            assert (TPU_DIR / fname).exists(), f"stale allowlist {fname}"
