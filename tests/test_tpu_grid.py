"""Tests of the flexible-quorum (grid vs majority) batched backend."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from frankenpaxos_tpu.tpu.grid_batched import (
    GridBatchedConfig,
    check_invariants,
    init_state,
    run_ticks,
    sweep,
    tick,
)


def run(cfg, ticks=150, seed=0):
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.zeros((), jnp.int32), ticks,
        jax.random.PRNGKey(seed),
    )
    jax.block_until_ready(state)
    return state, t


@pytest.mark.parametrize("mode", ["grid", "majority"])
def test_happy_path(mode):
    cfg = GridBatchedConfig(rows=3, cols=4, mode=mode, window=16,
                            slots_per_tick=2, lat_min=1, lat_max=2)
    state, t = run(cfg)
    assert int(state.committed) > 150 * 2 * 0.8
    assert 0 < int(state.retired) <= int(state.committed)
    assert all(check_invariants(cfg, state, t).values())


@pytest.mark.parametrize("mode", ["grid", "majority"])
def test_loss_recovered_by_retries(mode):
    cfg = GridBatchedConfig(rows=3, cols=3, mode=mode, window=16,
                            slots_per_tick=2, lat_min=1, lat_max=3,
                            drop_rate=0.2, retry_timeout=8)
    state1, _ = run(cfg, ticks=200, seed=1)
    state2, t2 = run(cfg, ticks=400, seed=1)
    assert int(state2.committed) > int(state1.committed) + 50  # sustained
    assert all(check_invariants(cfg, state2, t2).values())


def test_grid_needs_every_row():
    """With an entire row's messages never arriving, a grid can never form
    a write quorum — but a majority of the same acceptors can."""
    cfg = GridBatchedConfig(rows=2, cols=3, mode="grid", window=8,
                            slots_per_tick=1, lat_min=1, lat_max=1)
    state = init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(3)
    for i in range(30):
        state = tick(cfg, state, t, jax.random.fold_in(key, i))
        # Black-hole row 0 entirely: its Phase2as never arrive.
        state = dataclasses.replace(
            state,
            p2a_arrival=state.p2a_arrival.at[:, 0, :].set(2**30),
            p2b_arrival=state.p2b_arrival.at[:, 0, :].set(2**30),
        )
        t = t + 1
    assert int(state.committed) == 0  # every row is required


def test_sweep_compares_modes():
    results = sweep(
        [
            GridBatchedConfig(rows=4, cols=4, mode="grid", window=16,
                              slots_per_tick=2),
            GridBatchedConfig(rows=4, cols=4, mode="majority", window=16,
                              slots_per_tick=2),
        ],
        num_ticks=150,
    )
    assert {r["mode"] for r in results} == {"grid", "majority"}
    for r in results:
        assert r["committed"] > 0
        assert all(r["invariants"].values())
        assert r["acceptors"] == 16
    # A grid write quorum is 4 messages vs 9 for the majority — commit
    # latency (ticks) should never be worse for the grid here.
    by_mode = {r["mode"]: r for r in results}
    assert (
        by_mode["grid"]["p50_latency_ticks"]
        <= by_mode["majority"]["p50_latency_ticks"] + 1
    )


def test_large_grid_smoke():
    """A 100x100 grid (10k acceptors) runs and commits (the shape class of
    the 100k-acceptor sweep; full scale runs on real TPU via bench)."""
    cfg = GridBatchedConfig(rows=100, cols=100, mode="grid", window=16,
                            slots_per_tick=2)
    state, t = run(cfg, ticks=60)
    assert int(state.committed) > 0
    assert all(check_invariants(cfg, state, t).values())
