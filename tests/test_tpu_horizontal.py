"""Tests of the batched Horizontal MultiPaxos backend
(tpu/horizontal_batched.py): config-as-log-value reconfiguration with
the s+alpha chunk pipeline (horizontal/Leader.scala:459-498, 920-960),
bank isolation safety, alpha pipeline bound, handover discipline, and a
deterministic single-group walkthrough."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.tpu import horizontal_batched as hb


def run_random(cfg, seed, ticks):
    key = jax.random.PRNGKey(seed)
    state, t = hb.run_ticks(cfg, hb.init_state(cfg), jnp.int32(0), ticks, key)
    return state, t


def test_progress_without_reconfiguration():
    cfg = hb.BatchedHorizontalConfig(
        f=1, num_groups=8, window=32, slots_per_tick=2, alpha=16,
        lat_min=1, lat_max=3,
    )
    state, t = run_random(cfg, seed=0, ticks=200)
    s = hb.stats(cfg, state, t)
    assert s["committed"] > 8 * 150
    assert s["executed"] > 0
    assert s["reconfigs_done"] == 0
    inv = hb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_reconfiguration_churn_progress_and_safety():
    """Open workload with periodic config-as-log-value reconfigurations:
    chunks hand over, banks alternate, and every safety check holds."""
    cfg = hb.BatchedHorizontalConfig(
        f=1, num_groups=8, window=32, slots_per_tick=2, alpha=16,
        lat_min=1, lat_max=3, reconfigure_every=30,
    )
    state, t = run_random(cfg, seed=1, ticks=400)
    s = hb.stats(cfg, state, t)
    assert s["committed"] > 8 * 200
    assert s["reconfigs_proposed"] >= 8  # every group reconfigured
    assert s["reconfigs_done"] >= 8
    assert s["bank_violations"] == 0
    inv = hb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    # Epochs actually advanced (banks alternated).
    assert int(jax.device_get(state.epoch).min()) >= 1


def test_small_alpha_stalls_at_boundary():
    """With a tight alpha the old chunk drains before the new bank's
    phase 1 completes, so proposals must stall at the boundary (the
    throughput dip the churn timeline measures) — and never violate the
    alpha bound while doing so."""
    cfg = hb.BatchedHorizontalConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, alpha=4,
        lat_min=2, lat_max=4, reconfigure_every=25,
    )
    state, t = run_random(cfg, seed=2, ticks=300)
    s = hb.stats(cfg, state, t)
    assert s["reconfigs_done"] > 0
    assert s["boundary_stalls"] > 0  # phase 1 gated the new chunk
    inv = hb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_alpha_bound_is_tight():
    """next_slot - watermark never exceeds alpha, even under load."""
    cfg = hb.BatchedHorizontalConfig(
        f=1, num_groups=4, window=32, slots_per_tick=8, alpha=8,
        lat_min=2, lat_max=4,
    )
    key = jax.random.PRNGKey(3)
    state = hb.init_state(cfg)
    t = 0
    for _ in range(80):
        state = hb.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
        gap = np.asarray(state.next_slot) - np.asarray(state.head)
        assert (gap <= cfg.alpha).all(), gap
    assert int(state.alpha_stalls) > 0  # the gate actually fired


def test_bank_isolation_detector_has_teeth():
    """Forge a vote in the WRONG bank: the device-side ledger must count
    it and the votes_in_place invariant must trip."""
    cfg = hb.BatchedHorizontalConfig(
        f=1, num_groups=2, window=8, slots_per_tick=1, alpha=4,
        lat_min=1, lat_max=1,
    )
    key = jax.random.PRNGKey(4)
    state = hb.init_state(cfg)
    for t in range(10):
        state = hb.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
    live = np.asarray(state.status) == hb.PROPOSED
    assert live.any()
    g, w = map(int, np.argwhere(live)[0])
    # The slot's bank is epoch%2 = 0 (rows 0..n); forge row n (bank 1).
    state = dataclasses.replace(
        state, voted=state.voted.at[cfg.n, g, w].set(True)
    )
    inv = hb.check_invariants(cfg, state, jnp.int32(10))
    assert not bool(inv["votes_in_place"])
    state = hb.tick(cfg, state, jnp.int32(10), jax.random.fold_in(key, 10))
    assert int(state.bank_violations) > 0


def test_deterministic_chunk_walkthrough():
    """Single group, lat=1, K=1: follow one reconfiguration end to end —
    config proposed, chosen, crosses the watermark, boundary armed at
    s+alpha, phase 1 runs against bank 1, handover bumps the epoch, and
    post-handover slots are chosen by bank 1 only."""
    cfg = hb.BatchedHorizontalConfig(
        f=1, num_groups=1, window=16, slots_per_tick=1, alpha=6,
        lat_min=1, lat_max=1, reconfigure_every=1000,  # manual firing
    )
    key = jax.random.PRNGKey(5)
    state = hb.init_state(cfg)
    t = 1  # start past t=0 so the periodic driver can't fire in warm-up
    # Warm up: a few command slots flow through bank 0.
    for _ in range(8):
        state = hb.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    assert int(state.epoch[0]) == 0
    # reconfigure_every=1000 with stagger 7*0: fires at t % 1000 == 0 —
    # force a config proposal by replacing the next tick's t with 1000.
    state = hb.tick(cfg, state, jnp.int32(1000), jax.random.fold_in(key, t))
    assert int(state.reconfigs_proposed) == 1
    config_slot = int(state.next_slot[0]) - 1
    t = 1001  # time continues from the forced tick (arrivals are exact)
    # Run until handover.
    for _ in range(60):
        if int(state.epoch[0]) == 1:
            break
        state = hb.tick(
            cfg, state, jnp.int32(t), jax.random.fold_in(key, t)
        )
        t += 1
    assert int(state.epoch[0]) == 1, "handover never happened"
    assert int(state.boundary[0]) == hb.INF
    assert int(state.reconfigs_done) == 1
    # Watermark passed the boundary (= config_slot + alpha).
    assert int(state.head[0]) >= config_slot + cfg.alpha
    # Post-handover: run on, then check every live vote sits in bank 1.
    for _ in range(10):
        state = hb.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    voted = np.asarray(state.voted)  # [P, G, W]
    live = np.asarray(state.status) != hb.EMPTY
    n = cfg.n
    assert not voted[:n, 0, live[0]].any(), "bank-0 votes after handover"
    inv = hb.check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv


def test_throughput_dip_visible_in_timeline():
    """Per-tick committed counts around a reconfiguration show the
    boundary stall (the artifact scripts/horizontal_churn.py plots)."""
    cfg = hb.BatchedHorizontalConfig(
        f=1, num_groups=16, window=16, slots_per_tick=2, alpha=4,
        lat_min=2, lat_max=3, reconfigure_every=40,
    )
    key = jax.random.PRNGKey(6)
    state = hb.init_state(cfg)
    committed = []
    t = 0
    for _ in range(200):
        before = int(state.committed)
        state = hb.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        committed.append(int(state.committed) - before)
        t += 1
    inv = hb.check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv
    # Steady state exists and the dip exists: some tick commits far less
    # than the steady rate while reconfigurations churn.
    steady = sorted(committed[50:])[len(committed[50:]) // 2]
    assert steady >= 8  # alpha=4 throttles below K*G, but flow persists
    assert min(committed[50:]) <= steady // 2  # the reconfiguration dip
