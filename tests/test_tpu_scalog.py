"""Batched Scalog tests: the cut -> global-log projection (prefix sums),
in-order cut commits, and invariants under load skew."""

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu.scalog_batched import (
    BatchedScalogConfig,
    check_invariants,
    global_indices_of_cut,
    init_state,
    run_ticks,
)


def run(cfg, ticks, seed=0):
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), ticks, jax.random.PRNGKey(seed)
    )
    jax.block_until_ready(state)
    inv = {k: bool(v) for k, v in check_invariants(cfg, state, t).items()}
    assert all(inv.values()), inv
    return state


def test_global_log_grows_and_matches_cut_sum():
    cfg = BatchedScalogConfig(num_shards=4, appends_per_tick=4, append_jitter=2)
    state = run(cfg, 120)
    assert int(state.global_len) > 1000
    assert int(state.global_len) == int(np.asarray(state.last_committed_cut).sum())
    # The ordering layer keeps up: the global log trails local appends by
    # at most a few cut periods' worth of records.
    lag = int(np.asarray(state.local_len).sum()) - int(state.global_len)
    assert lag < 4 * 16 * cfg.cut_every * cfg.num_shards


def test_cut_projection_prefix_sums():
    """The projection assigns every record of every shard a unique,
    contiguous, gap-free global index range (Server.scala's cut ->
    global-log doc, as exclusive prefix sums)."""
    prev = jnp.array([3, 5, 0, 2])
    cut = jnp.array([6, 5, 2, 4])
    starts, ends = global_indices_of_cut(prev, cut)
    base = int(prev.sum())
    spans = []
    for s in range(4):
        spans.append((int(starts[s]), int(ends[s])))
    # Shard ranges tile [base, sum(cut)) exactly, in shard order.
    covered = []
    for lo, hi in spans:
        covered += list(range(lo, hi))
    assert covered == list(range(base, int(cut.sum())))


def test_latency_reflects_cut_period():
    """A slower aggregator period means records wait longer for global
    ordering (the snapshot-interval wait component grows with
    cut_every)."""
    fast = run(BatchedScalogConfig(num_shards=4, cut_every=1), 150, seed=2)
    slow = run(BatchedScalogConfig(num_shards=4, cut_every=6), 150, seed=2)
    mean_fast = float(fast.lat_sum) / max(1, int(fast.lat_count))
    mean_slow = float(slow.lat_sum) / max(1, int(slow.lat_count))
    assert mean_slow > mean_fast


def test_closed_workload_fully_orders():
    cfg = BatchedScalogConfig(
        num_shards=4, appends_per_tick=4, append_jitter=0,
        max_records_per_shard=40, cut_every=1,
    )
    state = run(cfg, 80)
    assert int(state.global_len) == 4 * 40  # every record globally ordered
