import pytest

from frankenpaxos_tpu.quorums import (
    Grid,
    SimpleMajority,
    UnanimousWrites,
    from_proto,
    to_proto,
)


def test_simple_majority():
    qs = SimpleMajority({1, 2, 3, 4, 5}, seed=0)
    assert qs.quorum_size == 3
    assert qs.is_read_quorum({1, 2, 3})
    assert not qs.is_read_quorum({1, 2})
    assert qs.is_write_quorum({3, 4, 5})
    with pytest.raises(ValueError):
        qs.is_read_quorum({1, 9})
    assert qs.is_superset_of_read_quorum({1, 2, 3, 99})
    assert not qs.is_superset_of_read_quorum({1, 99})
    rq = qs.random_read_quorum()
    assert len(rq) == 3 and rq <= qs.nodes()


def test_unanimous_writes():
    qs = UnanimousWrites({1, 2, 3}, seed=0)
    assert qs.is_read_quorum({2})
    assert not qs.is_write_quorum({1, 2})
    assert qs.is_write_quorum({1, 2, 3})
    assert qs.random_write_quorum() == {1, 2, 3}
    assert len(qs.random_read_quorum()) == 1
    assert qs.is_superset_of_write_quorum({1, 2, 3, 4})
    assert not qs.is_superset_of_write_quorum({1, 2})


def test_grid():
    qs = Grid([[1, 2, 3], [4, 5, 6]], seed=0)
    # Rows are read quorums.
    assert qs.is_read_quorum({1, 2, 3})
    assert qs.is_read_quorum({4, 5, 6})
    assert not qs.is_read_quorum({1, 2, 4})
    # One element per row is a write quorum.
    assert qs.is_write_quorum({1, 4})
    assert qs.is_write_quorum({2, 6})
    assert not qs.is_write_quorum({1, 2})
    # Read/write quorums intersect.
    assert qs.random_read_quorum() & qs.random_write_quorum()
    with pytest.raises(ValueError):
        Grid([[1, 2], [3]])


@pytest.mark.parametrize(
    "qs",
    [
        SimpleMajority({1, 2, 3}),
        UnanimousWrites({4, 5}),
        Grid([[1, 2], [3, 4]]),
    ],
)
def test_proto_roundtrip(qs):
    qs2 = from_proto(to_proto(qs))
    assert type(qs2) is type(qs)
    assert qs2.nodes() == qs.nodes()
    if isinstance(qs, Grid):
        assert qs2.grid == qs.grid


def test_read_write_intersection_property():
    # Every read quorum must intersect every write quorum.
    for qs in [
        SimpleMajority(set(range(7)), seed=1),
        UnanimousWrites(set(range(4)), seed=1),
        Grid([[0, 1, 2], [3, 4, 5], [6, 7, 8]], seed=1),
    ]:
        for _ in range(50):
            assert qs.random_read_quorum() & qs.random_write_quorum()
