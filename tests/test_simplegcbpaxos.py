"""Simple GC BPaxos sim tests: SimpleBPaxos behavior PLUS garbage
collection of proposer/acceptor/dep-index/replica state and snapshots."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport, wire
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import simplegcbpaxos as gc
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set
from test_epaxos import RecordingKv, _conflicting_order_violation


def make(f=1, num_clients=2, seed=0,
         watermark_every=2, snapshot_every=10 ** 9, dep_gc_every=4):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    n = 2 * f + 1
    config = gc.SimpleGcBPaxosConfig(
        f=f,
        leader_addresses=tuple(SimAddress(f"leader{i}") for i in range(f + 1)),
        proposer_addresses=tuple(
            SimAddress(f"proposer{i}") for i in range(f + 1)
        ),
        dep_service_node_addresses=tuple(
            SimAddress(f"dep{i}") for i in range(n)
        ),
        acceptor_addresses=tuple(SimAddress(f"acceptor{i}") for i in range(n)),
        replica_addresses=tuple(
            SimAddress(f"replica{i}") for i in range(f + 1)
        ),
        garbage_collector_addresses=tuple(
            SimAddress(f"gc{i}") for i in range(f + 1)
        ),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [
        gc.GcLeader(a, t, log(), config, seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    proposers = [
        gc.GcProposer(a, t, log(), config, seed=seed + 10 + i)
        for i, a in enumerate(config.proposer_addresses)
    ]
    deps = [
        gc.GcDepServiceNode(a, t, log(), config, KeyValueStore(),
                            garbage_collect_every_n_commands=dep_gc_every)
        for a in config.dep_service_node_addresses
    ]
    acceptors = [
        gc.GcAcceptor(a, t, log(), config)
        for a in config.acceptor_addresses
    ]
    options = gc.GcReplicaOptions(
        send_watermark_every_n_commands=watermark_every,
        send_snapshot_every_n_commands=snapshot_every,
    )
    replicas = [
        gc.GcReplica(a, t, log(), config, RecordingKv(), options,
                     seed=seed + 30 + i)
        for i, a in enumerate(config.replica_addresses)
    ]
    collectors = [
        gc.GcGarbageCollector(a, t, log(), config)
        for a in config.garbage_collector_addresses
    ]
    clients = [
        gc.GcClient(SimAddress(f"client{i}"), t, log(), config,
                    seed=seed + 50 + i)
        for i in range(num_clients)
    ]
    return t, config, leaders, proposers, deps, acceptors, replicas, clients


def drain(t, max_steps=200000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def pump(t, rounds=8, skip=lambda timer: False):
    drain(t)
    for _ in range(rounds):
        for timer in list(t.running_timers()):
            if not skip(timer):
                t.trigger_timer(timer.address, timer.name())
        drain(t)


def test_gcbpaxos_single_command():
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make()
    p = clients[0].propose(0, kv_set(("x", "1")))
    drain(t)
    assert p.done
    for r in replicas:
        assert r.state_machine.get() == {"x": "1"}


def test_gcbpaxos_conflicting_commands_converge():
    t, config, leaders, proposers, deps, acceptors, replicas, clients = \
        make(seed=4)
    p1 = clients[0].propose(0, kv_set(("x", "a")))
    p2 = clients[1].propose(0, kv_set(("x", "b")))
    rng = random.Random(5)
    for _ in range(4000):
        cmd = t.generate_command(rng)
        if cmd is None:
            break
        t.run_command(cmd, record=False)
    drain(t)
    assert p1.done and p2.done
    finals = {tuple(sorted(r.state_machine.get().items())) for r in replicas}
    assert len(finals) == 1, finals


def test_gcbpaxos_dependencies_are_compact():
    """After many non-conflicting commands through one leader, dependency
    sets stay small: contiguous vertex ids compress to a watermark."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make()
    for i in range(20):
        p = clients[0].propose(0, kv_set((f"k{i}", "v")))
        drain(t)
        assert p.done
    # The dep node's conflict answer for yet another write on the SAME key
    # space is a prefix, not 20 scattered ids.
    answer = deps[0].conflict_index.get_conflicts(kv_set(("k0", "z")))
    assert sum(len(s.values) for s in answer.sets) <= 2, answer


def test_gcbpaxos_proposer_and_acceptor_state_is_garbage_collected():
    """Replica frontiers flow through the GarbageCollector; proposers and
    acceptors drop chosen state below the f+1 watermark."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make()
    for i in range(12):
        p = clients[i % 2].propose(0, kv_set((f"k{i}", "v")))
        drain(t)
        assert p.done
    assert any(w > 0 for w in proposers[0].gc_watermark)
    for proposer in proposers:
        for vertex_id in proposer.states:
            assert vertex_id[1] >= proposer.gc_watermark[vertex_id[0]]
    for acceptor in acceptors:
        assert any(w > 0 for w in acceptor.gc_watermark)
        for vertex_id in acceptor.states:
            assert vertex_id[1] >= acceptor.gc_watermark[vertex_id[0]]


def test_gcbpaxos_gcd_vertex_recovery_ignored_by_proposer():
    """A Recover for a GC'd vertex is DROPPED by proposers (they can't
    propose below the watermark) — replicas answer instead."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make()
    for i in range(8):
        p = clients[0].propose(0, kv_set((f"k{i}", "v")))
        drain(t)
    gcd_vertex = (0, 0)
    assert proposers[0]._gcd(gcd_vertex)
    before = dict(proposers[0].states)
    proposers[0].receive(
        config.replica_addresses[0], gc.GcRecover(vertex_id=gcd_vertex)
    )
    drain(t)
    assert dict(proposers[0].states) == before


def test_gcbpaxos_snapshot_taken_and_catches_up_lagging_replica():
    """With snapshots enabled, a replica that missed a batch of commits
    recovers the GC'd vertices via CommitSnapshot from a peer and
    converges to the same state."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = \
        make(seed=9, snapshot_every=3)
    victim = config.replica_addresses[1]

    # Pin proposals to leader 0: replies for leader-0 vertices are striped
    # to replica 0, which is alive.
    class _L0:
        def randrange(self, n):
            return 0

    clients[0].rng = _L0()
    ps = []
    for i in range(10):
        ps.append(clients[0].propose(0, kv_set((f"k{i}", f"v{i}"))))
        while t.messages:
            m = t.messages[0]
            if m.dst == victim:
                t.drop_message(m)
            else:
                t.deliver_message(m)
    assert all(p.done for p in ps)
    assert replicas[0].snapshot is not None
    assert replicas[1].state_machine.get() == {}
    # A new command reaches replica 1: its deps are holes -> recover
    # timers -> peers answer with the snapshot + commits.
    p = clients[1].propose(0, kv_set(("final", "!")))
    pump(t, rounds=10)
    assert p.done
    assert replicas[1].state_machine.get() == replicas[0].state_machine.get()
    assert replicas[1].snapshot is not None
    assert replicas[1].snapshot.id == replicas[0].snapshot.id


def test_gcbpaxos_recovery_fills_stuck_vertex_with_noop():
    t, config, leaders, proposers, deps, acceptors, replicas, clients = \
        make(seed=7)

    class _L0:
        def randrange(self, n):
            return 0

    clients[0].rng = _L0()
    p1 = clients[0].propose(0, kv_set(("x", "1")))
    # Deliver dep requests/replies, then kill proposer 0 before phase 2.
    dead = config.proposer_addresses[0]
    while t.messages:
        m = t.messages[0]
        if m.dst == dead or m.src == dead:
            t.drop_message(m)
        else:
            t.deliver_message(m)
    t.partition_actor(dead)
    # A conflicting command through leader 1 picks up the stuck vertex as
    # a dependency; replica recovery proposes a noop through proposer 1.
    class _L1:
        def randrange(self, n):
            return 1

    clients[1].rng = _L1()
    p2 = clients[1].propose(0, kv_set(("x", "2")))
    pump(t, rounds=8, skip=lambda tm: tm.address == dead)
    assert p2.done


def test_gcbpaxos_snapshot_install_does_not_duplicate_history():
    """Regression: installing a snapshot re-executes unsnapshotted
    history; the loop must iterate a DETACHED list (execution appends to
    self.history), or entries double on every install."""
    from frankenpaxos_tpu.clienttable import ClientTable
    from frankenpaxos_tpu.statemachine import KeyValueStore as KV

    t, config, leaders, proposers, deps, acceptors, replicas, clients = \
        make(seed=21)

    class _L0:
        def randrange(self, n):
            return 0

    clients[0].rng = _L0()
    for i in range(4):
        p = clients[0].propose(0, kv_set((f"k{i}", "v")))
        drain(t)
        assert p.done
    replica = replicas[0]
    assert len(replica.history) == 4
    state_before = dict(replica.state_machine.get())
    # An empty snapshot (covers nothing) with a higher id: everything in
    # history is re-executed on top of the empty state.
    empty_table = ClientTable().to_proto(
        address_to_bytes=lambda ident: wire.encode(ident),
        output_to_bytes=lambda o: o,
    )
    replica.receive(
        config.replica_addresses[1],
        gc.GcCommitSnapshot(
            id=7,
            watermark=gc.VertexIdPrefixSet(config.num_leaders).to_tuple(),
            state_machine=KV().to_bytes(),
            client_table=empty_table,
        ),
    )
    drain(t)
    assert len(replica.history) == 4, replica.history
    assert dict(replica.state_machine.get()) == state_before


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    key: str
    value: str


class SimulatedGcBPaxos(SimulatedSystem):
    def __init__(self, f=1, snapshot_every=10 ** 9):
        self.f = f
        self.snapshot_every = snapshot_every
        self._kv = KeyValueStore()

    def new_system(self, seed):
        return make(self.f, seed=seed, snapshot_every=self.snapshot_every)

    def get_state(self, system):
        replicas = system[6]
        return tuple(
            tuple(r.state_machine.executed_commands) for r in replicas
        )

    def generate_command(self, system, rng):
        t = system[0]
        clients = system[7]
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (1, Propose(i, pseudonym, f"k{rng.randrange(2)}",
                                    f"v{rng.randrange(50)}"))
                    )
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t = system[0]
        clients = system[7]
        if isinstance(command, Propose):
            clients[command.client_index].propose(
                command.pseudonym, kv_set((command.key, command.value))
            )
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        class _H:
            pass

        fakes = []
        for log in state:
            sm = _H()
            sm.executed_commands = list(log)
            h = _H()
            h.state_machine = sm
            fakes.append(h)
        return _conflicting_order_violation(fakes, self._kv.conflicts)


@pytest.mark.parametrize("f", [1, 2])
def test_gcbpaxos_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedGcBPaxos(f), run_length=120, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_gcbpaxos_safety_randomized_with_snapshots():
    bad = simulate_and_minimize(
        SimulatedGcBPaxos(1, snapshot_every=3), run_length=150, num_runs=8,
        seed=99,
    )
    assert bad is None, f"\n{bad}"
