"""Tier-1 multi-chip smoke: the generic sharding layer
(``frankenpaxos_tpu/parallel/sharding.py``) runs the sharded flagship
AND the compartmentalized backend on the 8-virtual-device CPU mesh
(conftest sets ``--xla_force_host_platform_device_count=8``), with

  * per-device GROUP LOCALITY pinned as a compile-time fact — no
    collective moves signed (simulation-state) data beyond the small
    commit/watermark/histogram reductions,
  * seed-stable, sharded-vs-unsharded BIT-IDENTICAL results (integer
    psums are exact, so mesh size cannot change a single bit),
  * donation surviving GSPMD partitioning (single-buffered per shard)
    — with AND without the kernel planes engaged,
  * and the kernels x mesh COMPOSITION: a policy that engages the
    Pallas planes under a >1-device mesh lowers them per-device via
    ``jax.shard_map`` (3-seed sharded+kernels == unsharded+kernels ==
    reference, full state), while a plane declared non-shardable
    (no ShardSpec) stays a loud ``ValueError``, never a silent
    mis-lowering.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.parallel import sharding as sh
from frankenpaxos_tpu.tpu import compartmentalized_batched as cb
from frankenpaxos_tpu.tpu import epaxos_batched as eb
from frankenpaxos_tpu.tpu import multipaxos_batched as mb

# HLO collective census helpers shared with the flagship sharding tests.
from test_hlo_sharding import (
    _all_reduce_sizes,
    _prng_collective_sizes,
    _state_collectives,
)

_BIG = ("all-gather", "collective-permute", "all-to-all")


def _mesh(n=None):
    devices = jax.devices()
    return sh.make_mesh(devices[: n or len(devices)])


def _ccfg(**kw):
    return dataclasses.replace(
        cb.analysis_config(), num_groups=8, **kw
    )


def _compiled_sharded_text(backend, cfg, state_fn, mesh, ticks=40):
    # Default 40 ticks: the SAME (config, ticks) signature as the
    # bit-identity run below, so the census/donation tests reuse one
    # compiled 8-device program instead of paying a second GSPMD
    # compile (num_ticks is static — a new count is a new program).
    state = sh.shard_state(backend, state_fn(cfg), mesh)
    lowered = sh.lower_sharded(
        backend, cfg, mesh, state, jnp.zeros((), jnp.int32), ticks,
        jax.random.PRNGKey(0),
    )
    return lowered.compile().as_text()


def test_compartmentalized_write_and_read_paths_are_group_local():
    """The whole role pipeline — batchers, proxies, the [R, C, G, W]
    grid, replicas, unbatchers, read probes — partitions group-locally:
    no collective carries signed state, and every stat all-reduce is
    bounded by the LAT_BINS histogram."""
    cfg = _ccfg()
    txt = _compiled_sharded_text(
        "compartmentalized", cfg, cb.init_state, _mesh()
    )
    offenders = _state_collectives(txt, _BIG)
    assert not offenders, f"compartmentalized moved state: {offenders}"
    sizes = _all_reduce_sizes(txt)
    assert sizes, "stat reductions must exist (commit/watermark/hist)"
    assert all(s <= 64 for s in sizes), sizes
    # PRNG sweep assembly stays bounded by one tick's largest draw.
    R, C, G, W = (cfg.grid_rows, cfg.grid_cols, cfg.num_groups, cfg.window)
    assert all(s <= R * C * G * W for s in _prng_collective_sizes(txt))


def test_flagship_via_generic_registry_is_group_local():
    """The registry-driven wrapper compiles the flagship write path
    with the same zero-state-movement property the legacy wrapper had
    (exact config + tick count of test_hlo_sharding's write-path test,
    so the two files share one compiled program)."""
    cfg = mb.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, drop_rate=0.1,
        retry_timeout=8,
    )
    txt = _compiled_sharded_text("multipaxos", cfg, mb.init_state,
                                 _mesh(), ticks=4)
    offenders = _state_collectives(txt, _BIG)
    assert not offenders, f"flagship moved state: {offenders}"
    assert all(s <= 64 for s in _all_reduce_sizes(txt))


def test_donation_aliases_survive_the_mesh():
    """Sharded donation stays single-buffered: the compiled sharded
    module aliases every donated State leaf (double-buffering under a
    mesh would pay 2x HBM on EVERY device)."""
    from frankenpaxos_tpu.analysis.rules_trace import _alias_param_indices

    cfg = _ccfg()
    state = cb.init_state(cfg)
    n_leaves = len(jax.tree_util.tree_leaves(state))
    txt = _compiled_sharded_text(
        "compartmentalized", cfg, cb.init_state, _mesh()
    )
    aliased = _alias_param_indices(txt)
    missing = sorted(set(range(n_leaves)) - aliased)
    assert not missing, f"unaliased sharded State leaves: {missing}"


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_vs_unsharded_bit_identity(seed):
    """8-device sharded run == unsharded run, bit for bit, per seed —
    and the sharded run is seed-stable across invocations."""
    cfg = _ccfg()
    mesh = _mesh()
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)

    st = sh.shard_state("compartmentalized", cb.init_state(cfg), mesh)
    st, t = sh.run_ticks_sharded(
        "compartmentalized", cfg, mesh, st, t0, 40, key
    )
    jax.block_until_ready(st)

    st2 = sh.shard_state("compartmentalized", cb.init_state(cfg), mesh)
    st2, _ = sh.run_ticks_sharded(
        "compartmentalized", cfg, mesh, st2, t0, 40, key
    )
    assert int(st.committed) == int(st2.committed)  # seed-stable

    ust, _ = cb.run_ticks(cfg, cb.init_state(cfg), t0, 40, key)
    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(ust)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_kernel_policy_sharded_mesh1_bit_identity():
    """Mesh of ONE device: any kernel policy is allowed, and the
    sharded wrapper with the kernels ENGAGED (interpret mode — the
    actual kernel path, executable on CPU) replays the unsharded run
    bit for bit."""
    cfg = dataclasses.replace(
        mb.analysis_config(), kernels=KernelPolicy(mode="interpret")
    )
    mesh1 = sh.make_mesh(jax.devices()[:1])
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)
    st = sh.shard_state("multipaxos", mb.init_state(cfg), mesh1)
    st, _ = sh.run_ticks_sharded("multipaxos", cfg, mesh1, st, t0, 3, key)
    ust, _ = mb.run_ticks(cfg, mb.init_state(cfg), t0, 3, key)
    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(ust)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_kernel_policy_mesh_gt1_validates_shardable_planes():
    """Engaged planes WITH a ShardSpec now validate under a >1-device
    mesh (the shard_map composition layer lowers them per-device);
    only a plane declared NON-shardable still raises — loudly, never a
    silent mis-lowering."""
    mesh = _mesh()
    engaged = dataclasses.replace(
        mb.analysis_config(), num_groups=8,
        kernels=KernelPolicy(mode="interpret"),
    )
    sh.validate_policy("multipaxos", engaged, mesh)
    legacy = dataclasses.replace(
        mb.analysis_config(), num_groups=8, use_pallas=True
    )
    sh.validate_policy("multipaxos", legacy, mesh)
    sh.validate_policy("multipaxos",
                       dataclasses.replace(mb.analysis_config(),
                                           num_groups=8), mesh)
    sh.validate_policy("compartmentalized", _ccfg(), mesh)
    sh.validate_policy(
        "compartmentalized",
        dataclasses.replace(_ccfg(), kernels=KernelPolicy(mode="interpret")),
        mesh,
    )


def test_non_shardable_plane_mesh_gt1_is_a_validation_error(monkeypatch):
    """Strip one plane's ShardSpec: engaging it under a mesh must be a
    ValueError again (the guard retired for shardable planes, not for
    cross-group ones)."""
    mesh = _mesh()
    plane = ops_registry.PLANES["multipaxos_vote_quorum"]
    monkeypatch.setitem(
        ops_registry.PLANES,
        "multipaxos_vote_quorum",
        dataclasses.replace(plane, shard=None),
    )
    bad = dataclasses.replace(
        mb.analysis_config(), num_groups=8,
        kernels=KernelPolicy(mode="interpret"),
    )
    with pytest.raises(ValueError, match="non-shardable"):
        sh.validate_policy("multipaxos", bad, mesh)
    # Disabling the stripped plane (and the megakernel that subsumes
    # it) restores validity: the remaining engaged planes all shard.
    ok = dataclasses.replace(
        bad,
        kernels=KernelPolicy(
            mode="interpret",
            disable=("multipaxos_vote_quorum", "multipaxos_fused_tick"),
        ),
    )
    sh.validate_policy("multipaxos", ok, mesh)


# ---------------------------------------------------------------------------
# Kernels x mesh composition: every (backend x kernels on/off) cell is
# 3-seed full-state bit-identical — sharded+kernels == unsharded+kernels
# == sharded reference. epaxos has no registered planes, so its kernels
# cell degenerates to the reference program (still pinned 3-seed).
# ---------------------------------------------------------------------------


def _full_leaves(st):
    return jax.tree_util.tree_leaves(st)


def _assert_states_equal(a, b):
    for x, y in zip(_full_leaves(a), _full_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _cell(backend):
    if backend == "multipaxos":
        mod = mb
        cfg = dataclasses.replace(mb.analysis_config(), num_groups=8)
    elif backend == "compartmentalized":
        mod = cb
        cfg = _ccfg()
    else:
        mod = eb
        cfg = dataclasses.replace(eb.analysis_config(), num_columns=8)
    return mod, cfg


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("backend", ["multipaxos", "compartmentalized"])
def test_sharded_kernels_bit_identity(backend, seed):
    """The flagship acceptance cell: mesh>1 with the KernelPolicy
    ENGAGED (interpret — the actual shard_map-lowered kernel path,
    executable on CPU; for multipaxos this includes the
    multipaxos_fused_tick megakernel) compiles, runs, and replays both
    the unsharded kernel run and the sharded reference bit for bit,
    full state including the telemetry ring."""
    mod, base = _cell(backend)
    mesh = _mesh()
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)
    cfg_k = dataclasses.replace(base, kernels=KernelPolicy(mode="interpret"))
    cfg_r = dataclasses.replace(base, kernels=KernelPolicy.reference())

    st = sh.shard_state(backend, mod.init_state(cfg_k), mesh)
    st, _ = sh.run_ticks_sharded(backend, cfg_k, mesh, st, t0, 20, key)
    assert int(st.committed) > 0

    ust, _ = mod.run_ticks(cfg_k, mod.init_state(cfg_k), t0, 20, key)
    _assert_states_equal(st, ust)

    rst = sh.shard_state(backend, mod.init_state(cfg_r), mesh)
    rst, _ = sh.run_ticks_sharded(backend, cfg_r, mesh, rst, t0, 20, key)
    _assert_states_equal(st, rst)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bpaxos_sharded_lane_bit_identity(seed):
    """bpaxos is LANE-sharded: the [L, ...] rings and the lane-major
    packed adjacency split over the leader axis, the per-replica views
    on their second axis. Sharded == unsharded bit for bit per seed,
    full state including the dependency graph."""
    from frankenpaxos_tpu.tpu import bpaxos_batched as bp

    cfg = dataclasses.replace(bp.analysis_config(), num_leaders=8)
    mesh = _mesh()
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)
    st = sh.shard_state("bpaxos", bp.init_state(cfg), mesh)
    st, _ = sh.run_ticks_sharded("bpaxos", cfg, mesh, st, t0, 24, key)
    assert int(st.committed_total) > 0
    ust, _ = bp.run_ticks(cfg, bp.init_state(cfg), t0, 24, key)
    _assert_states_equal(st, ust)


def test_bpaxos_lane_planes_are_lane_sharded():
    """The registered bpaxos layout: lane rings and adjacency rows ride
    the group axis, replica views shard their SECOND axis, stats and
    telemetry replicate."""
    from jax.sharding import PartitionSpec as P

    mesh = _mesh()
    specs = sh.state_shardings("bpaxos", mesh)
    for f in ("next_cmd", "gc_head", "proposed", "committed", "adj"):
        assert specs[f].spec == P(sh.GROUP_AXIS), f
    for f in ("head_r", "rep_commit_tick"):
        assert specs[f].spec == P(None, sh.GROUP_AXIS), f
    for f in ("committed_total", "lat_hist", "telemetry"):
        assert specs[f].spec == P(), f


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_epaxos_sharded_cell_bit_identity(seed):
    """epaxos rides the registry with no registered planes: the
    kernels-on and kernels-off cells are the same program; sharded ==
    unsharded per seed."""
    mod, cfg = _cell("epaxos")
    mesh = _mesh()
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)
    st = sh.shard_state("epaxos", mod.init_state(cfg), mesh)
    st, _ = sh.run_ticks_sharded("epaxos", cfg, mesh, st, t0, 20, key)
    ust, _ = mod.run_ticks(cfg, mod.init_state(cfg), t0, 20, key)
    _assert_states_equal(st, ust)


@pytest.mark.parametrize("backend", ["multipaxos", "compartmentalized"])
def test_donation_survives_mesh_with_kernels_engaged(backend):
    """Donation under the mesh with the shard_map-lowered kernels
    live: the compiled kernels-engaged sharded module still aliases
    every donated State leaf (the kernel lowering must not break
    single-buffering)."""
    from frankenpaxos_tpu.analysis.rules_trace import _alias_param_indices

    mod, base = _cell(backend)
    cfg = dataclasses.replace(base, kernels=KernelPolicy(mode="interpret"))
    mesh = _mesh()
    state = sh.shard_state(backend, mod.init_state(cfg), mesh)
    n_leaves = len(_full_leaves(state))
    txt = sh.lower_sharded(
        backend, cfg, mesh, state, jnp.zeros((), jnp.int32), 20,
        jax.random.PRNGKey(0),
    ).compile().as_text()
    aliased = _alias_param_indices(txt)
    missing = sorted(set(range(n_leaves)) - aliased)
    assert not missing, f"unaliased sharded State leaves: {missing}"


def test_axis_divisibility_is_checked():
    with pytest.raises(ValueError, match="divisible by the mesh size"):
        sh.shard_state(
            "compartmentalized",
            cb.init_state(dataclasses.replace(cb.analysis_config(),
                                              num_groups=6)),
            _mesh(4),
        )


def test_registry_covers_the_sharded_families():
    assert set(sh.SHARDINGS) >= {"multipaxos", "epaxos", "compartmentalized"}
    for spec in sh.SHARDINGS.values():
        # Every spec resolves its module and builds shardings.
        shardings = sh.state_shardings(spec.backend, _mesh())
        assert shardings


# ---------------------------------------------------------------------------
# Million-session layout (PR 16): the session table + workload lane
# bookkeeping partition over the groups axis instead of replicating.
# ---------------------------------------------------------------------------


def _session_cfg(**kw):
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    kw.setdefault(
        "workload", WorkloadPlan(arrival="constant", rate=1.5, zipf_s=0.8)
    )
    return mb.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, retry_timeout=8,
        pack_planes=True,
        lifecycle=LifecyclePlan(sessions=8, resubmit_rate=0.15),
        **kw,
    )


def test_session_lane_state_is_group_sharded():
    """The [L, S] session table (packed occupancy included) and the
    workload engine's per-lane bookkeeping land P('groups') on the
    mesh — NOT replicated (replication is what caps the distinct-
    session count at one device's HBM). Non-lane client leaves (the
    traced rate scalar, the rotation books) stay replicated."""
    cfg = _session_cfg()
    mesh = _mesh()
    st = sh.shard_state("multipaxos", mb.init_state(cfg), mesh)

    def spec(leaf):
        return tuple(leaf.sharding.spec)

    assert sh.GROUP_AXIS in spec(st.lifecycle.sess_last)
    assert sh.GROUP_AXIS in spec(st.lifecycle.sess_res)
    assert sh.GROUP_AXIS in spec(st.lifecycle.sess_occ)
    assert sh.GROUP_AXIS in spec(st.workload.backlog)
    assert sh.GROUP_AXIS in spec(st.workload.adm_total)
    assert sh.GROUP_AXIS not in spec(st.lifecycle.rot_count)
    assert sh.GROUP_AXIS not in spec(st.workload.rate)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_sessions_bit_identity(seed):
    """Sharded sessions + packed planes == the unsharded run, bit for
    bit, across TWO segments (the output constraint keeps the layout
    pinned between executables) — exactly-once books included."""
    cfg = _session_cfg()
    mesh = _mesh()
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)

    st = sh.shard_state("multipaxos", mb.init_state(cfg), mesh)
    st, t = sh.run_ticks_sharded("multipaxos", cfg, mesh, st, t0, 30, key)
    st, t = sh.run_ticks_sharded("multipaxos", cfg, mesh, st, t, 30, key)

    ust = mb.init_state(cfg)
    ust, ut = mb.run_ticks(cfg, ust, t0, 30, key)
    ust, ut = mb.run_ticks(cfg, ust, ut, 30, key)

    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(ust)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(st.lifecycle.cache_hits) > 0  # exactly-once exercised


def test_sharded_trace_mode_checkpoint_resume_bit_exact(tmp_path):
    """The full PR 16 composition: a trace-driven open-loop run with
    packed planes and group-sharded sessions checkpoints mid-flight
    (PR 13 subsystem) and resumes to the uninterrupted twin's exact
    bits — the cursor, the session table, and every protocol plane."""
    from frankenpaxos_tpu.tpu import checkpoint as ck
    from frankenpaxos_tpu.tpu import packing
    from frankenpaxos_tpu.tpu import workload as workload_mod
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    n = 64
    rng = np.random.default_rng(5)
    words = packing.encode_trace(
        np.sort(rng.integers(0, 40, size=n)).astype(np.int64),
        rng.integers(0, 8, size=n).astype(np.int64),
    )
    cfg = _session_cfg(
        workload=WorkloadPlan(arrival="trace", trace_len=n)
    )
    mesh = _mesh()
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)

    def fresh():
        st = mb.init_state(cfg)
        st = dataclasses.replace(
            st, workload=workload_mod.load_trace(st.workload, words)
        )
        return sh.shard_state("multipaxos", st, mesh)

    # Uninterrupted twin: two 30-tick segments.
    tw, tt = sh.run_ticks_sharded(
        "multipaxos", cfg, mesh, fresh(), t0, 30, key
    )
    tw, tt = sh.run_ticks_sharded("multipaxos", cfg, mesh, tw, tt, 30, key)

    # Checkpointed run: segment, save, restore into a FRESH sharded
    # state, second segment.
    st, t = sh.run_ticks_sharded(
        "multipaxos", cfg, mesh, fresh(), t0, 30, key
    )
    d = str(tmp_path / "ck")
    ck.save_state(d, mb, cfg, st, t, step=0)
    restored, t_r, _ = ck.restore_state(d, mb, cfg, fresh())
    restored = sh.shard_state("multipaxos", restored, mesh)
    restored, t_r = sh.run_ticks_sharded(
        "multipaxos", cfg, mesh, restored, t_r, 30, key
    )

    assert int(t_r) == int(tt)
    for a, b in zip(
        jax.tree_util.tree_leaves(restored), jax.tree_util.tree_leaves(tw)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.workload.trace_cursor) == n
