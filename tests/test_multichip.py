"""Tier-1 multi-chip smoke: the generic sharding layer
(``frankenpaxos_tpu/parallel/sharding.py``) runs the sharded flagship
AND the compartmentalized backend on the 8-virtual-device CPU mesh
(conftest sets ``--xla_force_host_platform_device_count=8``), with

  * per-device GROUP LOCALITY pinned as a compile-time fact — no
    collective moves signed (simulation-state) data beyond the small
    commit/watermark/histogram reductions,
  * seed-stable, sharded-vs-unsharded BIT-IDENTICAL results (integer
    psums are exact, so mesh size cannot change a single bit),
  * donation surviving GSPMD partitioning (single-buffered per shard),
  * and the KernelPolicy x mesh validation: a policy that would lower
    Pallas inside a >1-device mesh is a loud ``ValueError``, never a
    silent mis-lowering; at mesh=1 the engaged kernels stay
    bit-identical to the unsharded run.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.parallel import sharding as sh
from frankenpaxos_tpu.tpu import compartmentalized_batched as cb
from frankenpaxos_tpu.tpu import multipaxos_batched as mb

# HLO collective census helpers shared with the flagship sharding tests.
from test_hlo_sharding import (
    _all_reduce_sizes,
    _prng_collective_sizes,
    _state_collectives,
)

_BIG = ("all-gather", "collective-permute", "all-to-all")


def _mesh(n=None):
    devices = jax.devices()
    return sh.make_mesh(devices[: n or len(devices)])


def _ccfg(**kw):
    return dataclasses.replace(
        cb.analysis_config(), num_groups=8, **kw
    )


def _compiled_sharded_text(backend, cfg, state_fn, mesh, ticks=40):
    # Default 40 ticks: the SAME (config, ticks) signature as the
    # bit-identity run below, so the census/donation tests reuse one
    # compiled 8-device program instead of paying a second GSPMD
    # compile (num_ticks is static — a new count is a new program).
    state = sh.shard_state(backend, state_fn(cfg), mesh)
    lowered = sh.lower_sharded(
        backend, cfg, mesh, state, jnp.zeros((), jnp.int32), ticks,
        jax.random.PRNGKey(0),
    )
    return lowered.compile().as_text()


def test_compartmentalized_write_and_read_paths_are_group_local():
    """The whole role pipeline — batchers, proxies, the [R, C, G, W]
    grid, replicas, unbatchers, read probes — partitions group-locally:
    no collective carries signed state, and every stat all-reduce is
    bounded by the LAT_BINS histogram."""
    cfg = _ccfg()
    txt = _compiled_sharded_text(
        "compartmentalized", cfg, cb.init_state, _mesh()
    )
    offenders = _state_collectives(txt, _BIG)
    assert not offenders, f"compartmentalized moved state: {offenders}"
    sizes = _all_reduce_sizes(txt)
    assert sizes, "stat reductions must exist (commit/watermark/hist)"
    assert all(s <= 64 for s in sizes), sizes
    # PRNG sweep assembly stays bounded by one tick's largest draw.
    R, C, G, W = (cfg.grid_rows, cfg.grid_cols, cfg.num_groups, cfg.window)
    assert all(s <= R * C * G * W for s in _prng_collective_sizes(txt))


def test_flagship_via_generic_registry_is_group_local():
    """The registry-driven wrapper compiles the flagship write path
    with the same zero-state-movement property the legacy wrapper had
    (exact config + tick count of test_hlo_sharding's write-path test,
    so the two files share one compiled program)."""
    cfg = mb.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, drop_rate=0.1,
        retry_timeout=8,
    )
    txt = _compiled_sharded_text("multipaxos", cfg, mb.init_state,
                                 _mesh(), ticks=4)
    offenders = _state_collectives(txt, _BIG)
    assert not offenders, f"flagship moved state: {offenders}"
    assert all(s <= 64 for s in _all_reduce_sizes(txt))


def test_donation_aliases_survive_the_mesh():
    """Sharded donation stays single-buffered: the compiled sharded
    module aliases every donated State leaf (double-buffering under a
    mesh would pay 2x HBM on EVERY device)."""
    from frankenpaxos_tpu.analysis.rules_trace import _alias_param_indices

    cfg = _ccfg()
    state = cb.init_state(cfg)
    n_leaves = len(jax.tree_util.tree_leaves(state))
    txt = _compiled_sharded_text(
        "compartmentalized", cfg, cb.init_state, _mesh()
    )
    aliased = _alias_param_indices(txt)
    missing = sorted(set(range(n_leaves)) - aliased)
    assert not missing, f"unaliased sharded State leaves: {missing}"


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_vs_unsharded_bit_identity(seed):
    """8-device sharded run == unsharded run, bit for bit, per seed —
    and the sharded run is seed-stable across invocations."""
    cfg = _ccfg()
    mesh = _mesh()
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)

    st = sh.shard_state("compartmentalized", cb.init_state(cfg), mesh)
    st, t = sh.run_ticks_sharded(
        "compartmentalized", cfg, mesh, st, t0, 40, key
    )
    jax.block_until_ready(st)

    st2 = sh.shard_state("compartmentalized", cb.init_state(cfg), mesh)
    st2, _ = sh.run_ticks_sharded(
        "compartmentalized", cfg, mesh, st2, t0, 40, key
    )
    assert int(st.committed) == int(st2.committed)  # seed-stable

    ust, _ = cb.run_ticks(cfg, cb.init_state(cfg), t0, 40, key)
    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(ust)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_kernel_policy_sharded_mesh1_bit_identity():
    """Mesh of ONE device: any kernel policy is allowed, and the
    sharded wrapper with the kernels ENGAGED (interpret mode — the
    actual kernel path, executable on CPU) replays the unsharded run
    bit for bit."""
    cfg = dataclasses.replace(
        mb.analysis_config(), kernels=KernelPolicy(mode="interpret")
    )
    mesh1 = sh.make_mesh(jax.devices()[:1])
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)
    st = sh.shard_state("multipaxos", mb.init_state(cfg), mesh1)
    st, _ = sh.run_ticks_sharded("multipaxos", cfg, mesh1, st, t0, 3, key)
    ust, _ = mb.run_ticks(cfg, mb.init_state(cfg), t0, 3, key)
    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(ust)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_kernel_policy_mesh_gt1_is_a_validation_error():
    """A policy that resolves any plane off the reference path under a
    >1-device mesh raises instead of silently mis-lowering the Pallas
    body. The default auto policy resolves to the reference twins on
    CPU, so it passes."""
    mesh = _mesh()
    bad = dataclasses.replace(
        mb.analysis_config(), num_groups=8,
        kernels=KernelPolicy(mode="interpret"),
    )
    with pytest.raises(ValueError, match="SPMD partitioning rule"):
        sh.validate_policy("multipaxos", bad, mesh)
    legacy = dataclasses.replace(
        mb.analysis_config(), num_groups=8, use_pallas=True
    )
    with pytest.raises(ValueError, match="SPMD partitioning rule"):
        sh.validate_policy("multipaxos", legacy, mesh)
    ok = dataclasses.replace(mb.analysis_config(), num_groups=8)
    sh.validate_policy("multipaxos", ok, mesh)  # auto -> reference on CPU
    sh.validate_policy("compartmentalized", _ccfg(), mesh)


def test_axis_divisibility_is_checked():
    with pytest.raises(ValueError, match="divisible by the mesh size"):
        sh.shard_state(
            "compartmentalized",
            cb.init_state(dataclasses.replace(cb.analysis_config(),
                                              num_groups=6)),
            _mesh(4),
        )


def test_registry_covers_the_sharded_families():
    assert set(sh.SHARDINGS) >= {"multipaxos", "epaxos", "compartmentalized"}
    for spec in sh.SHARDINGS.values():
        # Every spec resolves its module and builds shardings.
        shardings = sh.state_shardings(spec.backend, _mesh())
        assert shardings
