"""Horizontal MultiPaxos sim tests: chunked log, in-log reconfiguration
taking effect at slot + alpha, failover across chunk boundaries, and
randomized safety."""

import dataclasses

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import horizontal as hz
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import ReadableAppendLog


class Cluster:
    def __init__(self, seed=0, f=1, num_clients=2, num_acceptors=None,
                 alpha=4):
        num_acceptors = num_acceptors or 2 * f + 2  # one spare
        self.transport = SimTransport(FakeLogger(LogLevel.FATAL))
        t = self.transport
        self.config = hz.HorizontalConfig(
            f=f,
            leader_addresses=tuple(
                SimAddress(f"leader{i}") for i in range(f + 1)
            ),
            leader_election_addresses=tuple(
                SimAddress(f"election{i}") for i in range(f + 1)
            ),
            acceptor_addresses=tuple(
                SimAddress(f"acceptor{i}") for i in range(num_acceptors)
            ),
            replica_addresses=tuple(
                SimAddress(f"replica{i}") for i in range(f + 1)
            ),
        )
        log = lambda: FakeLogger(LogLevel.FATAL)
        options = hz.HzLeaderOptions(alpha=alpha)
        self.leaders = [
            hz.HzLeader(a, t, log(), self.config, options, seed=seed + i)
            for i, a in enumerate(self.config.leader_addresses)
        ]
        self.acceptors = [
            hz.HzAcceptor(a, t, log(), self.config)
            for a in self.config.acceptor_addresses
        ]
        self.replicas = [
            hz.HzReplica(a, t, log(), self.config, ReadableAppendLog(),
                         seed=seed + 30 + i)
            for i, a in enumerate(self.config.replica_addresses)
        ]
        self.clients = [
            hz.HzClient(SimAddress(f"client{i}"), t, log(), self.config,
                        seed=seed + 50 + i)
            for i in range(num_clients)
        ]
        self.driver = hz.HzDriver(
            SimAddress("driver"), t, log(), self.config, seed=seed + 99
        )

    def drain(self, max_steps=300000):
        steps = 0
        t = self.transport
        while t.messages and steps < max_steps:
            t.deliver_message(t.messages[0])
            steps += 1
        assert steps < max_steps

    def pump(self, rounds=8, skip=lambda timer: False):
        infra = set(self.config.leader_election_addresses)
        self.drain()
        for _ in range(rounds):
            for timer in list(self.transport.running_timers()):
                if timer.address not in infra and not skip(timer):
                    self.transport.trigger_timer(timer.address, timer.name())
            self.drain()


def test_hz_single_command():
    cluster = Cluster()
    cluster.drain()  # leader 0's initial chunk phase 1
    p = cluster.clients[0].propose(0, b"hello")
    cluster.drain()
    assert p.done
    for r in cluster.replicas:
        assert r.state_machine.log == [b"hello"]


def test_hz_sequential_commands():
    cluster = Cluster(seed=3, alpha=8)
    cluster.drain()
    for i in range(10):
        p = cluster.clients[i % 2].propose(i // 2, f"c{i}".encode())
        cluster.drain()
        assert p.done, i
    for r in cluster.replicas:
        assert r.state_machine.log == [f"c{i}".encode() for i in range(10)]


def test_hz_reconfiguration_takes_effect_at_alpha():
    """A chosen Configuration at slot s opens a new chunk at s + alpha;
    commands keep flowing across the chunk boundary on the new quorum."""
    cluster = Cluster(seed=5, alpha=4)
    cluster.drain()
    p = cluster.clients[0].propose(0, b"w0")
    cluster.drain()
    assert p.done
    # Reconfigure to {1, 2, 3}; chosen at slot 1 -> new chunk at slot 5.
    cluster.driver.force_reconfiguration(members=(1, 2, 3))
    cluster.drain()
    leader = cluster.leaders[0]
    assert leader.active_first_slots[-1] == 1 + 4
    assert len(leader.state.chunks) == 2
    assert leader.state.chunks[1].quorum.nodes() == frozenset({1, 2, 3})
    assert leader.state.chunks[0].last_slot == 1 + 4 - 1
    # Fill the boundary: slots 2-4 in the old chunk, 5+ in the new one.
    for i in range(6):
        p = cluster.clients[i % 2].propose(1 + i // 2, f"x{i}".encode())
        cluster.drain()
        assert p.done, i
    # The old chunk is now defunct and pruned.
    assert len(leader.state.chunks) == 1
    assert leader.state.chunks[0].first_slot == 5
    # Votes for slots >= 5 live only on the new quorum members.
    for slot, (first_slot, _, _) in cluster.acceptors[0].states.items():
        assert slot < 5, "acceptor 0 voted in the new chunk"
    for r in cluster.replicas:
        assert len(r.state_machine.log) == 7


def test_hz_alpha_bounds_pipeline():
    """At most alpha commands may sit past the chosen watermark: extra
    proposals are dropped and recovered by client resends."""
    cluster = Cluster(seed=7, alpha=2)
    cluster.drain()
    # Propose 4 commands without delivering anything: only 2 slots may
    # receive phase2as.
    ps = [cluster.clients[0].propose(i, f"c{i}".encode()) for i in range(4)]
    leader = cluster.leaders[0]
    chunk = leader.state.chunks[0]
    assert len(chunk.phase.values) <= 2
    cluster.pump(rounds=6)
    assert all(p.done for p in ps)


def test_hz_failover_into_current_chunk():
    """After a reconfiguration, a new leader starts its chunk at the
    FIRST ACTIVE chunk's slot with that chunk's configuration — chosen
    commands survive, and new commands commit on the new quorum."""
    cluster = Cluster(seed=9, alpha=4)
    cluster.drain()
    p = cluster.clients[0].propose(0, b"pre")
    cluster.drain()
    assert p.done
    cluster.driver.force_reconfiguration(members=(1, 2, 3))
    cluster.drain()
    # Choose enough commands to pass the boundary (slot 5).
    for i in range(5):
        p = cluster.clients[0].propose(1 + i, f"f{i}".encode())
        cluster.drain()
        assert p.done
    # Leader 0 dies; leader 1 takes over.
    dead = cluster.config.leader_addresses[0]
    cluster.transport.partition_actor(dead)
    cluster.transport.partition_actor(
        cluster.config.leader_election_addresses[0]
    )
    cluster.leaders[1]._on_election(1)
    cluster.pump(skip=lambda tm: tm.address == dead)
    leader1 = cluster.leaders[1]
    assert isinstance(leader1.state, hz._HzActive)
    assert leader1.state.chunks[0].quorum.nodes() == frozenset({1, 2, 3})
    p2 = cluster.clients[1].propose(0, b"post")
    cluster.pump(skip=lambda tm: tm.address == dead)
    assert p2.done
    assert cluster.replicas[0].state_machine.log[-1] == b"post"


def test_hz_dropped_chosen_recovered_by_replicas():
    cluster = Cluster(seed=11)
    cluster.drain()
    victim = cluster.config.replica_addresses[1]
    t = cluster.transport
    p = cluster.clients[0].propose(0, b"lost")
    while t.messages:
        m = t.messages[0]
        if m.dst == victim:
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert p.done
    assert cluster.replicas[1].state_machine.log == []
    p2 = cluster.clients[0].propose(0, b"next")
    cluster.pump(rounds=6)
    assert p2.done
    assert cluster.replicas[1].state_machine.log == [b"lost", b"next"]


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    value: str


@dataclasses.dataclass(frozen=True)
class Reconfigure:
    members: tuple


class SimulatedHz(SimulatedSystem):
    def __init__(self, f=1, reconfigure=True, alpha=4):
        self.f = f
        self.reconfigure = reconfigure
        self.alpha = alpha

    def new_system(self, seed):
        cluster = Cluster(seed=seed, f=self.f, alpha=self.alpha)
        cluster.drain()
        return cluster

    def get_state(self, system):
        return tuple(
            tuple(r.state_machine.log) for r in system.replicas
        )

    def generate_command(self, system, rng):
        ops = []
        for i, c in enumerate(system.clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (2, Propose(i, pseudonym, f"v{rng.randrange(100)}"))
                    )
        if self.reconfigure:
            n = len(system.config.acceptor_addresses)
            ops.append((1, Reconfigure(
                tuple(rng.sample(range(n), 2 * self.f + 1))
            )))
        return mixed_command(rng, system.transport, ops)

    def run_command(self, system, command):
        if isinstance(command, Propose):
            system.clients[command.client_index].propose(
                command.pseudonym, command.value.encode()
            )
        elif isinstance(command, Reconfigure):
            system.driver.force_reconfiguration(members=command.members)
        else:
            system.transport.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        for i in range(len(state)):
            for j in range(i + 1, len(state)):
                a, b = state[i], state[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return f"replica logs diverge: {a!r} vs {b!r}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if n[: len(o)] != o:
                return f"replica log rewrote history: {o!r} -> {n!r}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_hz_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedHz(f), run_length=150, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_hz_safety_randomized_small_alpha():
    bad = simulate_and_minimize(
        SimulatedHz(1, alpha=2), run_length=150, num_runs=8, seed=31
    )
    assert bad is None, f"\n{bad}"
