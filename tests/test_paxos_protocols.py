"""Sim tests of echo, unreplicated, and single-decree paxos."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import (
    DeliverMessage,
    FakeLogger,
    SimAddress,
    SimTransport,
    TriggerTimer,
)
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import paxos as px
from frankenpaxos_tpu.protocols import unreplicated as unrep
from frankenpaxos_tpu.protocols.echo import EchoClient, EchoServer
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import AppendLog


def drain(t, max_steps=50000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def test_echo():
    t = SimTransport(FakeLogger())
    server_addr, client_addr = SimAddress("server"), SimAddress("client")
    server = EchoServer(server_addr, t, FakeLogger())
    client = EchoClient(client_addr, t, FakeLogger(), server_addr)
    client.echo("hello")
    t.trigger_timer(client_addr, "pingTimer")
    drain(t)
    assert server.num_messages_received == 2
    assert client.num_messages_received == 2


def test_unreplicated_exactly_once():
    t = SimTransport(FakeLogger())
    server_addr, client_addr = SimAddress("server"), SimAddress("client")
    sm = AppendLog()
    unrep.Server(server_addr, t, FakeLogger(), sm)
    client = unrep.Client(client_addr, t, FakeLogger(), server_addr)
    p1 = client.propose(0, b"a")
    p2 = client.propose(1, b"b")
    # Force a resend (duplicates the request in flight).
    t.trigger_timer(client_addr, "resendClientRequest0")
    drain(t)
    assert p1.done and p2.done
    assert sm.log == [b"a", b"b"] or sm.log == [b"b", b"a"]  # executed once each
    # A second write on pseudonym 0 works after the first completes.
    p3 = client.propose(0, b"c")
    drain(t)
    assert p3.done and sm.log.count(b"c") == 1


def make_paxos(f=1, seed=0):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    config = px.PaxosConfig(
        f=f,
        leader_addresses=tuple(SimAddress(f"leader{i}") for i in range(f + 1)),
        acceptor_addresses=tuple(
            SimAddress(f"acceptor{i}") for i in range(2 * f + 1)
        ),
    )
    leaders = [
        px.PaxosLeader(a, t, FakeLogger(LogLevel.FATAL), config, seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    acceptors = [
        px.PaxosAcceptor(a, t, FakeLogger(LogLevel.FATAL), config)
        for a in config.acceptor_addresses
    ]
    clients = [
        px.PaxosClient(SimAddress(f"client{i}"), t, FakeLogger(LogLevel.FATAL), config)
        for i in range(2)
    ]
    return t, config, leaders, acceptors, clients


def test_paxos_chooses_one_value_happy_path():
    t, config, leaders, acceptors, clients = make_paxos()
    p = clients[0].propose("apple")
    drain(t)
    assert p.done and p.result() == "apple"
    assert clients[0].chosen == "apple"


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    value: str


class SimulatedPaxos(SimulatedSystem):
    """Invariant: every chosen value across clients+leaders is the same."""

    def __init__(self, f=1):
        self.f = f

    def new_system(self, seed):
        return make_paxos(self.f, seed)

    def get_state(self, system):
        t, config, leaders, acceptors, clients = system
        return tuple(c.chosen for c in clients) + tuple(l.chosen for l in leaders)

    def generate_command(self, system, rng):
        t, config, leaders, acceptors, clients = system
        ops = [
            (1, Propose(i, f"value{i}"))
            for i, c in enumerate(clients)
            if c.promise is None and c.chosen is None
        ]
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, config, leaders, acceptors, clients = system
        if isinstance(command, Propose):
            clients[command.client_index].propose(command.value)
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        chosen = {v for v in state if v is not None}
        if len(chosen) > 1:
            return f"multiple values chosen: {chosen}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if o is not None and n != o:
                return f"chosen value changed from {o!r} to {n!r}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_paxos_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedPaxos(f), run_length=100, num_runs=30, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_paxos_liveness_with_contention():
    """Two clients propose different values; after enough scheduling, one
    value is chosen everywhere."""
    rng = random.Random(5)
    sim = SimulatedPaxos(1)
    system = sim.new_system(5)
    t, config, leaders, acceptors, clients = system
    sim.run_command(system, Propose(0, "a"))
    sim.run_command(system, Propose(1, "b"))
    for _ in range(500):
        cmd = sim.generate_command(system, rng)
        if cmd is None:
            break
        sim.run_command(system, cmd)
    drain(t)
    chosen = {c.chosen for c in clients}
    assert len(chosen) == 1 and None not in chosen
