"""Production-lifecycle contract (thin wrapper): every
lifecycle-threaded batched *Config accepts a ``lifecycle:
LifecyclePlan`` field, validates it (rotation alignment, resubmit
cache) in ``__post_init__``, and applies it in ``tick``; under
``LifecyclePlan.none()`` the carried state is structurally empty and
feeds no tick equation; and steering the traced membership/epoch (the
serve reconfiguration verbs) never recompiles.

The checkers are the ``lifecycle-*`` / ``trace-lifecycle-*`` rules in
``frankenpaxos_tpu/analysis``; the behavioral pins live in
``tests/test_lifecycle.py``.
"""

import pytest

from frankenpaxos_tpu import analysis

pytestmark = pytest.mark.lint


@pytest.mark.parametrize(
    "rule_id",
    [
        "lifecycle-config-field",
        "lifecycle-validate",
        "lifecycle-apply",
    ],
)
def test_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()


@pytest.mark.parametrize(
    "rule_id",
    ["lifecycle-noop", "trace-lifecycle-retrace"],
)
def test_trace_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()
