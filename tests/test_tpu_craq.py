"""Tests of the batched CRAQ backend (craq_batched.py) including
cross-validation against the per-actor CRAQ protocol
(craq/ChainNode.scala:120-299 semantics)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu import craq_batched as cb


def run_random(cfg, seed, ticks):
    key = jax.random.PRNGKey(seed)
    state, t = cb.run_ticks(cfg, cb.init_state(cfg), jnp.int32(0), ticks, key)
    return state, t


def test_craq_progress_and_invariants():
    cfg = cb.BatchedCraqConfig(
        num_chains=8, chain_len=4, num_keys=16, window=16,
        writes_per_tick=2, reads_per_tick=3, read_window=16,
        lat_min=1, lat_max=3,
    )
    state, t = run_random(cfg, seed=0, ticks=200)
    inv = cb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    s = cb.stats(cfg, state, t)
    assert s["writes_done"] > 8 * 100  # pipeline saturates well below cap
    assert s["reads_done"] > 8 * 100
    # Apportioned queries: most reads are clean, some hit dirty keys.
    assert 0.5 < s["clean_fraction"] <= 1.0
    assert s["reads_dirty"] > 0
    assert s["read_lin_violations"] == 0
    # A write crosses L-1=3 hops down + 1 reply hop minimum.
    assert s["write_latency_p50_ticks"] >= 4


def test_craq_more_nodes_fewer_dirty_reads_per_node():
    """The apportioned-queries payoff: read capacity spreads over the
    chain; the dirty (tail-forwarded) fraction stays bounded as load
    grows because only keys with in-flight writes are dirty."""
    cfg = cb.BatchedCraqConfig(
        num_chains=4, chain_len=3, num_keys=64, window=8,
        writes_per_tick=1, reads_per_tick=4, read_window=32,
        lat_min=1, lat_max=2,
    )
    state, t = run_random(cfg, seed=1, ticks=200)
    s = cb.stats(cfg, state, t)
    # 64 keys, <=8 in flight per chain: most sampled keys are clean.
    assert s["clean_fraction"] > 0.7
    inv = cb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def _inject_write(state, slot, key_id, version, t):
    return dataclasses.replace(
        state,
        w_status=state.w_status.at[0, slot].set(cb.W_DOWN),
        w_key=state.w_key.at[0, slot].set(key_id),
        w_version=state.w_version.at[0, slot].set(version),
        w_node=state.w_node.at[0, slot].set(0),
        w_arrival=state.w_arrival.at[0, slot].set(t + 1),
        w_issue=state.w_issue.at[0, slot].set(t),
        next_version=state.next_version.at[0].set(version + 1),
    )


def _inject_read(state, slot, key_id, node, t, floor):
    return dataclasses.replace(
        state,
        r_status=state.r_status.at[0, slot].set(cb.R_AT_NODE),
        r_key=state.r_key.at[0, slot].set(key_id),
        r_node=state.r_node.at[0, slot].set(node),
        r_arrival=state.r_arrival.at[0, slot].set(t + 1),
        r_issue=state.r_issue.at[0, slot].set(t),
        r_floor=state.r_floor.at[0, slot].set(floor),
        r_version=state.r_version.at[0, slot].set(-1),
    )


def test_cross_validation_craq_dirty_routing():
    """Aligned scenario against the per-actor protocol: (1) write v0 to
    key x and let it fully ack; (2) start write v1 and stall it at the
    head; (3) a read at the MID node is clean and serves v0 locally;
    (4) a read at the HEAD is dirty and is forwarded to the tail, which
    serves v0; (5) release the write; (6) a head read is clean and
    serves v1. Both executions must make identical routing decisions
    and return identical values (version k <-> "v<k>")."""
    from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import craq as cq
    from test_fastpaxos_craq import drain, make_craq

    # ---- Per-actor side.
    t, config, nodes, clients = make_craq(n=3, num_clients=2)
    head_addr = config.chain_node_addresses[0]
    mid_addr = config.chain_node_addresses[1]
    tail_addr = config.chain_node_addresses[-1]

    clients[0].write(0, "x", "v0")
    drain(t)
    assert all(n.state_machine.get("x") == "v0" for n in nodes)

    clients[0].write(0, "x", "v1")  # deliver only to the head: stalled
    for m in [m for m in t.messages if m.dst == head_addr]:
        t.deliver_message(m)
    assert nodes[0].pending_writes and not nodes[1].pending_writes
    stalled = [m for m in t.messages if m.dst == mid_addr]  # v1 -> mid

    class _Pick:
        def __init__(self, n):
            self.n = n

        def randrange(self, _):
            return self.n

    def drain_except_stalled(t):
        for _ in range(1000):
            pend = [m for m in t.messages if m not in stalled]
            if not pend:
                return
            t.deliver_message(pend[0])
        raise AssertionError("drain did not quiesce")

    # (3) Clean read at the mid node.
    clients[1].rng = _Pick(1)
    r_mid = clients[1].read(0, "x")
    drain_except_stalled(t)
    assert r_mid.result() == "v0"

    # (4) Dirty read at the head: forwarded to the tail.
    clients[1].rng = _Pick(0)
    r_head = clients[1].read(1, "x")
    for m in [m for m in t.messages if m.dst == head_addr and m not in stalled]:
        t.deliver_message(m)
    assert any(
        m.dst == tail_addr for m in t.messages if m not in stalled
    ), "head must forward the dirty read to the tail"
    drain_except_stalled(t)
    assert r_head.result() == "v0"

    # (5)+(6) Release v1; a head read is clean and serves v1.
    drain(t)
    assert all(n.state_machine.get("x") == "v1" for n in nodes)
    assert not nodes[0].pending_writes
    clients[1].rng = _Pick(0)
    r_final = clients[1].read(2, "x")
    drain(t)
    assert r_final.result() == "v1"

    # ---- Batched side: same chain, deterministic 1-tick hops, manual
    # injections, no PRNG traffic.
    cfg = cb.BatchedCraqConfig(
        num_chains=1, chain_len=3, num_keys=2, window=4,
        writes_per_tick=0, reads_per_tick=0, read_window=4,
        lat_min=1, lat_max=1,
    )
    key = jax.random.PRNGKey(0)
    state = cb.init_state(cfg)
    tt = 0

    def run(state, tt, n):
        for _ in range(n):
            state = cb.tick(cfg, state, jnp.int32(tt), jax.random.fold_in(key, tt))
            tt += 1
        return state, tt

    # (1) Write v0 (version 0) to key 0; let it fully ack.
    state = _inject_write(state, slot=0, key_id=0, version=0, t=tt)
    state, tt = run(state, tt, 8)
    assert int(state.w_status[0, 0]) == cb.W_EMPTY
    assert np.all(np.asarray(state.node_version[0, :, 0]) == 0)

    # (2) Write v1 (version 1); stall it after it passes the head.
    state = _inject_write(state, slot=1, key_id=0, version=1, t=tt)
    state, tt = run(state, tt, 2)  # arrives at head, marked dirty there
    assert int(state.node_dirty[0, 0, 0]) == 1
    assert int(state.node_dirty[0, 1, 0]) == 0
    state = dataclasses.replace(
        state, w_arrival=state.w_arrival.at[0, 1].set(tt + 1000)
    )

    # (3) Clean read at the mid node serves version 0 locally.
    state = _inject_read(state, slot=0, key_id=0, node=1, t=tt,
                         floor=int(state.node_version[0, 2, 0]))
    state, tt = run(state, tt, 3)
    assert int(state.reads_clean) == 1 and int(state.reads_dirty) == 0
    assert int(state.reads_done) == 1
    # The completed read slot recorded the served version before clearing.
    # (r_version persists after completion until slot reuse.)
    assert int(state.r_version[0, 0]) == 0

    # (4) Dirty read at the head goes via the tail, serves version 0.
    state = _inject_read(state, slot=1, key_id=0, node=0, t=tt,
                         floor=int(state.node_version[0, 2, 0]))
    state, tt = run(state, tt, 4)
    assert int(state.reads_dirty) == 1
    assert int(state.reads_done) == 2
    assert int(state.r_version[0, 1]) == 0

    # (5) Release v1 and let it commit + ack everywhere.
    state = dataclasses.replace(
        state, w_arrival=state.w_arrival.at[0, 1].set(tt + 1)
    )
    state, tt = run(state, tt, 8)
    assert int(state.w_status[0, 1]) == cb.W_EMPTY
    assert np.all(np.asarray(state.node_version[0, :, 0]) == 1)
    assert int(state.node_dirty[0, 0, 0]) == 0

    # (6) Head read is clean now and serves version 1.
    state = _inject_read(state, slot=2, key_id=0, node=0, t=tt,
                         floor=int(state.node_version[0, 2, 0]))
    state, tt = run(state, tt, 3)
    assert int(state.reads_clean) == 2
    assert int(state.r_version[0, 2]) == 1

    inv = cb.check_invariants(cfg, state, jnp.int32(tt))
    assert all(bool(v) for v in inv.values()), inv

    # Alignment: per-actor returned (v0, v0, v1); batched returned
    # versions (0, 0, 1) with identical clean/dirty routing at each step.
    assert [r_mid.result(), r_head.result(), r_final.result()] == [
        "v0", "v0", "v1"
    ]
