"""Dependency graph tests (the analog of depgraph/DependencyGraphTest.scala
+ TarjanDependencyGraphTest cases)."""

import pytest

from frankenpaxos_tpu.depgraph import TarjanDependencyGraph, from_name


def make():
    return TarjanDependencyGraph()


def test_empty_graph():
    g = make()
    assert g.execute() == ([], set())
    assert g.num_vertices == 0


def test_single_vertex_no_deps():
    g = make()
    g.commit("a", 0, set())
    assert g.execute() == (["a"], set())
    # Never returned twice.
    assert g.execute() == ([], set())


def test_chain_executes_in_dependency_order():
    g = make()
    g.commit("a", 0, set())
    g.commit("b", 1, {"a"})
    g.commit("c", 2, {"b"})
    executed, blockers = g.execute()
    assert executed.index("a") < executed.index("b") < executed.index("c")
    assert blockers == set()


def test_missing_dependency_blocks():
    g = make()
    g.commit("b", 1, {"a"})
    executed, blockers = g.execute()
    assert executed == []
    assert blockers == {"a"}
    # Committing the dependency unblocks.
    g.commit("a", 0, set())
    executed, blockers = g.execute()
    assert executed == ["a", "b"]
    assert blockers == set()


def test_transitive_missing_dependency_blocks():
    g = make()
    g.commit("c", 2, {"b"})
    g.commit("b", 1, {"a"})
    executed, blockers = g.execute()
    assert executed == []
    assert blockers == {"a"}


def test_cycle_executes_as_component_in_seq_order():
    g = make()
    g.commit("b", 5, {"a"})
    g.commit("a", 9, {"b"})
    components, blockers = g.execute_by_component()
    assert blockers == set()
    assert components == [["b", "a"]]  # sorted by (seq, key): (5,b) < (9,a)


def test_cycle_with_equal_seq_sorts_by_key():
    g = make()
    g.commit("b", 1, {"a"})
    g.commit("a", 1, {"b"})
    components, _ = g.execute_by_component()
    assert components == [["a", "b"]]


def test_cycle_blocked_by_external_dep():
    g = make()
    g.commit("a", 0, {"b", "x"})
    g.commit("b", 1, {"a"})
    executed, blockers = g.execute()
    assert executed == []
    assert blockers == {"x"}
    g.commit("x", 2, set())
    executed, blockers = g.execute()
    assert set(executed) == {"a", "b", "x"}
    assert executed.index("x") < executed.index("a")


def test_components_in_reverse_topological_order():
    g = make()
    g.commit("a", 0, set())
    g.commit("b", 1, {"a"})
    g.commit("c", 2, {"b"})
    g.commit("d", 3, {"c", "a"})
    components, _ = g.execute_by_component()
    flat = [k for comp in components for k in comp]
    assert flat.index("a") < flat.index("b") < flat.index("c") < flat.index("d")


def test_two_cycles_chain():
    # {a,b} <- {c,d}: the ab component must execute before the cd one.
    g = make()
    g.commit("a", 0, {"b"})
    g.commit("b", 1, {"a"})
    g.commit("c", 2, {"d", "a"})
    g.commit("d", 3, {"c"})
    components, blockers = g.execute_by_component()
    assert blockers == set()
    assert components == [["a", "b"], ["c", "d"]]


def test_self_loop():
    g = make()
    g.commit("a", 0, {"a"})
    assert g.execute() == (["a"], set())


def test_update_executed_skips_and_unblocks():
    g = make()
    g.update_executed({"a"})
    g.commit("b", 1, {"a"})
    assert g.execute() == (["b"], set())
    # Committing an executed key is ignored.
    g.commit("a", 0, set())
    assert g.num_vertices == 0
    assert g.execute() == ([], set())


def test_num_blockers_early_return():
    g = make()
    for i in range(10):
        g.commit(f"v{i}", i, {f"missing{i}"})
    executed, blockers = g.execute(num_blockers=1)
    assert executed == []
    assert len(blockers) >= 1  # stopped early rather than scanning all


def test_deep_chain_no_recursion_limit():
    g = make()
    n = 50_000
    g.commit(0, 0, set())
    for i in range(1, n):
        g.commit(i, i, {i - 1})
    executed, blockers = g.execute()
    assert len(executed) == n
    assert blockers == set()
    assert executed == sorted(executed)


def test_interleaved_commit_execute():
    g = make()
    g.commit("a", 0, set())
    assert g.execute() == (["a"], set())
    g.commit("b", 1, {"a"})  # a already executed
    assert g.execute() == (["b"], set())
    g.commit("d", 3, {"c"})
    assert g.execute() == ([], {"c"})
    g.commit("c", 2, {"b", "a"})
    executed, blockers = g.execute()
    assert executed == ["c", "d"]


def test_registry():
    assert isinstance(from_name("Tarjan"), TarjanDependencyGraph)
    with pytest.raises(ValueError):
        from_name("Jgrapht")


def test_abandoned_stack_does_not_leak_executions():
    """Regression: a vertex closed under an ineligible root (via a cycle
    whose ineligibility it can't see) must NOT be treated as executed by a
    later root in the same pass."""
    g = make()
    g.commit(0, 0, {1})
    g.commit(1, 1, {2, 4})  # 4 is uncommitted
    g.commit(2, 2, {0})
    g.commit(3, 3, {2})
    executed, blockers = g.execute()
    assert executed == [], f"executed {executed} despite uncommitted blocker"
    assert blockers == {4}
    # Committing 4 releases everything in one consistent order.
    g.commit(4, 4, set())
    executed, blockers = g.execute()
    assert set(executed) == {0, 1, 2, 3, 4}
    assert blockers == set()
    assert executed.index(4) < executed.index(1)
    assert executed.index(2) > executed.index(1) or executed.index(2) > 0
