"""The production-lifecycle subsystem (tpu/lifecycle.py): in-graph
window rotation, the exactly-once client session table, and traced
acceptor reconfiguration.

The load-bearing guarantees, in order:

  * ``LifecyclePlan.none()`` (the default on both lifecycle-threaded
    configs) is a STRUCTURAL no-op — the multipaxos pin reuses the
    ``tests/test_workload.py`` pre-PR golden captures verbatim (3
    seeds), so any lifecycle-threading change that perturbs a default
    run by one bit fails against the true pre-lifecycle behavior.
  * Rotation is an EXACT renumbering: a run crossing >= 3 window
    rotations commits the same entry sequence — the ENTIRE protocol
    state replays bit for bit modulo the rebased slot numbering — as
    its unrotated twin, on both backends, while the rotated run's slot
    horizon stays constant.
  * Exactly-once is by construction: duplicate re-submissions are
    answered from the session-table cache on a disjoint PRNG stream
    and never re-propose — the resubmitting run's protocol history is
    bit-identical to the resubmit-free twin's.
  * Reconfiguration is recompile-free: membership/epoch are traced
    state, so a mid-run acceptor swap (and heal) replays the same
    compiled program, invariants and liveness intact — randomized
    against crash/partition schedules via the simtest axis.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.harness import simtest
from frankenpaxos_tpu.harness.serve import ServeConfig, ServeLoop
from frankenpaxos_tpu.tpu import compartmentalized_batched as cz
from frankenpaxos_tpu.tpu import lifecycle as lc_mod
from frankenpaxos_tpu.tpu import multipaxos_batched as mp
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan


def _hash(state, fields):
    m = hashlib.sha256()
    for f in fields:
        m.update(np.asarray(jax.device_get(getattr(state, f))).tobytes())
    return m.hexdigest()[:16]


def _run(mod, cfg, ticks, seed, state=None, t=None):
    state = mod.init_state(cfg) if state is None else state
    t = jnp.zeros((), jnp.int32) if t is None else t
    return mod.run_ticks(cfg, state, t, ticks, jax.random.PRNGKey(seed))


def _assert_invariants(mod, cfg, state, t):
    bad = {
        k: bool(v)
        for k, v in mod.check_invariants(cfg, state, t).items()
        if not bool(v)
    }
    assert not bad, bad


# ---------------------------------------------------------------------------
# none() bit-identity: the multipaxos goldens are the pre-PR captures
# from tests/test_workload.py (same fixed config/seeds, explicit none
# plan); the compartmentalized pin freezes the current default run.
# ---------------------------------------------------------------------------

GOLDEN_MULTIPAXOS = {
    0: (582, 562, 3426, "dd70eeb17ab45de2"),
    1: (581, 530, 3487, "c665a10d449618ae"),
    2: (583, 551, 3340, "ec2d56f23217dda9"),
}


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_multipaxos(seed):
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, lat_min=1,
        lat_max=3, drop_rate=0.05, retry_timeout=8,
        lifecycle=LifecyclePlan.none(),
    )
    assert mp.BatchedMultiPaxosConfig().lifecycle == cfg.lifecycle
    st, _ = _run(mp, cfg, 120, seed)
    got = (
        int(st.committed), int(st.retired), int(st.lat_sum),
        _hash(st, ("status", "slot_value", "chosen_round", "head",
                   "next_slot", "acc_round", "vote_round", "vote_value")),
    )
    assert got == GOLDEN_MULTIPAXOS[seed]
    # The carried lifecycle state is structurally EMPTY.
    assert all(
        leaf.size == 0
        for leaf in jax.tree_util.tree_leaves(st.lifecycle)
    )


GOLDEN_COMPARTMENTALIZED = {
    0: (818, 368, "3e99b934cf6a8cad"),
    1: (824, 372, "cfcdda6b246a824a"),
    2: (796, 365, "7809ddf78dad6fa3"),
}


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_compartmentalized(seed):
    cfg = cz.analysis_config(lifecycle=LifecyclePlan.none())
    assert cz.analysis_config().lifecycle == cfg.lifecycle
    st, _ = _run(cz, cfg, 120, seed)
    got = (
        int(st.committed), int(st.retired),
        _hash(st, ("status", "head", "next_slot", "rep_exec",
                   "p2b_arrival", "rd_bound")),
    )
    assert got == GOLDEN_COMPARTMENTALIZED[seed]
    assert all(
        leaf.size == 0
        for leaf in jax.tree_util.tree_leaves(st.lifecycle)
    )


# ---------------------------------------------------------------------------
# Rotation exactness: >= 3 rotations == the unrotated twin, rebased.
# ---------------------------------------------------------------------------

# Field -> the rebased-shift multiplier (in units of the per-group
# rotation base): slot counts shift by 1x, id/global-numbering fields
# by G (the global sequence is slot * G + g). Unlisted fields must be
# bitwise EQUAL between the rotated run and its twin.
def _mp_shift_mults(G):
    out = {f: 1 for f in ("head", "next_slot", "gc_watermark")}
    out.update({
        f: G
        for f in (
            "slot_value", "chosen_value", "vote_value", "kv_val",
            "ct_last", "client_last_issued", "max_chosen_global",
            "client_watermark", "resp_slot", "rb_target", "rb_floor",
        )
    })
    return out


def _cz_shift_mults(G):
    return {f: 1 for f in ("head", "next_slot", "rep_exec", "rd_bound")}


# Historical-table fields where an entry stale beyond the rotation
# margin demotes to the unset sentinel (outcome-preserving; see the
# rebase comment in multipaxos_batched.tick) — the twin comparison
# allows EXACTLY that: rotated == -1 where the twin's id predates the
# cumulative rebase, bitwise equality everywhere else.
_DEMOTABLE = {"kv_val", "ct_last"}


def _assert_rotated_equals_twin(rot_state, twin_state, shift_mults):
    base = int(rot_state.lifecycle.rot_base)
    assert base > 0
    for f in dataclasses.fields(twin_state):
        name = f.name
        if name in ("lifecycle", "telemetry"):
            continue  # rotation counters / the rotations ring column
        mult = shift_mults.get(name, 0)
        a_leaves = jax.tree_util.tree_leaves(
            jax.device_get(getattr(rot_state, name))
        )
        b_leaves = jax.tree_util.tree_leaves(
            jax.device_get(getattr(twin_state, name))
        )
        for a, b in zip(a_leaves, b_leaves):
            a, b = np.asarray(a), np.asarray(b)
            if mult:
                raw = a
                a = np.where(a >= 0, a + base * mult, a)
                if name in _DEMOTABLE:
                    demoted = (raw == -1) & (b >= 0) & (b < base * mult)
                    a = np.where(demoted, b, a)
            np.testing.assert_array_equal(a, b, err_msg=name)


def test_rotation_exactness_multipaxos():
    """A flagship run with kv dedup + reads crossing >= 3 rotations
    replays its unrotated twin bit for bit modulo the rebase — the
    commit sequence, the KV shards, the client tables, and the read
    path are all identical — while the rotated run's slot horizon
    stays bounded by one quantum + window."""
    kw = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2, retry_timeout=8,
        state_machine="kv", kv_keys=64, num_clients=8, dup_rate=0.1,
        read_rate=2, read_window=8,
    )
    plan = LifecyclePlan(rotate_every=32)
    cfg_r = mp.BatchedMultiPaxosConfig(lifecycle=plan, **kw)
    cfg_n = mp.BatchedMultiPaxosConfig(**kw)
    sr, tr = _run(mp, cfg_r, 250, 7)
    sn, _ = _run(mp, cfg_n, 250, 7)
    assert int(sr.lifecycle.rot_count) >= 3
    # Constant horizon: heads never run past a quantum + margin + W...
    assert int(jnp.max(sr.head)) < plan.rotate_every + 2 * cfg_r.window
    # ...while the twin's marched on unboundedly.
    assert int(jnp.max(sn.head)) > 3 * plan.rotate_every
    _assert_rotated_equals_twin(sr, sn, _mp_shift_mults(cfg_r.num_groups))
    _assert_invariants(mp, cfg_r, sr, tr)
    # The rotations telemetry column recorded every roll.
    assert int(
        sr.telemetry.totals[telemetry_mod.COL["rotations"]]
    ) == int(sr.lifecycle.rot_count)


def test_rotation_exactness_compartmentalized():
    plan = LifecyclePlan(rotate_every=16)
    cfg_r = cz.analysis_config(lifecycle=plan)
    cfg_n = cz.analysis_config()
    sr, tr = _run(cz, cfg_r, 300, 5)
    sn, _ = _run(cz, cfg_n, 300, 5)
    assert int(sr.lifecycle.rot_count) >= 3
    assert int(jnp.max(sr.head)) < plan.rotate_every + 2 * cfg_r.window
    _assert_rotated_equals_twin(sr, sn, _cz_shift_mults(cfg_r.num_groups))
    _assert_invariants(cz, cfg_r, sr, tr)


def test_rotation_span_ids_stable_across_rolls():
    """The span sampler records ABSOLUTE slot ids (local + rotation
    base): the rotated run exports the exact same completed spans as
    the unrotated twin — ids never jump at a roll."""
    plan = LifecyclePlan(rotate_every=32)
    cfg_r = mp.analysis_config(lifecycle=plan)
    cfg_n = mp.analysis_config()

    def spans_of(cfg):
        st = mp.init_state(cfg)
        st = dataclasses.replace(
            st, telemetry=telemetry_mod.make_telemetry(128, spans=8)
        )
        st, _ = mp.run_ticks(
            cfg, st, jnp.zeros((), jnp.int32), 200, jax.random.PRNGKey(3)
        )
        return st

    sr, sn = spans_of(cfg_r), spans_of(cfg_n)
    assert int(sr.lifecycle.rot_count) >= 3
    assert int(sr.telemetry.spans_done) > 0
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(sr.telemetry.span_ring)),
        np.asarray(jax.device_get(sn.telemetry.span_ring)),
    )


def test_force_rotation_verb():
    """request_rotation rolls EARLY — down to the largest retired
    alignment quantum — without waiting for rotate_every."""
    plan = LifecyclePlan(rotate_every=64)  # 4 quanta of the W=16 align
    cfg = mp.analysis_config(lifecycle=plan)
    st, t = _run(mp, cfg, 40, 0)  # heads well inside [16, 64)
    assert int(st.lifecycle.rot_count) == 0
    head_before = int(jnp.min(st.head))
    assert 16 <= head_before < 64, "test setup: one retired quantum"
    st = dataclasses.replace(
        st, lifecycle=lc_mod.request_rotation(st.lifecycle)
    )
    st, t = mp.run_ticks(cfg, st, t, 1, jax.random.PRNGKey(1))
    assert int(st.lifecycle.rot_count) == 1
    assert int(st.lifecycle.rot_base) % 16 == 0
    assert int(jnp.min(st.head)) < head_before
    _assert_invariants(mp, cfg, st, t)


# ---------------------------------------------------------------------------
# Exactly-once session table
# ---------------------------------------------------------------------------


def _protocol_hash(state):
    m = hashlib.sha256()
    for f in dataclasses.fields(state):
        if f.name == "lifecycle":
            continue
        for leaf in jax.tree_util.tree_leaves(
            jax.device_get(getattr(state, f.name))
        ):
            m.update(np.asarray(leaf).tobytes())
    return m.hexdigest()[:16]


@pytest.mark.parametrize("mod,cfg_fn", [
    (mp, mp.analysis_config), (cz, cz.analysis_config),
])
def test_exactly_once_duplicates_never_touch_protocol(mod, cfg_fn):
    """Duplicate submissions are answered from the cache and NEVER
    re-propose: the resubmitting run's protocol history (every field
    but the lifecycle books) is bit-identical to the resubmit-free
    twin's — exactly-once by construction, on both backends."""
    cfg_s = cfg_fn(
        lifecycle=LifecyclePlan(sessions=4, resubmit_rate=0.2)
    )
    cfg_0 = cfg_fn()
    ss, ts = _run(mod, cfg_s, 150, 3)
    s0, _ = _run(mod, cfg_0, 150, 3)
    assert _protocol_hash(ss) == _protocol_hash(s0)
    assert int(ss.lifecycle.cache_hits) > 0
    assert int(ss.lifecycle.resubmits) >= int(ss.lifecycle.cache_hits)
    _assert_invariants(mod, cfg_s, ss, ts)
    # The table recorded every client-visible completion (committed
    # entries on both backends).
    assert int(jnp.sum(ss.lifecycle.sess_total)) == int(ss.committed)


def test_sessions_compose_with_kv_dup_injection():
    """The session table layers ON TOP of the kv client-table dedup:
    fault-injected eager duplicates (FaultPlan.dup_rate), re-issued
    command ids (cfg.dup_rate -> ct_last filtering), and session-level
    re-submissions all together — every dedup invariant holds."""
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, retry_timeout=8,
        state_machine="kv", kv_keys=64, num_clients=8, dup_rate=0.2,
        faults=FaultPlan(dup_rate=0.1),
        lifecycle=LifecyclePlan(
            rotate_every=32, sessions=8, resubmit_rate=0.15
        ),
    )
    st, t = _run(mp, cfg, 200, 1)
    _assert_invariants(mp, cfg, st, t)
    assert int(st.lifecycle.rot_count) >= 2
    assert int(st.dups_filtered) > 0  # ct_last filtered re-issues
    assert int(st.lifecycle.cache_hits) > 0  # cache answered resubmits


def test_sessions_conserve_with_workload_engine():
    """The extended conservation contract: with the closed-loop
    workload engine active, the session table's completion totals
    reconcile against WorkloadState.completed exactly (checked inside
    lifecycle_ok every segment), and workload_ok still holds."""
    cfg = mp.analysis_config(
        workload=WorkloadPlan(
            arrival="constant", rate=1.5, closed_window=6, think_time=2
        ),
        lifecycle=LifecyclePlan(sessions=4, resubmit_rate=0.1),
    )
    st, t = _run(mp, cfg, 150, 2)
    _assert_invariants(mp, cfg, st, t)
    assert int(jnp.sum(st.lifecycle.sess_total)) == int(
        st.workload.completed
    )


# ---------------------------------------------------------------------------
# Traced reconfiguration
# ---------------------------------------------------------------------------


def test_reconfig_swap_is_recompile_free_and_live_multipaxos():
    """A mid-run acceptor swap + heal through the traced epoch axis:
    the jit cache stays flat, invariants hold at every boundary, and
    commits keep flowing in every regime (the dip-and-recover)."""
    cfg = mp.analysis_config(
        lifecycle=LifecyclePlan(rotate_every=16, reconfig=True)
    )
    st, t = _run(mp, cfg, 80, 0)
    before_cache = mp.run_ticks._cache_size()
    c0 = int(st.committed)
    st = dataclasses.replace(
        st, lifecycle=lc_mod.swap_acceptor(st.lifecycle, 1)
    )
    st, t = mp.run_ticks(cfg, st, t, 80, jax.random.PRNGKey(1))
    _assert_invariants(mp, cfg, st, t)
    c1 = int(st.committed)
    assert c1 > c0, "commits stalled under the swapped-out acceptor"
    assert int(st.lifecycle.applied) == 1
    assert int(jnp.sum(st.lifecycle.acc_mask)) == 2 * cfg.num_groups
    st = dataclasses.replace(
        st, lifecycle=lc_mod.set_membership(st.lifecycle, True)
    )
    st, t = mp.run_ticks(cfg, st, t, 80, jax.random.PRNGKey(2))
    _assert_invariants(mp, cfg, st, t)
    assert int(st.committed) > c1
    assert int(st.lifecycle.applied) == 2
    assert mp.run_ticks._cache_size() == before_cache, (
        "reconfiguration recompiled the serve program"
    )
    # Old epochs were garbage-collected behind the watermark.
    assert int(st.lifecycle.epochs_gcd) > 0


def test_reconfig_grid_cell_swap_compartmentalized():
    cfg = cz.analysis_config(lifecycle=LifecyclePlan(reconfig=True))
    st, t = _run(cz, cfg, 80, 0)
    before_cache = cz.run_ticks._cache_size()
    c0 = int(st.committed)
    mask = np.ones((2, 2, cfg.num_groups), bool)
    mask[1, 0, :] = False  # swap one grid cell out (rows stay live)
    st = dataclasses.replace(
        st,
        lifecycle=lc_mod.set_membership(st.lifecycle, jnp.asarray(mask)),
    )
    st, t = cz.run_ticks(cfg, st, t, 80, jax.random.PRNGKey(1))
    _assert_invariants(cz, cfg, st, t)
    assert int(st.committed) > c0
    st = dataclasses.replace(
        st, lifecycle=lc_mod.set_membership(st.lifecycle, True)
    )
    st, t = cz.run_ticks(cfg, st, t, 80, jax.random.PRNGKey(2))
    _assert_invariants(cz, cfg, st, t)
    assert cz.run_ticks._cache_size() == before_cache


def test_simtest_reconfig_axis():
    """The randomized [faults x epochs] axis: reconfiguration epochs
    churn against crash/partition schedules at segment boundaries;
    invariants hold throughout and progress resumes after the final
    heal (liveness-after-heal under churn), on both backends."""
    import random as _random

    for name in ("multipaxos", "compartmentalized"):
        spec = simtest.SPECS[name]
        rng = _random.Random(42)
        for i in range(2):
            plan = simtest.random_plan(rng, spec, 160)
            if plan.has_partition and (
                plan.partition_heal < 0 or plan.partition_heal > 120
            ):
                plan = dataclasses.replace(
                    plan,
                    partition_heal=80,
                    partition_start=min(plan.partition_start, 79),
                )
            lplan = simtest.random_lifecycle(rng, spec, 160)
            res = simtest.run_reconfig_schedule(
                spec, plan, seed=i, ticks=160, lifecycle=lplan,
                epoch_seed=i,
            )
            assert res["ok"], (name, i, res["violations"], res)


def test_serve_loop_lifecycle_verbs():
    """The serve control plane end to end: a live loop swaps an
    acceptor, heals, and force-rotates between chunks — zero
    recompiles — and the report carries the lifecycle summary."""
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, retry_timeout=8,
        lifecycle=LifecyclePlan(
            rotate_every=16, sessions=4, resubmit_rate=0.1,
            reconfig=True,
        ),
    )
    serve = ServeConfig(chunk_ticks=20, telemetry_window=64,
                        max_chunks=6)
    loop = ServeLoop(mp, cfg, serve, seed=0)
    # Drive chunks manually so verbs land between them.
    snap = loop._dispatch_chunk()
    loop.swap_acceptor(2)
    snap2 = loop._dispatch_chunk()
    loop._drain(snap)
    cache = mp.run_ticks._cache_size()
    loop.reconfigure(True)  # heal
    loop.rotate()
    snap3 = loop._dispatch_chunk()
    loop._drain(snap2)
    loop._drain(snap3)
    assert mp.run_ticks._cache_size() == cache
    report = loop.report(1.0)
    lc = report["lifecycle"]
    assert lc["epoch"] == 2 and lc["epoch_applied"] == 2
    assert lc["rotations"] >= 1
    assert lc["live_acceptors"] == 3 * cfg.num_groups
    _assert_invariants(mp, cfg, loop.state, loop.t)
    verb_names = {
        s["name"] for s in loop.host_spans if s["name"].startswith("verb:")
    }
    assert {"verb:swap_acceptor", "verb:reconfigure",
            "verb:rotate"} <= verb_names
