"""Donation contract (thin wrapper): every jitted *State-threading
entry point in ``tpu/`` must donate its state buffers.

The actual checker is the ``donation-jit`` rule in
``frankenpaxos_tpu/analysis`` (plus ``backend-inventory`` for the
13-backend floor); this file just binds it into tier-1. The rule's
teeth — that the decorator matcher really parses ``@functools.partial
(jax.jit, ...)`` shapes and that violations are flagged — are exercised
against synthetic fixture trees in ``test_analysis_engine.py``. The
COMPILED counterpart (donation actually aliasing in the HLO) is the
``trace-donation-alias`` rule in ``test_analysis_trace.py``.

Intentional exceptions go in ``analysis/allowlists.py`` with a reason.
"""

import pytest

from frankenpaxos_tpu import analysis

pytestmark = pytest.mark.lint


@pytest.mark.parametrize("rule_id", ["backend-inventory", "donation-jit"])
def test_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()
