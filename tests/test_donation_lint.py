"""AST lint: every jitted state-threading entry point in
``frankenpaxos_tpu/tpu/`` must donate its state buffers.

The HBM-bandwidth pass made buffer donation the repo-wide contract: a
``@jax.jit``-decorated function that threads a ``*State`` dataclass
(parameter annotated ``...State``) without ``donate_argnums`` silently
double-buffers the whole cluster state in device memory — exactly the
regression this lint exists to catch. New backends get the contract for
free: add the backend, forget the donation, this test fails.

Intentional exceptions go in ALLOWLIST with a reason.
"""

import ast
import pathlib

import pytest

TPU_DIR = (
    pathlib.Path(__file__).resolve().parent.parent
    / "frankenpaxos_tpu"
    / "tpu"
)

# (filename, function name) -> reason the exception is intentional.
ALLOWLIST = {
    # Nothing is currently exempt. Example entry:
    # ("foo_batched.py", "replay_ticks"):
    #     "replay keeps the input state for post-hoc divergence dumps",
}


def _jit_decorator_info(dec):
    """(is_jit, has_donate) for one decorator expression, matching
    ``@jax.jit`` and ``@functools.partial(jax.jit, ...)`` shapes."""

    def is_jax_jit(node):
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        )

    if is_jax_jit(dec):
        return True, False
    if isinstance(dec, ast.Call):
        callee = dec.func
        # functools.partial(jax.jit, ...) / partial(jax.jit, ...)
        is_partial = (
            isinstance(callee, ast.Attribute) and callee.attr == "partial"
        ) or (isinstance(callee, ast.Name) and callee.id == "partial")
        if is_partial and dec.args and is_jax_jit(dec.args[0]):
            has_donate = any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in dec.keywords
            )
            return True, has_donate
        # jax.jit(...) called directly as a decorator factory
        if is_jax_jit(callee):
            has_donate = any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in dec.keywords
            )
            return True, has_donate
    return False, False


def _threads_state(func: ast.FunctionDef) -> bool:
    """True iff some parameter annotation names a *State dataclass."""
    for arg in func.args.args + func.args.posonlyargs + func.args.kwonlyargs:
        ann = arg.annotation
        if ann is None:
            continue
        text = ast.unparse(ann)
        if "State" in text:
            return True
    # Fallback for unannotated entry points (e.g. grid_batched.run_ticks):
    # the repo-wide convention names the threaded state parameter
    # ``state``.
    return any(
        a.arg == "state"
        for a in func.args.args + func.args.posonlyargs
    )


def _lint_file(path: pathlib.Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        jitted = False
        donated = False
        for dec in node.decorator_list:
            is_jit, has_donate = _jit_decorator_info(dec)
            jitted = jitted or is_jit
            donated = donated or has_donate
        if not jitted or not _threads_state(node):
            continue
        if donated:
            continue
        if (path.name, node.name) in ALLOWLIST:
            continue
        offenders.append((path.name, node.name, node.lineno))
    return offenders


def test_tpu_backends_exist():
    files = sorted(TPU_DIR.glob("*_batched.py"))
    assert len(files) >= 13, [f.name for f in files]


def test_every_jitted_state_entry_point_donates():
    offenders = []
    for path in sorted(TPU_DIR.glob("*.py")):
        offenders.extend(_lint_file(path))
    assert not offenders, (
        "jitted *State-threading entry points without donate_argnums "
        "(single-buffer contract, see tpu/common.py dtype/donation "
        f"policy) — add donation or an ALLOWLIST entry: {offenders}"
    )


def test_allowlist_entries_still_exist():
    """Stale allowlist entries (renamed/removed functions) must be
    pruned, or the lint silently loses coverage."""
    for (fname, func), _reason in ALLOWLIST.items():
        path = TPU_DIR / fname
        assert path.exists(), f"allowlisted file gone: {fname}"
        tree = ast.parse(path.read_text())
        names = {
            n.name
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        assert func in names, f"allowlisted function gone: {fname}:{func}"


@pytest.mark.parametrize(
    "fname,expected",
    [("multipaxos_batched.py", "run_ticks")],
)
def test_lint_sees_known_entry_points(fname, expected):
    """The lint actually parses the decorators it claims to check: the
    flagship run_ticks must be detected as jitted + donated (not skipped
    by a matcher bug)."""
    tree = ast.parse((TPU_DIR / fname).read_text())
    found = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == expected:
            jitted = donated = False
            for dec in node.decorator_list:
                is_jit, has_donate = _jit_decorator_info(dec)
                jitted |= is_jit
                donated |= has_donate
            found = (jitted, donated, _threads_state(node))
    assert found == (True, True, True), found
