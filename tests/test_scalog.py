"""Scalog sim tests (the analog of the reference's scalog unit/sim
coverage): shard-local appends, aggregator cuts, the cut-ordering Paxos
group, projection onto the global log, recovery of dropped entries, and
leader failover — all on one SimTransport."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import scalog as sc
from frankenpaxos_tpu.protocols.multipaxos.messages import Chosen
from frankenpaxos_tpu.protocols.multipaxos.replica import (
    Replica,
    ReplicaOptions,
)
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import ReadableAppendLog


class ScalogCluster:
    def __init__(self, seed=0, f=1, num_shards=2, num_clients=2,
                 push_size=1, cuts_per_proposal=1):
        logger = FakeLogger(LogLevel.FATAL)
        self.transport = SimTransport(logger)
        t = self.transport
        self.config = sc.ScalogConfig(
            f=f,
            server_addresses=tuple(
                tuple(SimAddress(f"server_{s}_{i}") for i in range(f + 1))
                for s in range(num_shards)
            ),
            aggregator_address=SimAddress("aggregator"),
            leader_addresses=tuple(
                SimAddress(f"leader{i}") for i in range(f + 1)
            ),
            acceptor_addresses=tuple(
                SimAddress(f"acceptor{i}") for i in range(2 * f + 1)
            ),
            replica_addresses=tuple(
                SimAddress(f"replica{i}") for i in range(f + 1)
            ),
        )
        log = lambda: FakeLogger(LogLevel.FATAL)
        self.servers = [
            sc.ScServer(
                a, t, log(), self.config,
                sc.ScServerOptions(push_size=push_size), seed=seed + 100 + i,
            )
            for i, a in enumerate(self.config.flat_servers)
        ]
        self.aggregator = sc.ScAggregator(
            self.config.aggregator_address, t, log(), self.config,
            sc.ScAggregatorOptions(
                num_shard_cuts_per_proposal=cuts_per_proposal
            ),
        )
        self.leaders = [
            sc.ScLeader(a, t, log(), self.config, seed=seed + 200 + i)
            for i, a in enumerate(self.config.leader_addresses)
        ]
        self.acceptors = [
            sc.ScAcceptor(a, t, log(), self.config)
            for a in self.config.acceptor_addresses
        ]
        self.replicas = [
            Replica(
                a, t, log(), ReadableAppendLog(),
                sc.replica_config(self.config),
                ReplicaOptions(
                    log_grow_size=100,
                    send_chosen_watermark_every_n_entries=10,
                ),
                seed=seed + 300 + i,
            )
            for i, a in enumerate(self.config.replica_addresses)
        ]
        self.clients = [
            sc.ScClient(
                SimAddress(f"client{i}"), t, log(), self.config,
                seed=seed + 400 + i,
            )
            for i in range(num_clients)
        ]

    def drain(self, max_steps=200000):
        steps = 0
        t = self.transport
        while t.messages and steps < max_steps:
            t.deliver_message(t.messages[0])
            steps += 1
        assert steps < max_steps, "message drain did not terminate"

    def pump(self, rounds=8, skip=lambda timer: False):
        """Drain, then alternate timer firings and drains — the sim analog
        of letting push/resend/recover timers make progress."""
        self.drain()
        for _ in range(rounds):
            for timer in list(self.transport.running_timers()):
                if not skip(timer):
                    self.transport.trigger_timer(timer.address, timer.name())
            self.drain()


def test_scalog_single_write():
    """One write lands in every replica's log and the client's promise
    resolves with the append index."""
    cluster = ScalogCluster()
    p = cluster.clients[0].write(0, b"hello")
    cluster.pump()
    assert p.done
    for replica in cluster.replicas:
        assert replica.state_machine.log == [b"hello"]


def test_scalog_multi_shard_total_order():
    """Writes spread over both shards end with identical replica logs
    (the global log is a total order, not per-shard)."""
    cluster = ScalogCluster(seed=7, num_clients=3)
    promises = []
    for i, client in enumerate(cluster.clients):
        for pseudonym in (0, 1):
            promises.append(client.write(pseudonym, f"w{i}.{pseudonym}".encode()))
    cluster.pump()
    assert all(p.done for p in promises)
    logs = {tuple(r.state_machine.log) for r in cluster.replicas}
    assert len(logs) == 1, logs
    (log,) = logs
    assert sorted(log) == sorted(
        f"w{i}.{p}".encode() for i in range(3) for p in (0, 1)
    )


def test_scalog_servers_route_through_both_shards():
    """Sanity: with enough writes, the chosen cuts credit servers in BOTH
    shards (clients pick a uniformly random server, and any server — not
    just a designated primary — accepts appends)."""
    cluster = ScalogCluster(seed=3, num_clients=4)
    for rnd in range(4):
        for i, client in enumerate(cluster.clients):
            client.write(rnd, f"r{rnd}c{i}".encode())
        cluster.pump()
    final = cluster.aggregator.cuts[-1]
    assert sum(final) == 16
    shard0, shard1 = final[:2], final[2:]
    assert sum(shard0) > 0 and sum(shard1) > 0, final


def test_scalog_dropped_chosen_recovered_via_aggregator():
    """A replica that misses a Chosen has a log hole; its recover timer
    sends Recover to the aggregator, which locates the owning server from
    the cut log and has it re-send (Aggregator.findSlot path)."""
    cluster = ScalogCluster(seed=11)
    t = cluster.transport
    victim = cluster.config.replica_addresses[1]
    p = cluster.clients[0].write(0, b"lost")
    # Drop every Chosen headed at replica 1; deliver everything else.
    while t.messages:
        m = t.messages[0]
        from frankenpaxos_tpu.core import wire
        if m.dst == victim and isinstance(wire.decode(m.data), Chosen):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert p.done  # replica 0 executed and replied
    assert cluster.replicas[1].state_machine.log == []
    # Second write creates a hole AFTER the missing slot so the recover
    # timer (which fires on executed_watermark) targets slot 0.
    p2 = cluster.clients[0].write(0, b"next")
    cluster.pump()
    assert p2.done
    assert cluster.replicas[1].state_machine.log == [b"lost", b"next"]


def test_scalog_leader_failover_repairs_cut_log():
    """Kill leader 0 mid-slot, have leader 1 take over: phase 1 re-chooses
    the in-flight cut in the higher round and the write completes."""
    cluster = ScalogCluster(seed=13)
    t = cluster.transport
    dead = cluster.config.leader_addresses[0]
    p = cluster.clients[0].write(0, b"failover")
    # Deliver everything except the Phase2bs headed back at leader 0: the
    # acceptors have voted, but the leader dies before learning it.
    from frankenpaxos_tpu.core import wire
    while t.messages:
        m = t.messages[0]
        if m.dst == dead and isinstance(wire.decode(m.data), sc.ScPhase2b):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    t.partition_actor(dead)
    assert all(r.state_machine.log == [] for r in cluster.replicas)
    cluster.leaders[1].become_leader()
    cluster.pump(skip=lambda timer: timer.address == dead)
    assert p.done
    for replica in cluster.replicas:
        assert replica.state_machine.log == [b"failover"]
    # The new leader announced itself to the aggregator, so proposals
    # reroute and writes issued AFTER the failover also commit.
    p2 = cluster.clients[1].write(0, b"post-failover")
    cluster.pump(skip=lambda timer: timer.address == dead)
    assert p2.done
    for replica in cluster.replicas:
        assert replica.state_machine.log == [b"failover", b"post-failover"]


def test_scalog_nonmonotone_cuts_pruned():
    """Duplicate or stale chosen cuts must not double-count entries: the
    aggregator proposes only cuts that ADVANCE the newest chosen cut, and
    any non-monotone raw cut that still gets chosen (in-flight races) is
    pruned from the ordered cut log."""
    cluster = ScalogCluster(seed=17)
    agg = cluster.aggregator
    p = cluster.clients[0].write(0, b"once")
    cluster.pump()
    assert p.done
    processed_before = agg.raw_cuts_processed
    # Re-pushing unchanged watermarks proposes NOTHING (no Paxos rounds).
    for server in cluster.servers:
        server.push()
    cluster.pump()
    assert agg.raw_cuts_processed == processed_before
    # A raced duplicate of an already-chosen cut at a later raw slot is
    # ordered but PRUNED (not appended to the cut log).
    stale = agg.cuts[-1]
    agg.receive(
        cluster.config.leader_addresses[0],
        sc.ScRawCutChosen(slot=agg.raw_cuts_watermark, cut=stale),
    )
    cluster.drain()
    assert agg.raw_cuts_processed == processed_before + 1
    assert list(agg.cuts) == [stale]
    for replica in cluster.replicas:
        assert replica.state_machine.log == [b"once"]


def test_scalog_lost_raw_cut_chosen_recovered():
    """A lost leader->aggregator RawCutChosen leaves a hole in the raw cut
    log; without recovery the watermark wedges and NO later write can ever
    commit. The aggregator's recover timer re-requests the slot from the
    leaders' chosen-cut caches."""
    from frankenpaxos_tpu.core import wire

    cluster = ScalogCluster(seed=23)
    t = cluster.transport
    p = cluster.clients[0].write(0, b"wedge?")
    dropped = 0
    while t.messages:
        m = t.messages[0]
        if (
            dropped == 0
            and m.dst == cluster.config.aggregator_address
            and isinstance(wire.decode(m.data), sc.ScRawCutChosen)
        ):
            t.drop_message(m)
            dropped += 1
        else:
            t.deliver_message(m)
    assert dropped == 1
    assert not p.done
    # A later write chooses a HIGHER raw slot; the aggregator must detect
    # the hole below it and recover. Everything then commits in order.
    p2 = cluster.clients[1].write(0, b"after")
    cluster.pump()
    assert p.done and p2.done
    logs = {tuple(r.state_machine.log) for r in cluster.replicas}
    assert logs == {(b"wedge?", b"after")}, logs


def test_scalog_backup_serves_recovery_after_owner_crash():
    """Cuts only cover fully-replicated prefixes, so when the server that
    ORIGINATED an entry crashes, its in-shard backup can serve recovery:
    the aggregator routes Recover to the whole owning shard."""
    from frankenpaxos_tpu.core import wire

    cluster = ScalogCluster(seed=29)
    t = cluster.transport
    owner = cluster.config.flat_servers[0]

    class _Pick0:
        def randrange(self, n):
            return 0

    cluster.clients[0].rng = _Pick0()
    victim = cluster.config.replica_addresses[1]
    p = cluster.clients[0].write(0, b"backed-up")
    while t.messages:
        m = t.messages[0]
        if m.dst == victim and isinstance(wire.decode(m.data), Chosen):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert p.done
    assert cluster.replicas[1].state_machine.log == []
    # The originating server dies. Its backup (same shard) holds the entry.
    t.partition_actor(owner)

    class _Pick2:
        def randrange(self, n):
            return 2  # a server in the OTHER shard

    cluster.clients[1].rng = _Pick2()
    p2 = cluster.clients[1].write(0, b"later")
    cluster.pump(skip=lambda timer: timer.address == owner)
    assert p2.done
    assert cluster.replicas[1].state_machine.log == [b"backed-up", b"later"]


def test_scalog_garbage_collection():
    """Replica ChosenWatermarks flow through the aggregator to the
    servers: fully-executed cuts are pruned everywhere and local log
    prefixes are dropped."""
    cluster = ScalogCluster(seed=31)
    for rnd in range(12):
        ps = [c.write(rnd, f"r{rnd}c{i}".encode())
              for i, c in enumerate(cluster.clients)]
        cluster.pump()
        assert all(p.done for p in ps)
    # 24 entries total; watermark broadcasts are round-robin sharded over
    # replicas every 10 executions, so by now EVERY replica has reported
    # to the aggregator and min-over-reports allows GC.
    assert cluster.aggregator.cuts_base_slot > 0
    assert all(len(s.cuts) < cluster.aggregator.raw_cuts_processed
               for s in cluster.servers)
    assert any(
        log.watermark > 0 for s in cluster.servers for log in s.logs
    )
    # And the system still works after pruning.
    ps = [c.write(9, b"post-gc") for c in cluster.clients[:1]]
    cluster.pump()
    assert all(p.done for p in ps)
    logs = {tuple(r.state_machine.log) for r in cluster.replicas}
    assert len(logs) == 1


def test_scalog_recover_raw_cut_after_reelection():
    """Regression: a leader preempted and RE-elected holds a stale
    phase-2 round for a stalled slot. Recovery must re-propose in the
    CURRENT round — replaying the cached round draws equal-round nacks
    forever and wedges the cut log on the hole."""
    from frankenpaxos_tpu.core import wire

    cluster = ScalogCluster(seed=41)
    t = cluster.transport
    p = cluster.clients[0].write(0, b"stuck")
    # Slot 0's Phase2as all vanish: proposed, never voted.
    while t.messages:
        m = t.messages[0]
        if isinstance(wire.decode(m.data), sc.ScPhase2a):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert 0 in cluster.leaders[0].phase2s
    # Leader 1 takes over (round 1), then leader 0 re-takes (round 2);
    # neither phase 1 sees any vote for slot 0, and leader 0 keeps
    # next_slot=1, so slot 0 stays a permanent hole without recovery.
    cluster.leaders[1].become_leader()
    cluster.drain()
    cluster.leaders[0].become_leader()
    cluster.drain()
    assert cluster.leaders[0].active and cluster.leaders[0].round == 2
    p2 = cluster.clients[1].write(0, b"later")
    cluster.pump(rounds=12)
    assert p.done and p2.done
    logs = {tuple(r.state_machine.log) for r in cluster.replicas}
    assert len(logs) == 1, logs


def test_scalog_chaos_converges():
    """Liveness under lossy chaos: 10% drops + 5% duplicates across ALL
    message types, then a fault-free repair phase. Every retransmission
    path (client resend, backup acks, phase-2 re-drive, raw-cut recovery,
    newest-cut re-broadcast, replica hole recovery) must cooperate for
    all writes to commit."""
    cluster = ScalogCluster(seed=37, num_clients=3)
    t = cluster.transport
    rng = random.Random(99)
    promises = []
    for burst in range(5):
        for i, client in enumerate(cluster.clients):
            promises.append(client.write(burst, f"b{burst}c{i}".encode()))
        steps = 0
        while t.messages and steps < 5000:
            m = t.messages[0]
            r = rng.random()
            if r < 0.10:
                t.drop_message(m)
            elif r < 0.15:
                t.duplicate_message(m)
            else:
                t.deliver_message(m)
            steps += 1
    cluster.pump(rounds=30)
    assert all(p.done for p in promises)
    logs = {tuple(r.state_machine.log) for r in cluster.replicas}
    assert len(logs) == 1
    assert len(next(iter(logs))) == len(promises)


# -- Randomized safety --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WriteCmd:
    client_index: int
    pseudonym: int
    value: bytes


class SimulatedScalog(SimulatedSystem):
    def __init__(self, f=1, num_shards=2):
        self.f = f
        self.num_shards = num_shards

    def new_system(self, seed):
        return ScalogCluster(seed=seed, f=self.f, num_shards=self.num_shards)

    def get_state(self, system):
        return tuple(
            tuple(r.state_machine.log) for r in system.replicas
        )

    def generate_command(self, system, rng):
        ops = []
        for i, client in enumerate(system.clients):
            for pseudonym in (0, 1):
                if pseudonym not in client.pending:
                    ops.append(
                        (1, WriteCmd(i, pseudonym, f"v{rng.randrange(100)}".encode()))
                    )
        return mixed_command(rng, system.transport, ops)

    def run_command(self, system, command):
        if isinstance(command, WriteCmd):
            system.clients[command.client_index].write(
                command.pseudonym, command.value
            )
        else:
            system.transport.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        for i in range(len(state)):
            for j in range(i + 1, len(state)):
                a, b = state[i], state[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return (
                        f"replica logs not prefix-compatible: {a!r} vs {b!r}"
                    )
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if n[: len(o)] != o:
                return f"replica log shrank or changed: {o!r} -> {n!r}"
        return None


@pytest.mark.parametrize("f,num_shards", [(1, 1), (1, 2), (2, 2)])
def test_scalog_safety_randomized(f, num_shards):
    bad = simulate_and_minimize(
        SimulatedScalog(f, num_shards), run_length=150, num_runs=10,
        seed=10 * f + num_shards,
    )
    assert bad is None, f"\n{bad}"
