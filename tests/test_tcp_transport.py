"""Echo smoke over the asyncio TCP transport (the analog of the reference's
benchmark smoke of NettyTcpTransport)."""

import dataclasses

from frankenpaxos_tpu.core import Actor, FakeLogger, HostPort, wire
from frankenpaxos_tpu.core.tcp_transport import TcpTransport


@wire.message
@dataclasses.dataclass(frozen=True)
class TcpEchoReq:
    text: str


@wire.message
@dataclasses.dataclass(frozen=True)
class TcpEchoReply:
    text: str


class EchoServer(Actor):
    def receive(self, src, msg):
        self.chan(src).send(TcpEchoReply(msg.text))


class EchoClient(Actor):
    def __init__(self, address, transport, logger, server, n):
        super().__init__(address, transport, logger)
        self.server = server
        self.n = n
        self.replies = []

    def kick(self):
        for i in range(self.n):
            self.chan(self.server).send(TcpEchoReq(f"m{i}"))

    def receive(self, src, msg):
        self.replies.append(msg.text)
        if len(self.replies) == self.n:
            self.transport.shutdown()


def test_tcp_echo_roundtrip():
    t = TcpTransport(FakeLogger())
    saddr = HostPort("127.0.0.1", 18571)
    caddr = HostPort("127.0.0.1", 18572)
    EchoServer(saddr, t, FakeLogger())
    client = EchoClient(caddr, t, FakeLogger(), saddr, 5)
    # Failsafe so a bug can't hang the test forever.
    failsafe = t.timer(caddr, "failsafe", 10.0, t.shutdown)
    failsafe.start()
    t.run(on_start=client.kick)
    assert client.replies == [f"m{i}" for i in range(5)]
