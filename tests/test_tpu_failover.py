"""Device-side failure detection + elections in the batched backend:
leader deaths, heartbeat-miss detection, round-robin elections, and
phase-1 repair all happen INSIDE the compiled lax.scan — no host
injection (SURVEY §2.7 'heartbeat/elections → timer-counter arrays +
vmapped transitions'; heartbeat/Participant.scala:72-209)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.parallel import make_mesh, run_ticks_sharded, shard_state
from frankenpaxos_tpu.tpu import (
    BatchedMultiPaxosConfig,
    TpuSimTransport,
    check_invariants,
    init_state,
    run_ticks,
    tick,
)
from frankenpaxos_tpu.tpu.multipaxos_batched import INF, INF16, NOOP_VALUE, PROPOSED


def make(**kw):
    defaults = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2,
    )
    defaults.update(kw)
    return BatchedMultiPaxosConfig(**defaults)


def test_prng_failures_trigger_elections_inside_scan():
    """A single run_ticks scan with fail_rate > 0 must elect new leaders
    on-device and keep committing — the whole failure/recovery loop
    compiles into one XLA program."""
    cfg = make(fail_rate=0.01, revive_rate=0.1, heartbeat_timeout=4)
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(400)  # ONE compiled scan segment; no host between ticks
    s = sim.stats()
    assert s["elections"] > 0, "no device-side elections despite failures"
    assert s["committed"] > 1000
    assert s["round"] > 0
    assert all(sim.check_invariants().values()), sim.check_invariants()


def test_failover_latency_cost_visible():
    """Failures must cost throughput (repair + silent windows) but not
    break liveness: the failing run commits less than the healthy run,
    and still grows monotonically."""
    healthy = TpuSimTransport(make(), seed=1)
    failing = TpuSimTransport(
        make(fail_rate=0.02, revive_rate=0.1, heartbeat_timeout=4), seed=1
    )
    healthy.run(300)
    failing.run(300)
    assert 0 < failing.stats()["committed"] < healthy.stats()["committed"]
    assert all(failing.check_invariants().values())


def test_deterministic_kill_elects_and_preserves_voted_value():
    """Kill group 0's round-0 owner after one acceptor voted: the
    device-side election must install candidate 1 and repair the slot to
    the voted value (never a noop, never a lost value)."""
    cfg = make(
        num_groups=2, window=8, slots_per_tick=1, lat_min=1, lat_max=1,
        thrifty=False, retry_timeout=100, max_slots_per_group=1,
        device_elections=True, heartbeat_timeout=3,
    )
    key = jax.random.PRNGKey(2)
    state = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    # Let exactly acceptor 0 of group 0 receive the Phase2a; block others.
    p2a = np.asarray(state.p2a_arrival).copy()
    p2a[1:, :, :] = INF16
    p2a[:, 1, :] = INF16
    state = dataclasses.replace(state, p2a_arrival=jnp.asarray(p2a))
    state = tick(cfg, state, jnp.int32(1), jax.random.fold_in(key, 1))
    assert int(state.committed) == 0
    voted_value = int(np.asarray(state.vote_value)[0, 0, 0])
    assert voted_value >= 0

    # Kill candidate 0 (round 0's owner) of BOTH groups.
    alive = np.asarray(state.leader_alive).copy()
    alive[0, :] = False
    state = dataclasses.replace(state, leader_alive=jnp.asarray(alive))

    t = 2
    for _ in range(30):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    assert int(state.elections) == 2  # one election per group
    rounds = np.asarray(state.leader_round)
    assert (rounds == 1).all()  # candidate 1 owns round 1
    # Group 0's voted slot kept its value; group 1's unvoted slot became
    # a noop repair.
    assert int(state.retired) == 2
    inv = check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv
    # The committed value survived: chosen_value was consumed by retire,
    # so check via the executed latency histogram being non-trivial and
    # via a fresh run asserting before retirement instead:
    state2 = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    p2a = np.asarray(state2.p2a_arrival).copy()
    p2a[1:, :, :] = INF16
    p2a[:, 1, :] = INF16
    state2 = dataclasses.replace(state2, p2a_arrival=jnp.asarray(p2a))
    state2 = tick(cfg, state2, jnp.int32(1), jax.random.fold_in(key, 1))
    alive = np.asarray(state2.leader_alive).copy()
    alive[0, :] = False
    state2 = dataclasses.replace(
        state2,
        leader_alive=jnp.asarray(alive),
        # Freeze replica delivery so chosen slots stay in the ring.
        replica_arrival=jnp.full_like(state2.replica_arrival, int(INF)),
    )
    t = 2
    for _ in range(20):
        state2 = tick(cfg, state2, jnp.int32(t), jax.random.fold_in(key, t))
        state2 = dataclasses.replace(
            state2,
            replica_arrival=jnp.full_like(state2.replica_arrival, int(INF)),
        )
        t += 1
    chosen_value = np.asarray(state2.chosen_value)
    assert int(chosen_value[0, 0]) == voted_value, "repair lost the voted value"
    assert int(chosen_value[1, 0]) == NOOP_VALUE  # unvoted -> noop repair


def test_all_candidates_dead_stalls_until_revival():
    cfg = make(
        num_groups=2, device_elections=True, heartbeat_timeout=3,
    )
    sim = TpuSimTransport(cfg, seed=3)
    sim.run(20)
    c0 = sim.committed()
    # Kill every candidate of group 0; group 1 stays healthy.
    alive = np.asarray(sim.state.leader_alive).copy()
    alive[:, 0] = False
    sim.state = dataclasses.replace(sim.state, leader_alive=jnp.asarray(alive))
    sim.run(60)
    mid = sim.stats()
    head_stalled = int(jax.device_get(sim.state.next_slot)[0])
    assert mid["committed"] > c0  # group 1 alone still commits
    sim.run(30)
    assert int(jax.device_get(sim.state.next_slot)[0]) == head_stalled, (
        "a group with no live leader candidates must not propose"
    )
    # Revive candidate 2: election fires, the group resumes.
    alive = np.asarray(sim.state.leader_alive).copy()
    alive[2, 0] = True
    sim.state = dataclasses.replace(sim.state, leader_alive=jnp.asarray(alive))
    sim.run(40)
    assert int(jax.device_get(sim.state.next_slot)[0]) > head_stalled
    assert all(sim.check_invariants().values())


def test_failover_with_reads_and_loss():
    """The full stack in one compiled program: writes under loss, device
    elections, and linearizable reads — safety invariants (including the
    read floor) hold throughout."""
    cfg = make(
        fail_rate=0.01, revive_rate=0.2, heartbeat_timeout=4,
        drop_rate=0.1, retry_timeout=6,
        read_rate=2, read_window=8, read_mode="linearizable",
    )
    sim = TpuSimTransport(cfg, seed=4)
    sim.run(400)
    s = sim.stats()
    assert s["elections"] > 0
    assert s["reads_done"] > 0
    assert s["committed"] > 500
    assert all(sim.check_invariants().values()), sim.check_invariants()


def test_failover_sharded_matches_unsharded():
    cfg = make(
        num_groups=8, fail_rate=0.02, revive_rate=0.1, heartbeat_timeout=4
    )
    key = jax.random.PRNGKey(5)
    t0 = jnp.zeros((), jnp.int32)
    plain, _ = run_ticks(cfg, init_state(cfg), t0, 200, key)
    mesh = make_mesh()
    sharded, _ = run_ticks_sharded(
        cfg, mesh, shard_state(init_state(cfg), mesh), t0, 200, key
    )
    for field in ("committed", "retired", "elections", "lat_sum"):
        assert int(jax.device_get(getattr(plain, field))) == int(
            jax.device_get(getattr(sharded, field))
        ), field
    assert int(jax.device_get(plain.elections)) > 0
    a = jax.device_get(plain.leader_alive)
    b = jax.device_get(sharded.leader_alive)
    assert (a == b).all()


def test_feature_off_is_inert():
    sim = TpuSimTransport(make(), seed=6)
    sim.run(50)
    assert jax.device_get(sim.state.leader_alive).all()
    assert int(sim.state.elections) == 0
    assert "elections" not in sim.stats()
    assert all(sim.check_invariants().values())


# ---------------------------------------------------------------------------
# FaultPlan partition semantics (tpu/faults.py): a partitioned MINORITY
# leaves the quorum intact; a partitioned MAJORITY stalls the group until
# the scheduled heal tick, after which the retry plane restores liveness.
# ---------------------------------------------------------------------------


def test_partitioned_minority_stalls_while_majority_commits():
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    # Cut acceptor 2 (one of 2f+1 = 3) for the whole run: f+1 = 2 live
    # acceptors still form every quorum, so commits proceed — but the cut
    # acceptor casts no votes after the partition starts.
    cfg = make(
        faults=FaultPlan(
            partition=(0, 0, 1), partition_start=0, partition_heal=-1
        )
    )
    cut = TpuSimTransport(cfg, seed=7)
    cut.run(150)
    s = cut.stats()
    assert s["committed"] > 150, "majority side must keep committing"
    # The cut side stalls: acceptor 2 never votes (its vote_round
    # entries would be >= 0 otherwise).
    assert not bool(
        jax.device_get((cut.state.vote_round[2] >= 0).any())
    ), "a cut acceptor must cast no votes"
    assert all(cut.check_invariants().values()), cut.check_invariants()


def test_partitioned_majority_stalls_and_heals_on_schedule():
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    # Cut TWO of three acceptors from tick 40 to tick 140: no f+1 quorum
    # exists, so commits freeze; after the heal the retry timers re-send
    # Phase2as to the whole group and the backlog drains.
    cfg = make(
        retry_timeout=6,
        faults=FaultPlan(
            partition=(0, 1, 1), partition_start=40, partition_heal=120
        ),
    )
    sim = TpuSimTransport(cfg, seed=8)
    sim.run(40)
    pre = sim.committed()
    sim.run(80)  # entirely inside the cut window [40, 120)
    mid = sim.committed()
    # In-flight quorums at the cut boundary may still land; nothing new
    # commits deep inside the window.
    assert mid - pre <= cfg.window * cfg.num_groups
    sim.run(80)  # crosses the heal tick + recovery (same compiled length)
    post = sim.committed()
    assert post - mid > 50, "liveness must resume after the scheduled heal"
    assert all(sim.check_invariants().values()), sim.check_invariants()


def test_partition_heal_is_bit_deterministic():
    """The same (config, seed) partition run replays bit-identically —
    the determinism contract shrinking and reproducers rely on."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        init_state as mk_state,
        run_ticks as mp_run,
    )

    cfg = make(
        retry_timeout=6,
        faults=FaultPlan(
            drop_rate=0.1, partition=(0, 1, 1), partition_start=20,
            partition_heal=60,
        ),
    )
    key = jax.random.PRNGKey(9)
    t0 = jnp.zeros((), jnp.int32)
    a, _ = mp_run(cfg, mk_state(cfg), t0, 120, key)
    b, _ = mp_run(cfg, mk_state(cfg), t0, 120, key)
    for field in ("committed", "retired", "lat_sum"):
        assert int(getattr(a, field)) == int(getattr(b, field))
    assert (
        jax.device_get(a.status) == jax.device_get(b.status)
    ).all()
