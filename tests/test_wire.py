import dataclasses

import pytest

from frankenpaxos_tpu.core import wire
from frankenpaxos_tpu.core.serializer import (
    BytesSerializer,
    IntSerializer,
    StringSerializer,
    WireSerializer,
)


@wire.message
@dataclasses.dataclass(frozen=True)
class Inner:
    x: int
    tag: str


@wire.message
@dataclasses.dataclass(frozen=True)
class Outer:
    inner: Inner
    blob: bytes
    items: list
    maybe: object


@pytest.mark.parametrize(
    "value",
    [
        None,
        True,
        False,
        0,
        -1,
        2**62,
        -(2**62),
        2**100,
        -(2**100),
        1.5,
        "",
        "héllo",
        b"",
        b"\x00\xff",
        [1, "a", b"b", None],
        (1, 2),
        {"k": 1, "j": [2, 3]},
        frozenset([3, 1, 2]),
        Inner(7, "t"),
        Outer(Inner(1, "i"), b"xyz", [Inner(2, "j"), 5], None),
    ],
)
def test_roundtrip(value):
    assert wire.decode(wire.encode(value)) == value


def test_roundtrip_preserves_type():
    assert isinstance(wire.decode(wire.encode((1, 2))), tuple)
    assert isinstance(wire.decode(wire.encode([1, 2])), list)
    assert isinstance(wire.decode(wire.encode(Inner(0, ""))), Inner)


def test_structural_equality_of_bytes():
    a = wire.encode(Outer(Inner(1, "i"), b"xyz", [1], None))
    b = wire.encode(Outer(Inner(1, "i"), b"xyz", [1], None))
    assert a == b


def test_trailing_bytes_rejected():
    with pytest.raises(ValueError):
        wire.decode(wire.encode(1) + b"\x00")


def test_basic_serializers():
    assert IntSerializer().from_bytes(IntSerializer().to_bytes(-42)) == -42
    assert StringSerializer().from_bytes(StringSerializer().to_bytes("hé")) == "hé"
    assert BytesSerializer().from_bytes(b"raw") == b"raw"
    s = WireSerializer()
    assert s.from_bytes(s.to_bytes(Inner(9, "z"))) == Inner(9, "z")
