import dataclasses
import random

from frankenpaxos_tpu.core import (
    Actor,
    DeliverMessage,
    FakeLogger,
    SimAddress,
    SimTransport,
    TriggerTimer,
    wire,
)


@wire.message
@dataclasses.dataclass(frozen=True)
class Ping:
    n: int


@wire.message
@dataclasses.dataclass(frozen=True)
class Pong:
    n: int


class Pinger(Actor):
    def __init__(self, address, transport, logger, peer):
        super().__init__(address, transport, logger)
        self.peer = peer
        self.got = []
        self.timer_fired = 0
        self.t = self.timer("resend", 1.0, self._on_timer)
        self.t.start()

    def _on_timer(self):
        self.timer_fired += 1
        self.chan(self.peer).send(Ping(self.timer_fired))

    def receive(self, src, msg):
        self.got.append(msg)


class Ponger(Actor):
    def receive(self, src, msg):
        self.chan(src).send(Pong(msg.n + 100))


def make():
    t = SimTransport(FakeLogger())
    a, b = SimAddress("pinger"), SimAddress("ponger")
    pinger = Pinger(a, t, FakeLogger(), b)
    ponger = Ponger(b, t, FakeLogger())
    return t, a, b, pinger, ponger


def test_timer_then_message_roundtrip():
    t, a, b, pinger, ponger = make()
    assert t.messages == []
    t.trigger_timer(a, "resend")
    assert len(t.messages) == 1
    ping = t.messages[0]
    assert (ping.src, ping.dst) == (a, b)
    t.deliver_message(ping)
    # Ponger replied; deliver the reply.
    assert len(t.messages) == 1
    t.deliver_message(t.messages[0])
    assert pinger.got == [Pong(101)]


def test_deliver_absent_message_is_noop():
    t, a, b, pinger, ponger = make()
    t.trigger_timer(a, "resend")
    msg = t.messages[0]
    t.deliver_message(msg)
    t.deliver_message(msg)  # already delivered: no-op
    assert len(t.messages) == 1  # just the pong


def test_trigger_stopped_timer_is_noop():
    t, a, b, pinger, ponger = make()
    pinger.t.stop()
    t.trigger_timer(a, "resend")
    assert pinger.timer_fired == 0
    assert t.messages == []


def test_timer_stops_itself_but_can_restart():
    t, a, b, pinger, ponger = make()
    t.trigger_timer(a, "resend")
    assert not pinger.t.running
    t.trigger_timer(a, "resend")  # no-op: not running
    assert pinger.timer_fired == 1
    pinger.t.reset()
    t.trigger_timer(a, "resend")
    assert pinger.timer_fired == 2


def test_duplicate_and_drop():
    t, a, b, pinger, ponger = make()
    t.trigger_timer(a, "resend")
    msg = t.messages[0]
    t.duplicate_message(msg)
    assert t.messages.count(msg) == 2
    t.drop_message(msg)
    assert t.messages.count(msg) == 1
    t.drop_message(msg)
    assert t.messages == []


def test_partition():
    t, a, b, pinger, ponger = make()
    t.trigger_timer(a, "resend")
    t.partition_actor(b)
    assert t.messages == []  # pending messages to b dropped
    pinger.t.start()
    t.trigger_timer(a, "resend")
    assert t.messages == []  # sends to b dropped
    t.unpartition_actor(b)
    pinger.t.start()
    t.trigger_timer(a, "resend")
    assert len(t.messages) == 1


def test_generate_command_deterministic_and_weighted():
    t, a, b, pinger, ponger = make()
    t.trigger_timer(a, "resend")
    pinger.t.start()
    rng1, rng2 = random.Random(7), random.Random(7)
    cmds1 = [t.generate_command(rng1) for _ in range(20)]
    cmds2 = [t.generate_command(rng2) for _ in range(20)]
    assert cmds1 == cmds2
    kinds = {type(c) for c in cmds1}
    assert kinds <= {DeliverMessage, TriggerTimer}


def test_history_recorded():
    t, a, b, pinger, ponger = make()
    t.trigger_timer(a, "resend")
    t.deliver_message(t.messages[0])
    assert len(t.history) == 2
    assert isinstance(t.history[0], TriggerTimer)
    assert isinstance(t.history[1], DeliverMessage)


def test_send_no_flush_buffers_until_flush():
    t = SimTransport(FakeLogger())
    a, b = SimAddress("x"), SimAddress("y")

    class Silent(Actor):
        def receive(self, src, msg):
            pass

    x = Silent(a, t, FakeLogger())
    Silent(b, t, FakeLogger())
    x.chan(b).send_no_flush(Ping(1))
    x.chan(b).send_no_flush(Ping(2))
    assert t.messages == []
    x.chan(b).flush()
    assert len(t.messages) == 2
