"""AST lint for the kernel layer (ops/): three structural contracts.

1. ``pallas_call`` appears ONLY inside ``frankenpaxos_tpu/ops/`` — the
   registry is the single dispatch point; a backend reaching for Pallas
   directly bypasses the policy knob, the autotune table, and the
   bit-identity test matrix.
2. Every plane registered for a backend is actually dispatched by that
   backend's tick (a ``...dispatch("<plane>", cfg, ...)`` call with the
   plane name as a literal) — registering a kernel nobody calls is dead
   weight; calling one that isn't registered is a KeyError at trace
   time, caught here at lint time instead.
3. Every registered kernel declares a reference twin with the SAME
   positional signature (kernel = reference + block/interpret), and the
   owning config carries a validated ``kernels: KernelPolicy`` knob.

Intentional exceptions go in ALLOWLIST with a reason.
"""

import ast
import inspect
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent / "frankenpaxos_tpu"

ALLOWLIST: dict = {
    # Nothing is currently exempt.
}


def _py_files(base: pathlib.Path):
    return sorted(p for p in base.rglob("*.py") if "__pycache__" not in p.parts)


def test_pallas_call_only_inside_ops():
    offenders = []
    for path in _py_files(ROOT):
        rel = path.relative_to(ROOT)
        if rel.parts[0] == "ops":
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            name = None
            if isinstance(node, ast.Attribute) and node.attr == "pallas_call":
                name = "pallas_call"
            elif isinstance(node, ast.Name) and node.id == "pallas_call":
                name = "pallas_call"
            if name and (str(rel), name) not in ALLOWLIST:
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "pallas_call outside frankenpaxos_tpu/ops/ — route the plane "
        f"through ops.registry.dispatch instead: {offenders}"
    )


def _dispatched_plane_names(module_path: pathlib.Path) -> set:
    """Literal plane names passed to a ``*.dispatch(...)`` call."""
    tree = ast.parse(module_path.read_text())
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_dispatch = (
            isinstance(func, ast.Attribute) and func.attr == "dispatch"
        ) or (isinstance(func, ast.Name) and func.id == "dispatch")
        if not is_dispatch or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            names.add(first.value)
    return names


# backend key in the registry -> the tpu module that owns it.
BACKEND_MODULES = {
    "multipaxos": "multipaxos_batched.py",
    "mencius": "mencius_batched.py",
    "craq": "craq_batched.py",
}


def test_every_registered_plane_is_dispatched_by_its_backend():
    from frankenpaxos_tpu.ops import registry

    covered = registry.coverage()
    assert set(covered) == set(BACKEND_MODULES), (
        "registry backends and lint BACKEND_MODULES drifted apart — "
        "teach the lint about the new backend"
    )
    for backend, planes in covered.items():
        module = ROOT / "tpu" / BACKEND_MODULES[backend]
        dispatched = _dispatched_plane_names(module)
        missing = set(planes) - dispatched
        assert not missing, (
            f"{BACKEND_MODULES[backend]} never dispatches registered "
            f"plane(s) {sorted(missing)}"
        )
        unknown = dispatched - set(registry.PLANES)
        assert not unknown, (
            f"{BACKEND_MODULES[backend]} dispatches unregistered "
            f"plane(s) {sorted(unknown)}"
        )


def test_every_kernel_declares_a_reference_twin():
    from frankenpaxos_tpu.ops import registry

    for name, plane in registry.PLANES.items():
        assert plane.reference.__name__.startswith("reference_"), name
        ref_params = list(inspect.signature(plane.reference).parameters)
        ker_params = list(inspect.signature(plane.kernel).parameters)
        extras = {"block", "interpret"}
        assert [p for p in ker_params if p not in extras] == [
            p for p in ref_params
        ], (
            f"plane {name}: kernel signature must be the reference's "
            f"plus block/interpret (got {ker_params} vs {ref_params})"
        )


def test_covered_configs_carry_validated_kernel_policy():
    """Each covered backend's config declares ``kernels: KernelPolicy``
    and its __post_init__ validates it (so a bad policy fails at config
    construction, not at trace time)."""
    for backend, fname in BACKEND_MODULES.items():
        path = ROOT / "tpu" / fname
        tree = ast.parse(path.read_text())
        cfg_classes = [
            node
            for node in ast.walk(tree)
            if isinstance(node, ast.ClassDef) and node.name.endswith("Config")
        ]
        assert cfg_classes, fname
        for cls in cfg_classes:
            fields = {
                stmt.target.id
                for stmt in cls.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
            assert "kernels" in fields, f"{fname}:{cls.name} lacks kernels"
            post = next(
                (
                    stmt
                    for stmt in cls.body
                    if isinstance(stmt, ast.FunctionDef)
                    and stmt.name == "__post_init__"
                ),
                None,
            )
            assert post is not None, f"{fname}:{cls.name}"
            validates = any(
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "validate"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == "kernels"
                for node in ast.walk(post)
            )
            assert validates, (
                f"{fname}:{cls.name}.__post_init__ must call "
                "self.kernels.validate()"
            )
