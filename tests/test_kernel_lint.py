"""Kernel-layer contract (thin wrapper): ``pallas_call`` only inside
``ops/``, every registered plane dispatched by its backend, every
kernel signature-twinned with a ``reference_*`` function, and every
covered config carrying a validated ``kernels: KernelPolicy`` knob.

The checkers are the ``kernel-*`` rules in ``frankenpaxos_tpu/analysis``
(the registry-introspection rules import ``ops.registry``, so this
wrapper doubles as their import smoke test). Intentional exceptions go
in ``analysis/allowlists.py`` with a reason.
"""

import pytest

from frankenpaxos_tpu import analysis

pytestmark = pytest.mark.lint


@pytest.mark.parametrize(
    "rule_id",
    [
        "kernel-pallas-containment",
        "kernel-dispatch-coverage",
        "kernel-reference-twin",
        "kernel-policy-knob",
    ],
)
def test_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()


def test_state_dead_write_clean():
    """The dead-write detector rides with the kernel lint wrapper:
    every State leaf a tick writes must reach an invariant, telemetry,
    or host-summary sink, or it is dead bytes on every tick sweep.
    Since ANALYSIS_VERSION 2.4 this is the jaxpr-reachability rule
    (``state-dead-write-reachable``, analysis/rules_dataflow.py) — the
    AST ``replace()``-pattern heuristic it replaced is retired."""
    report = analysis.run(rule_ids=["state-dead-write-reachable"])
    assert not report.findings, "\n" + report.format()
