"""Kernel-layer contract (thin wrapper): ``pallas_call`` only inside
``ops/``, every registered plane dispatched by its backend, every
kernel signature-twinned with a ``reference_*`` function, and every
covered config carrying a validated ``kernels: KernelPolicy`` knob.

The checkers are the ``kernel-*`` rules in ``frankenpaxos_tpu/analysis``
(the registry-introspection rules import ``ops.registry``, so this
wrapper doubles as their import smoke test). Intentional exceptions go
in ``analysis/allowlists.py`` with a reason.
"""

import pytest

from frankenpaxos_tpu import analysis

pytestmark = pytest.mark.lint


@pytest.mark.parametrize(
    "rule_id",
    [
        "kernel-pallas-containment",
        "kernel-dispatch-coverage",
        "kernel-reference-twin",
        "kernel-policy-knob",
    ],
)
def test_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()


def test_state_dead_write_clean():
    """The dead-write detector (new in the analysis subsystem) rides
    with the kernel lint wrapper: every State field must be consumed
    somewhere, or it is dead bytes on every tick sweep."""
    report = analysis.run(rule_ids=["state-dead-write"])
    assert not report.findings, "\n" + report.format()
