"""Batched Simple BPaxos (tpu/bpaxos_batched.py): the leaderless
dependency-graph backend built on the ``depgraph_execute`` plane.
Progress, conservation, and THE dep-graph safety invariant (no replica
executes a vertex before the vertices its adjacency row names), under
conflict-density extremes, closed workloads, faults, and the traced
conflict knob."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu.bpaxos_batched import (
    BatchedBPaxosConfig,
    analysis_config,
    check_invariants,
    init_state,
    run_ticks,
)
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan


def _run(cfg, ticks, seed=0):
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.zeros((), jnp.int32), ticks,
        jax.random.PRNGKey(seed),
    )
    inv = check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    return state


def test_bpaxos_progress_and_coexecution():
    """The canonical config makes steady progress; at a dense conflict
    regime the SCC condensation actually fires (same-tick mutual
    conflicts form cycles, so closure batches co-execute)."""
    state = _run(analysis_config(), 200)
    assert int(state.committed_total) > 300
    assert int(state.executed_total) > 1000  # 4 replicas
    assert int(state.retired_total) > 200
    dense = dataclasses.replace(analysis_config(), conflict_rate=0.75)
    state_d = _run(dense, 200, seed=1)
    assert int(state_d.coexecuted) > 0


def test_bpaxos_closed_workload_drains_exactly():
    """max_cmds_per_leader caps each lane: the run drains to exactly
    L x N commands retired and L x N x R replica executions, then
    stays there (the ring empties, nothing else is proposed)."""
    cfg = BatchedBPaxosConfig(
        num_leaders=3, window=16, cmds_per_tick=2, num_replicas=4,
        conflict_rate=0.25, max_cmds_per_leader=20,
    )
    state = _run(cfg, 120)
    assert int(state.retired_total) == 3 * 20
    assert int(state.executed_total) == 3 * 20 * 4
    assert int(state.committed_total) == 3 * 20
    assert not bool(jnp.any(state.proposed))
    assert bool(jnp.all(state.adj == jnp.uint32(0)))


def test_bpaxos_conflict_density_orders_throughput():
    """conflict_rate=0 never links vertices across lanes (commands are
    independent, execution tracks commit), while a fully conflicting
    workload stalls chains behind every straggler — strictly less
    execution on the same tick budget either way."""
    lo = _run(
        dataclasses.replace(analysis_config(), conflict_rate=0.0), 150
    )
    hi = _run(
        dataclasses.replace(analysis_config(), conflict_rate=1.0),
        150, seed=2,
    )
    assert int(lo.executed_total) > int(hi.executed_total) > 0


def test_bpaxos_partition_defers_to_heal_then_resumes():
    """A leader-axis partition stalls the cut lane's commits (and every
    dependency chain through them) until the heal tick; afterwards the
    backlog drains and the run ends healthy."""
    plan = FaultPlan(
        partition=(0, 0, 1), partition_start=10, partition_heal=60,
    )
    cfg = analysis_config(faults=plan)
    key = jax.random.PRNGKey(4)
    t0 = jnp.zeros((), jnp.int32)
    mid, t_mid = run_ticks(cfg, init_state(cfg), t0, 55, key)
    assert all(
        bool(v) for v in check_invariants(cfg, mid, t_mid).values()
    )
    exec_mid = int(mid.executed_total)  # before donation eats `mid`
    end, _ = run_ticks(cfg, mid, t_mid, 120, key)
    # The cut window held executions back; the heal releases them.
    assert int(end.executed_total) > exec_mid + 100


def test_bpaxos_traced_conflict_knob_matches_static_rate():
    """A WorkloadPlan carrying conflict_rate routes the SAME bit-sliced
    sampler through a traced scalar: equal rates draw equal bits, so
    the protocol state is bit-identical to the static-config twin —
    and the density re-sweeps on the compiled program via
    set_conflict_rate, no retrace."""
    from frankenpaxos_tpu.tpu import workload as workload_mod

    cfg_s = analysis_config()  # static conflict_rate=0.25
    plan = dataclasses.replace(WorkloadPlan.none(), conflict_rate=0.25)
    cfg_t = dataclasses.replace(cfg_s, workload=plan)
    key = jax.random.PRNGKey(5)
    t0 = jnp.zeros((), jnp.int32)
    ss, _ = run_ticks(cfg_s, init_state(cfg_s), t0, 80, key)
    st, tt = run_ticks(cfg_t, init_state(cfg_t), t0, 80, key)
    for f in (
        "next_cmd", "gc_head", "head_r", "proposed", "propose_tick",
        "commit_tick", "committed", "rep_commit_tick", "adj",
        "committed_total", "executed_total", "retired_total",
        "coexecuted", "lat_sum", "lat_hist",
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(ss, f)), np.asarray(getattr(st, f)),
            err_msg=f,
        )
    # Re-sweep the density as STATE on the same compiled executable.
    st2 = init_state(cfg_t)
    st2 = dataclasses.replace(
        st2, workload=workload_mod.set_conflict_rate(st2.workload, 0.875)
    )
    s9, t9 = run_ticks(cfg_t, st2, t0, 80, key)
    inv = check_invariants(cfg_t, s9, t9)
    assert all(bool(v) for v in inv.values()), inv
    assert int(s9.executed_total) > 0
