import pytest

from frankenpaxos_tpu.core.logger import FakeLogger, FatalError, LogLevel
from frankenpaxos_tpu.monitoring import (
    FakeCollectors,
    PrometheusCollectors,
)


def test_logger_levels_and_lazy():
    log = FakeLogger(level=LogLevel.WARN)
    forced = []

    def lazy():
        forced.append(1)
        return "expensive"

    log.debug(lazy)
    assert forced == []  # below level: not forced
    log.warn(lazy)
    assert forced == [1]
    assert log.records == [(LogLevel.WARN, "expensive")]


def test_checks():
    log = FakeLogger()
    log.check(True)
    log.check_eq(1, 1)
    log.check_lt(1, 2)
    log.check_ge(2, 2)
    with pytest.raises(FatalError):
        log.check(False)
    with pytest.raises(FatalError):
        log.check_eq(1, 2)
    with pytest.raises(FatalError):
        log.check_ne("a", "a")


def test_counter_gauge_summary():
    c = FakeCollectors()
    ctr = c.counter("requests_total", "reqs")
    ctr.inc()
    ctr.inc(2)
    assert ctr.get() == 3
    g = c.gauge("depth", "queue depth")
    g.set(5)
    g.dec()
    assert g.get() == 4
    s = c.summary("latency", "ms")
    for v in [1.0, 2.0, 3.0, 4.0]:
        s.observe(v)
    assert s.count == 4 and s.sum == 10.0
    assert 1.0 <= s.quantile(0.5) <= 4.0


def test_labels_and_exposition():
    c = PrometheusCollectors()
    ctr = c.counter("msgs_total", "messages", labels=("type",))
    ctr.labels("ping").inc()
    ctr.labels("ping").inc()
    ctr.labels("pong").inc()
    text = c.expose_text()
    assert 'msgs_total{type="ping"} 2' in text
    assert 'msgs_total{type="pong"} 1' in text
    assert "# TYPE msgs_total counter" in text


def test_same_metric_returned():
    c = FakeCollectors()
    assert c.counter("x", "") is c.counter("x", "")
    with pytest.raises(TypeError):
        c.gauge("x", "")
