"""The unified fault-injection subsystem (tpu/faults.py) + the
simulation-testing harness (harness/simtest.py).

The load-bearing guarantee first: ``FaultPlan.none()`` is a STRUCTURAL
no-op. The golden values below were captured from the pre-fault-subsystem
tree (PR 2 head, commit f899c3f) on fixed configs/seeds — committed
counters plus a sha256 over the full protocol state arrays — so any
fault-threading change that perturbs a default run by even one bit fails
here against the true pre-PR behavior, not against a tautology.
"""

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.harness import simtest
from frankenpaxos_tpu.tpu import (
    craq_batched,
    mencius_batched,
    multipaxos_batched,
    unreplicated_batched,
)
from frankenpaxos_tpu.tpu.faults import (
    FaultPlan,
    effective_process_rates,
    message_faults,
    partition_row,
    tcp_latency,
)


def _hash(state, fields):
    m = hashlib.sha256()
    for f in fields:
        m.update(np.asarray(jax.device_get(getattr(state, f))).tobytes())
    return m.hexdigest()[:16]


# ---------------------------------------------------------------------------
# none() bit-identity against pre-PR golden captures (3+ backends x 3 seeds)
# ---------------------------------------------------------------------------

GOLDEN_MULTIPAXOS = {
    0: (582, 562, 3426, "dd70eeb17ab45de2"),
    1: (581, 530, 3487, "c665a10d449618ae"),
    2: (583, 551, 3340, "ec2d56f23217dda9"),
}
GOLDEN_MENCIUS = {
    0: (629, 629, 0, "43957a3dc956da37"),
    1: (648, 648, 0, "432e6df357085ede"),
    2: (654, 654, 0, "7e2bae9c0af561e9"),
}
GOLDEN_CRAQ = {
    0: (374, 743, 251, "b6fe4b6285011bda"),
    1: (368, 747, 231, "0025adf193587ca4"),
    2: (370, 750, 219, "d9c0363c64b1db0c"),
}
GOLDEN_UNREPLICATED = {
    0: (929, 3663, "589abaf0933332b2"),
    1: (929, 3705, "bbd795f9ce1b7c01"),
    2: (928, 3692, "f8fe3872c1751c1a"),
}


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_multipaxos(seed):
    mp = multipaxos_batched
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, lat_min=1,
        lat_max=3, drop_rate=0.05, retry_timeout=8,
    )
    assert cfg.faults == FaultPlan.none()
    st, _ = mp.run_ticks(
        cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), 120,
        jax.random.PRNGKey(seed),
    )
    got = (
        int(st.committed), int(st.retired), int(st.lat_sum),
        _hash(st, ("status", "slot_value", "chosen_round", "head",
                   "next_slot", "acc_round", "vote_round", "vote_value")),
    )
    assert got == GOLDEN_MULTIPAXOS[seed]


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_mencius(seed):
    me = mencius_batched
    cfg = me.BatchedMenciusConfig(
        f=1, num_leaders=4, window=16, slots_per_tick=2, idle_rate=0.1,
        drop_rate=0.05, retry_timeout=8,
    )
    st, _ = me.run_ticks(
        cfg, me.init_state(cfg), jnp.zeros((), jnp.int32), 120,
        jax.random.PRNGKey(seed),
    )
    got = (
        int(st.committed), int(st.committed_real), int(st.skips),
        _hash(st, ("status", "slot_value", "head", "next_slot",
                   "committed_prefix", "voted")),
    )
    assert got == GOLDEN_MENCIUS[seed]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_craq(seed):
    cr = craq_batched
    cfg = cr.BatchedCraqConfig(
        num_chains=4, chain_len=3, num_keys=8, window=8,
        writes_per_tick=2, reads_per_tick=2, read_window=8,
    )
    st, _ = cr.run_ticks(
        cfg, cr.init_state(cfg), jnp.zeros((), jnp.int32), 120,
        jax.random.PRNGKey(seed),
    )
    got = (
        int(st.writes_done), int(st.reads_done), int(st.reads_dirty),
        _hash(st, ("w_status", "w_version", "node_version", "node_dirty",
                   "r_status")),
    )
    assert got == GOLDEN_CRAQ[seed]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_unreplicated(seed):
    ur = unreplicated_batched
    cfg = ur.BatchedUnreplicatedConfig(
        num_servers=4, window=16, ops_per_tick=2,
    )
    st, _ = ur.run_ticks(
        cfg, ur.init_state(cfg), jnp.zeros((), jnp.int32), 120,
        jax.random.PRNGKey(seed),
    )
    got = (
        int(st.done), int(st.lat_sum),
        _hash(st, ("status", "issue", "arrival", "executed")),
    )
    assert got == GOLDEN_UNREPLICATED[seed]


# ---------------------------------------------------------------------------
# FaultPlan semantics
# ---------------------------------------------------------------------------


def test_fault_plan_validation_rejects_malformed_plans():
    with pytest.raises(AssertionError):
        FaultPlan(drop_rate=1.0).validate()
    with pytest.raises(AssertionError):
        FaultPlan(drop_rate=-0.1).validate()
    with pytest.raises(AssertionError):
        FaultPlan(jitter=-1).validate()
    with pytest.raises(AssertionError):
        FaultPlan(partition=(0, 2, 0)).validate(axis=3)
    with pytest.raises(AssertionError):
        FaultPlan(partition=(0, 1)).validate(axis=3)  # wrong axis
    with pytest.raises(AssertionError):
        FaultPlan(
            partition=(0, 1, 0), partition_start=50, partition_heal=40
        ).validate(axis=3)
    # And a well-formed plan passes, also via the config path.
    FaultPlan(
        drop_rate=0.1, partition=(0, 0, 1), partition_start=10,
        partition_heal=60,
    ).validate(axis=3)
    with pytest.raises(AssertionError):
        multipaxos_batched.BatchedMultiPaxosConfig(
            faults=FaultPlan(partition=(0, 1))  # axis is 2f+1 = 3
        )


def test_fault_plan_round_trips_through_json():
    plan = FaultPlan(
        drop_rate=0.125, dup_rate=0.05, jitter=2, crash_rate=0.01,
        revive_rate=0.2, partition=(0, 1, 1), partition_start=8,
        partition_heal=80,
    )
    again = FaultPlan.from_dict(json.loads(json.dumps(plan.to_dict())))
    assert again == plan
    assert again.has_partition and again.has_crash and again.active


def test_message_faults_inactive_is_identity_and_active_draws():
    key = jax.random.PRNGKey(0)
    lat = jnp.full((3, 4, 8), 2, jnp.int32)
    d, lat2 = message_faults(FaultPlan.none(), key, (3, 4, 8), lat)
    assert bool(jnp.all(d)) and lat2 is lat
    d, lat2 = message_faults(
        FaultPlan(drop_rate=0.5), key, (3, 4, 8), lat
    )
    frac = float(jnp.mean(d.astype(jnp.float32)))
    assert 0.3 < frac < 0.7  # ~half dropped
    assert bool(jnp.all(lat2 == lat))  # no jitter knob -> untouched
    # Duplication strictly raises delivery probability under drops.
    d_dup, _ = message_faults(
        FaultPlan(drop_rate=0.5, dup_rate=0.9), key, (3, 4, 8), lat
    )
    assert int(jnp.sum(d_dup)) > int(jnp.sum(d))
    # Jitter only delays (never earlier than base latency).
    d_j, lat_j = message_faults(
        FaultPlan(jitter=3), key, (3, 4, 8), lat
    )
    assert bool(jnp.all(d_j)) and bool(jnp.all(lat_j >= lat))
    assert int(jnp.max(lat_j)) > 2  # some jitter actually landed


def test_tcp_latency_drops_become_penalties():
    key = jax.random.PRNGKey(1)
    lat = jnp.full((64,), 2, jnp.int32)
    out = tcp_latency(FaultPlan.none(), key, (64,), lat)
    assert out is lat
    out = tcp_latency(
        FaultPlan(drop_rate=0.5, drop_penalty=7), key, (64,), lat
    )
    assert bool(jnp.all((out == 2) | (out == 9)))  # base or base+penalty
    assert int(jnp.sum(out == 9)) > 10


def test_partition_row_window_semantics():
    plan = FaultPlan(
        partition=(0, 1, 1), partition_start=10, partition_heal=20
    )
    before = partition_row(plan, jnp.int32(9), 3)
    during = partition_row(plan, jnp.int32(10), 3)
    after = partition_row(plan, jnp.int32(20), 3)
    assert bool(jnp.all(before)) and bool(jnp.all(after))
    assert [bool(x) for x in during] == [True, False, False]
    # Never-healing: stays cut forever.
    never = dataclasses.replace(plan, partition_heal=-1)
    assert not bool(partition_row(never, jnp.int32(10 ** 6), 3)[1])


def test_effective_process_rates_compose():
    assert effective_process_rates(FaultPlan.none(), 0.02, 0.1) == (0.02, 0.1)
    f, r = effective_process_rates(
        FaultPlan(crash_rate=0.5, revive_rate=0.3), 0.5, 0.1
    )
    assert abs(f - 0.75) < 1e-9 and r == 0.3


# ---------------------------------------------------------------------------
# Faulted behavior on the flagship
# ---------------------------------------------------------------------------


def _mp_cfg(**kw):
    base = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2, retry_timeout=8,
    )
    base.update(kw)
    return multipaxos_batched.BatchedMultiPaxosConfig(**base)


def test_drops_cost_throughput_but_not_safety():
    mp = multipaxos_batched
    healthy = _mp_cfg()
    faulty = _mp_cfg(faults=FaultPlan(drop_rate=0.25, dup_rate=0.1, jitter=2))
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)
    sh, th = mp.run_ticks(healthy, mp.init_state(healthy), t0, 120, key)
    sf, tf = mp.run_ticks(faulty, mp.init_state(faulty), t0, 120, key)
    assert 0 < int(sf.committed) < int(sh.committed)
    inv = mp.check_invariants(faulty, sf, tf)
    assert all(bool(v) for v in inv.values()), inv
    # Faults feed the telemetry drops counter for free.
    from frankenpaxos_tpu.tpu.telemetry import COL

    assert int(sf.telemetry.totals[COL["drops"]]) > 0
    assert int(sh.telemetry.totals[COL["drops"]]) == 0


def test_crash_plan_drives_device_elections():
    mp = multipaxos_batched
    cfg = _mp_cfg(faults=FaultPlan(crash_rate=0.03, revive_rate=0.2))
    st, t = mp.run_ticks(
        cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), 200,
        jax.random.PRNGKey(0),
    )
    assert int(st.elections) > 0
    assert int(st.committed) > 0
    inv = mp.check_invariants(cfg, st, t)
    assert all(bool(v) for v in inv.values()), inv
    # And the telemetry leader_changes counter saw them.
    from frankenpaxos_tpu.tpu.telemetry import COL

    assert int(st.telemetry.totals[COL["leader_changes"]]) == int(st.elections)


# ---------------------------------------------------------------------------
# simtest harness
# ---------------------------------------------------------------------------


def test_random_plan_is_deterministic_and_well_formed():
    import random

    spec = simtest.SPECS["multipaxos"]
    a = [simtest.random_plan(random.Random(7), spec, 120) for _ in range(8)]
    b = [simtest.random_plan(random.Random(7), spec, 120) for _ in range(8)]
    assert a == b
    for plan in a:
        plan.validate(axis=spec.partition_axis)
        if plan.has_partition:
            assert plan.partition_heal % simtest.SEGMENT == 0
            assert 0 < plan.partition_heal <= 120


def test_run_schedule_reports_progress_and_invariants():
    spec = simtest.SPECS["multipaxos"]
    res = simtest.run_schedule(
        spec, FaultPlan(drop_rate=0.1), seed=3, ticks=80, segment=40
    )
    assert res["ok"] and not res["violations"]
    assert len(res["progress"]) == 2
    assert res["progress"][-1] > 0
    assert FaultPlan.from_dict(res["plan"]) == FaultPlan(drop_rate=0.1)


def test_run_many_seeds_vmaps_invariants_over_the_seed_axis():
    spec = simtest.SPECS["mencius"]
    res = simtest.run_many_seeds(
        spec, FaultPlan(drop_rate=0.15, jitter=1), seeds=[0, 1, 2, 3],
        ticks=60,
    )
    assert res["ok"] and res["per_seed_ok"] == [True] * 4
    assert all(p > 0 for p in res["progress"])


def test_run_schedule_replays_run_many_seeds_histories():
    """The find-then-shrink contract: a (plan, seed) found by the
    vmapped device sweep must replay IDENTICALLY under the segmented
    invariant-checking runner (per-tick keys fold the global tick
    index in both), or counterexamples could never be minimized."""
    spec = simtest.SPECS["multipaxos"]
    plan = FaultPlan(drop_rate=0.15, jitter=1)
    seg = simtest.run_schedule(spec, plan, seed=3, ticks=80, segment=40)
    vmapped = simtest.run_many_seeds(spec, plan, seeds=[3], ticks=80)
    assert seg["progress"][-1] == vmapped["progress"][0]


def test_liveness_resumes_after_scheduled_heal():
    spec = simtest.SPECS["multipaxos"]
    plan = FaultPlan(
        partition=(0, 1, 1), partition_start=20,
        partition_heal=simtest.SEGMENT,
    )
    res = simtest.check_liveness_after_heal(spec, plan, seed=0)
    assert res["resumed"] and res["invariants_ok"]


def test_shrink_minimizes_to_a_reproducer_json(tmp_path):
    """The bad-history workflow end-to-end: a seeded, deliberately-broken
    invariant ("this run never drops a message") fails under a fat plan;
    the greedy shrinking loop must strip every irrelevant knob and
    minimize drop_rate, and the reproducer JSON must round-trip and
    still fail."""
    from frankenpaxos_tpu.tpu.telemetry import COL

    spec = simtest.SPECS["multipaxos"]
    seed, ticks = 5, 48

    def failing(plan: FaultPlan) -> bool:
        mp = spec.module
        cfg = spec.make_config(plan)
        st, _ = mp.run_ticks(
            cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), ticks,
            jax.random.PRNGKey(seed),
        )
        return int(st.telemetry.totals[COL["drops"]]) > 0

    fat = FaultPlan(
        drop_rate=0.2, partition=(0, 0, 1), partition_start=16,
        partition_heal=40,
    )
    small = simtest.shrink(spec, fat, seed, ticks, failing=failing)
    # Everything irrelevant to "a drop happened" must be gone...
    assert small.dup_rate == 0.0
    assert small.jitter == 0
    assert small.crash_rate == 0.0
    # ...and exactly ONE drop source survives, minimized. (A partition
    # cut IS a drop on the multipaxos planes, so the greedy loop keeps
    # whichever single source it reached first and strips the other.)
    assert (small.drop_rate > 0.0) != small.has_partition
    if small.drop_rate:
        assert small.drop_rate < fat.drop_rate
    else:
        assert small.partition_start == 0  # window slid to the left edge
        span0 = fat.partition_heal - fat.partition_start
        assert 0 < small.partition_heal - small.partition_start < span0
    assert failing(small)

    path = tmp_path / "reproducer.json"
    simtest.dump_reproducer(
        str(path), spec, small, seed, ticks, note="drops>0 sentinel"
    )
    spec2, plan2, seed2, ticks2 = simtest.load_reproducer(str(path))
    assert spec2 is spec and plan2 == small
    assert (seed2, ticks2) == (seed, ticks)
    assert failing(plan2)


def test_shrink_ddmin_minimizes_partition_side_bit_sets():
    """Delta debugging over the cut SET: a failure that needs replicas
    0 AND 2 cut (1, 3, 4 irrelevant). The greedy candidate list only
    drops the LAST cut bit, so it strips 4 and 3 but then stalls at
    {0, 1, 2} (dropping 2 passes); the ddmin pass must minimize the cut
    to exactly {0, 2}."""
    spec = simtest.SPECS["multipaxos"]  # predicate never runs the sim

    def failing(plan: FaultPlan) -> bool:
        ones = {i for i, s in enumerate(plan.partition) if s}
        return {0, 2} <= ones

    fat = FaultPlan(
        partition=(1, 1, 1, 1, 1), partition_start=0, partition_heal=20
    )
    small = simtest.shrink(spec, fat, 0, 48, failing=failing)
    assert [i for i, s in enumerate(small.partition) if s] == [0, 2]

    # 1-minimality survives when ONLY ddmin can see it: a predicate
    # needing the first and last replica ({0, 4}) stalls greedy
    # immediately (dropping bit 4 passes), ddmin still minimizes.
    def failing_ends(plan: FaultPlan) -> bool:
        ones = {i for i, s in enumerate(plan.partition) if s}
        return {0, 4} <= ones

    small2 = simtest.shrink(spec, fat, 0, 48, failing=failing_ends)
    assert [i for i, s in enumerate(small2.partition) if s] == [0, 4]


def test_sweep_smoke():
    res = simtest.sweep(
        backends=["unreplicated"], schedules=1, seeds_per_schedule=2,
        ticks=80, base_seed=1, check_liveness=False,
    )
    assert res["ok"], res
    row = res["backends"]["unreplicated"]
    assert row["runs"] == 2 and not row["failures"]


def test_registry_covers_every_backend_and_reps_run():
    """Registry sanity, tier-1 sized: all 16 backends are registered
    with valid config factories (construction exercises every
    __post_init__ + FaultPlan.validate), and four representative specs
    run a none-plan schedule with green invariants and progress. The
    full 16-backend run is the slow-marked test below."""
    assert len(simtest.SPECS) == 16
    for spec in simtest.SPECS.values():
        cfg = spec.make_config(FaultPlan.none())
        assert cfg.faults == FaultPlan.none()
    for name in ("multipaxos", "craq", "scalog"):
        res = simtest.run_schedule(
            simtest.SPECS[name], FaultPlan.none(), seed=0, ticks=40,
            segment=40,
        )
        assert res["ok"], (name, res["violations"])
        assert res["progress"][-1] > 0, name


@pytest.mark.slow
def test_every_registered_spec_runs_a_plain_schedule():
    """Full-fleet variant: all 16 backends run one none-plan schedule
    with green invariants and nonzero progress."""
    for name, spec in simtest.SPECS.items():
        res = simtest.run_schedule(
            spec, FaultPlan.none(), seed=0, ticks=40, segment=40
        )
        assert res["ok"], (name, res["violations"])
        assert res["progress"][-1] > 0, name
