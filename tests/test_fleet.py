"""Fleet-axis tests (``parallel/sharding.py`` two-axis product mesh):

  * DETERMINISM — per-instance runs on the fleet mesh are bit-identical
    (3 seeds, full state) to the same configs run sequentially on a
    mesh WITHOUT the fleet axis, with the kernel planes ENGAGED
    (interpret mode — the actual shard_map-lowered kernel path,
    executable on CPU) and on the reference path,
  * MESH-SHAPE AGNOSTICISM — the same brick on (2, 4), (4, 2), and
    (1, 8) product meshes replays bit for bit,
  * JIT-CACHE ISOLATION — ``_runner``s are keyed per (backend, mesh):
    a brick on one fleet shape never touches another shape's cache,
    and a traced-rate re-sweep keeps every cache FLAT (one compiled
    executable per mesh — the fleet contract the
    ``trace-fleet-onecompile`` analysis rule also pins),
  * AUTOTUNE — the per-device block lookup under the product mesh
    divides the batch axis by the GROUP-axis extent, never the total
    device count (the fleet axis changes the divisor),
  * donation aliases surviving the product mesh, and the divisibility
    guards.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.parallel import sharding as sh
from frankenpaxos_tpu.tpu import multipaxos_batched as mb
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan

# A 4-instance brick of distinct traced cells: offered rates and
# [drop, dup, crash, revive] fault-rate rows.
RATES = (0.5, 1.0, 1.5, 2.0)
FRATES = (
    (0.0, 0.0, 0.0, 0.0),
    (0.05, 0.0, 0.0, 0.0),
    (0.1, 0.05, 0.0, 0.0),
    (0.2, 0.0, 0.01, 0.2),
)


def _traced_cfg(**kw):
    """The flagship analysis config with both sweep axes state-side:
    traced Bernoulli fault rates + a shaped (traced-rate) workload."""
    cfg = mb.analysis_config(
        faults=FaultPlan(traced=True),
        workload=WorkloadPlan(arrival="constant", rate=1.0),
    )
    return dataclasses.replace(cfg, num_groups=8, **kw)


def _brick(cfg, n=4):
    return sh.fleet_states(
        "multipaxos", cfg, n, rates=RATES[:n], fault_rates=FRATES[:n]
    )


def _seq_state(cfg, rate, frate):
    st = mb.init_state(cfg)
    return dataclasses.replace(
        st,
        workload=dataclasses.replace(
            st.workload,
            rate=jnp.float32(rate),
            fault_rates=jnp.asarray(frate, jnp.float32),
        ),
    )


def _assert_instance_equals(states, i, ref_state):
    got = jax.tree_util.tree_map(lambda a: a[i], states)
    for a, b in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(ref_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("seed_base", [0, 7, 21])
def test_fleet_vs_sequential_bit_identity_reference(seed_base, fleet_mesh):
    """Every fleet instance == the sequential single-instance run of
    the same (traced config, rates, seed), full state, reference path,
    20 ticks on the (2, 4) product mesh."""
    cfg = _traced_cfg()
    t0 = jnp.zeros((), jnp.int32)
    seeds = [seed_base + i for i in range(4)]
    states = sh.shard_fleet_state("multipaxos", _brick(cfg), fleet_mesh)
    states, t = sh.run_ticks_fleet(
        "multipaxos", cfg, fleet_mesh, states, t0, 20, sh.fleet_keys(seeds)
    )
    assert list(np.asarray(t)) == [20] * 4
    for i, seed in enumerate(seeds):
        ref, _ = mb.run_ticks(
            cfg, _seq_state(cfg, RATES[i], FRATES[i]), t0, 20,
            jax.random.PRNGKey(seed),
        )
        _assert_instance_equals(states, i, ref)


@pytest.mark.parametrize("seed_base", [0, 7, 21])
def test_fleet_vs_sequential_bit_identity_kernels(seed_base, fleet_mesh):
    """The fleet x kernels composition cell: the same brick with the
    kernel planes ENGAGED (interpret — shard_map-lowered over the group
    axis, the fleet axis routed via spmd_axis_name) replays the
    sequential kernels-engaged runs bit for bit, 3 seeds."""
    cfg = _traced_cfg(kernels=KernelPolicy(mode="interpret"))
    t0 = jnp.zeros((), jnp.int32)
    seeds = [seed_base + i for i in range(4)]
    states = sh.shard_fleet_state("multipaxos", _brick(cfg), fleet_mesh)
    states, _ = sh.run_ticks_fleet(
        "multipaxos", cfg, fleet_mesh, states, t0, 6, sh.fleet_keys(seeds)
    )
    assert int(np.sum(np.asarray(states.committed))) > 0
    for i, seed in enumerate(seeds):
        ref, _ = mb.run_ticks(
            cfg, _seq_state(cfg, RATES[i], FRATES[i]), t0, 6,
            jax.random.PRNGKey(seed),
        )
        _assert_instance_equals(states, i, ref)


def test_fleet_mesh_shape_agnostic():
    """One brick, three mesh shapes — (2, 4), (4, 2), (1, 8) — all
    bit-identical: the sharding layer is mesh-shape-agnostic and the
    fleet axis never changes a value."""
    cfg = _traced_cfg()
    t0 = jnp.zeros((), jnp.int32)
    keys = sh.fleet_keys(range(4))
    results = []
    for fleet in (2, 4, 1):
        mesh = sh.make_fleet_mesh(fleet=fleet)
        states = sh.shard_fleet_state("multipaxos", _brick(cfg), mesh)
        states, _ = sh.run_ticks_fleet(
            "multipaxos", cfg, mesh, states, t0, 20, keys
        )
        results.append(jax.device_get(states))
    for other in results[1:]:
        for a, b in zip(
            jax.tree_util.tree_leaves(results[0]),
            jax.tree_util.tree_leaves(other),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fleet_runner_cache_isolated_across_meshes():
    """The jit-cache isolation spy: ``_fleet_runner`` is keyed per
    (backend, mesh) — running a brick on one fleet shape never grows
    another shape's cache — and a TRACED-rate re-sweep keeps each
    mesh's cache flat at ONE executable."""
    cfg = _traced_cfg()
    t0 = jnp.zeros((), jnp.int32)
    keys = sh.fleet_keys(range(4))
    mesh_a = sh.make_fleet_mesh(fleet=2)
    mesh_b = sh.make_fleet_mesh(fleet=4)
    runner_a = sh._fleet_runner("multipaxos", mesh_a, None)
    runner_b = sh._fleet_runner("multipaxos", mesh_b, None)
    assert runner_a is not runner_b

    # Delta-based: the runner is lru-cached per (backend, mesh), so
    # other tests in this process may already hold entries.
    size_a0 = runner_a._cache_size()
    size_b0 = runner_b._cache_size()
    sa = sh.shard_fleet_state("multipaxos", _brick(cfg), mesh_a)
    sa, _ = sh.run_ticks_fleet("multipaxos", cfg, mesh_a, sa, t0, 9, keys)
    jax.block_until_ready(sa.committed)
    assert runner_a._cache_size() == size_a0 + 1

    # A fresh brick with DIFFERENT traced rates: same executable.
    sa2 = sh.fleet_states(
        "multipaxos", cfg, 4,
        rates=(2.0, 0.25, 0.75, 1.25),
        fault_rates=((0.3, 0.0, 0.0, 0.0),) * 4,
    )
    sa2 = sh.shard_fleet_state("multipaxos", sa2, mesh_a)
    sa2, _ = sh.run_ticks_fleet("multipaxos", cfg, mesh_a, sa2, t0, 9, keys)
    jax.block_until_ready(sa2.committed)
    assert runner_a._cache_size() == size_a0 + 1, "rate re-sweep recompiled"

    # Mesh B runs its own brick: its own runner compiles, mesh A's
    # cache does not move.
    sb = sh.shard_fleet_state("multipaxos", _brick(cfg), mesh_b)
    sb, _ = sh.run_ticks_fleet("multipaxos", cfg, mesh_b, sb, t0, 9, keys)
    jax.block_until_ready(sb.committed)
    assert runner_b._cache_size() == size_b0 + 1
    assert runner_a._cache_size() == size_a0 + 1, (
        "mesh B leaked into mesh A"
    )


def test_fleet_donation_aliases_under_product_mesh():
    """Donation stays single-buffered per shard under the product mesh:
    the compiled fleet program aliases every donated State leaf."""
    from frankenpaxos_tpu.analysis.rules_trace import _alias_param_indices

    cfg = _traced_cfg()
    mesh = sh.make_fleet_mesh(fleet=2)
    states = sh.shard_fleet_state("multipaxos", _brick(cfg), mesh)
    n_leaves = len(jax.tree_util.tree_leaves(states))
    txt = sh.lower_fleet(
        "multipaxos", cfg, mesh, states, jnp.zeros((), jnp.int32), 4,
        sh.fleet_keys(range(4)),
    ).compile().as_text()
    missing = sorted(set(range(n_leaves)) - _alias_param_indices(txt))
    assert not missing, f"unaliased fleet State leaves: {missing}"


def test_autotune_resolves_at_per_device_shape_under_product_mesh():
    """The nearest-G fallback keys on the PER-DEVICE shape: under a
    (2, 4) product mesh the batch-axis extent divides by the GROUP-axis
    extent (4), not the total device count (8) — the fleet axis changes
    the divisor and must not leak into the lookup."""
    cfg = _traced_cfg(kernels=KernelPolicy(mode="interpret"))
    mesh = sh.make_fleet_mesh(fleet=2)
    states = sh.shard_fleet_state("multipaxos", _brick(cfg), mesh)
    ops_registry.RESOLVED_BLOCKS.clear()
    sh.lower_fleet(
        "multipaxos", cfg, mesh, states, jnp.zeros((), jnp.int32), 2,
        sh.fleet_keys(range(4)),
    )
    resolved = ops_registry.RESOLVED_BLOCKS
    assert resolved, "kernels-engaged lowering recorded no blocks"
    G = cfg.num_groups
    for name, row in resolved.items():
        plane = ops_registry.PLANES[name]
        ax = plane.batch_axis
        assert row["group_axis_devices"] == 4, (name, row)
        assert row["per_device_key"][ax] == G // 4, (name, row)
        assert row["mesh_axes"] == {"fleet": 2, "groups": 4}
    plan = sh.fleet_block_plan("multipaxos", cfg, mesh)
    # Planes that actually dispatched (the megakernel subsumes the
    # per-plane twins, so not every engaged plane runs) carry a block.
    dispatched = {n: plan[n] for n in resolved}
    assert dispatched
    for row in dispatched.values():
        assert row["block"] is not None and row["block"] > 0


def test_fleet_divisibility_and_registry_guards():
    cfg = _traced_cfg()
    mesh = sh.make_fleet_mesh(fleet=2)
    with pytest.raises(ValueError, match="fleet instances"):
        sh.shard_fleet_state("multipaxos", _brick(cfg, n=3), mesh)
    cfg6 = dataclasses.replace(_traced_cfg(), num_groups=6)
    states6 = sh.fleet_states(
        "multipaxos", cfg6, 4, rates=RATES, fault_rates=FRATES
    )
    with pytest.raises(ValueError, match="divisible by the group-axis"):
        sh.shard_fleet_state("multipaxos", states6, mesh)
    with pytest.raises(AssertionError, match="devices do not divide"):
        sh.make_fleet_mesh(fleet=3)


def test_fleet_states_requires_traced_axes():
    """Per-instance rates demand the traced plumbing: a none-workload
    config cannot take per-instance offered rates, an untraced fault
    plan cannot take per-instance fault rates."""
    cfg = mb.analysis_config()
    with pytest.raises(AssertionError, match="shaped WorkloadPlan"):
        sh.fleet_states("multipaxos", cfg, 2, rates=(1.0, 2.0))
    cfg2 = mb.analysis_config(
        workload=WorkloadPlan(arrival="constant", rate=1.0)
    )
    with pytest.raises(AssertionError, match="traced"):
        sh.fleet_states(
            "multipaxos", cfg2, 2,
            fault_rates=((0.1, 0, 0, 0), (0.2, 0, 0, 0)),
        )


def test_multihost_helpers_single_process_behavior(monkeypatch):
    """The multi-host entry points on a single process (the only leg CI
    can run): ``maybe_init_distributed`` is a no-op returning False
    with no coordination config, raises on a BAD config instead of
    silently degrading, and ``host_sync`` is a no-op barrier."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert sh.maybe_init_distributed() is False
    sh.host_sync("test-noop")  # must not raise or block
    # A coordination config on an already-running single-process
    # backend must surface loudly (jax.distributed.initialize raises
    # once computations have run), never pass silently.
    with pytest.raises(RuntimeError):
        sh.maybe_init_distributed(
            coordinator_address="127.0.0.1:1", num_processes=2,
            process_id=0,
        )


def test_simtest_fleet_brick_mesh_invariance(fleet_mesh):
    """``simtest.run_fleet``'s verdicts and progress are identical with
    and without a mesh (the brick is ONE program either way), and the
    per-mesh program cache holds exactly one executable."""
    from frankenpaxos_tpu.harness import simtest

    spec = simtest.SPECS["multipaxos"]
    a = simtest.run_fleet(
        spec, schedules=3, seeds_per_schedule=2, ticks=40
    )
    b = simtest.run_fleet(
        spec, schedules=3, seeds_per_schedule=2, ticks=40,
        mesh=fleet_mesh,
    )
    assert a["ok"] and b["ok"]
    assert a["per_instance_ok"] == b["per_instance_ok"]
    assert a["progress"] == b["progress"]
    assert simtest._fleet_program(
        "multipaxos", fleet_mesh, None
    )._cache_size() == 1


def test_single_instance_rejects_fleet_axis(fleet_mesh):
    """The non-partitionable-threefry guard: a SINGLE-instance state on
    a >1-fleet-axis mesh is a loud ValueError, never a silent bit
    drift. (XLA's partitioner makes an unbatched PRNG sweep's values
    depend on how the spare mesh axis tiles it — the fleet API's
    explicit instance axis is the supported route, pinned bit-identical
    above.) A TRIVIAL fleet axis stays allowed and bit-identical: one
    mesh type serves both layers."""
    cfg = dataclasses.replace(mb.analysis_config(), num_groups=8)
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)
    with pytest.raises(ValueError, match="fleet axis"):
        sh.shard_state("multipaxos", mb.init_state(cfg), fleet_mesh)
    with pytest.raises(ValueError, match="fleet axis"):
        sh.run_ticks_sharded(
            "multipaxos", cfg, fleet_mesh, mb.init_state(cfg), t0, 4, key
        )
    mesh1 = sh.make_fleet_mesh(fleet=1)
    st = sh.shard_state("multipaxos", mb.init_state(cfg), mesh1)
    st, _ = sh.run_ticks_sharded(
        "multipaxos", cfg, mesh1, st, t0, 20, key
    )
    ust, _ = mb.run_ticks(cfg, mb.init_state(cfg), t0, 20, key)
    for a, b in zip(
        jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(ust)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Fleet observability: DrainCursor under the fleet axis + fleet_summary
# ---------------------------------------------------------------------------

from frankenpaxos_tpu.tpu import telemetry as T  # noqa: E402


def _telemetry_brick(cfg, n=4, window=32, rates=None, frates=None):
    """A fleet brick whose every instance carries a SIZED telemetry
    ring (the fleet serve layout: fleet_states with a base template)."""
    base = dataclasses.replace(
        mb.init_state(cfg), telemetry=T.make_telemetry(window)
    )
    return sh.fleet_states(
        "multipaxos", cfg, n,
        rates=RATES[:n] if rates is None else rates,
        fault_rates=FRATES[:n] if frates is None else frates,
        base=base,
    )


def _run_fleet_chunks(cfg, states, mesh, chunks, chunk_ticks, seeds):
    """The fleet serve dispatch shape: per-chunk run_ticks_fleet with
    per-chunk vmapped fold_in keys — instance i replays exactly the
    single-instance serve chunking of seed i."""
    base_keys = sh.place_fleet_keys(sh.fleet_keys(seeds), mesh)
    t = jnp.zeros((), jnp.int32)
    for e in range(chunks):
        keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
            base_keys, e
        )
        states, t = sh.run_ticks_fleet(
            "multipaxos", cfg, mesh, states, t, chunk_ticks, keys
        )
        yield states


def _drain_rows(d):
    """The comparable payload of one single-instance drain dict."""
    return (
        d["tick"].tolist(),
        {k: d[k].tolist() for k in T.COUNTER_FIELDS},
        d["totals"],
        d["lat_hist"].tolist(),
        d["dropped_ticks"],
    )


@pytest.mark.parametrize("seed_base", [0, 7, 21])
def test_fleet_drain_chunked_equals_one_shot_and_sequential(
    seed_base, fleet_mesh
):
    """The fleet drain exactness contract, kernels ENGAGED: chunked
    fleet drains are bit-identical PER INSTANCE to (a) a one-shot
    capture of the identical fleet run, (b) sequential per-config
    single-instance runs drained at the same chunk boundaries, and
    (c) the same brick on the transposed (4, 2) mesh — 3 seeds."""
    cfg = _traced_cfg(kernels=KernelPolicy(mode="interpret"))
    CH, NCH, W = 13, 3, 32
    seeds = [seed_base + i for i in range(4)]

    def chunked_drains(mesh):
        states = _telemetry_brick(cfg, window=W)
        if mesh is not None:
            states = sh.shard_fleet_state("multipaxos", states, mesh)
        cur = T.DrainCursor()
        per_inst = [[] for _ in range(4)]
        for states in _run_fleet_chunks(cfg, states, mesh, NCH, CH, seeds):
            d = cur.drain(states.telemetry)
            assert d["fleet"] == 4 and d["dropped_ticks"] == 0
            for i, di in enumerate(d["instances"]):
                per_inst[i].append(_drain_rows(di))
        return per_inst, states

    chunked, final_states = chunked_drains(fleet_mesh)

    # (a) One-shot capture of the identical run.
    for states in _run_fleet_chunks(
        cfg,
        sh.shard_fleet_state(
            "multipaxos", _telemetry_brick(cfg, window=W), fleet_mesh
        ),
        fleet_mesh, NCH, CH, seeds,
    ):
        pass
    one_shot = T.DrainCursor().drain(states.telemetry)
    for i in range(4):
        assert (
            chunked[i][-1][2] == one_shot["instances"][i]["totals"]
        ), i
        # Every tick seen exactly once across the chunked drains.
        ticks = [t for rows in chunked[i] for t in rows[0]]
        assert ticks == list(range(NCH * CH)), i

    # (b) Sequential per-config single-instance runs, same chunking,
    # drained at the same boundaries — bit-identical rows per chunk.
    for i, seed in enumerate(seeds):
        st = dataclasses.replace(
            _seq_state(cfg, RATES[i], FRATES[i]),
            telemetry=T.make_telemetry(W),
        )
        t = jnp.zeros((), jnp.int32)
        cur = T.DrainCursor()
        key = jax.random.PRNGKey(seed)
        for e in range(NCH):
            st, t = mb.run_ticks(
                cfg, st, t, CH, jax.random.fold_in(key, e)
            )
            d = cur.drain(st.telemetry)
            assert _drain_rows(d) == chunked[i][e], (i, e)

    # (c) Mesh-shape agnosticism: the transposed product mesh.
    chunked_t, _ = chunked_drains(sh.make_fleet_mesh(fleet=4))
    assert chunked_t == chunked


def test_fleet_drain_overrun_honest_per_instance():
    """A fleet drain slower than the ring period reports the overrun
    PER INSTANCE in dropped_ticks and returns only the retained rows —
    never double-counted across instances or drains."""
    cfg = _traced_cfg()
    W, TICKS = 16, 40
    states = _telemetry_brick(cfg, window=W)
    states, _ = sh.run_ticks_fleet(
        "multipaxos", cfg, None, states, jnp.zeros((), jnp.int32),
        TICKS, sh.fleet_keys(range(4)),
    )
    cur = T.DrainCursor()
    d = cur.drain(states.telemetry)
    assert d["dropped_ticks"] == 4 * (TICKS - W)
    for di in d["instances"]:
        assert di["ticks_total"] == TICKS
        assert di["dropped_ticks"] == TICKS - W
        assert di["tick"].tolist() == list(range(TICKS - W, TICKS))
    # A second drain sees nothing new (no double count).
    d2 = cur.drain(states.telemetry)
    assert d2["dropped_ticks"] == 0
    for di in d2["instances"]:
        assert di["tick"].tolist() == []


def test_fleet_summary_flags_only_the_hostile_instance():
    """The in-graph straggler test on a HOMOGENEOUS fleet: identical
    plan rates, one instance with a hostile traced drop rate — the
    summary flags it (and only it), and the summary columns carry the
    windowed commit rate + histogram percentiles."""
    cfg = _traced_cfg()
    n = 4
    rate = 2.0
    frates = [[0.0, 0.0, 0.0, 0.0] for _ in range(n)]
    frates[2][0] = 0.6
    states = _telemetry_brick(
        cfg, n=n, window=64, rates=[rate] * n, frates=frates
    )
    states, _ = sh.run_ticks_fleet(
        "multipaxos", cfg, None, states, jnp.zeros((), jnp.int32), 60,
        sh.fleet_keys(range(n)),
    )
    s = np.asarray(T.fleet_summary(
        states.telemetry,
        wait_hist=states.workload.wait_hist,
        shed=states.workload.shed,
    ))
    col = T.SUMMARY_COL
    assert s.shape == (n, T.NUM_SUMMARY_COLS)
    assert [int(x) for x in s[:, col["straggler"]]] == [0, 0, 1, 0]
    assert all(s[:, col["ticks"]] == 60)
    assert all(s[:, col["window_ticks"]] == 60)
    # The hostile instance's p99 exceeds its siblings'.
    p99 = s[:, col["p99_commit_latency"]]
    assert p99[2] > max(p99[i] for i in (0, 1, 3))
    # The analytical anchor: an expected rate far above everyone flags
    # the whole fleet (a fleet-wide brownout has no MAD outlier).
    s2 = np.asarray(T.fleet_summary(
        states.telemetry,
        wait_hist=states.workload.wait_hist,
        shed=states.workload.shed,
        expected_rate_x1000=10_000_000,
    ))
    assert all(s2[:, col["straggler"]] == 1)


def test_set_fleet_rates_applies_per_instance_without_recompile():
    """sharding.set_fleet_rates: the clamp vector lands per instance
    (sibling rates untouched) and the SAME fleet executable keeps
    running — the jit cache stays flat across the clamp."""
    cfg = _traced_cfg()
    states = _brick(cfg)
    t0 = jnp.zeros((), jnp.int32)
    keys = sh.fleet_keys(range(4))
    runner = sh._fleet_runner("multipaxos", None, None)
    states, t = sh.run_ticks_fleet(
        "multipaxos", cfg, None, states, t0, 6, keys
    )
    jax.block_until_ready(states.committed)
    before = runner._cache_size()
    states = sh.set_fleet_rates(states, [0.5, 0.05, 1.5, 2.0])
    np.testing.assert_allclose(
        np.asarray(states.workload.rate), [0.5, 0.05, 1.5, 2.0]
    )
    states, _ = sh.run_ticks_fleet(
        "multipaxos", cfg, None, states, t, 6,
        jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys),
    )
    jax.block_until_ready(states.committed)
    assert runner._cache_size() == before, "clamp recompiled"
    with pytest.raises(AssertionError, match="fleet state"):
        sh.set_fleet_rates(mb.init_state(cfg), [1.0])
