"""Batched EPaxos/BPaxos dependency-graph backend tests, including the
equivalence check against the per-actor ``TarjanDependencyGraph``: fed the
same commit stream (same instances, same prefix-shaped dependency sets),
the batched eligibility-closure must execute exactly the set of vertices
the Tarjan graph executes, tick for tick — SCCs included
(``depgraph/TarjanDependencyGraph.scala:149`` semantics: execute eligible
components in reverse topological order; per tick the union of executed
components is the eligible set, which is what the closure computes).

The batched backend factors each instance's dependency vector through
the frontier history (fpre/fpost rows + packed same-tick visibility
bits); the oracle below MATERIALIZES those factored rows back into the
explicit instance sets the per-actor depgraph consumes, so the
equivalence check also pins the factored representation itself.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.tpu.epaxos_batched import (
    BatchedEPaxosConfig,
    check_invariants,
    eligible_closure,
    init_state,
    run_ticks,
    tick,
)


def materialize_deps(dep_row, column, index):
    """Expand a prefix watermark vector into the explicit instance set the
    per-actor depgraph consumes (minus self)."""
    deps = {
        (d, j)
        for d, w in enumerate(dep_row)
        for j in range(int(w))
    }
    deps.discard((column, index))
    return deps


def dep_row_of(state, cfg, c, s, t):
    """Materialize the factored dependency vector of instance (c, s)
    proposed at tick t: fpre[t % H] bumped to fpost[t % H] for visible
    peers, own column = own index (all own predecessors)."""
    H = cfg.frontier_history
    W = cfg.window
    C = cfg.num_columns
    fpre = np.asarray(state.fpre[t % H])
    fpost = np.asarray(state.fpost[t % H])
    bits = np.asarray(state.vis_bits[c, s % W])
    row = fpre.copy()
    for e in range(C):
        if (int(bits[e // 32]) >> (e % 32)) & 1:
            row[e] = fpost[e]
    row[c] = s
    return row


def run_cross_validation(cfg, seed, num_ticks):
    """Step the batched sim tick-by-tick; mirror every commit into a
    TarjanDependencyGraph and compare per-tick executed sets."""
    key = jax.random.PRNGKey(seed)
    state = init_state(cfg)
    graph = TarjanDependencyGraph()
    known_committed = set()
    batched_executed = set()
    tarjan_executed = set()
    scc_events = 0
    # Dep rows snapshotted at PROPOSAL time: the live ring row is
    # overwritten when a slot retires and is re-proposed, so reading it
    # at commit-mirroring time is only safe via this snapshot.
    dep_snapshot = {}

    C, W = cfg.num_columns, cfg.window
    for t in range(num_ticks):
        prev_head = np.asarray(state.head).copy()
        prev_next = np.asarray(state.next_instance).copy()
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))

        committed = np.asarray(state.committed)
        head = np.asarray(state.head)
        next_instance = np.asarray(state.next_instance)

        for c in range(C):
            for s in range(int(prev_next[c]), int(next_instance[c])):
                dep_snapshot[(c, s)] = dep_row_of(state, cfg, c, s, t)

        # Newly executed this tick, in absolute coordinates: execution is
        # in column order and retires immediately, so the executed set is
        # exactly the head advance.
        new_exec = {
            (c, s)
            for c in range(C)
            for s in range(int(prev_head[c]), int(head[c]))
        }

        # Mirror this tick's NEW commits into the Tarjan graph (anything
        # at or below the head executed, hence committed, first).
        for c in range(C):
            for s in range(int(prev_head[c]), int(next_instance[c])):
                v = (c, s)
                if v in known_committed:
                    continue
                in_ring = s >= head[c]
                if (in_ring and committed[c, s % W]) or s < head[c]:
                    known_committed.add(v)
                    graph.commit(
                        v, 0, materialize_deps(dep_snapshot[v], c, s)
                    )

        components, _blockers = graph.execute_by_component()
        tarjan_new = [v for comp in components for v in comp]
        scc_events += sum(1 for comp in components if len(comp) > 1)

        assert new_exec == set(tarjan_new), (
            f"tick {t}: batched executed {sorted(new_exec)} but Tarjan "
            f"executed {sorted(tarjan_new)}"
        )
        batched_executed |= new_exec
        tarjan_executed |= set(tarjan_new)

    assert batched_executed == tarjan_executed
    return len(batched_executed), scc_events


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("window", [16, 4])
def test_batched_epaxos_matches_tarjan(seed, window):
    """window=4 saturates the ring (retire + same-tick re-proposal), the
    backpressure regime where execution order is most stressed."""
    cfg = BatchedEPaxosConfig(
        num_columns=3,
        window=window,
        instances_per_tick=window // 8 or 2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.3,
        see_same_tick_rate=0.625,
    )
    executed, scc_events = run_cross_validation(cfg, seed=seed, num_ticks=40)
    assert executed > 30
    # The run must actually exercise the cycle path: mutual same-tick
    # visibility guarantees SCCs of size > 1 appear.
    assert scc_events > 0, "no SCC formed; the test lost its teeth"


def test_batched_epaxos_matches_tarjan_wide():
    """Cross-column chains at C=5 (single visibility word)."""
    cfg = BatchedEPaxosConfig(
        num_columns=5,
        window=8,
        instances_per_tick=1,
        lat_min=1,
        lat_max=2,
        slow_path_rate=0.2,
        see_same_tick_rate=0.5,
    )
    executed, scc_events = run_cross_validation(cfg, seed=2, num_ticks=40)
    assert executed > 50
    assert scc_events > 0


def test_batched_epaxos_matches_tarjan_multiword():
    """C=40 > 32 lanes: the packed visibility mask spans TWO uint32
    words, so a word-index/lane-order bug in _pack_bool or _instance_ok
    (e.g. for columns >= 32) would execute instances before their
    cross-column deps commit — exactly what the Tarjan oracle catches."""
    cfg = BatchedEPaxosConfig(
        num_columns=40,
        window=8,
        instances_per_tick=1,
        lat_min=1,
        lat_max=2,
        slow_path_rate=0.2,
        see_same_tick_rate=0.25,
    )
    executed, scc_events = run_cross_validation(cfg, seed=3, num_ticks=30)
    assert executed > 400
    assert scc_events > 0


def test_batched_epaxos_simplebpaxos_latency():
    """Simple BPaxos pays an extra RTT before commit (the disaggregated
    proposer -> dep-service hop); same dependency semantics."""
    common = dict(
        num_columns=5,
        window=32,
        instances_per_tick=2,
        lat_min=2,
        lat_max=2,
        slow_path_rate=0.0,
        see_same_tick_rate=0.0,
        max_instances_per_column=40,
    )
    key = jax.random.PRNGKey(3)
    stats = {}
    for name, flag in [("epaxos", False), ("bpaxos", True)]:
        cfg = BatchedEPaxosConfig(simplebpaxos=flag, **common)
        state, t = run_ticks(cfg, init_state(cfg), jnp.int32(0), 80, key)
        inv = check_invariants(cfg, state, t)
        assert all(bool(v) for v in inv.values()), inv
        assert int(state.executed_total) == 5 * 40
        stats[name] = float(state.lat_sum) / int(state.executed_total)
    # 2 one-way hops at lat=2 -> fast path 4 ticks; BPaxos adds 2 more
    # hops -> 8 ticks (plus the tick-granularity execute delay on both).
    assert stats["bpaxos"] == pytest.approx(stats["epaxos"] + 4, abs=0.5)


def test_batched_epaxos_invariants_random():
    """Open workload with slow paths and cycles: invariants hold and the
    pipeline makes progress."""
    cfg = BatchedEPaxosConfig(
        num_columns=5,
        window=64,
        instances_per_tick=2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.25,
        see_same_tick_rate=0.5,
    )
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), 200, jax.random.PRNGKey(7)
    )
    inv = check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.executed_total) > 1000
    assert int(state.coexecuted) > 0  # chains/components co-executed


def test_batched_epaxos_wide_columns():
    """The factored representation's reason to exist: >=1024 columns
    (multi-word visibility masks) run with healthy throughput and clean
    invariants."""
    cfg = BatchedEPaxosConfig(
        num_columns=1024,
        window=32,
        instances_per_tick=2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.2,
        see_same_tick_rate=0.5,
        frontier_history=64,
    )
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), 60, jax.random.PRNGKey(9)
    )
    inv = check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    # 1024 columns x 2/tick x 60 ticks = 122,880 offered; the pipeline
    # must execute the bulk of them (ramp-up and in-flight tail allowed).
    assert int(state.executed_total) > 80_000
    assert int(state.coexecuted) > 0


def test_eligible_closure_blocks_on_uncommitted():
    """A committed instance whose dependency is uncommitted must not
    execute (it is a blocker, DependencyGraph.scala execute()); a
    committed mutual 2-cycle executes together. Dependencies are built
    through the factored representation (frontier rows + vis bits)."""
    C, W, H = 2, 4, 8
    head = jnp.zeros((C,), jnp.int32)
    w_iota_zeros = jnp.zeros((C, W), jnp.int32)

    def closure(committed, proposed, propose_tick, vis, fpre, fpost, nxt):
        return eligible_closure(
            committed, proposed, propose_tick, vis, fpre, fpost, head, nxt
        )

    # Scenario: both columns proposed instance 0 at tick 0 (fpre row 0 =
    # [0, 0], fpost row 0 = [1, 1]). (0,0) SEES (1,0) — depends on it —
    # but only (0,0) is committed: blocked.
    proposed = jnp.array([[True, False, False, False]] * 2)
    propose_tick = jnp.where(proposed, 0, 10**9)
    committed = jnp.array(
        [[True, False, False, False], [False, False, False, False]]
    )
    fpre = jnp.zeros((H, C), jnp.int32)
    fpost = jnp.zeros((H, C), jnp.int32).at[0].set(jnp.array([1, 1]))
    nxt = jnp.array([1, 1], jnp.int32)
    vis = jnp.zeros((C, W, 1), jnp.uint32)
    vis = vis.at[0, 0, 0].set(jnp.uint32(0b10))  # (0,0) sees column 1
    newly, run = closure(
        committed, proposed, propose_tick, vis, fpre, fpost, nxt
    )
    assert not bool(newly[0, 0])  # blocked on uncommitted (1,0)
    assert not bool(newly[1, 0])  # uncommitted
    assert int(run.sum()) == 0

    # Mutual 2-cycle, both committed: both execute together.
    committed = jnp.array([[True, False, False, False]] * 2)
    vis = vis.at[1, 0, 0].set(jnp.uint32(0b01))  # (1,0) sees column 0
    newly, run = closure(
        committed, proposed, propose_tick, vis, fpre, fpost, nxt
    )
    assert bool(newly[0, 0]) and bool(newly[1, 0])
    assert int(run.sum()) == 2
