"""Batched EPaxos/BPaxos dependency-graph backend tests, including the
equivalence check against the per-actor ``TarjanDependencyGraph``: fed the
same commit stream (same instances, same prefix-shaped dependency sets),
the batched eligibility-closure must execute exactly the set of vertices
the Tarjan graph executes, tick for tick — SCCs included
(``depgraph/TarjanDependencyGraph.scala:149`` semantics: execute eligible
components in reverse topological order; per tick the union of executed
components is the eligible set, which is what the closure computes).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.tpu.epaxos_batched import (
    BatchedEPaxosConfig,
    check_invariants,
    eligible_closure,
    init_state,
    run_ticks,
    tick,
)


def materialize_deps(dep_row, column, index):
    """Expand a prefix watermark vector into the explicit instance set the
    per-actor depgraph consumes (minus self)."""
    deps = {
        (d, j)
        for d, w in enumerate(dep_row)
        for j in range(int(w))
    }
    deps.discard((column, index))
    return deps


def run_cross_validation(cfg, seed, num_ticks):
    """Step the batched sim tick-by-tick; mirror every commit into a
    TarjanDependencyGraph and compare per-tick executed sets."""
    key = jax.random.PRNGKey(seed)
    state = init_state(cfg)
    graph = TarjanDependencyGraph()
    known_committed = set()
    batched_executed = set()
    tarjan_executed = set()
    scc_events = 0
    # Dep rows snapshotted at PROPOSAL time: the live ring row is
    # overwritten when a slot retires and is re-proposed, so reading it at
    # commit-mirroring time is only safe via this snapshot.
    dep_snapshot = {}

    C, W = cfg.num_columns, cfg.window
    for t in range(num_ticks):
        prev_executed = np.asarray(state.executed).copy()
        prev_head = np.asarray(state.head).copy()
        prev_next = np.asarray(state.next_instance).copy()
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))

        committed = np.asarray(state.committed)
        executed = np.asarray(state.executed)
        dep = np.asarray(state.dep)
        head = np.asarray(state.head)
        next_instance = np.asarray(state.next_instance)

        for c in range(C):
            for s in range(int(prev_next[c]), int(next_instance[c])):
                dep_snapshot[(c, s)] = dep[c, s % W].copy()

        # Newly executed this tick, in absolute coordinates. Retired slots
        # are handled by comparing in absolute instance space: anything at
        # or above prev_head that became executed (including instances
        # that retired this very tick — they were executed first, and
        # retirement only advances over executed instances).
        new_exec = set()
        for c in range(C):
            for s in range(int(prev_head[c]), int(next_instance[c])):
                was = s < prev_head[c] or (
                    prev_executed[c, s % W] and s >= prev_head[c]
                )
                now = s < head[c] or executed[c, s % W]
                if now and not was:
                    new_exec.add((c, s))

        # Mirror this tick's NEW commits into the Tarjan graph.
        for c in range(C):
            for s in range(int(prev_head[c]), int(next_instance[c])):
                v = (c, s)
                if v in known_committed:
                    continue
                in_ring = s >= head[c]
                if (in_ring and committed[c, s % W]) or s < head[c]:
                    known_committed.add(v)
                    graph.commit(
                        v, 0, materialize_deps(dep_snapshot[v], c, s)
                    )

        components, _blockers = graph.execute_by_component()
        tarjan_new = [v for comp in components for v in comp]
        scc_events += sum(1 for comp in components if len(comp) > 1)

        assert new_exec == set(tarjan_new), (
            f"tick {t}: batched executed {sorted(new_exec)} but Tarjan "
            f"executed {sorted(tarjan_new)}"
        )
        batched_executed |= new_exec
        tarjan_executed |= set(tarjan_new)

    assert batched_executed == tarjan_executed
    return len(batched_executed), scc_events


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("window", [16, 4])
def test_batched_epaxos_matches_tarjan(seed, window):
    """window=4 saturates the ring (retire + same-tick re-proposal), the
    backpressure regime where execution order is most stressed."""
    cfg = BatchedEPaxosConfig(
        num_columns=3,
        window=window,
        instances_per_tick=window // 8 or 2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.3,
        see_same_tick_rate=0.6,
    )
    executed, scc_events = run_cross_validation(cfg, seed=seed, num_ticks=40)
    assert executed > 30
    # The run must actually exercise the cycle path: mutual same-tick
    # visibility guarantees SCCs of size > 1 appear.
    assert scc_events > 0, "no SCC formed; the test lost its teeth"


def test_batched_epaxos_simplebpaxos_latency():
    """Simple BPaxos pays an extra RTT before commit (the disaggregated
    proposer -> dep-service hop); same dependency semantics."""
    common = dict(
        num_columns=5,
        window=32,
        instances_per_tick=2,
        lat_min=2,
        lat_max=2,
        slow_path_rate=0.0,
        see_same_tick_rate=0.0,
        max_instances_per_column=40,
    )
    key = jax.random.PRNGKey(3)
    stats = {}
    for name, flag in [("epaxos", False), ("bpaxos", True)]:
        cfg = BatchedEPaxosConfig(simplebpaxos=flag, **common)
        state, t = run_ticks(cfg, init_state(cfg), jnp.int32(0), 80, key)
        inv = check_invariants(cfg, state, t)
        assert all(bool(v) for v in inv.values()), inv
        assert int(state.executed_total) == 5 * 40
        stats[name] = float(state.lat_sum) / int(state.executed_total)
    # 2 one-way hops at lat=2 -> fast path 4 ticks; BPaxos adds 2 more
    # hops -> 8 ticks (plus the tick-granularity execute delay on both).
    assert stats["bpaxos"] == pytest.approx(stats["epaxos"] + 4, abs=0.5)


def test_batched_epaxos_invariants_random():
    """Open workload with slow paths and cycles: invariants hold and the
    pipeline makes progress."""
    cfg = BatchedEPaxosConfig(
        num_columns=5,
        window=64,
        instances_per_tick=2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.25,
        see_same_tick_rate=0.5,
    )
    state, t = run_ticks(cfg, init_state(cfg), jnp.int32(0), 200, jax.random.PRNGKey(7))
    inv = check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.executed_total) > 1000
    assert int(state.coexecuted) > 0  # chains/components co-executed


def test_eligible_closure_blocks_on_uncommitted():
    """A committed instance whose dependency is uncommitted must not
    execute (it is a blocker, DependencyGraph.scala execute())."""
    cfg = BatchedEPaxosConfig(num_columns=2, window=4, instances_per_tick=1)
    C, W = 2, 4
    committed = jnp.array(
        [[True, False, False, False], [False, False, False, False]]
    )
    executed = jnp.zeros((C, W), bool)
    # (0,0) depends on (1,0), which is uncommitted: (0,0) is blocked.
    dep = jnp.zeros((C, W, C), jnp.int32)
    dep = dep.at[0, 0, 1].set(1)  # (0,0) -> {(1,0)}
    head = jnp.zeros((C,), jnp.int32)
    E = eligible_closure(committed, executed, dep, head)
    assert not bool(E[0, 0])  # blocked
    assert not bool(E[1, 0])  # uncommitted

    # Mutual 2-cycle, both committed: both execute together.
    committed = jnp.array([[True, False, False, False]] * 2)
    dep = jnp.zeros((C, W, C), jnp.int32)
    dep = dep.at[0, 0, 1].set(1)
    dep = dep.at[1, 0, 0].set(1)
    E = eligible_closure(committed, executed, dep, head)
    assert bool(E[0, 0]) and bool(E[1, 0])
