"""Batched EPaxos/BPaxos dependency-graph backend tests, including the
equivalence check against the per-actor ``TarjanDependencyGraph``: fed the
same commit stream (same instances, same prefix-shaped dependency sets),
the batched eligibility-closure must execute exactly the set of vertices
the Tarjan graph executes, tick for tick — SCCs included
(``depgraph/TarjanDependencyGraph.scala:149`` semantics: execute eligible
components in reverse topological order; per tick the union of executed
components is the eligible set, which is what the closure computes).

The batched backend factors each instance's dependency vector through
the frontier history (fpre/fpost rows + packed same-tick visibility
bits); the oracle below MATERIALIZES those factored rows back into the
explicit instance sets the per-actor depgraph consumes, so the
equivalence check also pins the factored representation itself.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.tpu.epaxos_batched import (
    BatchedEPaxosConfig,
    check_invariants,
    eligible_closure,
    init_state,
    run_ticks,
    tick,
)


def materialize_deps(dep_row, column, index):
    """Expand a prefix watermark vector into the explicit instance set the
    per-actor depgraph consumes (minus self)."""
    deps = {
        (d, j)
        for d, w in enumerate(dep_row)
        for j in range(int(w))
    }
    deps.discard((column, index))
    return deps


def dep_row_of(state, cfg, c, s, t):
    """Materialize the factored dependency vector of instance (c, s)
    proposed at tick t: fpre[t % H] bumped to fpost[t % H] for visible
    peers, own column = own index (all own predecessors)."""
    H = cfg.frontier_history
    W = cfg.window
    C = cfg.num_columns
    fpre = np.asarray(state.fpre[t % H])
    fpost = np.asarray(state.fpost[t % H])
    bits = np.asarray(state.vis_bits[c, s % W])
    row = fpre.copy()
    for e in range(C):
        if (int(bits[e // 32]) >> (e % 32)) & 1:
            row[e] = fpost[e]
    row[c] = s
    return row


def run_cross_validation(cfg, seed, num_ticks, gc=False):
    """Step the batched sim tick-by-tick; mirror every commit into a
    TarjanDependencyGraph and compare per-tick executed sets. With the
    GC layer on (``gc=True``), the execution watermark is exec_wm (head
    is the prune base and lags it)."""
    key = jax.random.PRNGKey(seed)
    state = init_state(cfg)
    graph = TarjanDependencyGraph()
    known_committed = set()
    batched_executed = set()
    tarjan_executed = set()
    scc_events = 0
    # Dep rows snapshotted at PROPOSAL time: the live ring row is
    # overwritten when a slot retires and is re-proposed, so reading it
    # at commit-mirroring time is only safe via this snapshot.
    dep_snapshot = {}

    def wm(st):
        return np.asarray(st.exec_wm if gc else st.head).copy()

    C, W = cfg.num_columns, cfg.window
    for t in range(num_ticks):
        prev_wm = wm(state)
        prev_next = np.asarray(state.next_instance).copy()
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))

        committed = np.asarray(state.committed)
        cur_wm = wm(state)
        next_instance = np.asarray(state.next_instance)

        for c in range(C):
            for s in range(int(prev_next[c]), int(next_instance[c])):
                dep_snapshot[(c, s)] = dep_row_of(state, cfg, c, s, t)

        # Newly executed this tick, in absolute coordinates: execution
        # is in column order, so the executed set is exactly the
        # watermark advance.
        new_exec = {
            (c, s)
            for c in range(C)
            for s in range(int(prev_wm[c]), int(cur_wm[c]))
        }

        # Mirror this tick's NEW commits into the Tarjan graph (anything
        # below the watermark executed, hence committed, first).
        for c in range(C):
            for s in range(int(prev_wm[c]), int(next_instance[c])):
                v = (c, s)
                if v in known_committed:
                    continue
                in_ring = s >= cur_wm[c]
                if (in_ring and committed[c, s % W]) or s < cur_wm[c]:
                    known_committed.add(v)
                    graph.commit(
                        v, 0, materialize_deps(dep_snapshot[v], c, s)
                    )

        components, _blockers = graph.execute_by_component()
        tarjan_new = [v for comp in components for v in comp]
        scc_events += sum(1 for comp in components if len(comp) > 1)

        assert new_exec == set(tarjan_new), (
            f"tick {t}: batched executed {sorted(new_exec)} but Tarjan "
            f"executed {sorted(tarjan_new)}"
        )
        batched_executed |= new_exec
        tarjan_executed |= set(tarjan_new)

    assert batched_executed == tarjan_executed
    return len(batched_executed), scc_events


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("window", [16, 4])
def test_batched_epaxos_matches_tarjan(seed, window):
    """window=4 saturates the ring (retire + same-tick re-proposal), the
    backpressure regime where execution order is most stressed."""
    cfg = BatchedEPaxosConfig(
        num_columns=3,
        window=window,
        instances_per_tick=window // 8 or 2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.3,
        see_same_tick_rate=0.625,
    )
    executed, scc_events = run_cross_validation(cfg, seed=seed, num_ticks=40)
    assert executed > 30
    # The run must actually exercise the cycle path: mutual same-tick
    # visibility guarantees SCCs of size > 1 appear.
    assert scc_events > 0, "no SCC formed; the test lost its teeth"


def test_batched_epaxos_matches_tarjan_wide():
    """Cross-column chains at C=5 (single visibility word)."""
    cfg = BatchedEPaxosConfig(
        num_columns=5,
        window=8,
        instances_per_tick=1,
        lat_min=1,
        lat_max=2,
        slow_path_rate=0.2,
        see_same_tick_rate=0.5,
    )
    executed, scc_events = run_cross_validation(cfg, seed=2, num_ticks=40)
    assert executed > 50
    assert scc_events > 0


def test_batched_epaxos_matches_tarjan_multiword():
    """C=40 > 32 lanes: the packed visibility mask spans TWO uint32
    words, so a word-index/lane-order bug in _pack_bool or _instance_ok
    (e.g. for columns >= 32) would execute instances before their
    cross-column deps commit — exactly what the Tarjan oracle catches."""
    cfg = BatchedEPaxosConfig(
        num_columns=40,
        window=8,
        instances_per_tick=1,
        lat_min=1,
        lat_max=2,
        slow_path_rate=0.2,
        see_same_tick_rate=0.25,
    )
    executed, scc_events = run_cross_validation(cfg, seed=3, num_ticks=30)
    assert executed > 400
    assert scc_events > 0


def test_batched_epaxos_simplebpaxos_latency():
    """Simple BPaxos pays an extra RTT before commit (the disaggregated
    proposer -> dep-service hop); same dependency semantics."""
    common = dict(
        num_columns=5,
        window=32,
        instances_per_tick=2,
        lat_min=2,
        lat_max=2,
        slow_path_rate=0.0,
        see_same_tick_rate=0.0,
        max_instances_per_column=40,
    )
    key = jax.random.PRNGKey(3)
    stats = {}
    for name, flag in [("epaxos", False), ("bpaxos", True)]:
        cfg = BatchedEPaxosConfig(simplebpaxos=flag, **common)
        state, t = run_ticks(cfg, init_state(cfg), jnp.int32(0), 80, key)
        inv = check_invariants(cfg, state, t)
        assert all(bool(v) for v in inv.values()), inv
        assert int(state.executed_total) == 5 * 40
        stats[name] = float(state.lat_sum) / int(state.executed_total)
    # 2 one-way hops at lat=2 -> fast path 4 ticks; BPaxos adds 2 more
    # hops -> 8 ticks (plus the tick-granularity execute delay on both).
    assert stats["bpaxos"] == pytest.approx(stats["epaxos"] + 4, abs=0.5)


def test_batched_epaxos_invariants_random():
    """Open workload with slow paths and cycles: invariants hold and the
    pipeline makes progress."""
    cfg = BatchedEPaxosConfig(
        num_columns=5,
        window=64,
        instances_per_tick=2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.25,
        see_same_tick_rate=0.5,
    )
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), 200, jax.random.PRNGKey(7)
    )
    inv = check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.executed_total) > 1000
    assert int(state.coexecuted) > 0  # chains/components co-executed


def test_batched_epaxos_wide_columns():
    """The factored representation's reason to exist: >=1024 columns
    (multi-word visibility masks) run with healthy throughput and clean
    invariants."""
    cfg = BatchedEPaxosConfig(
        num_columns=1024,
        window=32,
        instances_per_tick=2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.2,
        see_same_tick_rate=0.5,
        frontier_history=64,
    )
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), 60, jax.random.PRNGKey(9)
    )
    inv = check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    # 1024 columns x 2/tick x 60 ticks = 122,880 offered; the pipeline
    # must execute the bulk of them (ramp-up and in-flight tail allowed).
    assert int(state.executed_total) > 80_000
    assert int(state.coexecuted) > 0


def test_eligible_closure_blocks_on_uncommitted():
    """A committed instance whose dependency is uncommitted must not
    execute (it is a blocker, DependencyGraph.scala execute()); a
    committed mutual 2-cycle executes together. Dependencies are built
    through the factored representation (frontier rows + vis bits)."""
    C, W, H = 2, 4, 8
    head = jnp.zeros((C,), jnp.int32)
    w_iota_zeros = jnp.zeros((C, W), jnp.int32)

    def closure(committed, proposed, propose_tick, vis, fpre, fpost, nxt):
        return eligible_closure(
            committed, proposed, propose_tick, vis, fpre, fpost, head, nxt
        )

    # Scenario: both columns proposed instance 0 at tick 0 (fpre row 0 =
    # [0, 0], fpost row 0 = [1, 1]). (0,0) SEES (1,0) — depends on it —
    # but only (0,0) is committed: blocked.
    proposed = jnp.array([[True, False, False, False]] * 2)
    propose_tick = jnp.where(proposed, 0, 10**9)
    committed = jnp.array(
        [[True, False, False, False], [False, False, False, False]]
    )
    fpre = jnp.zeros((H, C), jnp.int32)
    fpost = jnp.zeros((H, C), jnp.int32).at[0].set(jnp.array([1, 1]))
    nxt = jnp.array([1, 1], jnp.int32)
    vis = jnp.zeros((C, W, 1), jnp.uint32)
    vis = vis.at[0, 0, 0].set(jnp.uint32(0b10))  # (0,0) sees column 1
    newly, run = closure(
        committed, proposed, propose_tick, vis, fpre, fpost, nxt
    )
    assert not bool(newly[0, 0])  # blocked on uncommitted (1,0)
    assert not bool(newly[1, 0])  # uncommitted
    assert int(run.sum()) == 0

    # Mutual 2-cycle, both committed: both execute together.
    committed = jnp.array([[True, False, False, False]] * 2)
    vis = vis.at[1, 0, 0].set(jnp.uint32(0b01))  # (1,0) sees column 0
    newly, run = closure(
        committed, proposed, propose_tick, vis, fpre, fpost, nxt
    )
    assert bool(newly[0, 0]) and bool(newly[1, 0])
    assert int(run.sum()) == 2


def test_gc_bounded_state_under_open_workload():
    """The simplegcbpaxos GC layer: pruning waits for the quorum
    watermark's snapshot barrier, yet the ring stays bounded (window_ok)
    and the pipeline keeps executing under replica crash churn."""
    cfg = BatchedEPaxosConfig(
        num_columns=16,
        window=64,
        instances_per_tick=2,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.2,
        see_same_tick_rate=0.5,
        num_exec_replicas=3,
        replica_lag=2,
        rep_crash_rate=0.02,
        rep_revive_rate=0.2,
        snapshot_every=8,
        gc_quorum=2,
    )
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), 300, jax.random.PRNGKey(11)
    )
    inv = check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.executed_total) > 4000
    # Pruning genuinely lags execution (the barrier is periodic)...
    assert int(state.retired_total) <= int(state.executed_total)
    assert int(state.rep_crashes) > 0
    # ...and crashed replicas that fell behind the pruned prefix were
    # served from snapshots.
    assert int(state.snapshots_served) > 0


def test_gc_recovery_serves_snapshot_deterministically():
    """Crash one replica by hand, run until the prune base passes its
    watermark, revive it: the recovery must be served from the snapshot
    barrier (watermark jumps to snapshot_wm, snapshots_served bumps) —
    the GC'd prefix is not replayable (Replica.scala:317-363)."""
    cfg = BatchedEPaxosConfig(
        num_columns=4,
        window=32,
        instances_per_tick=2,
        lat_min=1,
        lat_max=2,
        slow_path_rate=0.0,
        see_same_tick_rate=0.0,
        num_exec_replicas=3,
        replica_lag=1,
        rep_crash_rate=0.0,
        rep_revive_rate=0.0,
        snapshot_every=4,
        gc_quorum=2,
    )
    key = jax.random.PRNGKey(12)
    state = init_state(cfg)
    t = 0
    for _ in range(20):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    # Crash replica 2.
    state = dataclasses.replace(
        state, rep_down=state.rep_down.at[2].set(True)
    )
    stuck = np.asarray(state.rep_exec)[2].copy()
    served0 = int(state.snapshots_served)
    for _ in range(40):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    # The quorum (replicas 0, 1) kept GC moving past the crashed
    # replica's watermark.
    assert (np.asarray(state.head) > stuck).all()
    assert int(state.snapshots_served) == served0  # down: not served yet
    # Revive: the next tick must serve it from the snapshot barrier.
    state = dataclasses.replace(
        state, rep_down=state.rep_down.at[2].set(False)
    )
    state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
    assert int(state.snapshots_served) > served0
    rep2 = np.asarray(state.rep_exec)[2]
    snap = np.asarray(state.head)  # head IS the snapshot barrier
    assert (rep2 >= snap).all(), (rep2, snap)
    inv = check_invariants(cfg, state, jnp.int32(t + 1))
    assert all(bool(v) for v in inv.values()), inv


def test_gc_execution_matches_tarjan():
    """With the GC layer on, executed-but-unpruned slots linger in the
    ring; the closure must still execute exactly the Tarjan-eligible set
    (watermark = exec_wm, not head)."""
    cfg = BatchedEPaxosConfig(
        num_columns=3,
        window=16,
        instances_per_tick=1,
        lat_min=1,
        lat_max=3,
        slow_path_rate=0.3,
        see_same_tick_rate=0.5,
        num_exec_replicas=3,
        replica_lag=2,
        snapshot_every=6,
        gc_quorum=2,
    )
    executed, scc_events = run_cross_validation(
        cfg, seed=13, num_ticks=40, gc=True
    )
    assert executed > 30
    assert scc_events > 0


def test_unanimous_bpaxos_matches_tarjan():
    """Unanimous BPaxos mode: failed fast paths widen deps to the union
    of dep-service reports; the closure must still execute exactly the
    Tarjan-eligible set over the widened graph."""
    cfg = BatchedEPaxosConfig(
        num_columns=3,
        window=16,
        instances_per_tick=1,
        lat_min=1,
        lat_max=3,
        see_same_tick_rate=0.5,
        unanimous_mode=True,
        unanimity_rate=0.5,
    )
    executed, scc_events = run_cross_validation(cfg, seed=21, num_ticks=40)
    assert executed > 30
    assert scc_events > 0


def test_unanimous_fast_path_fraction_tracks_unanimity():
    """unanimity_rate=1.0 -> every proposal is fast; 0.0 -> every
    proposal that saw concurrency pays the classic round, and the mean
    commit->execute latency is strictly worse."""
    common = dict(
        num_columns=8,
        window=32,
        instances_per_tick=2,
        lat_min=2,
        lat_max=2,
        see_same_tick_rate=1.0,  # every instance sees its peers
        unanimous_mode=True,
    )
    key = jax.random.PRNGKey(22)
    out = {}
    for rate in (1.0, 0.0):
        cfg = BatchedEPaxosConfig(unanimity_rate=rate, **common)
        state, t = run_ticks(cfg, init_state(cfg), jnp.int32(0), 120, key)
        total = int(state.next_instance.sum())
        out[rate] = {
            "fast_fraction": int(state.fast_path_total) / max(1, total),
            "mean_lat": float(state.lat_sum)
            / max(1, int(state.executed_total)),
        }
        inv = check_invariants(cfg, state, t)
        assert all(bool(v) for v in inv.values()), inv
    assert out[1.0]["fast_fraction"] > 0.99
    assert out[0.0]["fast_fraction"] < 0.01
    assert out[0.0]["mean_lat"] > out[1.0]["mean_lat"] + 3  # +1 RTT at lat=2


def test_epaxos_sharded_matches_unsharded():
    """The column axis shards over the virtual 8-device mesh (the
    factored representation's design goal): the sharded run is
    bit-identical to the unsharded one — with the GC layer on, so the
    replica watermarks ([R, C], second-axis sharded) and snapshot
    recovery cross-validate too."""
    from frankenpaxos_tpu.parallel import (
        make_mesh,
        run_epaxos_ticks_sharded,
        shard_epaxos_state,
    )

    cfg = BatchedEPaxosConfig(
        num_columns=16,
        window=16,
        instances_per_tick=2,
        lat_min=1,
        lat_max=3,
        see_same_tick_rate=0.5,
        frontier_history=64,
        num_exec_replicas=3,
        rep_crash_rate=0.02,
        rep_revive_rate=0.2,
        snapshot_every=8,
    )
    key = jax.random.PRNGKey(31)
    t0 = jnp.zeros((), jnp.int32)
    plain, _ = run_ticks(cfg, init_state(cfg), t0, 100, key)
    mesh = make_mesh()
    sharded0 = shard_epaxos_state(init_state(cfg), mesh)
    sharded, _ = run_epaxos_ticks_sharded(cfg, mesh, sharded0, t0, 100, key)
    for field in (
        "executed_total", "committed_total", "retired_total", "head",
        "exec_wm", "next_instance", "coexecuted", "snapshots_served",
        "rep_exec", "fast_path_total",
    ):
        a = np.asarray(jax.device_get(getattr(plain, field)))
        b = np.asarray(jax.device_get(getattr(sharded, field)))
        assert (a == b).all(), field
    assert int(plain.executed_total) > 1000


def test_general_deps_matches_factored_bit_exactly():
    """``general_deps=True`` swaps the factored watermark fixpoint for
    a materialized [C*W, ceil(C*W/32)] adjacency driven through the
    ``depgraph_execute`` plane — and the run stays state-equal tick
    for tick to the factored twin on every leaf except the adjacency
    itself, with GC replicas and faults engaged, and the dep-graph
    safety invariant (nothing executes before its dependency rows are
    contained in the executed set) holding at the end."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    base = dict(
        num_columns=3, window=8, instances_per_tick=2,
        see_same_tick_rate=0.5,
    )
    variants = {
        "plain": {},
        "gc": dict(num_exec_replicas=2),
        "faulty": dict(
            faults=FaultPlan(
                drop_rate=0.1, jitter=1, partition=(0, 1, 0),
                partition_start=10, partition_heal=30,
            )
        ),
    }
    for name, kw in variants.items():
        for seed in (0, 1):
            cfg_f = BatchedEPaxosConfig(**base, **kw)
            cfg_g = dataclasses.replace(cfg_f, general_deps=True)
            key = jax.random.PRNGKey(seed)
            t0 = jnp.zeros((), jnp.int32)
            sf, tf = run_ticks(cfg_f, init_state(cfg_f), t0, 60, key)
            sg, tg = run_ticks(cfg_g, init_state(cfg_g), t0, 60, key)
            assert int(sf.executed_total) > 0, (name, seed)
            for field in dataclasses.fields(sf):
                if field.name == "adj":
                    continue
                la = jax.tree_util.tree_leaves(getattr(sf, field.name))
                lb = jax.tree_util.tree_leaves(getattr(sg, field.name))
                assert len(la) == len(lb), (name, field.name)
                for a, b in zip(la, lb):
                    np.testing.assert_array_equal(
                        np.asarray(a), np.asarray(b),
                        err_msg=f"{name}[{seed}].{field.name}",
                    )
            inv = check_invariants(cfg_g, sg, tg)
            assert "dep_safety_ok" in inv
            assert all(bool(v) for v in inv.values()), (name, inv)


def test_general_deps_traced_conflict_knob_sweeps_density():
    """A WorkloadPlan carrying ``conflict_rate`` turns the same-tick
    visibility density into TRACED state: the general path still
    matches the factored twin under it, and re-tracing is not needed
    to sweep it (set_conflict_rate edits state, the compiled program
    replays)."""
    from frankenpaxos_tpu.tpu import workload as workload_mod
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    plan = WorkloadPlan(
        arrival="constant", rate=1.5, conflict_rate=0.5
    )
    cfg_f = BatchedEPaxosConfig(
        num_columns=3, window=8, instances_per_tick=2, workload=plan,
    )
    cfg_g = dataclasses.replace(cfg_f, general_deps=True)
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)
    sf, _ = run_ticks(cfg_f, init_state(cfg_f), t0, 50, key)
    sg, _ = run_ticks(cfg_g, init_state(cfg_g), t0, 50, key)
    np.testing.assert_array_equal(
        np.asarray(sf.vis_bits), np.asarray(sg.vis_bits)
    )
    assert int(sf.executed_total) == int(sg.executed_total) > 0
    # The knob is state, not structure: resweep the density on the
    # SAME compiled run_ticks via set_conflict_rate.
    st = init_state(cfg_g)
    st = dataclasses.replace(
        st, workload=workload_mod.set_conflict_rate(st.workload, 0.9)
    )
    s9, t9 = run_ticks(cfg_g, st, t0, 50, key)
    inv = check_invariants(cfg_g, s9, t9)
    assert all(bool(v) for v in inv.values()), inv
