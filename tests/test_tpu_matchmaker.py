"""Device-side Matchmaker reconfiguration in the batched backend
(BASELINE config 4): MatchA/MatchB quorums, phase-1 against the OLD
configuration via real message arrivals (a true f+1 read quorum, not an
oracle), i/i+1 round-config binding, proposal stalls (the churn dip),
and the old-config GC pipeline — all inside the compiled lax.scan."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.parallel import make_mesh, run_ticks_sharded, shard_state
from frankenpaxos_tpu.tpu import (
    BatchedMultiPaxosConfig,
    TpuSimTransport,
    check_invariants,
    init_state,
    run_ticks,
    tick,
)
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    INF,
    INF16,
    RC_NORMAL,
    CHOSEN,
    PROPOSED,
)


def make(**kw):
    defaults = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, reconfigure_every=40,
    )
    defaults.update(kw)
    return BatchedMultiPaxosConfig(**defaults)


def test_churn_runs_inside_one_scan_with_invariants():
    """>= 10 configuration changes inside ONE compiled scan: progress
    continues, invariants hold, every group reaches the same epoch."""
    sim = TpuSimTransport(make(reconfigure_every=30), seed=0)
    sim.run(330)  # one scan segment; reconfigs at t=30,60,...,330
    s = sim.stats()
    assert s["reconfigurations"] >= 10 * sim.config.num_groups
    assert s["config_epoch_max"] >= 10
    assert s["round"] == s["config_epoch_max"]  # i/i+1 round-config binding
    assert s["committed"] > 1000
    assert s["old_configs_gcd"] > 0  # the GC pipeline retires old configs
    assert all(sim.check_invariants().values()), sim.check_invariants()


def test_churn_dips_and_recovers():
    """The churn sweep signal: ticks during a reconfiguration commit less
    than steady-state ticks, and throughput recovers after (the
    vldb20_matchmaker lt dip/recovery figure)."""
    cfg = make(num_groups=8, reconfigure_every=50, window=32, slots_per_tick=4)
    sim = TpuSimTransport(cfg, seed=1)
    rates = []
    for _ in range(25):  # 10-tick segments over 250 ticks: reconfigs at 50k
        before = sim.committed()
        sim.run(10)
        rates.append(sim.committed() - before)
    # Segments containing the reconfiguration exchange (indices 5, 10, ...)
    # must commit less than the steady-state segments around them.
    dips = [rates[i] for i in (5, 10, 15, 20)]
    steady = [rates[i] for i in (3, 8, 13, 18, 23)]
    assert min(steady) > max(dips), (dips, steady)
    # And it RECOVERS: the segment after each dip is back near steady.
    post = [rates[i + 1] for i in (5, 10, 15, 20)]
    assert min(post) > max(dips), (post, dips)
    assert all(sim.check_invariants().values())


def test_possibly_chosen_value_survives_via_quorum_intersection():
    """A value voted by a full write quorum (f+1 acceptors) but never
    LEARNED as chosen must survive reconfiguration: phase 1 reads only
    the first f+1 Phase1bs, and ANY f+1 read quorum intersects the
    {0, 1} write quorum — the safety property the Matchmaker path exists
    to preserve (Reconfigurer.scala's phase-1-against-old-configs)."""
    cfg = make(
        num_groups=2, window=8, slots_per_tick=1, lat_min=1, lat_max=1,
        thrifty=False, retry_timeout=100, max_slots_per_group=1,
        reconfigure_every=4,
    )
    key = jax.random.PRNGKey(2)
    state = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    # Phase2as reach acceptors 0 and 1 only (a full f+1 write quorum:
    # the value is possibly-chosen); acceptor 2 never hears of it.
    p2a = np.asarray(state.p2a_arrival).copy()
    p2a[2, :, :] = INF16
    state = dataclasses.replace(state, p2a_arrival=jnp.asarray(p2a))
    values = {}
    epoch1 = False
    for t in range(1, 30):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        if not epoch1:
            # Block every Phase2b until the reconfiguration completes:
            # the slot is voted-but-never-chosen in the old config.
            state = dataclasses.replace(
                state,
                p2b_arrival=jnp.full_like(state.p2b_arrival, INF16),
            )
            if t == 1:
                vr = np.asarray(state.vote_round)
                assert (vr[:2, :, 0] == 0).all()  # quorum {0,1} voted
                assert (vr[2, :, 0] == -1).all()
                values = np.asarray(state.vote_value)[0, :, 0].copy()
                assert (values >= 0).all()
            if int(np.asarray(state.config_epoch).max()) == 1:
                epoch1 = True
        elif (np.asarray(state.status)[:, 0] == CHOSEN).all():
            break
    assert epoch1, "reconfiguration never completed"
    # Committed in the NEW configuration with the ORIGINAL values — the
    # learned read quorum intersected the {0,1} write quorum.
    assert (np.asarray(state.status)[:, 0] == CHOSEN).all()
    assert (np.asarray(state.chosen_value)[:, 0] == values).all(), (
        np.asarray(state.chosen_value)[:, 0], values,
    )
    assert int(np.asarray(state.chosen_round).max()) == 1  # new round
    inv = check_invariants(cfg, state, jnp.int32(t + 1))
    assert all(bool(v) for v in inv.values()), inv


def test_matchmaker_with_reads_failover_and_loss():
    """Everything at once in one compiled program: churn + device
    elections + linearizable reads + message loss."""
    cfg = make(
        num_groups=4, reconfigure_every=60, drop_rate=0.1, retry_timeout=6,
        fail_rate=0.005, revive_rate=0.2, heartbeat_timeout=5,
        read_rate=2, read_window=8, read_mode="linearizable",
    )
    sim = TpuSimTransport(cfg, seed=3)
    sim.run(400)
    s = sim.stats()
    assert s["reconfigurations"] > 0
    assert s["reads_done"] > 0
    assert s["committed"] > 500
    assert all(sim.check_invariants().values()), sim.check_invariants()


def test_matchmaker_sharded_matches_unsharded():
    cfg = make(num_groups=8, reconfigure_every=40)
    key = jax.random.PRNGKey(4)
    t0 = jnp.zeros((), jnp.int32)
    plain, _ = run_ticks(cfg, init_state(cfg), t0, 150, key)
    mesh = make_mesh()
    sharded, _ = run_ticks_sharded(
        cfg, mesh, shard_state(init_state(cfg), mesh), t0, 150, key
    )
    for field in ("committed", "retired", "reconfigs", "configs_gcd"):
        assert int(jax.device_get(getattr(plain, field))) == int(
            jax.device_get(getattr(sharded, field))
        ), field
    assert int(jax.device_get(plain.reconfigs)) > 0


def test_feature_off_is_inert():
    sim = TpuSimTransport(make(reconfigure_every=0), seed=5)
    sim.run(60)
    assert int(sim.state.reconfigs) == 0
    assert int(jax.device_get(sim.state.recon_phase).max()) == RC_NORMAL
    assert "reconfigurations" not in sim.stats()
    assert all(sim.check_invariants().values())


def test_straggler_messages_with_wide_latency_spread():
    """lat_max=3 (the bench/config4 setting) makes some Phase1a/MatchB
    messages arrive AFTER their reconfiguration wave completes. A
    straggler must promise the round its message was sent for (not the
    live, already-bumped round — which would lock it out of voting,
    starving thrifty quorums for retry_timeout ticks), and stale replies
    must never count toward the NEXT wave's quorums."""
    sim = TpuSimTransport(
        make(lat_min=1, lat_max=3, reconfigure_every=12, retry_timeout=16),
        seed=2,
    )
    committed_prev = 0
    for _ in range(8):
        sim.run(30)
        inv = sim.check_invariants()
        assert all(inv.values()), inv
        s = sim.stats()
        # Progress continues across every wave (no locked-out acceptors
        # starving the thrifty quorums).
        assert s["committed"] > committed_prev
        committed_prev = s["committed"]
    assert sim.stats()["reconfigurations"] >= 15


def test_randomized_elections_with_reconfiguration_churn():
    """Device-side elections RACING matchmaker reconfigurations: leader
    deaths (fail_rate) bump leader_round past an in-flight rc_round, so
    the p1_done install must jnp.maximum acc_round rather than overwrite
    it (overwriting would regress acceptors below their vote_round and
    break promise monotonicity / round_ok). Randomized over seeds so the
    interleaving space — elections landing before, during, and after
    each reconfiguration wave — is actually explored."""
    total_elections = 0
    for seed in range(6):
        cfg = make(
            num_groups=4, reconfigure_every=15, lat_min=1, lat_max=3,
            fail_rate=0.03, revive_rate=0.15, heartbeat_timeout=3,
            device_elections=True, retry_timeout=8,
        )
        sim = TpuSimTransport(cfg, seed=seed)
        sim.run(300)
        s = sim.stats()
        inv = sim.check_invariants()
        assert all(inv.values()), (seed, inv)
        assert s["committed"] > 100, (seed, s["committed"])
        assert s["reconfigurations"] > 0, seed
        total_elections += s["elections"]
    # The seeds must actually interleave elections with the churn
    # (otherwise this test exercises nothing new).
    assert total_elections > 0


def test_election_midflight_reconfiguration_keeps_promises_monotone():
    """Deterministic interleaving of ADVICE r03 (medium): an election
    bumps leader_round PAST an in-flight reconfiguration's rc_round
    (candidate 1 also dead -> delta 2), the repair re-proposal makes
    acceptors vote at the election round, and only then does the
    reconfiguration's p1_done install fire. The install must jnp.maximum
    acc_round with rc_round, not overwrite — overwriting regresses
    acceptors below their vote_round (round_ok / promise monotonicity)."""
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=1, window=8, slots_per_tick=1, lat_min=1, lat_max=1,
        device_elections=True, heartbeat_timeout=3, reconfigure_every=20,
        retry_timeout=100,
    )
    key = jax.random.PRNGKey(0)

    def freeze(st):
        # Chosen slots must stay in the ring (with their votes) so the
        # invariant can see them at p1_done time.
        return dataclasses.replace(
            st, replica_arrival=jnp.full_like(st.replica_arrival, int(INF))
        )

    state = tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    # Slot 0's Phase2a reaches only acceptor 0: it stays PROPOSED with a
    # single round-0 vote, so the election's phase-1 repair later
    # re-proposes it at the election round.
    p2a = np.asarray(state.p2a_arrival).copy()
    p2a[1:, :, 0] = INF16
    state = freeze(dataclasses.replace(state, p2a_arrival=jnp.asarray(p2a)))

    injected = False
    saw_vote_at_election_round = False
    for t in range(1, 60):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        state = freeze(state)
        if not injected and (np.asarray(state.rc_p1b_arrival) < int(INF)).any():
            # The wave is mid-phase-1: hold its Phase1b replies until
            # t=45 and kill candidates 0 AND 1 (so the election's round
            # delta is 2, overtaking rc_round = 1).
            p1b = np.asarray(state.rc_p1b_arrival).copy()
            p1b[p1b < int(INF)] = 45
            alive = np.asarray(state.leader_alive).copy()
            alive[0, :] = False
            alive[1, :] = False
            state = dataclasses.replace(
                state,
                rc_p1b_arrival=jnp.asarray(p1b),
                leader_alive=jnp.asarray(alive),
            )
            injected = True
        inv = check_invariants(cfg, state, jnp.int32(t))
        assert all(bool(v) for v in inv.values()), (t, inv)
        if int(np.asarray(state.vote_round).max()) == 2:
            saw_vote_at_election_round = True
    assert injected
    assert int(state.elections) == 1
    assert saw_vote_at_election_round, (
        "scenario must actually vote at the election round mid-flight"
    )
    # The install completed (phase back to normal) without regressing
    # any acceptor below its votes.
    assert int(np.asarray(state.recon_phase)[0]) == RC_NORMAL
    assert int(np.asarray(state.acc_round).min()) == 2
