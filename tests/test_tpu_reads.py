"""Tests of the batched read path: device-resident ReadBatchers
(ReadBatcher.scala:239-338) whose per-group batches ride a shared
MaxSlot probe wave — linearizable/sequential/eventual modes, the
device-side linearizability floor, read conservation, throughput
scaling with the group count, and sharded equality (conftest: CPU, 8
virtual devices)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.parallel import make_mesh, run_ticks_sharded, shard_state
from frankenpaxos_tpu.tpu import (
    BatchedMultiPaxosConfig,
    TpuSimTransport,
    check_invariants,
    init_state,
    leader_change,
    run_ticks,
    tick,
)
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    INF,
    R_BOUND,
    R_EMPTY,
    R_SENT,
    R_WAIT,
)


def make(mode="linearizable", **kw):
    defaults = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, read_rate=2, read_window=8,
        read_mode=mode,
    )
    defaults.update(kw)
    return BatchedMultiPaxosConfig(**defaults)


@pytest.mark.parametrize("mode", ["linearizable", "sequential", "eventual"])
def test_reads_complete_and_invariants_hold(mode):
    sim = TpuSimTransport(make(mode), seed=0)
    sim.run(80)
    stats = sim.stats()
    assert stats["committed"] > 0
    assert stats["reads_done"] > 0
    assert stats["read_latency_mean_ticks"] > 0
    assert all(sim.check_invariants().values()), sim.check_invariants()


def test_linearizable_reads_slower_than_eventual():
    """A linearizable batch pays the MaxSlot wave round-trip plus the
    watermark wait; an eventual batch pays one hop. The model must show
    the ordering the reference's consistency modes exist to trade."""
    lin = TpuSimTransport(make("linearizable"), seed=1)
    ev = TpuSimTransport(make("eventual"), seed=1)
    lin.run(200)
    ev.run(200)
    assert (
        lin.stats()["read_latency_mean_ticks"]
        > ev.stats()["read_latency_mean_ticks"]
    )
    assert ev.stats()["reads_done"] >= lin.stats()["reads_done"]


def test_reads_under_loss_and_failover():
    sim = TpuSimTransport(
        make("linearizable", drop_rate=0.2, retry_timeout=6), seed=2
    )
    sim.run(60)
    sim.leader_change()
    sim.run(200)
    stats = sim.stats()
    assert stats["reads_done"] > 0
    assert all(sim.check_invariants().values()), sim.check_invariants()


def test_linearizability_floor_is_enforced_by_construction():
    """Every bound batch's target must be >= the max globally chosen slot
    at its issue tick (read/write quorum intersection). The invariant
    counter must stay zero over a long, lossy, failover-heavy run."""
    cfg = make("linearizable", drop_rate=0.1, retry_timeout=6, f=2)
    sim = TpuSimTransport(cfg, seed=3)
    for _ in range(4):
        sim.run(60)
        sim.leader_change()
    sim.run(100)
    inv = sim.check_invariants()
    assert inv["read_lin_ok"], "a read bound below its issue-time floor"
    assert all(inv.values()), inv


def test_lin_violation_detector_has_teeth():
    """Force an impossible floor under every outstanding batch: any later
    bind must then increment the violation counter (weighted by the
    batch's read count), and read_lin_ok must trip."""
    cfg = make("linearizable")
    key = jax.random.PRNGKey(4)
    state = init_state(cfg)
    t = 0
    for _ in range(12):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    status = np.asarray(state.rb_status)
    assert (status == R_WAIT).any()  # waves keep batches in flight
    state = dataclasses.replace(
        state, rb_floor=jnp.full_like(state.rb_floor, 10**9)
    )
    for _ in range(12):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    assert int(state.read_lin_violations) > 0
    inv = check_invariants(cfg, state, jnp.int32(t))
    assert not bool(inv["read_lin_ok"])


def test_read_target_tracks_committed_writes():
    """After the cluster commits for a while, linearizable batches bind
    to recent targets (close to the global watermark), and completed
    reads advance the client watermark monotonically."""
    sim = TpuSimTransport(make("linearizable"), seed=5)
    prev_wm = -1
    for _ in range(6):
        sim.run(40)
        wm = int(jax.device_get(sim.state.client_watermark))
        assert wm >= prev_wm
        prev_wm = wm
    assert prev_wm > 0  # reads saw real committed state


def test_sequential_reads_bound_by_own_history():
    sim = TpuSimTransport(make("sequential"), seed=6)
    sim.run(120)
    stats = sim.stats()
    assert stats["reads_done"] > 0
    # Sequential targets come from the client's own watermark, which only
    # moves forward; batches never wait on a wave (no R_WAIT).
    status = np.asarray(sim.state.rb_status)
    assert ((status == R_EMPTY) | (status == R_BOUND) | (status == R_SENT)).all()
    assert all(sim.check_invariants().values())


def test_read_conservation():
    """Every read the workload offers is accounted for exactly once:
    done + shed + still-in-flight == G * read_rate * ticks."""
    cfg = make("linearizable")
    sim = TpuSimTransport(cfg, seed=7)
    sim.run(150)
    offered = cfg.num_groups * cfg.read_rate * 150
    done = int(sim.state.reads_done)
    shed = int(sim.state.reads_shed)
    in_flight = int(jax.device_get(sim.state.rb_count).sum())
    assert done + shed + in_flight == offered
    assert done > 0


def test_read_throughput_scales_with_groups():
    """The whole point of the batcher redesign: read throughput is
    proportional to the cluster size (each group's batcher carries
    read_rate reads per tick), not a fixed global trickle."""
    small = TpuSimTransport(make("linearizable", num_groups=4), seed=8)
    big = TpuSimTransport(make("linearizable", num_groups=16), seed=8)
    small.run(200)
    big.run(200)
    r_small = small.stats()["reads_done"]
    r_big = big.stats()["reads_done"]
    assert r_small > 0
    # 4x the groups must give ~4x the reads (allow slack for shedding).
    assert r_big > 3 * r_small
    assert all(big.check_invariants().values())


def test_reads_sharded_matches_unsharded():
    """Read batches ride a wave that fans out to every group (the one
    cross-device pattern); the sharded run must still be bit-identical
    to the unsharded one."""
    cfg = make("linearizable", num_groups=8, drop_rate=0.1, retry_timeout=6)
    key = jax.random.PRNGKey(7)
    t0 = jnp.zeros((), jnp.int32)
    plain, plain_t = run_ticks(cfg, init_state(cfg), t0, 120, key)
    mesh = make_mesh()
    sharded0 = shard_state(init_state(cfg), mesh)
    sharded, sharded_t = run_ticks_sharded(cfg, mesh, sharded0, t0, 120, key)
    for field in (
        "reads_done", "reads_shed", "read_lat_sum", "read_lin_violations",
        "committed", "retired", "client_watermark", "max_chosen_global",
    ):
        a = jax.device_get(getattr(plain, field))
        b = jax.device_get(getattr(sharded, field))
        assert (a == b).all(), field
    assert int(jax.device_get(plain.reads_done)) > 0


def test_reads_off_state_is_empty_and_cheap():
    """read_rate=0 keeps every read array zero-sized — the write-only
    model's compiled program carries no read traffic."""
    cfg = make(read_rate=0, read_window=0)
    state = init_state(cfg)
    assert state.req_arrival.size == 0
    assert state.rb_status.size == 0
    sim = TpuSimTransport(cfg, seed=8)
    sim.run(30)
    assert "reads_done" not in sim.stats()
    assert all(sim.check_invariants().values())
