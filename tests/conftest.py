"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip hardware is not
available in CI). The TPU plugin in this environment overrides
``JAX_PLATFORMS``, so forcing CPU requires BOTH the XLA flag (before
import) and ``jax.config.update`` (after import).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
