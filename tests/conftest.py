"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip hardware is not
available in CI). The TPU plugin in this environment overrides
``JAX_PLATFORMS``, so forcing CPU requires BOTH the XLA flag (before
import) and ``jax.config.update`` (after import).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's wall clock is dominated
# by compiling the batched backends (each test file's configs compile
# fresh programs); with the cache warm, repeated tier-1 runs skip most
# of that. Keyed by program + flags, so correctness is unaffected; the
# first run pays full price and fills the cache.
_CACHE_DIR = os.environ.get(
    "FRANKENPAXOS_JAX_CACHE", "/tmp/frankenpaxos_jax_cache"
)
try:
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass  # older jax without the persistent cache: run uncached

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest


@pytest.fixture
def fleet_mesh():
    """The canonical 2x4 ``('fleet', 'groups')`` product mesh over the
    conftest's 8 virtual devices — the fleet-axis tests
    (tests/test_fleet.py, the test_harness brick smoke) run on it;
    mesh-shape-agnostic tests build their own variants."""
    from frankenpaxos_tpu.parallel import sharding as sh

    return sh.make_fleet_mesh(fleet=2)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy cases excluded from the tier-1 budget "
        "(run with -m slow or no marker filter)",
    )
    config.addinivalue_line(
        "markers",
        "lint: static-analysis suite (frankenpaxos_tpu.analysis rule "
        "wrappers + engine tests); `pytest -m lint` runs just these",
    )


# XLA's CPU JIT keeps every compiled executable's code pages mapped for
# as long as the jit caches hold the executable, and the full tier-1
# suite compiles enough distinct programs to cross the kernel's
# vm.max_map_count ceiling (65530 by default) around the ~800th test —
# at which point LLVM's next code-buffer mmap fails and the COMPILER
# aborts the whole process (observed as a deterministic
# segfault/abort in backend_compile at a fixed test index). Dropping
# the jax caches releases the executables and their mappings. Gate the
# clear on the live mapping count so warm-cache behavior (and wall
# clock) is untouched until the process nears the ceiling.
_MAPS_CLEAR_THRESHOLD = 45_000


def _proc_map_count() -> int:
    try:
        with open("/proc/self/maps", "rb") as f:
            return sum(1 for _ in f)
    except OSError:  # non-Linux host: the ceiling doesn't apply
        return 0


def pytest_runtest_teardown(item):
    if _proc_map_count() > _MAPS_CLEAR_THRESHOLD:
        import gc

        jax.clear_caches()
        gc.collect()
