"""SimpleBPaxos sim tests (the analog of shared/src/test/scala/simplebpaxos)."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport, wire
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import simplebpaxos as bp
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set
from test_epaxos import RecordingKv, _conflicting_order_violation


def make(f=1, num_clients=2, seed=0):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    n = 2 * f + 1
    config = bp.SimpleBPaxosConfig(
        f=f,
        leader_addresses=tuple(SimAddress(f"leader{i}") for i in range(f + 1)),
        proposer_addresses=tuple(
            SimAddress(f"proposer{i}") for i in range(f + 1)
        ),
        dep_service_node_addresses=tuple(
            SimAddress(f"dep{i}") for i in range(n)
        ),
        acceptor_addresses=tuple(SimAddress(f"acceptor{i}") for i in range(n)),
        replica_addresses=tuple(SimAddress(f"replica{i}") for i in range(f + 1)),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [
        bp.BpLeader(a, t, log(), config, seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    proposers = [
        bp.BpProposer(a, t, log(), config, seed=seed + 10 + i)
        for i, a in enumerate(config.proposer_addresses)
    ]
    deps = [
        bp.BpDepServiceNode(a, t, log(), config, KeyValueStore())
        for a in config.dep_service_node_addresses
    ]
    acceptors = [
        bp.BpAcceptor(a, t, log(), config) for a in config.acceptor_addresses
    ]
    replicas = [
        bp.BpReplica(a, t, log(), config, RecordingKv(), seed=seed + 30 + i)
        for i, a in enumerate(config.replica_addresses)
    ]
    clients = [
        bp.BpClient(SimAddress(f"client{i}"), t, log(), config, seed=seed + 50 + i)
        for i in range(num_clients)
    ]
    return t, config, leaders, proposers, deps, acceptors, replicas, clients


def drain(t, max_steps=100000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def test_simplebpaxos_single_command():
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make()
    p = clients[0].propose(0, kv_set(("x", "1")))
    drain(t)
    assert p.done
    for r in replicas:
        assert r.state_machine.get() == {"x": "1"}


def test_simplebpaxos_round_zero_skips_phase1():
    """A vertex's own proposer owns round 0, so no Phase1a hits the wire."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make()
    clients[0].propose(0, kv_set(("x", "1")))
    phase1as = 0
    while t.messages:
        m = t.messages[0]
        if isinstance(wire.decode(m.data), bp.BpPhase1a):
            phase1as += 1
        t.deliver_message(m)
    assert phase1as == 0


def test_simplebpaxos_conflicting_commands_converge():
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make(seed=4)
    p1 = clients[0].propose(0, kv_set(("x", "a")))
    p2 = clients[1].propose(0, kv_set(("x", "b")))
    rng = random.Random(5)
    for _ in range(4000):
        cmd = t.generate_command(rng)
        if cmd is None:
            break
        t.run_command(cmd, record=False)
    drain(t)
    assert p1.done and p2.done
    finals = {tuple(sorted(r.state_machine.get().items())) for r in replicas}
    assert len(finals) == 1, finals


def test_simplebpaxos_recovery_fills_stuck_vertex_with_noop():
    """Kill a leader after its dep requests go out; the dependent command's
    replica recovers the stuck vertex via the proposer (noop)."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make(seed=7)

    class _L0:
        def randrange(self, n):
            return 0

    clients[0].rng = _L0()
    p1 = clients[0].propose(0, kv_set(("x", "1")))
    # Deliver the client request so leader 0 creates vertex (0, 0) and sends
    # dependency requests; deliver those so the dep service learns the
    # vertex; then the leader dies before seeing any replies.
    t.deliver_message(t.messages[0])
    while t.messages:
        m = t.messages[0]
        if isinstance(wire.decode(m.data), bp.BpDependencyRequest):
            t.deliver_message(m)
        elif m.dst == config.leader_addresses[0]:
            t.drop_message(m)
        else:
            t.deliver_message(m)
    t.partition_actor(config.leader_addresses[0])
    t.partition_actor(config.proposer_addresses[0])

    # A conflicting command through leader 1 picks up vertex (0,0) as a
    # dependency and blocks on it.
    class _L1:
        def randrange(self, n):
            return 1

    clients[1].rng = _L1()
    p2 = clients[1].propose(0, kv_set(("x", "2")))
    drain(t)
    assert not p2.done  # blocked on the stuck vertex
    # Fire recover timers on live replicas until proposer 1 fills the hole.
    for _ in range(6):
        for timer in list(t.running_timers()):
            if timer.address in (
                config.replica_addresses + (config.proposer_addresses[1],)
            ):
                t.trigger_timer(timer.address, timer.name())
        drain(t)
    assert p2.done, "recovery did not unblock the dependent command"
    finals = {tuple(sorted(r.state_machine.get().items())) for r in replicas}
    assert len(finals) == 1


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    key: str
    value: str


class SimulatedSimpleBPaxos(SimulatedSystem):
    def __init__(self, f=1):
        self.f = f
        self._kv = KeyValueStore()

    def new_system(self, seed):
        return make(self.f, seed=seed)

    def get_state(self, system):
        replicas = system[6]
        return tuple(
            tuple(r.state_machine.executed_commands) for r in replicas
        )

    def generate_command(self, system, rng):
        t = system[0]
        clients = system[7]
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (1, Propose(i, pseudonym, f"k{rng.randrange(2)}",
                                    f"v{rng.randrange(50)}"))
                    )
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t = system[0]
        clients = system[7]
        if isinstance(command, Propose):
            clients[command.client_index].propose(
                command.pseudonym, kv_set((command.key, command.value))
            )
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        class _Holder:
            pass

        fakes = []
        for log in state:
            sm = _Holder()
            sm.executed_commands = list(log)
            holder = _Holder()
            holder.state_machine = sm
            fakes.append(holder)
        return _conflicting_order_violation(fakes, self._kv.conflicts)


@pytest.mark.parametrize("f", [1, 2])
def test_simplebpaxos_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedSimpleBPaxos(f), run_length=120, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_simplebpaxos_lost_reply_retry_gets_cached_reply():
    """A client whose reply is lost retries; the command is NOT re-executed
    and the cached reply is resent (review regression)."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make(seed=13)
    p = clients[0].propose(0, kv_set(("x", "1")))
    # Deliver everything except client-bound replies (drop them).
    while t.messages:
        m = t.messages[0]
        if isinstance(wire.decode(m.data), bp.BpClientReply):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert not p.done
    execs_before = [len(r.state_machine.executed_commands) for r in replicas]
    # The client's resend timer fires; this time let replies through.
    t.trigger_timer(clients[0].address, "resendBp[0;0]")
    drain(t)
    assert p.done, "retry after lost reply never completed"
    execs_after = [len(r.state_machine.executed_commands) for r in replicas]
    assert execs_after == execs_before, "command was re-executed on retry"


def test_simplebpaxos_lost_phase1b_recovered_by_resend():
    """An equal-round Phase1a resend must get a fresh Phase1b, not a nack
    (review regression: lost Phase1bs stalled recovery forever)."""
    t, config, leaders, proposers, deps, acceptors, replicas, clients = make(seed=17)
    # Proposer 1 recovers a stuck vertex owned by leader 0 => phase 1.
    vertex = (0, 0)
    proposers[1]._propose_impl(vertex, None, ())
    # Drop ALL Phase1bs, deliver everything else.
    while t.messages:
        m = t.messages[0]
        if isinstance(wire.decode(m.data), bp.BpPhase1b):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert vertex in proposers[1].states
    # Fire the resendPhase1a timer; acceptors must answer again.
    t.trigger_timer(proposers[1].address, f"resendPhase1a{vertex}")
    drain(t)
    from frankenpaxos_tpu.protocols.simplebpaxos import _BpChosen

    assert isinstance(proposers[1].states[vertex], _BpChosen)
