"""Tests of the simulation harness, using the reference's two pedagogical
systems: a bank account (shared/src/test/scala/bankaccount) and the Die Hard
water-jug puzzle (shared/src/test/scala/diehard), which demonstrates that
the simulator can *find* states."""

import dataclasses
import random

from frankenpaxos_tpu.sim import (
    BadHistory,
    SimulatedSystem,
    minimize,
    run_history,
    simulate,
    simulate_and_minimize,
)


class BankAccount:
    """Deliberately buggy: withdraw doesn't check the balance."""

    def __init__(self):
        self.balance = 0

    def deposit(self, amount):
        self.balance += amount

    def withdraw(self, amount):
        self.balance -= amount  # BUG: can go negative


@dataclasses.dataclass(frozen=True)
class Deposit:
    amount: int


@dataclasses.dataclass(frozen=True)
class Withdraw:
    amount: int


class SimulatedBankAccount(SimulatedSystem):
    def new_system(self, seed):
        return BankAccount()

    def get_state(self, system):
        return system.balance

    def generate_command(self, system, rng):
        if rng.random() < 0.5:
            return Deposit(rng.randrange(0, 100))
        return Withdraw(rng.randrange(0, 100))

    def run_command(self, system, command):
        if isinstance(command, Deposit):
            system.deposit(command.amount)
        else:
            system.withdraw(command.amount)
        return system

    def state_invariant(self, state):
        if state < 0:
            return f"balance {state} is negative"
        return None


def test_finds_bank_account_bug_and_minimizes():
    bad = simulate_and_minimize(
        SimulatedBankAccount(), run_length=50, num_runs=20, seed=0
    )
    assert bad is not None
    assert "negative" in bad.error
    # Minimal counterexample: a single withdraw.
    assert len(bad.history) == 1
    assert isinstance(bad.history[0], Withdraw)
    # The bad history replays deterministically.
    assert run_history(SimulatedBankAccount(), bad.seed, bad.history) is not None


class SafeBankAccount(SimulatedBankAccount):
    def run_command(self, system, command):
        if isinstance(command, Deposit):
            system.deposit(command.amount)
        elif system.balance - command.amount >= 0:
            system.withdraw(command.amount)
        return system


def test_safe_system_passes():
    assert simulate(SafeBankAccount(), run_length=100, num_runs=50, seed=0) is None


# -- Die Hard puzzle: 3-gallon and 5-gallon jugs; reach exactly 4 -----------


@dataclasses.dataclass(frozen=True)
class Fill:
    jug: int  # 0 = small(3), 1 = big(5)


@dataclasses.dataclass(frozen=True)
class Empty:
    jug: int


@dataclasses.dataclass(frozen=True)
class Pour:
    src: int
    dst: int


class SimulatedDieHard(SimulatedSystem):
    CAP = (3, 5)

    def new_system(self, seed):
        return [0, 0]

    def get_state(self, system):
        return tuple(system)

    def generate_command(self, system, rng):
        choices = [Fill(0), Fill(1), Empty(0), Empty(1), Pour(0, 1), Pour(1, 0)]
        return rng.choice(choices)

    def run_command(self, system, command):
        if isinstance(command, Fill):
            system[command.jug] = self.CAP[command.jug]
        elif isinstance(command, Empty):
            system[command.jug] = 0
        else:
            amount = min(system[command.src], self.CAP[command.dst] - system[command.dst])
            system[command.src] -= amount
            system[command.dst] += amount
        return system

    def state_invariant(self, state):
        # "Invariant": big jug never holds exactly 4 gallons. The simulator
        # violating this = solving the puzzle.
        if state[1] == 4:
            return "big jug holds 4 gallons: puzzle solved"
        return None


def test_simulator_solves_diehard():
    bad = simulate_and_minimize(
        SimulatedDieHard(), run_length=30, num_runs=200, seed=0
    )
    assert bad is not None
    assert "solved" in bad.error
    # The optimal solution takes 6 steps; shrinking should get close.
    assert len(bad.history) <= 8
    # Replaying the minimized history ends with big jug at 4.
    sim = SimulatedDieHard()
    system = sim.new_system(bad.seed)
    for cmd in bad.history:
        system = sim.run_command(system, cmd)
    assert system[1] == 4


def test_minimize_requires_bad_history():
    import pytest

    with pytest.raises(ValueError):
        minimize(SafeBankAccount(), 0, [Deposit(5)])


def test_step_and_history_invariants():
    class Monotone(SimulatedSystem):
        def new_system(self, seed):
            return [0]

        def get_state(self, system):
            return system[0]

        def generate_command(self, system, rng):
            return rng.choice([1, -1])

        def run_command(self, system, command):
            system[0] += command
            return system

        def step_invariant(self, old, new):
            if new < old:
                return f"decreased from {old} to {new}"
            return None

        def history_invariant(self, history):
            if len(history) > 3 and history[-1] == 0:
                return "returned to zero late"
            return None

    bad = simulate(Monotone(), run_length=20, num_runs=5, seed=0)
    assert bad is not None
    assert "decreased" in bad.error or "zero" in bad.error
