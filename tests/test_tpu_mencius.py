"""Batched Mencius tests: invariants under load skew, the skip mechanism
(a permanently slow leader must NOT stall the global log once skips kick
in), and the global execution watermark formula."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.tpu.mencius_batched import (
    NOOP_VALUE,
    BatchedMenciusConfig,
    check_invariants,
    init_state,
    run_ticks,
)


def run(cfg, ticks, seed=0):
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), ticks, jax.random.PRNGKey(seed)
    )
    jax.block_until_ready(state)
    inv = {k: bool(v) for k, v in check_invariants(cfg, state, t).items()}
    assert all(inv.values()), inv
    return state


def test_balanced_load_executes_globally():
    cfg = BatchedMenciusConfig(
        f=1, num_leaders=4, window=32, slots_per_tick=4,
        lat_min=1, lat_max=2,
    )
    state = run(cfg, 100)
    assert int(state.committed) > 1000
    # Balanced stripes: the global prefix tracks total commits closely.
    assert int(state.executed_global) > 800
    assert int(state.skips) == 0  # nobody lags enough to skip
    # No skips -> every chosen slot is a real command.
    assert int(state.committed_real) == int(state.committed)


def test_skew_triggers_skips_and_global_progress():
    """idle_rate makes stripes advance unevenly; skips must fill the
    slow stripes so the GLOBAL watermark keeps advancing."""
    cfg = BatchedMenciusConfig(
        f=1, num_leaders=4, window=64, slots_per_tick=4,
        idle_rate=0.6, skip_threshold=8, lat_min=1, lat_max=2,
    )
    state = run(cfg, 200, seed=3)
    assert int(state.skips) > 0, "no skips despite 60% idle ticks"
    # The global log advances far beyond what the slowest unskipped
    # stripe would allow.
    assert int(state.executed_global) > 1000
    # Noop fills are chosen slots but NOT real commands: the headline
    # command rate must exclude them (advisor round 2).
    assert 0 < int(state.committed_real) < int(state.committed)


def test_no_skips_stalls_global_log():
    """The control: a permanently unloaded stripe pins the global
    watermark at ZERO when skips are disabled — the exact problem
    Mencius's high-watermark skips exist to solve — and skips restore
    full global progress."""
    base = dict(
        f=1, num_leaders=4, window=64, slots_per_tick=4,
        num_idle_leaders=1, lat_min=1, lat_max=2,
    )
    without = run(
        BatchedMenciusConfig(skip_threshold=10**6, **base), 200, seed=5
    )
    assert int(without.executed_global) == 0  # stripe 0 never commits
    with_skips = run(
        BatchedMenciusConfig(skip_threshold=8, **base), 200, seed=5
    )
    assert int(with_skips.executed_global) > 1000
    assert int(with_skips.skips) > 0


def test_global_watermark_formula():
    """executed_global == min over stripes of (c_l * L + l)."""
    cfg = BatchedMenciusConfig(
        f=1, num_leaders=3, window=16, slots_per_tick=2,
        idle_rate=0.3, skip_threshold=6, lat_min=1, lat_max=3,
    )
    state = run(cfg, 120, seed=7)
    L = cfg.num_leaders
    prefix = np.asarray(state.committed_prefix)
    expect = min(int(prefix[l]) * L + l for l in range(L))
    assert int(state.executed_global) == expect


def test_closed_workload_drains():
    cfg = BatchedMenciusConfig(
        f=1, num_leaders=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=1, max_slots_per_leader=10,
    )
    state = run(cfg, 60)
    # All 40 slots chosen and the whole global log executable.
    assert int(state.committed) == 40
    assert int(state.executed_global) == 40
