"""The kernel registry contract (ops/registry.py): policy resolution,
autotune-table lookup, and — load-bearing — BIT-IDENTITY of every
registry-dispatched backend between kernel (interpret) and reference
modes: the same run replayed on 3 seeds under
``KernelPolicy(mode="interpret")`` and ``KernelPolicy.reference()``
must produce sha256-identical protocol state arrays."""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import craq_batched, mencius_batched, multipaxos_batched


def _hash(state, fields):
    m = hashlib.sha256()
    for f in fields:
        m.update(np.asarray(jax.device_get(getattr(state, f))).tobytes())
    return m.hexdigest()[:16]


# ---------------------------------------------------------------------------
# KernelPolicy / registry semantics
# ---------------------------------------------------------------------------


def test_policy_of_folds_legacy_use_pallas():
    cfg = multipaxos_batched.BatchedMultiPaxosConfig(
        use_pallas=True, pallas_block_g=128
    )
    pol = registry.policy_of(cfg)
    assert pol.mode == "on" and pol.block == 128
    # Without the legacy flag the config's own policy wins.
    cfg2 = multipaxos_batched.BatchedMultiPaxosConfig(
        kernels=KernelPolicy(mode="interpret", block=64)
    )
    pol2 = registry.policy_of(cfg2)
    assert pol2.mode == "interpret" and pol2.block == 64


def test_resolve_mode_on_cpu():
    mk = multipaxos_batched.BatchedMultiPaxosConfig
    # auto -> reference off-TPU; on -> interpret; reference/off -> reference.
    assert (
        registry.resolve_mode("multipaxos_vote_quorum", mk()) == "reference"
    )
    assert (
        registry.resolve_mode("multipaxos_vote_quorum", mk(use_pallas=True))
        == "interpret"
    )
    assert (
        registry.resolve_mode(
            "multipaxos_vote_quorum", mk(kernels=KernelPolicy(mode="interpret"))
        )
        == "interpret"
    )
    assert (
        registry.resolve_mode(
            "multipaxos_vote_quorum", mk(kernels=KernelPolicy.reference())
        )
        == "reference"
    )
    # Per-plane disable forces the reference even under mode="interpret".
    cfg = mk(
        kernels=KernelPolicy(
            mode="interpret", disable=("multipaxos_vote_quorum",)
        )
    )
    assert registry.resolve_mode("multipaxos_vote_quorum", cfg) == "reference"
    assert registry.resolve_mode("multipaxos_dispatch", cfg) == "interpret"


def test_policy_validation_rejects_bad_values():
    with pytest.raises(AssertionError):
        multipaxos_batched.BatchedMultiPaxosConfig(
            kernels=KernelPolicy(mode="sometimes")
        )
    with pytest.raises(AssertionError):
        multipaxos_batched.BatchedMultiPaxosConfig(
            kernels=KernelPolicy(disable=("no_such_plane",))
        )


def test_registry_coverage_names_all_backends():
    cov = registry.coverage()
    assert set(cov["multipaxos"]) == {
        "multipaxos_vote_quorum",
        "multipaxos_p1_promise",
        "multipaxos_dispatch",
    }
    assert cov["mencius"] == ("mencius_vote",)
    assert cov["craq"] == ("craq_chain",)


def test_block_for_exact_nearest_and_default():
    name = "multipaxos_vote_quorum"
    table = registry._table()
    exact_key = (3, 3334, 64)  # checked-in flagship entry
    assert registry.table_key(name, exact_key) in table
    assert registry.block_for(name, exact_key) == table[
        registry.table_key(name, exact_key)
    ]
    # Nearest-G fallback: an unseen G resolves to some recorded entry,
    # never to a crash; an unseen plane shape falls back to the default.
    got = registry.block_for(name, (3, 3000, 64))
    assert got > 0
    assert (
        registry.block_for("craq_chain", (7, 7, 7, 7))
        == registry.PLANES["craq_chain"].default_block
    )


def test_write_table_merges(tmp_path):
    path = str(tmp_path / "autotune.json")
    payload = registry.write_table({"x|1|2|3": 128}, path=path)
    assert payload["blocks"]["x|1|2|3"] == 128
    # Existing (checked-in) entries survive the merge.
    assert any(k.startswith("multipaxos_vote_quorum|") for k in payload["blocks"])
    registry._table.cache_clear()


# ---------------------------------------------------------------------------
# Mirror constants: ops must not import the backends, so their slot/value
# codes are mirrored — pin the mirrors to the backends' truth.
# ---------------------------------------------------------------------------


def test_ops_constant_mirrors_match_backends():
    from frankenpaxos_tpu.ops import craq as ops_craq
    from frankenpaxos_tpu.ops import multipaxos as ops_mp
    from frankenpaxos_tpu.tpu.common import INF

    assert ops_mp.EMPTY == multipaxos_batched.EMPTY
    assert ops_mp.PROPOSED == multipaxos_batched.PROPOSED
    assert ops_mp.CHOSEN == multipaxos_batched.CHOSEN
    assert ops_mp.NO_VALUE == multipaxos_batched.NO_VALUE
    assert ops_mp.NOOP_VALUE == multipaxos_batched.NOOP_VALUE
    assert ops_mp.INF_I == int(INF)
    assert ops_craq.W_EMPTY == craq_batched.W_EMPTY
    assert ops_craq.W_DOWN == craq_batched.W_DOWN
    assert ops_craq.W_UP == craq_batched.W_UP
    assert ops_craq.INF_I == int(INF)


# ---------------------------------------------------------------------------
# Interpret-vs-reference bit-identity per dispatched backend (3 seeds,
# sha256 over the protocol state arrays)
# ---------------------------------------------------------------------------

MP_FIELDS = (
    "status", "slot_value", "chosen_round", "chosen_value", "head",
    "next_slot", "acc_round", "vote_round", "vote_value", "p2a_arrival",
    "p2b_arrival", "committed", "retired", "lat_sum", "lat_hist",
)
MENCIUS_FIELDS = (
    "status", "slot_value", "head", "next_slot", "committed_prefix",
    "voted", "p2a_arrival", "p2b_arrival", "committed", "skips",
)
CRAQ_FIELDS = (
    "w_status", "w_node", "w_arrival", "w_version", "node_dirty",
    "node_version", "writes_done", "reads_done", "r_status",
)


def _run_both(mod, make_cfg, ticks, seed, fields):
    hashes = {}
    for pol in (KernelPolicy(mode="interpret"), KernelPolicy.reference()):
        cfg = make_cfg(pol)
        st, _ = mod.run_ticks(
            cfg, mod.init_state(cfg), jnp.zeros((), jnp.int32), ticks,
            jax.random.PRNGKey(seed),
        )
        hashes[pol.mode] = _hash(st, fields)
    return hashes


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_multipaxos_interpret_matches_reference(seed):
    mp = multipaxos_batched

    def make_cfg(pol):
        # Elections + drops exercise all three planes (vote/quorum,
        # p1 repair, dispatch) through the registry.
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=3, window=8, slots_per_tick=2, lat_min=1,
            lat_max=3, drop_rate=0.1, retry_timeout=6,
            device_elections=True, fail_rate=0.02, heartbeat_timeout=4,
            kernels=pol,
        )

    hashes = _run_both(mp, make_cfg, 30, seed, MP_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_mencius_interpret_matches_reference(seed):
    me = mencius_batched

    def make_cfg(pol):
        return me.BatchedMenciusConfig(
            f=1, num_leaders=3, window=8, slots_per_tick=2, idle_rate=0.2,
            drop_rate=0.1, retry_timeout=6, kernels=pol,
        )

    hashes = _run_both(me, make_cfg, 30, seed, MENCIUS_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_craq_interpret_matches_reference(seed):
    cr = craq_batched

    def make_cfg(pol):
        return cr.BatchedCraqConfig(
            num_chains=3, chain_len=3, num_keys=4, window=8,
            writes_per_tick=2, reads_per_tick=2, read_window=8,
            kernels=pol,
        )

    hashes = _run_both(cr, make_cfg, 30, seed, CRAQ_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


def test_craq_partitioned_plan_routes_to_reference():
    """A partition plan must not reach the kernel (it does not model
    heal deferral): the registry reports reference mode, and the run
    matches the same config in explicit reference mode bit for bit."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    cr = craq_batched
    plan = FaultPlan(
        partition=(0, 0, 1), partition_start=5, partition_heal=15
    )

    def make_cfg(pol):
        return cr.BatchedCraqConfig(
            num_chains=3, chain_len=3, num_keys=4, window=8,
            writes_per_tick=2, reads_per_tick=0, read_window=8,
            faults=plan, kernels=pol,
        )

    assert (
        registry.resolve_mode("craq_chain", make_cfg(KernelPolicy("interpret")))
        == "reference"
    )
    hashes = _run_both(cr, make_cfg, 25, 0, CRAQ_FIELDS)
    assert hashes["interpret"] == hashes["reference"]
