"""The kernel registry contract (ops/registry.py): policy resolution,
autotune-table lookup, and — load-bearing — BIT-IDENTITY of every
registry-dispatched backend between kernel (interpret) and reference
modes: the same run replayed on 3 seeds under
``KernelPolicy(mode="interpret")`` and ``KernelPolicy.reference()``
must produce sha256-identical protocol state arrays."""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import (
    bpaxos_batched,
    compartmentalized_batched,
    craq_batched,
    fastmultipaxos_batched,
    horizontal_batched,
    mencius_batched,
    multipaxos_batched,
    scalog_batched,
)


def _hash(state, fields):
    m = hashlib.sha256()
    for f in fields:
        m.update(np.asarray(jax.device_get(getattr(state, f))).tobytes())
    return m.hexdigest()[:16]


# ---------------------------------------------------------------------------
# KernelPolicy / registry semantics
# ---------------------------------------------------------------------------


def test_policy_of_folds_legacy_use_pallas():
    cfg = multipaxos_batched.BatchedMultiPaxosConfig(
        use_pallas=True, pallas_block_g=128
    )
    pol = registry.policy_of(cfg)
    assert pol.mode == "on" and pol.block == 128
    # Without the legacy flag the config's own policy wins.
    cfg2 = multipaxos_batched.BatchedMultiPaxosConfig(
        kernels=KernelPolicy(mode="interpret", block=64)
    )
    pol2 = registry.policy_of(cfg2)
    assert pol2.mode == "interpret" and pol2.block == 64


def test_resolve_mode_on_cpu():
    mk = multipaxos_batched.BatchedMultiPaxosConfig
    # auto -> reference off-TPU; on -> interpret; reference/off -> reference.
    assert (
        registry.resolve_mode("multipaxos_vote_quorum", mk()) == "reference"
    )
    assert (
        registry.resolve_mode("multipaxos_vote_quorum", mk(use_pallas=True))
        == "interpret"
    )
    assert (
        registry.resolve_mode(
            "multipaxos_vote_quorum", mk(kernels=KernelPolicy(mode="interpret"))
        )
        == "interpret"
    )
    assert (
        registry.resolve_mode(
            "multipaxos_vote_quorum", mk(kernels=KernelPolicy.reference())
        )
        == "reference"
    )
    # Per-plane disable forces the reference even under mode="interpret".
    cfg = mk(
        kernels=KernelPolicy(
            mode="interpret", disable=("multipaxos_vote_quorum",)
        )
    )
    assert registry.resolve_mode("multipaxos_vote_quorum", cfg) == "reference"
    assert registry.resolve_mode("multipaxos_dispatch", cfg) == "interpret"


def test_policy_validation_rejects_bad_values():
    with pytest.raises(AssertionError):
        multipaxos_batched.BatchedMultiPaxosConfig(
            kernels=KernelPolicy(mode="sometimes")
        )
    with pytest.raises(AssertionError):
        multipaxos_batched.BatchedMultiPaxosConfig(
            kernels=KernelPolicy(disable=("no_such_plane",))
        )


def test_registry_coverage_names_all_backends():
    cov = registry.coverage()
    assert set(cov["multipaxos"]) == {
        "multipaxos_vote_quorum",
        "multipaxos_p1_promise",
        "multipaxos_dispatch",
        "multipaxos_fused_tick",
    }
    assert cov["mencius"] == ("mencius_vote",)
    assert cov["craq"] == ("craq_chain",)
    assert cov["fastmultipaxos"] == ("fastmultipaxos_vote",)
    assert cov["horizontal"] == ("horizontal_vote",)
    assert cov["scalog"] == ("scalog_cut_commit",)
    assert cov["compartmentalized"] == ("compartmentalized_grid_vote",)
    assert cov["bpaxos"] == ("depgraph_execute",)


def test_block_for_exact_model_and_legacy():
    name = "multipaxos_vote_quorum"
    table = registry._table()
    exact_key = (3, 3334, 64)  # checked-in flagship entry
    assert registry.table_key(name, exact_key) in table
    assert registry.block_for(name, exact_key) == table[
        registry.table_key(name, exact_key)
    ]
    # Unseen shape: the cost model ranks the autotune candidates
    # (ops/costmodel.py) — never a crash, always a sweepable block; a
    # key arity the model's spec tables cannot evaluate degrades to
    # the plane default (the dispatch path must never raise).
    from frankenpaxos_tpu.ops import costmodel

    got = registry.block_for(name, (3, 3000, 64))
    assert got in costmodel.CANDIDATE_BLOCKS
    assert got == costmodel.model_block(
        name, (3, 3000, 64), costmodel.params_for_backend()
    )
    assert (
        registry.block_for("craq_chain", (7, 7, 7, 7))
        == registry.PLANES["craq_chain"].default_block
    )
    # The legacy nearest-batch-extent heuristic survives as
    # nearest_block() (the baseline the model dominates in
    # tests/test_costmodel.py): same-arity keys resolve to a recorded
    # entry, alien arities to None.
    assert registry.nearest_block(name, (3, 3000, 64)) in {
        v for k, v in table.items() if k.startswith(name + "|")
    }
    assert registry.nearest_block("craq_chain", (7, 7, 7, 7)) is None


def test_per_device_autotune_resolution():
    """The kernels x mesh layer keys the block lookup on the PER-DEVICE
    shape (G/D): with no exact entry at the local G, the model-ranked
    fallback resolves deterministically to a sweepable candidate — so
    shard-local block picks never crash and never drift between
    devices (every device computes the same lookup)."""
    from frankenpaxos_tpu.ops import costmodel

    name = "multipaxos_vote_quorum"
    table = registry._table()
    for n_dev in (2, 4, 8):
        per_dev = (3, 3334 // n_dev, 64)
        assert registry.table_key(name, per_dev) not in table
        got = registry.block_for(name, per_dev)
        assert got in costmodel.CANDIDATE_BLOCKS
        assert registry.block_for(name, per_dev) == got  # deterministic


def test_shard_specs_cover_reference_signatures():
    """Every plane of a backend in the sharding registry declares a
    ShardSpec whose arg_axes arity matches the reference twin's
    positional signature — the structural contract the shard_map
    lowering relies on (a miscounted spec would mis-partition)."""
    import inspect

    from frankenpaxos_tpu.parallel import sharding as sh

    sharded_backends = {
        s.planes_backend for s in sh.SHARDINGS.values() if s.planes_backend
    }
    checked = 0
    for name, plane in registry.PLANES.items():
        if plane.backend not in sharded_backends:
            continue
        assert plane.shard is not None, f"{name} lost its ShardSpec"
        n_params = sum(
            1
            for p in inspect.signature(plane.reference).parameters.values()
            if p.kind is not inspect.Parameter.KEYWORD_ONLY  # statics
        )
        assert len(plane.shard.arg_axes) == n_params, name
        assert len(plane.shard.out_axes) >= 1, name
        checked += 1
    assert checked >= 5  # 4 multipaxos planes + the grid-vote plane


def test_sharded_dispatch_keys_per_device_shape(monkeypatch):
    """Tracing a tick under shard_lowering consults the autotune table
    with the batch axis DIVIDED by the mesh size (the per-device shard
    the kernel actually sees)."""
    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.parallel import sharding as sh

    seen = []
    real = registry.block_for

    def spy(name, key):
        seen.append((name, tuple(key)))
        return real(name, key)

    monkeypatch.setattr(registry, "block_for", spy)
    mesh = sh.make_mesh(jax.devices())
    n_dev = mesh.devices.size
    mp = multipaxos_batched
    cfg = dataclasses.replace(
        mp.analysis_config(), num_groups=8,
        kernels=KernelPolicy(mode="interpret"),
    )
    state = mp.init_state(cfg)

    def run(s, t, k):
        with registry.shard_lowering(mesh, sh.GROUP_AXIS):
            return mp.tick(cfg, s, t, k)

    jax.make_jaxpr(run)(
        state, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0)
    )
    assert ("multipaxos_fused_tick", (3, 8 // n_dev, 16)) in seen


def test_write_table_merges(tmp_path):
    path = str(tmp_path / "autotune.json")
    payload = registry.write_table({"x|1|2|3": 128}, path=path)
    assert payload["blocks"]["x|1|2|3"] == 128
    # Existing (checked-in) entries survive the merge.
    assert any(k.startswith("multipaxos_vote_quorum|") for k in payload["blocks"])
    registry._table.cache_clear()


# ---------------------------------------------------------------------------
# Mirror constants: ops must not import the backends, so their slot/value
# codes are mirrored — pin the mirrors to the backends' truth.
# ---------------------------------------------------------------------------


def test_ops_constant_mirrors_match_backends():
    from frankenpaxos_tpu.ops import compartmentalized as ops_cz
    from frankenpaxos_tpu.ops import craq as ops_craq
    from frankenpaxos_tpu.ops import fastmultipaxos as ops_fmp
    from frankenpaxos_tpu.ops import horizontal as ops_hz
    from frankenpaxos_tpu.ops import multipaxos as ops_mp
    from frankenpaxos_tpu.tpu.common import INF

    assert ops_mp.EMPTY == multipaxos_batched.EMPTY
    assert ops_mp.PROPOSED == multipaxos_batched.PROPOSED
    assert ops_mp.CHOSEN == multipaxos_batched.CHOSEN
    assert ops_mp.NO_VALUE == multipaxos_batched.NO_VALUE
    assert ops_mp.NOOP_VALUE == multipaxos_batched.NOOP_VALUE
    assert ops_mp.AMS_FLOOR == multipaxos_batched.AMS_FLOOR
    assert ops_mp.INF_I == int(INF)
    assert ops_craq.W_EMPTY == craq_batched.W_EMPTY
    assert ops_craq.W_DOWN == craq_batched.W_DOWN
    assert ops_craq.W_UP == craq_batched.W_UP
    assert ops_craq.INF_I == int(INF)
    assert ops_fmp.S_OPEN == fastmultipaxos_batched.S_OPEN
    assert ops_fmp.S_RECOVER == fastmultipaxos_batched.S_RECOVER
    assert ops_fmp.S_CHOSEN == fastmultipaxos_batched.S_CHOSEN
    assert ops_fmp.NO_VALUE == fastmultipaxos_batched.NO_VALUE
    assert ops_fmp.INF_I == int(INF)
    assert ops_hz.EMPTY == horizontal_batched.EMPTY
    assert ops_hz.PROPOSED == horizontal_batched.PROPOSED
    assert ops_hz.CHOSEN == horizontal_batched.CHOSEN
    assert ops_hz.NO_VALUE == horizontal_batched.NO_VALUE
    assert ops_hz.INF_I == int(INF)
    assert ops_cz.EMPTY == compartmentalized_batched.EMPTY
    assert ops_cz.PROPOSED == compartmentalized_batched.PROPOSED
    assert ops_cz.CHOSEN == compartmentalized_batched.CHOSEN


# ---------------------------------------------------------------------------
# Interpret-vs-reference bit-identity per dispatched backend (3 seeds,
# sha256 over the protocol state arrays)
# ---------------------------------------------------------------------------

MP_FIELDS = (
    "status", "slot_value", "chosen_round", "chosen_value", "head",
    "next_slot", "acc_round", "vote_round", "vote_value", "p2a_arrival",
    "p2b_arrival", "committed", "retired", "lat_sum", "lat_hist",
)
MENCIUS_FIELDS = (
    "status", "slot_value", "head", "next_slot", "committed_prefix",
    "voted", "p2a_arrival", "p2b_arrival", "committed", "skips",
)
CRAQ_FIELDS = (
    "w_status", "w_node", "w_arrival", "w_version", "node_dirty",
    "node_version", "writes_done", "reads_done", "r_status",
)


def _run_both(mod, make_cfg, ticks, seed, fields):
    hashes = {}
    for pol in (KernelPolicy(mode="interpret"), KernelPolicy.reference()):
        cfg = make_cfg(pol)
        st, _ = mod.run_ticks(
            cfg, mod.init_state(cfg), jnp.zeros((), jnp.int32), ticks,
            jax.random.PRNGKey(seed),
        )
        hashes[pol.mode] = _hash(st, fields)
    return hashes


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_multipaxos_interpret_matches_reference(seed):
    mp = multipaxos_batched

    def make_cfg(pol):
        # Elections + drops exercise all three planes (vote/quorum,
        # p1 repair, dispatch) through the registry.
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=3, window=8, slots_per_tick=2, lat_min=1,
            lat_max=3, drop_rate=0.1, retry_timeout=6,
            device_elections=True, fail_rate=0.02, heartbeat_timeout=4,
            kernels=pol,
        )

    hashes = _run_both(mp, make_cfg, 30, seed, MP_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_mencius_interpret_matches_reference(seed):
    me = mencius_batched

    def make_cfg(pol):
        return me.BatchedMenciusConfig(
            f=1, num_leaders=3, window=8, slots_per_tick=2, idle_rate=0.2,
            drop_rate=0.1, retry_timeout=6, kernels=pol,
        )

    hashes = _run_both(me, make_cfg, 30, seed, MENCIUS_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_craq_interpret_matches_reference(seed):
    cr = craq_batched

    def make_cfg(pol):
        return cr.BatchedCraqConfig(
            num_chains=3, chain_len=3, num_keys=4, window=8,
            writes_per_tick=2, reads_per_tick=2, read_window=8,
            kernels=pol,
        )

    hashes = _run_both(cr, make_cfg, 30, seed, CRAQ_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


def test_craq_partitioned_plan_rides_the_kernel():
    """Partitioned plans ride the kernel (in-kernel defer-to-heal: the
    side bits enter as statics and hops into cut nodes wait for the
    heal tick): the registry resolves the kernel path, and the run
    matches explicit reference mode bit for bit through the partition
    window AND after the heal."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    cr = craq_batched
    plan = FaultPlan(
        partition=(0, 0, 1), partition_start=5, partition_heal=15
    )

    def make_cfg(pol):
        return cr.BatchedCraqConfig(
            num_chains=3, chain_len=3, num_keys=4, window=8,
            writes_per_tick=2, reads_per_tick=0, read_window=8,
            faults=plan, kernels=pol,
        )

    assert (
        registry.resolve_mode("craq_chain", make_cfg(KernelPolicy("interpret")))
        == "interpret"
    )
    hashes = _run_both(cr, make_cfg, 25, 0, CRAQ_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


def test_craq_never_healing_partition_rides_the_kernel():
    """partition_heal = -1 (never heals): cut hops defer forever (INF)
    in-kernel, still bit-identical to the reference path."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    cr = craq_batched
    plan = FaultPlan(partition=(0, 0, 1), partition_start=3)

    def make_cfg(pol):
        return cr.BatchedCraqConfig(
            num_chains=3, chain_len=3, num_keys=4, window=8,
            writes_per_tick=2, reads_per_tick=0, read_window=8,
            faults=plan, kernels=pol,
        )

    hashes = _run_both(cr, make_cfg, 20, 1, CRAQ_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


# ---------------------------------------------------------------------------
# New backend planes: interpret-vs-reference whole runs (3 seeds)
# ---------------------------------------------------------------------------

FMP_FIELDS = (
    "head", "acc_next", "cmd_seq", "status", "chosen_value",
    "fast_committed", "vote_value", "vote_seen", "rv_value", "rv_voted",
    "cmd_status", "cmd_id", "committed_slots", "fast_chosen",
    "recoveries", "cmds_done", "dups", "safety_violations", "lat_hist",
)
HORIZONTAL_FIELDS = (
    "next_slot", "head", "status", "is_config", "slot_epoch",
    "p2a_arrival", "p2b_arrival", "voted", "vote_epoch", "epoch",
    "boundary", "committed", "executed", "reconfigs_done",
    "bank_violations", "lat_hist",
)
SCALOG_FIELDS = (
    "local_len", "cut_vec", "cut_commit_tick", "cut_snap_tick",
    "next_cut", "committed_cuts", "global_len", "last_committed_cut",
    "lat_sum", "lat_count", "lat_hist",
)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fastmultipaxos_interpret_matches_reference(seed):
    fm = fastmultipaxos_batched

    def make_cfg(pol):
        # Jitter drives slot conflicts, so the fast path, the recovery
        # path, and the classic round all exercise through the plane.
        return fm.BatchedFastMultiPaxosConfig(
            f=1, num_groups=4, window=8, cmd_window=8, cmds_per_tick=2,
            jitter=2, recovery_timeout=10, retry_timeout=12, kernels=pol,
        )

    hashes = _run_both(fm, make_cfg, 30, seed, FMP_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fastmultipaxos_crash_plan_interpret_matches_reference(seed):
    """The newly-kerneled fastmultipaxos_vote plane under CRASHES: the
    proposer crash/revive axis (PR 3 follow-up (b)) gates proposing
    outside the plane, so the kernel path must replay the reference
    bit for bit through dead windows and revival re-broadcasts."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    fm = fastmultipaxos_batched
    plan = FaultPlan(drop_rate=0.05, crash_rate=0.05, revive_rate=0.3)

    def make_cfg(pol):
        return fm.BatchedFastMultiPaxosConfig(
            f=1, num_groups=4, window=8, cmd_window=8, cmds_per_tick=2,
            jitter=2, recovery_timeout=10, retry_timeout=6,
            faults=plan, kernels=pol,
        )

    hashes = _run_both(fm, make_cfg, 40, seed, FMP_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_horizontal_interpret_matches_reference(seed):
    hz = horizontal_batched

    def make_cfg(pol):
        # Periodic reconfiguration exercises both banks and the chunk
        # handover around the vote plane.
        return hz.BatchedHorizontalConfig(
            f=1, num_groups=4, window=16, slots_per_tick=2, alpha=8,
            retry_timeout=8, reconfigure_every=9, kernels=pol,
        )

    hashes = _run_both(hz, make_cfg, 30, seed, HORIZONTAL_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


CZ_FIELDS = (
    "status", "head", "next_slot", "p2a_arrival", "p2b_arrival",
    "rep_arrival", "rep_exec", "last_send", "propose_tick", "committed",
    "batches_committed", "writes_done", "reads_done", "lat_hist",
    "proxy_msgs", "unbat_msgs",
)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_compartmentalized_interpret_matches_reference(seed):
    """The grid-vote plane through a whole faulty run: drops + jitter +
    proxy crashes + a grid-cell partition with a scheduled heal all
    route through the fused kernel (interpret) and replay the pure-jnp
    reference bit for bit."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    cz = compartmentalized_batched
    plan = FaultPlan(
        drop_rate=0.1, jitter=1, crash_rate=0.02, revive_rate=0.2,
        partition=(0, 0, 0, 1), partition_start=5, partition_heal=25,
    )

    def make_cfg(pol):
        return dataclasses.replace(
            cz.analysis_config(faults=plan), kernels=pol
        )

    hashes = _run_both(cz, make_cfg, 30, seed, CZ_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scalog_interpret_matches_reference(seed):
    sc = scalog_batched

    def make_cfg(pol):
        return sc.BatchedScalogConfig(
            num_shards=5, max_inflight_cuts=4, cut_every=2, kernels=pol,
        )

    hashes = _run_both(sc, make_cfg, 30, seed, SCALOG_FIELDS)
    assert hashes["interpret"] == hashes["reference"]


# ---------------------------------------------------------------------------
# The whole-tick megakernel: sha256 bit-identity vs the multi-plane path
# (disable=("multipaxos_fused_tick",) restores the per-plane kernels) and
# vs the pure reference, 3 seeds, with and without faults — full state
# INCLUDING the telemetry ring.
# ---------------------------------------------------------------------------


def _mp_full_state_hash(st):
    m = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(st):
        m.update(np.asarray(jax.device_get(leaf)).tobytes())
    return m.hexdigest()[:16]


def _mega_cfg(pol, faults=None, **kw):
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    base = dict(
        f=1, num_groups=5, window=8, slots_per_tick=2, lat_min=1,
        lat_max=3, drop_rate=0.1, retry_timeout=6,
    )
    base.update(kw)
    return multipaxos_batched.BatchedMultiPaxosConfig(
        **base, faults=faults or FaultPlan.none(), kernels=pol,
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("faulty", [False, True])
def test_megakernel_matches_multiplane_and_reference(seed, faulty):
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    mp = multipaxos_batched
    faults = (
        FaultPlan(
            drop_rate=0.1, dup_rate=0.1, jitter=1, partition=(0, 0, 1),
            partition_start=5, partition_heal=15,
        )
        if faulty
        else None
    )
    policies = {
        "mega": KernelPolicy(mode="interpret"),
        "multiplane": KernelPolicy(
            mode="interpret", disable=("multipaxos_fused_tick",)
        ),
        "reference": KernelPolicy.reference(),
    }
    hashes = {}
    for name, pol in policies.items():
        cfg = _mega_cfg(pol, faults=faults)
        st, _ = mp.run_ticks(
            cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), 30,
            jax.random.PRNGKey(seed),
        )
        assert int(st.committed) > 0
        hashes[name] = _mp_full_state_hash(st)
    assert hashes["mega"] == hashes["multiplane"] == hashes["reference"]


def test_megakernel_resolution_and_age_routing():
    """The fused-tick plane resolves exactly like any other plane, and
    disabling it restores the per-plane dispatch path (both paths are
    live source code — the analysis dispatch-coverage rule sees both)."""
    mk = multipaxos_batched.BatchedMultiPaxosConfig
    assert (
        registry.resolve_mode("multipaxos_fused_tick", mk()) == "reference"
    )
    assert (
        registry.resolve_mode(
            "multipaxos_fused_tick", mk(kernels=KernelPolicy(mode="interpret"))
        )
        == "interpret"
    )
    cfg = mk(
        kernels=KernelPolicy(
            mode="interpret", disable=("multipaxos_fused_tick",)
        )
    )
    assert registry.resolve_mode("multipaxos_fused_tick", cfg) == "reference"
    assert registry.resolve_mode("multipaxos_vote_quorum", cfg) == "interpret"


def test_disabling_a_subsumed_plane_forces_the_multiplane_path():
    """The megakernel subsumes vote_quorum + dispatch: disabling EITHER
    must route the tick off the megakernel so the disable knob's
    reference-regardless-of-mode contract holds for the sub-plane (the
    traced tick then carries exactly one pallas_call — the remaining
    per-plane kernel — instead of the fused one running both halves)."""
    from frankenpaxos_tpu.analysis import rules_trace

    mk = multipaxos_batched.BatchedMultiPaxosConfig
    for disabled in ("multipaxos_dispatch", "multipaxos_vote_quorum"):
        cfg = mk(
            num_groups=8, window=16,
            kernels=KernelPolicy(mode="interpret", disable=(disabled,)),
        )
        eqns = rules_trace._tick_eqns("multipaxos", cfg)
        assert rules_trace._count_pallas_calls(eqns) == 1, disabled


def test_megakernel_with_elections_and_reads(seed=1):
    """Feature axes that re-route the megakernel's aging (elections:
    repairs write into pre-aged clocks, so the kernel runs age=False)
    and consume its max_ord output (reads): still bit-identical."""
    mp = multipaxos_batched
    kw = dict(
        device_elections=True, fail_rate=0.02, heartbeat_timeout=4,
        read_rate=2, read_window=10, num_groups=4,
    )
    hashes = {}
    for name, pol in (
        ("mega", KernelPolicy(mode="interpret")),
        ("reference", KernelPolicy.reference()),
    ):
        cfg = _mega_cfg(pol, **kw)
        st, _ = mp.run_ticks(
            cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), 30,
            jax.random.PRNGKey(seed),
        )
        hashes[name] = _mp_full_state_hash(st)
    assert hashes["mega"] == hashes["reference"]


# ---------------------------------------------------------------------------
# BPaxos: the depgraph_execute plane through the registry (3 seeds,
# faults engaged)
# ---------------------------------------------------------------------------

BPAXOS_FIELDS = (
    "next_cmd", "gc_head", "head_r", "proposed", "propose_tick",
    "commit_tick", "committed", "rep_commit_tick", "adj",
    "committed_total", "executed_total", "retired_total", "coexecuted",
    "lat_sum", "lat_hist",
)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bpaxos_interpret_matches_reference(seed):
    """The batched dependency-graph closure routed through the fused
    kernel (interpret mode) equals the reference path bit for bit over
    whole faulty runs — drops + jitter stretch the commit round and a
    healing leader partition stalls dependency chains, so the closure
    sees stalled, cyclic, and bursty graphs."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    bp = bpaxos_batched
    plan = FaultPlan(
        drop_rate=0.05, jitter=2,
        partition=(0, 0, 1), partition_start=10, partition_heal=25,
    )

    def make_cfg(pol):
        return bp.BatchedBPaxosConfig(
            num_leaders=3, window=16, cmds_per_tick=2,
            conflict_rate=0.375, num_replicas=4, faults=plan,
            kernels=pol,
        )

    assert (
        registry.resolve_mode(
            "depgraph_execute", make_cfg(KernelPolicy("interpret"))
        )
        == "interpret"
    )
    hashes = _run_both(bp, make_cfg, 40, seed, BPAXOS_FIELDS)
    assert hashes["interpret"] == hashes["reference"]
