"""The elastic-capacity subsystem (tpu/elastic.py +
monitoring/autoscaler.py): pre-allocated padded role planes behind
traced membership counts, and the SLO-driven policy ladder that grows
them under duress instead of only shedding load.

The load-bearing guarantees, in order:

  * A resize-free run with an ACTIVE ElasticPlan (every role at its
    initial count) is bit-identical to the ``ElasticPlan.none()`` twin
    (3 seeds, both backends): threading the padded planes costs
    default runs nothing, so elasticity is free until used.
  * The autoscaler ladder fires in ORDER: alarm -> scale-up of the
    feedforward bottleneck role -> admission clamp only once every
    role sits at padded capacity; on recovery the clamp releases
    FIRST, and capacity shrinks only after a sustained in-SLO trough.
  * Resizing is recompile-free at the serve layer: the resize verb
    edits traced state, the jit cache stays flat, invariants (books,
    conservation) hold across every generation.
  * The autoscaler's full decision state round-trips through
    ``to_state``/``restore_state`` — a restored engine replays the
    uninterrupted twin's decisions bit-exactly.
  * Fleet elasticity (``set_active_instances``) redistributes the
    total offered load over the first k instances through the traced
    rate vector — same executable, deactivated tail, capacity markers
    recorded.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.harness.serve import ServeConfig, ServeLoop
from frankenpaxos_tpu.monitoring.autoscaler import (
    Autoscaler, AutoscalerPolicy,
)
from frankenpaxos_tpu.tpu import compartmentalized_batched as cz
from frankenpaxos_tpu.tpu import elastic as el_mod
from frankenpaxos_tpu.tpu import multipaxos_batched as mp
from frankenpaxos_tpu.tpu.elastic import ElasticPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan


def _hash(state, fields):
    m = hashlib.sha256()
    for f in fields:
        m.update(np.asarray(jax.device_get(getattr(state, f))).tobytes())
    return m.hexdigest()[:16]


def _run(mod, cfg, ticks, seed, state=None, t=None):
    state = mod.init_state(cfg) if state is None else state
    t = jnp.zeros((), jnp.int32) if t is None else t
    return mod.run_ticks(cfg, state, t, ticks, jax.random.PRNGKey(seed))


def _assert_invariants(mod, cfg, state, t):
    bad = {
        k: bool(v)
        for k, v in mod.check_invariants(cfg, state, t).items()
        if not bool(v)
    }
    assert not bad, bad


# ---------------------------------------------------------------------------
# Resize-free bit-identity: an active plan at full initial counts IS
# the none() program (3 seeds, both backends).
# ---------------------------------------------------------------------------

_OPEN_LOOP = WorkloadPlan(arrival="constant", rate=2.0)

_MP_FIELDS = ("status", "slot_value", "chosen_round", "head",
              "next_slot", "acc_round", "vote_round", "vote_value")
_CZ_FIELDS = ("status", "head", "next_slot", "rep_exec",
              "p2b_arrival", "rd_bound")


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_resize_free_bit_identical_multipaxos(seed):
    el = mp.analysis_config(
        elastic=ElasticPlan(roles=(("groups", 4, 1),))
    )
    none = mp.analysis_config(workload=_OPEN_LOOP)
    assert el.workload == none.workload  # the open-loop substitution
    st_el, _ = _run(mp, el, 120, seed)
    st_none, _ = _run(mp, none, 120, seed)
    assert (int(st_el.committed), int(st_el.retired),
            _hash(st_el, _MP_FIELDS)) == (
        int(st_none.committed), int(st_none.retired),
        _hash(st_none, _MP_FIELDS))
    # none() carries structurally EMPTY elastic state.
    assert all(
        leaf.size == 0
        for leaf in jax.tree_util.tree_leaves(st_none.elastic)
    )
    assert int(st_el.elastic.gen) == 0  # resize-free: generation 0


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_resize_free_bit_identical_compartmentalized(seed):
    el = cz.analysis_config(
        elastic=ElasticPlan(roles=(
            ("proxies", 4, 1), ("batchers", 2, 1),
            ("unbatchers", 2, 1), ("replicas", 3, 1),
        ))
    )
    none = cz.analysis_config(workload=el.workload)
    st_el, _ = _run(cz, el, 120, seed)
    st_none, _ = _run(cz, none, 120, seed)
    assert (int(st_el.committed), int(st_el.retired),
            _hash(st_el, _CZ_FIELDS)) == (
        int(st_none.committed), int(st_none.retired),
        _hash(st_none, _CZ_FIELDS))
    assert all(
        leaf.size == 0
        for leaf in jax.tree_util.tree_leaves(st_none.elastic)
    )


# ---------------------------------------------------------------------------
# The ladder, at the policy layer: exact ordering over synthetic SLO
# statuses.
# ---------------------------------------------------------------------------


def _status(alarm, p99, scale=1.0, shed_breach=False):
    return {
        "p99": p99, "p99_target": 10.0, "p99_breach": p99 > 10.0,
        "shed_rate": 0.0, "shed_breach": shed_breach, "alarm": alarm,
        "fired": False, "cleared": False, "scale": scale,
    }


def test_ladder_order_scale_up_then_clamp_then_release_then_shrink():
    asc = Autoscaler(
        AutoscalerPolicy(cooldown_drains=0, trough_after=2),
        {"groups": (3, 1)}, initial={"groups": 1},
    )
    # Duress: capacity first, one step per drain.
    d = asc.decide(_status(True, 40.0, scale=0.9))
    assert d["actions"] == [{"role": "groups", "from": 1, "to": 2}]
    assert not d["clamp_engaged"] and d["effective_scale"] == 1.0
    d = asc.decide(_status(True, 40.0, scale=0.8))
    assert d["actions"] == [{"role": "groups", "from": 2, "to": 3}]
    # At padded capacity: ONLY now may the clamp bind, applying the
    # decay the SLO engine accumulated while capacity was tried first.
    d = asc.decide(_status(True, 40.0, scale=0.7))
    assert not d["actions"] and d["clamp_engaged"]
    assert d["effective_scale"] == pytest.approx(0.7)
    d = asc.decide(_status(True, 40.0, scale=0.6))  # latched, no re-fire
    assert d["clamp_engaged"] and asc.clamp_engagements == 1
    # Recovery: release FIRST (no shrink on the same drain).
    d = asc.decide(_status(False, 4.0, scale=0.6))
    assert not d["actions"] and not d["clamp_engaged"]
    assert d["effective_scale"] == 1.0
    # Trough: two consecutive deep drains before the first shrink.
    d = asc.decide(_status(False, 4.0))
    assert not d["actions"]
    d = asc.decide(_status(False, 4.0))
    assert d["actions"] == [{"role": "groups", "from": 3, "to": 2}]
    d = asc.decide(_status(False, 4.0))
    assert d["actions"] == [{"role": "groups", "from": 2, "to": 1}]
    d = asc.decide(_status(False, 4.0))  # at floor: nothing to give
    assert not d["actions"]
    kinds = [e["kind"] for e in asc.events]
    assert kinds == ["scale_up", "scale_up", "clamp_engage",
                     "clamp_release", "scale_down", "scale_down"]
    assert (asc.scale_up_events, asc.scale_down_events,
            asc.clamp_engagements, asc.clamp_releases) == (2, 2, 1, 1)
    # Every resize event carries the costmodel feedforward blob.
    for e in asc.events:
        if e["kind"] in ("scale_up", "scale_down"):
            assert "bottleneck_role" in e["feedforward"]


def test_ladder_shallow_lull_and_shed_breach_reset_the_trough():
    asc = Autoscaler(
        AutoscalerPolicy(cooldown_drains=0, trough_after=2),
        {"groups": (3, 1)}, initial={"groups": 2},
    )
    asc.decide(_status(False, 4.0))  # deep: streak 1
    asc.decide(_status(False, 9.0))  # in SLO but SHALLOW: reset
    asc.decide(_status(False, 4.0))
    d = asc.decide(_status(False, 4.0, shed_breach=True))  # reset again
    assert not d["actions"]
    asc.decide(_status(False, 4.0))
    d = asc.decide(_status(False, 4.0))
    assert d["actions"] == [{"role": "groups", "from": 2, "to": 1}]


def test_cooldown_spaces_actions():
    asc = Autoscaler(
        AutoscalerPolicy(cooldown_drains=2, trough_after=1),
        {"groups": (4, 1)}, initial={"groups": 1},
    )
    ups = sum(
        len(asc.decide(_status(True, 40.0, scale=0.9))["actions"])
        for _ in range(5)
    )
    assert ups == 2  # drains 1 and 4 act; 2, 3, 5 cool down


def test_feedforward_picks_the_bottleneck_role():
    """The grow pick is the lowest aggregate ceiling with headroom —
    with batchers the scarce role (HT-Paxos: the dissemination roles
    saturate first), proxies never grow first."""
    asc = Autoscaler(
        AutoscalerPolicy(cooldown_drains=0),
        {"proxies": (4, 1), "batchers": (2, 1)},
        initial={"proxies": 4, "batchers": 1},
    )
    d = asc.decide(_status(True, 40.0))
    assert d["actions"][0]["role"] == "batchers"
    # Shrink releases the MOST over-provisioned (highest ceiling).
    asc2 = Autoscaler(
        AutoscalerPolicy(cooldown_drains=0, trough_after=1),
        {"proxies": (4, 1), "batchers": (2, 1)},
        initial={"proxies": 4, "batchers": 1},
    )
    d = asc2.decide(_status(False, 1.0))
    assert d["actions"][0]["role"] == "proxies"


def _envelope_payload(ratios):
    return {
        "captures": {
            "kernel_microbench_rX.json": [
                {"plane": f"p{i}", "ratio": r}
                for i, r in enumerate(ratios)
            ]
        }
    }


def test_confidence_weighted_step_scales_the_up_stride():
    """A tight capture envelope earns multi-instance scale-up strides
    (``max_step`` x ``costmodel.envelope_confidence``); a wide or
    missing record decays back to single probes; scale-down always
    gives back one ``step`` regardless."""
    pol = AutoscalerPolicy(
        cooldown_drains=0, trough_after=1, max_step=4
    )
    tight = _envelope_payload([1.0, 1.05, 0.98])
    asc = Autoscaler(
        pol, {"groups": (9, 1)}, initial={"groups": 1}, envelope=tight
    )
    conf = asc.feedforward_confidence
    assert conf["samples"] == 3 and conf["confidence"] > 0.9
    assert asc._up_step() == 4
    d = asc.decide(_status(True, 40.0))
    assert d["actions"] == [{"role": "groups", "from": 1, "to": 5}]
    # The feedforward blob carries the confidence evidence.
    ff = asc.events[-1]["feedforward"]
    assert ff["up_step"] == 4
    assert ff["envelope_confidence"]["spread"] == conf["spread"]
    # Shrink stays one step however confident the model is.
    d = asc.decide(_status(False, 1.0))
    assert d["actions"] == [{"role": "groups", "from": 5, "to": 4}]

    # Wide spread (10x): confidence 0.1, stride floors at step.
    wide = Autoscaler(
        pol, {"groups": (9, 1)}, initial={"groups": 1},
        envelope=_envelope_payload([0.3, 3.0]),
    )
    assert wide.feedforward_confidence["confidence"] == pytest.approx(
        0.1
    )
    assert wide._up_step() == 1
    # No capture evidence at all: zero confidence, conservative probe.
    bare = Autoscaler(
        pol, {"groups": (9, 1)}, initial={"groups": 1},
        envelope={"captures": {}},
    )
    assert bare.feedforward_confidence["confidence"] == 0.0
    assert bare._up_step() == 1
    # The default policy (max_step=1) keeps the bit-identical
    # single-step ladder whatever the committed envelope says.
    dflt = Autoscaler(
        AutoscalerPolicy(cooldown_drains=0),
        {"groups": (3, 1)}, initial={"groups": 1},
    )
    assert dflt._up_step() == 1
    # max_step rides the policy's JSON round trip.
    assert AutoscalerPolicy.from_dict(pol.to_dict()) == pol


def test_autoscaler_state_round_trip_replays_bit_exactly():
    seq = (
        [_status(True, 40.0, scale=0.9)] * 4
        + [_status(False, 3.0)] * 6
        + [_status(True, 30.0, scale=0.8)] * 2
    )
    mk = lambda: Autoscaler(  # noqa: E731
        AutoscalerPolicy(cooldown_drains=0, trough_after=2),
        {"groups": (3, 1)}, initial={"groups": 1},
    )
    a, b = mk(), mk()
    decisions_a = [a.decide(s) for s in seq]
    cut = 5
    for s in seq[:cut]:
        b.decide(s)
    resumed = mk()
    resumed.restore_state(b.to_state())
    decisions_b = [b.decide(s) for s in seq[cut:]]
    decisions_r = [resumed.decide(s) for s in seq[cut:]]
    assert decisions_r == decisions_b == decisions_a[cut:]
    assert resumed.to_state() == a.to_state() == b.to_state()


# ---------------------------------------------------------------------------
# The serve layer: resize verbs are recompile-free and book-exact.
# ---------------------------------------------------------------------------


def test_serve_loop_resize_verbs_recompile_free():
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, retry_timeout=8,
        workload=_OPEN_LOOP,
        elastic=ElasticPlan(roles=(("groups", 4, 1),)),
    )
    serve = ServeConfig(chunk_ticks=20, telemetry_window=64,
                        max_chunks=6)
    loop = ServeLoop(mp, cfg, serve, seed=0,
                     elastic_initial={"groups": 2})
    snap = loop._dispatch_chunk()
    loop.resize("groups", 4)  # scale up: applies immediately
    snap2 = loop._dispatch_chunk()
    loop._drain(snap)
    cache = mp.run_ticks._cache_size()
    loop.resize("groups", 1)  # scale down: drain-then-deactivate
    snap3 = loop._dispatch_chunk()
    loop._drain(snap2)
    loop._drain(snap3)
    # Deactivation waits for the retiring lanes to drain — give the
    # loop a few more chunks to empty them and apply the generation.
    for _ in range(3):
        loop._drain(loop._dispatch_chunk())
    assert mp.run_ticks._cache_size() == cache, "resize recompiled"
    _assert_invariants(mp, cfg, loop.state, loop.t)
    assert int(loop.state.elastic.gen) >= 2  # both generations applied
    report = loop.report(1.0)
    groups = report["elastic"]["roles"]["groups"]
    assert groups["target"] == 1 and groups["capacity"] == 4
    verb_names = {
        s["name"] for s in loop.host_spans
        if s["name"].startswith("verb:")
    }
    assert "verb:resize" in verb_names
    # Resize spans are Perfetto INSTANT markers.
    assert all(
        s.get("instant") for s in loop.host_spans
        if s["name"] == "verb:resize"
    )


def test_serve_config_autoscaler_requires_slo():
    from frankenpaxos_tpu.monitoring.slo import SloPolicy

    with pytest.raises(AssertionError):
        ServeConfig(chunk_ticks=8, telemetry_window=32, max_chunks=1,
                    autoscaler=AutoscalerPolicy())
    ServeConfig(chunk_ticks=8, telemetry_window=32, max_chunks=1,
                slo=SloPolicy(p99_target_ticks=12),
                autoscaler=AutoscalerPolicy())


# ---------------------------------------------------------------------------
# The randomized [faults x resize] churn axis (harness/simtest.py).
# ---------------------------------------------------------------------------


def test_simtest_elastic_axis():
    """Randomized role-count churn against crash/partition schedules
    at segment boundaries; invariants and the elastic books hold
    throughout, and progress resumes across the final floor-pinned
    segment (liveness-after-scale-down under churn), on both
    backends."""
    import random as _random

    from frankenpaxos_tpu.harness import simtest

    for name in ("multipaxos", "compartmentalized"):
        spec = simtest.SPECS[name]
        assert spec.elastic_ok
        rng = _random.Random(7)
        for i in range(2):
            plan = simtest.random_plan(rng, spec, 160)
            if plan.has_partition and (
                plan.partition_heal < 0 or plan.partition_heal > 120
            ):
                plan = dataclasses.replace(
                    plan,
                    partition_heal=80,
                    partition_start=min(plan.partition_start, 79),
                )
            eplan = simtest.random_elastic(rng, spec)
            res = simtest.run_elastic_schedule(
                spec, plan, seed=i, ticks=160, elastic=eplan,
                churn_seed=i,
            )
            assert res["ok"], (name, i, res["violations"], res)
            assert res["resizes"] >= 1  # the floor pin always lands
            for role, tgt in res["targets"].items():
                assert tgt == eplan.floor_of(role), (role, tgt)
            for role, n in res["counts"].items():
                # Active counts sit between the pinned floor and cap
                # (a retiring lane may still be draining).
                assert (
                    eplan.floor_of(role) <= n <= eplan.capacity_of(role)
                ), (role, n)


def test_kill_and_recover_mid_resize(tmp_path):
    """The elastic worker shape of the kill-and-recover harness: a
    real serve subprocess with the SLO/autoscaler ladder scaling out
    from the floor is SIGKILLed mid-resize, restarts from the latest
    checkpoint, and finishes with the state digest, the device-side
    role books, AND the autoscaler's host-side ladder context all
    bit-identical to the uninterrupted twin's."""
    from frankenpaxos_tpu.harness import recovery

    res = recovery.run_kill_recover(
        str(tmp_path / "killed"), chunks=10, every=2, chunk_ticks=8,
        seed=0, backend="multipaxos", elastic=True, kill_seed=2,
        max_kills=1, chunk_delay=0.15, poll=0.05, backoff_base=0.05,
    )
    assert res.ok, res.to_dict()
    assert res.kills and res.restarts >= 1
    assert res.final["resumed"], "worker restarted fresh, not resumed"
    twin = recovery.uninterrupted_digest(
        chunks=10, every=2, chunk_ticks=8, seed=0,
        backend="multipaxos", out_dir=str(tmp_path / "twin"),
        elastic=True,
    )
    assert res.final["digest"] == twin["digest"]
    assert res.final["autoscaler"] == twin["autoscaler"]
    assert res.final["elastic"] == twin["elastic"]
    # The run actually climbed the ladder — the kill had resizes in
    # flight to land on.
    assert res.final["elastic"]["scale_ups"] >= 1
    assert res.final["autoscaler"]["targets"]["groups"] == 8


def test_elastic_reproducer_round_trip(tmp_path):
    from frankenpaxos_tpu.harness import simtest
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    spec = simtest.SPECS["multipaxos"]
    eplan = ElasticPlan(roles=(("groups", 4, 2),))
    plan = FaultPlan(drop_rate=0.05)
    path = str(tmp_path / "repro.json")
    simtest.dump_reproducer(
        path, spec, plan, seed=3, ticks=120,
        workload=_OPEN_LOOP, elastic=eplan, churn_seed=9,
    )
    loaded = simtest.load_reproducer(path)
    assert len(loaded) == 7
    lspec, lplan, lseed, lticks, lwork, lel, lchurn = loaded
    assert (lspec.name, lseed, lticks, lchurn) == (
        "multipaxos", 3, 120, 9
    )
    assert lplan == plan and lwork == _OPEN_LOOP and lel == eplan
    a = simtest.run_elastic_schedule(
        lspec, lplan, seed=lseed, ticks=lticks, workload=lwork,
        elastic=lel, churn_seed=lchurn,
    )
    b = simtest.run_elastic_schedule(
        spec, plan, seed=3, ticks=120, workload=_OPEN_LOOP,
        elastic=eplan, churn_seed=9,
    )
    assert a == b and a["ok"], a  # the artifact replays bit-exactly


# ---------------------------------------------------------------------------
# Fleet elasticity: the padded instance axis.
# ---------------------------------------------------------------------------


def test_fleet_set_active_instances_redistributes_and_marks():
    from frankenpaxos_tpu.harness.serve import (
        FleetServeConfig, FleetServeLoop,
    )
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, retry_timeout=8,
        workload=WorkloadPlan(arrival="constant", rate=2.0,
                              backlog_cap=256),
        faults=FaultPlan(traced=True),
    )
    n = 4
    loop = FleetServeLoop(
        "multipaxos", cfg,
        FleetServeConfig(chunk_ticks=10, telemetry_window=32,
                         max_chunks=2),
        n, seeds=list(range(n)), rates=[2.0] * n,
        fault_rates=[[0.0] * 4] * n,
    )
    snap = loop._dispatch_chunk()
    loop._drain(snap)
    runner = loop.sharding._fleet_runner("multipaxos", None, None)
    before = runner._cache_size()
    loop.set_active_instances(2)  # scale DOWN to 2 of 4
    np.testing.assert_allclose(
        np.asarray(loop.states.workload.rate), [4.0, 4.0, 0.0, 0.0]
    )
    snap = loop._dispatch_chunk()
    loop._drain(snap)
    loop.set_active_instances(4)  # back up: same verb
    np.testing.assert_allclose(
        np.asarray(loop.states.workload.rate), [2.0] * 4
    )
    assert runner._cache_size() == before, "fleet resize recompiled"
    kinds = [m["kind"] for m in loop.markers if m["instance"] == -1]
    assert kinds == ["scale_down", "scale_up"]
    report = loop.report(1.0)
    assert report["active_instances"] == 4
