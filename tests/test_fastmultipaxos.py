"""Fast MultiPaxos sim tests (the analog of
shared/src/test/scala/fastmultipaxos)."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport, wire
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import fastmultipaxos as fmp
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin, MixedRoundRobin
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import ReadableAppendLog


def make(f=1, num_clients=2, seed=0, round_system=None):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    n = 2 * f + 1
    num_leaders = f + 1
    config = fmp.FastMultiPaxosConfig(
        f=f,
        leader_addresses=tuple(
            SimAddress(f"leader{i}") for i in range(num_leaders)
        ),
        leader_election_addresses=tuple(
            SimAddress(f"election{i}") for i in range(num_leaders)
        ),
        leader_heartbeat_addresses=tuple(
            SimAddress(f"lheartbeat{i}") for i in range(num_leaders)
        ),
        acceptor_addresses=tuple(SimAddress(f"acceptor{i}") for i in range(n)),
        acceptor_heartbeat_addresses=tuple(
            SimAddress(f"aheartbeat{i}") for i in range(n)
        ),
        round_system=round_system or MixedRoundRobin(num_leaders),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [
        fmp.FmpLeader(a, t, log(), config, ReadableAppendLog(), seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    acceptors = [
        fmp.FmpAcceptor(a, t, log(), config, seed=seed + 10 + i)
        for i, a in enumerate(config.acceptor_addresses)
    ]
    clients = [
        fmp.FmpClient(SimAddress(f"client{i}"), t, log(), config,
                      seed=seed + 40 + i)
        for i in range(num_clients)
    ]
    return t, config, leaders, acceptors, clients


def drain(t, max_steps=200000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def pump(t, config, rounds=8, skip=lambda timer: False):
    """Fire protocol timers but NOT election/heartbeat infrastructure
    timers — firing those repeatedly churns leadership (safe, but it
    makes deterministic liveness assertions meaningless)."""
    infra = (
        set(config.leader_election_addresses)
        | set(config.leader_heartbeat_addresses)
        | set(config.acceptor_heartbeat_addresses)
    )
    drain(t)
    for _ in range(rounds):
        for timer in list(t.running_timers()):
            if timer.address not in infra and not skip(timer):
                t.trigger_timer(timer.address, timer.name())
        drain(t)


def chosen_logs_compatible(leaders):
    """Every pair of leaders must agree on every slot chosen by both."""
    for i in range(len(leaders)):
        for j in range(i + 1, len(leaders)):
            a, b = leaders[i].log, leaders[j].log
            for slot in set(a) & set(b):
                if a[slot] != b[slot]:
                    return f"slot {slot}: {a[slot]!r} != {b[slot]!r}"
    return None


def test_fmp_fast_path_single_client():
    """An uncontended command in fast round 0 commits with the client
    writing straight to acceptors — the leader proposes no command
    phase2as, only the any-suffix."""
    t, config, leaders, acceptors, clients = make()
    drain(t)  # leader 0's phase 1 + any-suffix
    command_phase2as = 0
    p = clients[0].propose(0, b"fast!")
    while t.messages:
        m = t.messages[0]
        decoded = wire.decode(m.data)
        if isinstance(decoded, fmp.FmpPhase2a) and decoded.kind == fmp.COMMAND:
            command_phase2as += 1
        if isinstance(decoded, fmp.FmpPhase2aBuffer):
            command_phase2as += sum(
                1 for x in decoded.phase2as if x.kind == fmp.COMMAND
            )
        t.deliver_message(m)
    assert p.done
    assert command_phase2as == 0
    assert leaders[0].log[0][0] == fmp.COMMAND
    assert leaders[0].state_machine.log == [b"fast!"]


def test_fmp_sequential_fast_commands():
    t, config, leaders, acceptors, clients = make()
    drain(t)
    for i in range(5):
        p = clients[i % 2].propose(i // 2, f"c{i}".encode())
        drain(t)
        assert p.done, i
    assert leaders[0].state_machine.log == [b"c0", b"c1", b"c2", b"c3", b"c4"]
    assert chosen_logs_compatible(leaders) is None


def test_fmp_conflict_degrades_to_classic():
    """Two clients race in the fast round with interleaved delivery, so
    acceptors vote in different orders; the stuck slot forces the leader
    into a (classic) higher round and both commands still commit."""
    t, config, leaders, acceptors, clients = make(seed=3)
    drain(t)
    p1 = clients[0].propose(0, b"a")
    p2 = clients[1].propose(0, b"b")
    # Interleave: acceptor 0 sees a,b; acceptors 1..2 see b,a.
    rng = random.Random(5)
    while t.messages:
        idx = rng.randrange(len(t.messages))
        t.deliver_message(t.messages[idx])
    pump(t, config, rounds=10)
    assert p1.done and p2.done
    assert chosen_logs_compatible(leaders) is None
    sm = leaders[0].state_machine.log
    assert sorted(sm) == [b"a", b"b"]


def test_fmp_classic_round_system():
    """With a purely classic round system the protocol runs like
    MultiPaxos: clients go through the leader."""
    t, config, leaders, acceptors, clients = make(
        round_system=ClassicRoundRobin(2)
    )
    drain(t)
    p = clients[0].propose(0, b"classic")
    drain(t)
    assert p.done
    assert leaders[0].state_machine.log == [b"classic"]


def test_fmp_client_round_catchup():
    """A client stuck in an old round learns the current round from
    LeaderInfo and reroutes (fast -> classic after a leader bump)."""
    t, config, leaders, acceptors, clients = make(seed=7)
    drain(t)
    # Force the leader into a higher classic round: with fewer than a
    # fast quorum of acceptors alive, leader_change goes classic.
    leaders[0].heartbeat.alive = set()
    leaders[0].leader_change(True, 0)
    drain(t)
    assert config.round_system.round_type(leaders[0].round).value == "classic"
    # The client still thinks round 0 (fast): its direct proposals are
    # dead ends; the repropose timer reaches the leaders, which reply
    # with LeaderInfo, and the client reroutes.
    p = clients[0].propose(0, b"catchup")
    pump(t, config, rounds=6)
    assert p.done
    assert clients[0].round == leaders[0].round


def test_fmp_leader_failover():
    """Partition leader 0; leader 1 takes over via leader_change and
    repairs: in-flight and new commands commit."""
    t, config, leaders, acceptors, clients = make(seed=9)
    drain(t)
    p = clients[0].propose(0, b"before")
    drain(t)
    assert p.done
    dead = config.leader_addresses[0]
    t.partition_actor(dead)
    t.partition_actor(config.leader_election_addresses[0])
    t.partition_actor(config.leader_heartbeat_addresses[0])
    leaders[1].leader_change(True, leaders[1].round)
    pump(t, config, rounds=6, skip=lambda tm: tm.address == dead)
    p2 = clients[1].propose(0, b"after")
    pump(t, config, rounds=8, skip=lambda tm: tm.address == dead)
    assert p2.done
    assert leaders[1].state_machine.log == [b"before", b"after"]


def test_fmp_duplicate_request_replays_cached_reply():
    t, config, leaders, acceptors, clients = make(seed=11)
    drain(t)
    p = clients[0].propose(0, b"dup")
    drain(t)
    assert p.done
    # Re-deliver the same command id straight to the leader.
    pending = fmp._FmpPending(id=0, command=b"dup", result=None, repropose=None)
    request = clients[0]._request(0, pending)
    leaders[0].receive(clients[0].address, request)
    drain(t)
    # Executed once, not twice.
    assert leaders[0].state_machine.log == [b"dup"]


def test_fmp_partial_fast_vote_driven_to_choice_by_resend():
    """Regression: with f=1 the fast quorum is ALL acceptors, so a slot
    where one acceptor missed the client's direct send sits at 2/3
    identical votes — not chosen, and never 'stuck' either (the missing
    vote could still complete it). The leader's phase2a resend timer must
    drive such slots to a decision by proposing the most-voted value."""
    t, config, leaders, acceptors, clients = make(seed=15)
    drain(t)
    lagger = config.acceptor_addresses[2]
    p = clients[0].propose(0, b"partial")
    while t.messages:
        m = t.messages[0]
        if m.dst == lagger and isinstance(
            wire.decode(m.data), fmp.FmpProposeRequest
        ):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert not p.done
    for timer in list(t.running_timers()):
        if timer.name() == "resendPhase2as":
            t.trigger_timer(timer.address, timer.name())
    drain(t)
    assert p.done
    assert leaders[0].state_machine.log == [b"partial"]


def test_fmp_lagging_acceptor_rejoins_fast_path_after_failover():
    """Regression: an acceptor that missed the vote on a trailing chosen
    slot has next_slot inside the [old log end, any-suffix start) gap
    after failover. The ANY_SUFFIX must advance its next_slot, or it
    silently drops every fast proposal and (with f=1, where the fast
    quorum is ALL acceptors) no command can ever commit fast again."""
    t, config, leaders, acceptors, clients = make(seed=13)
    drain(t)
    # Commit the first command in a CLASSIC round (quorum f+1 = 2) while
    # hiding the phase2as from acceptor 2: it lags behind the log end.
    leaders[0].heartbeat.alive = set()
    leaders[0].leader_change(True, 0)
    drain(t)
    lagger = config.acceptor_addresses[2]

    def drain_without_lagger():
        while t.messages:
            m = t.messages[0]
            if m.dst == lagger:
                t.drop_message(m)
            else:
                t.deliver_message(m)

    p = clients[0].propose(0, b"first")
    drain_without_lagger()
    for _ in range(3):
        if p.done:
            break
        # Only the client's repropose timer (its direct-to-acceptor fast
        # attempt was ignored by the classic-round acceptors).
        for timer in list(t.running_timers()):
            if timer.address == clients[0].address:
                t.trigger_timer(timer.address, timer.name())
        drain_without_lagger()
    assert p.done
    assert acceptors[2].next_slot < acceptors[0].next_slot
    # Fast-round failover: leader 1 takes over and opens a new suffix.
    leaders[1].leader_change(True, leaders[1].round)
    drain(t)
    assert config.round_system.round_type(leaders[1].round).value == "fast"
    # The lagger's next_slot must have jumped into the new suffix so the
    # next fast command gets all three votes.
    assert acceptors[2].next_slot == acceptors[0].next_slot
    p2 = clients[1].propose(0, b"second")
    drain(t)
    assert p2.done


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    value: str


class SimulatedFmp(SimulatedSystem):
    def __init__(self, f=1, round_system=None):
        self.f = f
        self.round_system = round_system

    def new_system(self, seed):
        return make(self.f, seed=seed, round_system=self.round_system)

    def get_state(self, system):
        leaders = system[2]
        return (
            tuple(dict(l.log) for l in leaders),
            tuple(tuple(l.state_machine.log) for l in leaders),
        )

    def generate_command(self, system, rng):
        t, clients = system[0], system[4]
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (1, Propose(i, pseudonym, f"v{rng.randrange(100)}"))
                    )
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, clients = system[0], system[4]
        if isinstance(command, Propose):
            clients[command.client_index].propose(
                command.pseudonym, command.value.encode()
            )
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        logs, machines = state
        # Chosen-value agreement across leaders.
        for i in range(len(logs)):
            for j in range(i + 1, len(logs)):
                for slot in set(logs[i]) & set(logs[j]):
                    if logs[i][slot] != logs[j][slot]:
                        return (
                            f"leaders disagree at slot {slot}: "
                            f"{logs[i][slot]!r} != {logs[j][slot]!r}"
                        )
        # Executed logs are prefix-compatible.
        for i in range(len(machines)):
            for j in range(i + 1, len(machines)):
                a, b = machines[i], machines[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return f"executions diverge: {a!r} vs {b!r}"
        return None

    def step_invariant(self, old, new):
        old_logs, _ = old
        new_logs, _ = new
        for o, n in zip(old_logs, new_logs):
            for slot in set(o) & set(n):
                if o[slot] != n[slot]:
                    return f"chosen value changed at slot {slot}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_fmp_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedFmp(f), run_length=120, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_fmp_safety_randomized_classic():
    bad = simulate_and_minimize(
        SimulatedFmp(1, round_system=ClassicRoundRobin(2)),
        run_length=120, num_runs=5, seed=77,
    )
    assert bad is None, f"\n{bad}"
