"""Tests of the batched Fast Paxos backend (fastpaxos_batched.py):
fast-path quorums, classic recovery with the O4 majority-of-quorum rule,
the fast-committed safety ledger, and cross-validation against the
per-actor protocol (protocols/fastpaxos.py; fastpaxos/Leader.scala)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu import fastpaxos_batched as fb


def run_random(cfg, seed, ticks):
    key = jax.random.PRNGKey(seed)
    state, t = fb.run_ticks(cfg, fb.init_state(cfg), jnp.int32(0), ticks, key)
    return state, t


def test_progress_and_invariants_under_conflicts():
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=8, window=16, instances_per_tick=2,
        conflict_rate=0.3, lat_min=1, lat_max=3, recovery_timeout=8,
    )
    state, t = run_random(cfg, seed=0, ticks=200)
    inv = fb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    s = fb.stats(cfg, state, t)
    assert s["chosen"] > 8 * 100
    assert s["recoveries"] > 0  # conflicts force classic recoveries
    assert 0.0 < s["fast_fraction"] < 1.0
    assert s["safety_violations"] == 0


def test_no_conflicts_is_all_fast_path():
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=4, window=8, instances_per_tick=2,
        conflict_rate=0.0, lat_min=1, lat_max=2, recovery_timeout=10,
    )
    state, t = run_random(cfg, seed=1, ticks=100)
    s = fb.stats(cfg, state, t)
    assert s["chosen"] > 0
    assert s["fast_fraction"] == 1.0
    assert s["recoveries"] == 0
    # Fast path = one client->acceptor hop + one reply hop.
    assert s["latency_p50_ticks"] <= 2 * 2
    inv = fb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def _inject_instance(cfg, state, votes, t, conflicted=True):
    """Place instance id=5 in slot (0, 0) in I_FAST with the given
    round-0 acceptor votes (list of 0 -> v0, 1 -> v1, None -> unvoted)
    and replies too slow for the fast counter to act before the
    recovery timeout."""
    v0, v1 = 10, 11  # _values_of(5)
    st = dataclasses.replace(
        state,
        status=state.status.at[0, 0].set(fb.I_FAST),
        conflicted=state.conflicted.at[0, 0].set(conflicted),
        issue_tick=state.issue_tick.at[0, 0].set(t),
        inst_id=state.inst_id.at[0, 0].set(5),
        next_inst=state.next_inst.at[0].set(6),
    )
    for a, v in enumerate(votes):
        if v is None:
            continue
        st = dataclasses.replace(
            st,
            vote_round=st.vote_round.at[a, 0, 0].set(0),
            vote_value=st.vote_value.at[a, 0, 0].set(v0 if v == 0 else v1),
            up_arrival=st.up_arrival.at[a, 0, 0].set(t + 1000),
        )
    return st


def _run_manual(cfg, state, t0, n, seed=0):
    key = jax.random.PRNGKey(seed)
    t = t0
    for _ in range(n):
        state = fb.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    return state, t


def test_o4_recovery_picks_popular_value():
    """Votes (v0, v0, v1) with no fast quorum: the classic round's O4
    rule must pick v0 (2 >= majority-of-quorum) — matching
    FpLeader._handle_phase1b's popular_items branch."""
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=1, window=4, instances_per_tick=0,
        conflict_rate=0.0, lat_min=1, lat_max=1, recovery_timeout=4,
    )
    state = _inject_instance(cfg, fb.init_state(cfg), [0, 0, 1], t=0)
    state, t = _run_manual(cfg, state, 0, 30)
    s = fb.stats(cfg, state, t)
    assert s["recoveries"] == 1
    assert s["chosen"] == 1
    assert s["chosen_fast"] == 0
    assert s["safety_violations"] == 0
    # The instance retired; its choice was v0 — visible via the counters
    # and the clean ledger (no violation despite v0 never fast-committed).
    inv = fb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_fast_committed_value_survives_unobserved():
    """All n acceptors voted v0 (v0 IS fast-committed) but every reply is
    too slow for the counter: the timeout triggers recovery, and phase 1
    must re-discover v0 from the vote reports — the safety ledger
    asserts the recovery chose the committed value."""
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=1, window=4, instances_per_tick=0,
        conflict_rate=0.0, lat_min=1, lat_max=1, recovery_timeout=4,
    )
    state = _inject_instance(cfg, fb.init_state(cfg), [0, 0, 0], t=0)
    state, t = _run_manual(cfg, state, 0, 30)
    s = fb.stats(cfg, state, t)
    assert s["recoveries"] == 1
    assert s["chosen"] == 1
    assert s["safety_violations"] == 0  # THE assertion: v0 was chosen
    inv = fb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_recovery_with_no_votes_picks_proposer0():
    """Timeout with no votes at all (proposals still in flight): phase 1
    sees an empty vote set and proposes proposer 0's value —
    FpLeader._handle_phase1b's k == -1 branch."""
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=1, window=4, instances_per_tick=0,
        conflict_rate=0.0, lat_min=1, lat_max=1, recovery_timeout=4,
    )
    state = _inject_instance(
        cfg, fb.init_state(cfg), [None, None, None], t=0, conflicted=False
    )
    state, t = _run_manual(cfg, state, 0, 30)
    s = fb.stats(cfg, state, t)
    assert s["chosen"] == 1 and s["recoveries"] == 1
    inv = fb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv  # incl. clean_value_ok


def test_cross_validation_fastpaxos_o4():
    """Aligned conflict scenario against the per-actor protocol: client 0
    ("a") wins acceptors 0-1, client 1 ("b") wins acceptor 2; no fast
    quorum (needs 3); the classic fallback's phase-1 quorum sees
    {a, a} and the O4 rule picks "a". The batched execution of the same
    vote split (test_o4_recovery_picks_popular_value's injection) picks
    v0 — both resolve the collision toward the popular value."""
    from test_fastpaxos_craq import make_fp

    t, config, leaders, acceptors, clients = make_fp()
    clients[0].propose("a")
    clients[1].propose("b")
    acc = config.acceptor_addresses

    def deliver_where(pred):
        for m in [m for m in t.messages if pred(m)]:
            t.deliver_message(m)

    # Client 0's proposal reaches acceptors 0 and 1 first; client 1's
    # reaches acceptor 2 first. The losers' copies arrive after and are
    # ignored (the acceptor has already cast its one fast vote).
    c0, c1 = clients[0].address, clients[1].address
    deliver_where(lambda m: m.src == c0 and m.dst in (acc[0], acc[1]))
    deliver_where(lambda m: m.src == c1 and m.dst == acc[2])
    deliver_where(lambda m: m.dst in acc)
    assert [a.vote_value for a in acceptors] == ["a", "a", "b"]
    # Phase2bs reach the clients: 2 < fast quorum (3) for "a", 1 for "b".
    deliver_where(lambda m: m.dst in (c0, c1))
    assert clients[0].chosen_value is None and clients[1].chosen_value is None

    # Client 0 times out and falls back through leader 0 only.
    t.trigger_timer(c0, "reproposeTimer")
    deliver_where(lambda m: m.dst == leaders[0].address)
    # Phase 1a to the acceptors; the phase-1 quorum is acceptors 0, 1.
    deliver_where(lambda m: m.src == leaders[0].address and m.dst in acc)
    deliver_where(
        lambda m: m.src in (acc[0], acc[1]) and m.dst == leaders[0].address
    )
    # Phase 2 completes and the choice propagates.
    deliver_where(lambda m: m.src == leaders[0].address and m.dst in acc)
    deliver_where(lambda m: m.dst == leaders[0].address)
    deliver_where(lambda m: m.dst in (c0, c1))
    assert leaders[0].chosen_value == "a"
    assert clients[0].chosen_value == "a"

    # Batched side: the identical vote split resolves to v0 (proposer 0)
    # via the same rule — proven by test_o4_recovery_picks_popular_value;
    # here we assert the decision agrees with the per-actor outcome.
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=1, window=4, instances_per_tick=0,
        conflict_rate=0.0, lat_min=1, lat_max=1, recovery_timeout=4,
    )
    state = _inject_instance(cfg, fb.init_state(cfg), [0, 0, 1], t=0)
    # Observe the choice before retirement: run tick-by-tick and capture
    # the chosen value when it appears.
    key = jax.random.PRNGKey(0)
    chosen_seen = None
    tt = 0
    for _ in range(30):
        state = fb.tick(cfg, state, jnp.int32(tt), jax.random.fold_in(key, tt))
        tt += 1
        if int(state.status[0, 0]) == fb.I_CHOSEN and chosen_seen is None:
            chosen_seen = int(state.chosen_value[0, 0])
    assert chosen_seen == 10  # v0 — proposer 0's value, same as "a"


def test_wide_latency_spread_no_phantom_votes():
    """lat_max >> lat_min: a conflicted instance can be fast-chosen and
    retired while a slow round-0 proposal is still in flight. The
    proposal must die with its instance — firing into the slot's next
    instance would be a phantom vote (caught by clean_value_ok)."""
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=8, window=8, instances_per_tick=2,
        conflict_rate=0.5, lat_min=1, lat_max=4, recovery_timeout=8,
    )
    state, t = run_random(cfg, seed=3, ticks=300)
    inv = fb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    s = fb.stats(cfg, state, t)
    assert s["chosen"] > 0 and s["safety_violations"] == 0


def test_straggler_phase1a_reports_vote_instead_of_casting():
    """Regression for the dn_phase captured-at-send fix
    (fastpaxos_batched.py BatchedFastPaxosState.dn_phase): a Phase1a
    message that delivers AFTER the counter has already advanced to
    I_REC2 must still act as a Phase1a — promote the acceptor and make
    it report its existing round-0 vote — NOT be misread (from the
    counter's live status) as a Phase2a casting a round-1 vote for the
    recovery value. Under the old live-status inference the acceptor
    below would end the tick with vote_round == 1 / vote_value == v1."""
    cfg = fb.BatchedFastPaxosConfig(
        f=1, num_groups=1, window=4, instances_per_tick=0,
        conflict_rate=0.0, lat_min=1, lat_max=1, recovery_timeout=4,
    )
    v0, v1 = 10, 11  # _values_of(5)
    t = 7
    state = _inject_instance(cfg, fb.init_state(cfg), [0, None, None], t=0)
    # Counter already in classic phase 2 proposing v1; acceptor 0 holds a
    # round-0 vote for v0 and a STRAGGLER Phase1a (sent during I_REC1,
    # phase captured at send) delivering this tick. Acceptors 1-2 already
    # saw their Phase2as and voted round-1 v1 (replies still in flight so
    # nothing is chosen during the distinguishing tick).
    st = dataclasses.replace(
        state,
        status=state.status.at[0, 0].set(fb.I_REC2),
        rec_value=state.rec_value.at[0, 0].set(v1),
        dn_arrival=state.dn_arrival.at[0, 0, 0].set(t),
        dn_phase=state.dn_phase.at[0, 0, 0].set(1),
        acc_round=state.acc_round.at[1, 0, 0].set(1)
        .at[2, 0, 0].set(1),
        vote_round=state.vote_round.at[1, 0, 0].set(1)
        .at[2, 0, 0].set(1),
        vote_value=state.vote_value.at[1, 0, 0].set(v1)
        .at[2, 0, 0].set(v1),
        up_arrival=state.up_arrival.at[1, 0, 0].set(t + 1000)
        .at[2, 0, 0].set(t + 1000),
    )
    st = fb.tick(cfg, st, jnp.int32(t), jax.random.PRNGKey(0))
    # The Phase1a was consumed: acceptor 0 promoted to the classic round
    # and scheduled a reply...
    assert int(st.acc_round[0, 0, 0]) == 1
    assert int(st.dn_arrival[0, 0, 0]) == fb.INF
    assert int(st.up_arrival[0, 0, 0]) == t + 1  # reply sent (lat == 1)
    # ...and that reply REPORTS the round-0 vote for v0 — it does not
    # cast a round-1 vote for the recovery value.
    assert int(st.vote_round[0, 0, 0]) == 0
    assert int(st.vote_value[0, 0, 0]) == v0


# ---------------------------------------------------------------------------
# Proposer crash semantics (PR 3 follow-up (b)): crash gates issuing and
# the counter-side transitions; revival restores liveness through the
# persisted replies + the recovery timeout.
# ---------------------------------------------------------------------------


def _crash_cfg(**fault_kw):
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    return fb.BatchedFastPaxosConfig(
        f=1, num_groups=4, window=16, instances_per_tick=2,
        conflict_rate=0.2, lat_min=1, lat_max=2, recovery_timeout=8,
        faults=FaultPlan(**fault_kw),
    )


def test_dead_proposers_stall_and_manual_revival_resumes():
    """Every round-0 proposer dead: in-flight instances drain (their
    replies persist but nobody counts them), then progress STOPS — no
    new instances, no recoveries; reviving the proposers restores
    choices via the persisted replies and the recovery timeout — the
    liveness-after-revive contract (revive_rate=0 keeps the PRNG from
    resurrecting anyone mid-stall)."""
    cfg = _crash_cfg(crash_rate=0.001, revive_rate=0.0)
    key = jax.random.PRNGKey(2)
    state, t = fb.run_ticks(cfg, fb.init_state(cfg), jnp.int32(0), 30, key)
    assert int(state.chosen_total) > 0

    state = dataclasses.replace(
        state, prop_alive=jnp.zeros((cfg.num_groups,), bool)
    )
    state, t = fb.run_ticks(cfg, state, t, 30, jax.random.fold_in(key, 1))
    c_drained = int(state.chosen_total)
    state, t = fb.run_ticks(cfg, state, t, 25, jax.random.fold_in(key, 2))
    assert int(state.chosen_total) == c_drained  # fully stalled
    assert not bool(np.asarray(state.prop_alive).any())

    state = dataclasses.replace(
        state, prop_alive=jnp.ones((cfg.num_groups,), bool)
    )
    state, t = fb.run_ticks(cfg, state, t, 40, jax.random.fold_in(key, 3))
    assert int(state.chosen_total) > c_drained
    inv = fb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_revival_counts_recovery_handoffs_in_telemetry():
    """High revive_rate: the tick after every proposer is killed, the
    revive draw brings (almost surely all of) them back, and each
    revival lands in the telemetry ring as one leader change."""
    from frankenpaxos_tpu.tpu.telemetry import COL

    cfg = _crash_cfg(crash_rate=0.001, revive_rate=0.99)
    key = jax.random.PRNGKey(2)
    state, t = fb.run_ticks(cfg, fb.init_state(cfg), jnp.int32(0), 20, key)
    state = dataclasses.replace(
        state, prop_alive=jnp.zeros((cfg.num_groups,), bool)
    )
    lc0 = int(state.telemetry.totals[COL["leader_changes"]])
    state, t = fb.run_ticks(cfg, state, t, 1, jax.random.fold_in(key, 5))
    alive = np.asarray(state.prop_alive)
    assert alive.any()  # p(all four stay dead) = 1e-8
    lc1 = int(state.telemetry.totals[COL["leader_changes"]])
    assert lc1 - lc0 == int(alive.sum())  # one handoff per revival


def test_crash_plan_randomized_schedules_hold_invariants():
    """The simtest axis the satellite enables: randomized crash/revive
    schedules over the proposer plane keep every invariant (incl. the
    fast-committed safety ledger) and make progress — liveness after
    revival, with revive_rate keeping dead windows finite."""
    from frankenpaxos_tpu.harness import simtest
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    spec = simtest.SPECS["fastpaxos"]
    assert spec.crash_ok  # the crash axis is now enabled
    plan = FaultPlan(crash_rate=0.05, revive_rate=0.3)
    out = simtest.run_many_seeds(spec, plan, seeds=(0, 1, 2, 3), ticks=80)
    assert out["ok"], out
    assert all(p > 0 for p in out["progress"])  # chooses despite crashes
