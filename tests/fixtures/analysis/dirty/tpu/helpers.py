# Helper module for the DIRTY fixture tree: the host sync lives one
# module away from the tick that calls it, so only a TRANSITIVE purity
# walk (not the old inline-only lint) can catch it.
import jax
from numpy import asarray


def pull(x):
    jax.block_until_ready(x)
    # host-sync-purity: a BARE from-imported asarray (numpy's) is a
    # host materialization just like np.asarray.
    return asarray(x)
