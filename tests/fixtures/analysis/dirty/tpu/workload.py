# Synthetic DIRTY workload module: `bad_fraction` is a float field the
# validate() body never range-checks (workload-rate-validated fires).
import dataclasses


@dataclasses.dataclass(frozen=True)
class ToyWorkloadPlan:
    rate: float = 0.0
    bad_fraction: float = 0.0

    def validate(self) -> None:
        assert self.rate >= 0.0
