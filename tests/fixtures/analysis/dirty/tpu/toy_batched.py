# Synthetic DIRTY backend for the analysis-engine tests: violates every
# AST-layer contract rule at least once (the expected finding set is
# asserted in test_analysis_engine.py). Parsed only, never imported.
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from dirty.tpu import helpers


@dataclasses.dataclass(frozen=True)
class ToyConfig:
    # fault-config-field: no `faults: FaultPlan` field.
    n: int = 4
    # fault-rate-validated: never range-checked below.
    loss_rate: float = 0.0

    def __post_init__(self):
        # fault-validate: no faults.validate(...) call.
        pass


@dataclasses.dataclass
class ToyState:
    # telemetry-state-carry: no `telemetry: Telemetry` field.
    counter: jnp.ndarray
    # state-dead-write: written in tick, read nowhere.
    ghost: jnp.ndarray


def init_state(cfg: ToyConfig) -> ToyState:
    return ToyState(
        counter=jnp.zeros((cfg.n,), jnp.int32),
        ghost=jnp.zeros((cfg.n,), jnp.int32),
    )


def _inline_sync(x):
    # host-sync-purity (transitive, same module): reached from tick.
    return jax.device_get(x)


class ToyDriver:
    def method_sync(self, x):
        # host-sync-purity (through a METHOD call): only the
        # method-resolving walk follows driver.method_sync(...).
        return jax.block_until_ready(x)


def _table_sync(x):
    # host-sync-purity (through a SWITCH TABLE): dispatched via
    # _HANDLERS[...] below — no direct call edge exists.
    return x.item()


_HANDLERS = {"sync": _table_sync}


def tick(cfg: ToyConfig, state: ToyState, t, key):
    # telemetry-tick-records: no record() call.
    # fault-apply: never touches cfg.faults / faults_mod.
    snapshot = _inline_sync(state.counter)
    remote = helpers.pull(state.counter)
    driver = ToyDriver()
    via_method = driver.method_sync(state.counter)
    via_table = _HANDLERS["sync"](state.counter)
    del snapshot, remote, via_method, via_table
    return dataclasses.replace(
        state, counter=state.counter + 1, ghost=state.ghost + 1
    )


@functools.partial(jax.jit, static_argnums=(0, 3))
def run_ticks(cfg: ToyConfig, state: ToyState, t0, num_ticks: int, key):
    # donation-jit: jitted *State entry point without donate_argnums.
    # host-sync-purity (inline): numpy materialization in-graph.
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks)
    )
    return state, np.asarray(t)


def reach_for_pallas(x):
    # kernel-pallas-containment: pallas_call outside ops/.
    return pl.pallas_call(lambda ref: ref, out_shape=x)  # noqa: F821


def stats(cfg, state, t) -> dict:
    # Reads `counter` but NOT `ghost` — ghost stays a dead write.
    return {"counter": int(state.counter.sum())}


def twiddle_packed(state, idx):
    # packing-containment: raw bit-twiddling on a packed plane (the
    # sess_occ occupancy bitmap) outside tpu/packing.py.
    return state.sess_occ | (1 << idx)
