"""Clean dataflow-rule fixture: a toy backend whose tick is a model
citizen of every dataflow-layer contract.

* PRNG: one draw per derived key — the fault/workload draws fold their
  declared family salts, the backend draw uses a split child; no key
  value feeds two draws, no key is minted from non-key data.
* State: every leaf the tick writes reaches ``check_invariants``.
* Donation: every read of a pre-update leaf value happens before the
  updated value is produced.

Loaded by ``tests/test_analysis_dataflow.py`` via importlib and handed
to the rules through ``Context.dataflow_targets``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.faults import FAULT_SALT
from frankenpaxos_tpu.tpu.workload import WORKLOAD_SALT

N = 32  # lanes
W = 16  # window (plane = N x W = 512 elems, above DONATION_MIN_ELEMS)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ToyState:
    plane: jnp.ndarray  # [N, W] the "data plane"
    count: jnp.ndarray  # [] admitted census


@dataclasses.dataclass(frozen=True)
class ToyConfig:
    lanes: int = N
    window: int = W


def analysis_config() -> ToyConfig:
    return ToyConfig()


def init_state(cfg: ToyConfig) -> ToyState:
    return ToyState(
        plane=jnp.zeros((cfg.lanes, cfg.window), jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


def tick(cfg, state: ToyState, t, key) -> ToyState:
    kf = jax.random.fold_in(key, FAULT_SALT)
    kw = jax.random.fold_in(key, WORKLOAD_SALT)
    kb, _ = jax.random.split(key)
    drop = jax.random.bernoulli(kf, 0.25, (cfg.lanes, cfg.window))
    arrive = jax.random.bernoulli(kw, 0.5, (cfg.lanes,))
    pick = jax.random.bits(kb, (cfg.lanes,)) % jnp.uint32(cfg.window)
    # Read old values BEFORE producing the new ones (donation-clean).
    inc = jnp.where(
        drop, 0, (jnp.arange(cfg.window)[None, :] == pick[:, None])
        * arrive[:, None]
    ).astype(jnp.int32)
    new_count = state.count + jnp.sum(arrive.astype(jnp.int32))
    new_plane = state.plane + inc
    return ToyState(plane=new_plane, count=new_count)


def check_invariants(cfg, state: ToyState, t) -> dict:
    return {
        "plane_nonneg": jnp.all(state.plane >= 0),
        "count_bounds": state.count >= 0,
    }
