"""Dirty dataflow-rule fixture: one seeded violation per dataflow
rule family, each of which the teeth tests prove produces its named
finding.

* ``prng-stream-lineage``: the same split child feeds TWO draws
  (stream reuse); a key is minted from ``PRNGKey(0)`` inside the tick
  (foreign root); one draw folds both the fault and workload family
  salts (mixed lineage).
* ``prng-salt-disjoint``: a fold constant 300 past the workload base
  escapes the family span.
* ``state-dead-write-reachable``: ``ghost`` is written every tick via
  a local alias (invisible to the retired AST rule's replace()
  heuristic) but read by nothing.
* ``donation-hazard``: the 512-element ``big`` plane's old value is
  consumed AFTER its replacement is produced.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.faults import FAULT_SALT
from frankenpaxos_tpu.tpu.workload import WORKLOAD_SALT

N = 32
W = 16


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DirtyState:
    big: jnp.ndarray  # [N, W]
    echo: jnp.ndarray  # [N, W] stale copy of big (post-alias read)
    ghost: jnp.ndarray  # [N] written every tick, read nowhere
    count: jnp.ndarray  # []


@dataclasses.dataclass(frozen=True)
class DirtyConfig:
    lanes: int = N
    window: int = W


def analysis_config() -> DirtyConfig:
    return DirtyConfig()


def init_state(cfg: DirtyConfig) -> DirtyState:
    return DirtyState(
        big=jnp.zeros((cfg.lanes, cfg.window), jnp.int32),
        echo=jnp.zeros((cfg.lanes, cfg.window), jnp.int32),
        ghost=jnp.zeros((cfg.lanes,), jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


def tick(cfg, state: DirtyState, t, key) -> DirtyState:
    k1, _k2 = jax.random.split(key)
    # Seeded violation: k1 feeds TWO independent draws (stream reuse).
    d1 = jax.random.bits(k1, (cfg.lanes,))
    d2 = jax.random.uniform(k1, (cfg.lanes,))
    # Seeded violation: a key minted inside the tick (foreign root).
    smuggled = jax.random.bits(jax.random.PRNGKey(0), (cfg.lanes,))
    # Seeded violation: fold constants from TWO declared families.
    kmix = jax.random.fold_in(
        jax.random.fold_in(key, FAULT_SALT), WORKLOAD_SALT
    )
    d3 = jax.random.bits(kmix, (cfg.lanes,))
    # Seeded violation: offset escapes the workload family span.
    kesc = jax.random.fold_in(key, WORKLOAD_SALT + 300)
    d4 = jax.random.bits(kesc, (cfg.lanes,))
    mix = (d1 + smuggled + d3 + d4).astype(jnp.int32) % 7 + (
        d2 > 0.5
    ).astype(jnp.int32)
    # Producer of the new plane FIRST...
    new_big = state.big + mix[:, None]
    # ...then the seeded post-alias read of the OLD plane.
    echo = state.big * 2
    # Seeded violation: self-feeding write through a local alias.
    g = state.ghost + 1
    return DirtyState(
        big=new_big,
        echo=echo,
        ghost=g,
        count=state.count + jnp.sum(mix),
    )


def check_invariants(cfg, state: DirtyState, t) -> dict:
    # Reads big/echo/count — but never ghost.
    return {
        "big_nonneg": jnp.all(state.big >= 0),
        "echo_even": jnp.all(state.echo % 2 == 0),
        "count_bounds": state.count >= 0,
    }
