# Synthetic CLEAN workload module for the analysis-engine tests:
# every float field of the Plan is range-checked in validate().
import dataclasses


@dataclasses.dataclass(frozen=True)
class ToyWorkloadPlan:
    rate: float = 0.0
    read_fraction: float = 0.0
    closed_window: int = 0

    def validate(self) -> None:
        assert self.rate >= 0.0
        assert 0.0 <= self.read_fraction < 1.0
        assert self.closed_window >= 0
