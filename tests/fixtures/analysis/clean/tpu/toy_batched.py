# Synthetic CLEAN backend for the analysis-engine tests: satisfies
# every AST-layer contract rule. Parsed only, never imported — names
# like FaultPlan/Telemetry need not resolve.
import dataclasses
import functools

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ToyConfig:
    n: int = 4
    loss_rate: float = 0.0
    faults: FaultPlan = None  # noqa: F821
    workload: WorkloadPlan = None  # noqa: F821

    def __post_init__(self):
        assert 0.0 <= self.loss_rate <= 1.0, self.loss_rate
        self.faults.validate(self.n)
        self.workload.validate()


@dataclasses.dataclass
class ToyState:
    counter: jnp.ndarray
    telemetry: Telemetry  # noqa: F821


def init_state(cfg: ToyConfig) -> ToyState:
    return ToyState(
        counter=jnp.zeros((cfg.n,), jnp.int32),
        telemetry=make_telemetry(),  # noqa: F821
    )


class ToyShaper:
    def scale(self, x):
        # A traced method — the method-following walk must NOT flag
        # pure helpers reached through attribute calls.
        return x * 2


def _double(x):
    return x + x


# A switch table of traced helpers: dispatching through it is clean.
_SHAPERS = {"double": _double}


def tick(cfg: ToyConfig, state: ToyState, t, key):
    drop = faults_mod.message_faults(cfg.faults, key)  # noqa: F821
    cap = workload_mod.admission(cfg.workload, state, drop)  # noqa: F821
    cap = ToyShaper().scale(cap)
    cap = _SHAPERS["double"](cap)
    tel = record(state.telemetry, commits=state.counter)  # noqa: F821
    return dataclasses.replace(
        state, counter=state.counter + cap - drop, telemetry=tel
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(cfg: ToyConfig, state: ToyState, t0, num_ticks: int, key):
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks)
    )
    return state, t


def stats(cfg, state, t) -> dict:
    # Reads every State field, so nothing is a dead write.
    return {
        "counter": int(state.counter.sum()),
        "telemetry": state.telemetry,
    }


def mark_packed(state, idx):
    # packing-containment compliant: the occupancy bitmap is touched
    # only through the tpu/packing.py helpers (parse-only fixture —
    # `packing` need not resolve).
    return packing.occ_set(state.sess_occ, idx)  # noqa: F821
