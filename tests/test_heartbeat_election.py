"""Sim-transport-driven tests of the heartbeat failure detector and the two
leader-election protocols."""

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.election import basic, raft
from frankenpaxos_tpu.heartbeat import HeartbeatOptions
from frankenpaxos_tpu.heartbeat import Participant as HeartbeatParticipant


def drain(t, max_steps=10000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps, "message storm"


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def make_heartbeat(n=3):
    t = SimTransport(FakeLogger())
    addrs = [SimAddress(f"hb{i}") for i in range(n)]
    clock = FakeClock()
    parts = [
        HeartbeatParticipant(
            a, t, FakeLogger(), addrs,
            HeartbeatOptions(num_retries=2), clock,
        )
        for a in addrs
    ]
    return t, addrs, parts, clock


def test_heartbeat_alive_and_delay():
    t, addrs, parts, clock = make_heartbeat()
    clock.now = 1.0
    drain(t)
    p0 = parts[0]
    assert p0.unsafe_alive() == set(addrs)
    delays = p0.unsafe_network_delay()
    assert all(d < float("inf") for d in delays.values())


def test_heartbeat_detects_failure():
    t, addrs, parts, clock = make_heartbeat()
    drain(t)
    p0 = parts[0]
    dead = addrs[2]
    t.partition_actor(dead)
    # After the initial pong the fail timer is stopped and the success timer
    # armed; fire the success timer to restart the ping/fail cycle, then let
    # the fail timer expire num_retries times.
    t.trigger_timer(addrs[0], f"successTimer{dead}")
    drain(t)
    for _ in range(2):
        t.trigger_timer(addrs[0], f"failTimer{dead}")
        drain(t)
    assert dead not in p0.unsafe_alive()
    assert p0.unsafe_network_delay()[dead] == float("inf")
    # Revive: unpartition, ping again via success/fail timer.
    t.unpartition_actor(dead)
    t.trigger_timer(addrs[0], f"failTimer{dead}")
    drain(t)
    assert dead in p0.unsafe_alive()


def make_basic_election(n=3):
    t = SimTransport(FakeLogger())
    addrs = [SimAddress(f"e{i}") for i in range(n)]
    parts = [
        basic.Participant(a, t, FakeLogger(), addrs, initial_leader_index=0, seed=i)
        for i, a in enumerate(addrs)
    ]
    return t, addrs, parts


def test_basic_election_initial_leader_pings():
    t, addrs, parts = make_basic_election()
    assert parts[0].state == basic.State.LEADER
    assert parts[1].state == basic.State.FOLLOWER
    t.trigger_timer(addrs[0], "pingTimer")
    assert len(t.messages) == 2  # pings to the other two
    drain(t)
    assert parts[1].leader_index == 0


def test_basic_election_failover():
    t, addrs, parts = make_basic_election()
    changes = []
    parts[1].register(lambda li: changes.append(li))
    t.partition_actor(addrs[0])
    # Follower 1 times out and becomes leader of round 1.
    t.trigger_timer(addrs[1], "noPingTimer")
    assert parts[1].state == basic.State.LEADER
    assert parts[1].round == 1
    assert changes == [1]
    drain(t)
    assert parts[2].leader_index == 1  # learned the new leader

    # Old leader comes back, hears the bigger ballot, steps down.
    t.unpartition_actor(addrs[0])
    t.trigger_timer(addrs[1], "pingTimer")
    drain(t)
    assert parts[0].state == basic.State.FOLLOWER
    assert parts[0].leader_index == 1


def test_basic_election_force_no_ping():
    t, addrs, parts = make_basic_election()
    ch = parts[0].chan(addrs[2])
    ch.send(basic.ForceNoPing())
    drain(t)
    assert parts[2].state == basic.State.LEADER
    assert parts[2].round >= 1


def make_raft_election(n=3, with_leader=True):
    t = SimTransport(FakeLogger())
    addrs = [SimAddress(f"r{i}") for i in range(n)]
    parts = [
        raft.Participant(
            a, t, FakeLogger(), addrs,
            leader=addrs[0] if with_leader else None, seed=i,
        )
        for i, a in enumerate(addrs)
    ]
    return t, addrs, parts


def test_raft_initial_roles():
    t, addrs, parts = make_raft_election()
    assert isinstance(parts[0].state, raft.Leader)
    assert isinstance(parts[1].state, raft.Follower)


def test_raft_election_from_scratch():
    t, addrs, parts = make_raft_election(with_leader=False)
    assert all(isinstance(p.state, raft.LeaderlessFollower) for p in parts)
    elected = []
    parts[1].register(lambda a: elected.append(a))
    # Node 1 times out and stands for election.
    t.trigger_timer(addrs[1], "noPingTimer")
    assert isinstance(parts[1].state, raft.Candidate)
    drain(t)
    assert isinstance(parts[1].state, raft.Leader)
    assert elected and elected[0] == addrs[1]
    assert all(
        isinstance(p.state, raft.Follower) for p in (parts[0], parts[2])
    )
    assert parts[0].state.leader == addrs[1]


def test_raft_failover_and_step_down():
    t, addrs, parts = make_raft_election()
    t.partition_actor(addrs[0])
    t.trigger_timer(addrs[2], "noPingTimer")
    drain(t)
    assert isinstance(parts[2].state, raft.Leader)
    assert parts[2].round == 1
    # The old leader reappears; new leader's ping demotes it.
    t.unpartition_actor(addrs[0])
    t.trigger_timer(addrs[2], "pingTimer")
    drain(t)
    assert isinstance(parts[0].state, raft.Follower)
    assert parts[0].state.leader == addrs[2]
