"""Batched Compartmentalized MultiPaxos: role-decoupled planes
(batchers / proxy leaders / acceptor grid / replicas / unbatchers /
read replicas), dtype-policy bit-identity, and fault semantics.

Compile budget: tests share ONE canonical 120-tick run of the
analysis_config (module fixture) wherever possible, and every
run_ticks call sticks to tick counts already compiled for its config
(num_ticks is a static argument — a new count is a new XLA program).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.tpu import compartmentalized_batched as cb
from frankenpaxos_tpu.tpu.common import widen_state
from frankenpaxos_tpu.tpu.faults import FaultPlan


def _run(cfg, ticks, seed=0, state=None, t0=None):
    state = cb.init_state(cfg) if state is None else state
    t0 = jnp.zeros((), jnp.int32) if t0 is None else t0
    return cb.run_ticks(cfg, state, t0, ticks, jax.random.PRNGKey(seed))


def _assert_invariants(cfg, state, t):
    inv = {k: bool(v) for k, v in cb.check_invariants(cfg, state, t).items()}
    assert all(inv.values()), inv


@pytest.fixture(scope="module")
def base_run():
    """One 120-tick run of the canonical config, shared by every test
    that only needs to OBSERVE a healthy pipeline."""
    cfg = cb.analysis_config()
    state, t = _run(cfg, 120)
    jax.block_until_ready(state)
    return cfg, state, t


def test_pipeline_progress_and_invariants(base_run):
    """The full pipeline moves: commands batch, batches commit through
    the grid, replicas execute, unbatchers reply, reads serve — and
    every invariant holds."""
    cfg, state, t = base_run
    _assert_invariants(cfg, state, t)
    s = cb.stats(cfg, state, t)
    assert s["committed_entries"] > 0
    assert s["batches_committed"] * cfg.batch_size == s["committed_entries"]
    assert 0 < s["writes_done"] <= s["committed_entries"]
    assert s["reads_done"] > 0
    assert s["proxy_msgs_total"] > 0
    assert s["unbatcher_replies_total"] > 0
    assert int(state.retired) <= int(state.batches_committed)


def test_roles_absorb_load_evenly(base_run):
    """Slot % P round-robin keeps proxy-leader load balanced (the
    compartmentalization premise: the role scales by adding members,
    none of which becomes the new bottleneck)."""
    _, state, _ = base_run
    pm = np.asarray(jax.device_get(state.proxy_msgs), dtype=np.float64)
    assert pm.min() > 0
    assert pm.max() / pm.mean() < 1.5, pm
    um = np.asarray(jax.device_get(state.unbat_msgs), dtype=np.float64)
    assert um.min() > 0


def test_telemetry_ring_records_pipeline(base_run):
    """The device-side ring sees the role planes: proposals (admitted
    commands), phase2 traffic, commits, executes, and read probes as
    phase1 messages."""
    from frankenpaxos_tpu.tpu.telemetry import COL

    _, state, _ = base_run
    totals = jax.device_get(state.telemetry.totals)
    assert int(state.telemetry.ticks) == 120
    assert totals[COL["proposals"]] > 0
    assert totals[COL["phase1_msgs"]] > 0  # read-quorum probes
    assert totals[COL["phase2_msgs"]] > 0
    assert int(totals[COL["commits"]]) == int(state.committed)
    assert totals[COL["executes"]] > 0


def test_none_plan_matches_explicit_default(base_run):
    """FaultPlan.none() is structural: a config built with an explicit
    none() equals the default-plan config (same jit cache entry) and
    replays identically."""
    cfg, state, _ = base_run
    cfg_b = cb.analysis_config(faults=FaultPlan.none())
    assert cfg_b == cfg and hash(cfg_b) == hash(cfg)
    sb, _ = _run(cfg_b, 120)
    for a, b in zip(
        jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(sb)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_narrow_vs_widened_bit_identity_three_seeds():
    """The dtype policy is storage-only: running the SAME tick on a
    widen_state()-upcast state replays bit for bit (int16 offset
    clocks, int8 statuses). Ticks are jitted once per dtype path."""
    cfg = cb.analysis_config()
    step = jax.jit(lambda s, t, k: cb.tick(cfg, s, t, k))
    for seed in (0, 1, 2):
        key = jax.random.PRNGKey(seed)
        narrow = cb.init_state(cfg)
        wide = widen_state(cb.init_state(cfg))
        t = jnp.zeros((), jnp.int32)
        for i in range(40):
            k = jax.random.fold_in(key, i)
            narrow = step(narrow, t, k)
            wide = step(wide, t, k)
            t = t + 1
        for a, b in zip(
            jax.tree_util.tree_leaves(widen_state(narrow)),
            jax.tree_util.tree_leaves(wide),
        ):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_partition_defers_and_heals_writes_and_reads():
    """Cutting grid cells degrades the write path (retries route around
    the cut transversal members) and defers read probes on cut rows;
    after the scheduled heal BOTH planes resume, and invariants hold
    throughout. (One 80-tick program, invoked twice.)"""
    plan = FaultPlan(
        partition=(0, 1, 1, 0), partition_start=10, partition_heal=80
    )
    cfg = cb.analysis_config(faults=plan)
    state, t = _run(cfg, 80)
    _assert_invariants(cfg, state, t)
    mid_committed = int(state.committed)
    mid_reads = int(state.reads_done)
    state, t = _run(cfg, 80, seed=99, state=state, t0=t)
    _assert_invariants(cfg, state, t)
    assert int(state.committed) > mid_committed, "writes did not resume"
    assert int(state.reads_done) > mid_reads, "reads did not resume"


def test_dead_proxies_stall_their_slots_until_revival():
    """Proxy leaders are the crash axis: with every proxy dead nothing
    new commits (votes cannot be collected, Phase2a cannot fan out);
    restoring the plane resumes progress. (Reuses the fixture's
    120-tick program — no extra compile.)"""
    cfg = cb.analysis_config()
    state, t = _run(cfg, 120)
    base = int(state.committed)
    dead = dataclasses.replace(
        state, proxy_alive=jnp.zeros_like(state.proxy_alive)
    )
    dead, t = _run(cfg, 120, seed=5, state=dead, t0=t)
    _assert_invariants(cfg, dead, t)
    assert int(dead.committed) == base, "commits advanced with proxies dead"
    revived = dataclasses.replace(
        dead, proxy_alive=jnp.ones_like(dead.proxy_alive)
    )
    revived, t = _run(cfg, 120, seed=6, state=revived, t0=t)
    _assert_invariants(cfg, revived, t)
    assert int(revived.committed) > base, "commits did not resume"


@pytest.mark.slow
def test_reads_scale_with_replicas_and_batching_amplifies():
    """The two compartmentalization scaling axes, measured head to
    head: doubling the read-replica count ~doubles served reads (reads
    never touch the write quorums), and 4x the batch size moves ~4x
    the entries through the SAME number of protocol messages
    (HT-Paxos batching economics)."""
    few = dataclasses.replace(cb.analysis_config(), num_replicas=2)
    many = dataclasses.replace(cb.analysis_config(), num_replicas=4)
    sf, _ = _run(few, 80)
    sm, _ = _run(many, 80)
    ratio = int(sm.reads_done) / max(int(sf.reads_done), 1)
    assert 1.6 < ratio < 2.4, (int(sf.reads_done), int(sm.reads_done))

    small = dataclasses.replace(
        cb.analysis_config(), batch_size=1, arrivals_per_tick=1
    )
    big = dataclasses.replace(
        cb.analysis_config(), batch_size=4, arrivals_per_tick=4
    )
    ss, _ = _run(small, 80)
    sb, _ = _run(big, 80)
    entries_ratio = int(sb.committed) / max(int(ss.committed), 1)
    assert entries_ratio > 3.0, (int(ss.committed), int(sb.committed))
    batches_ratio = int(sb.batches_committed) / max(
        int(ss.batches_committed), 1
    )
    assert 0.7 < batches_ratio < 1.4, (
        int(ss.batches_committed), int(sb.batches_committed),
    )


def test_analysis_config_traces_fast_and_is_hashable():
    """The canonical small config is a valid static jit argument (the
    retrace-guard contract) and reaches every plane."""
    cfg_a = cb.analysis_config()
    cfg_b = cb.analysis_config()
    assert cfg_a == cfg_b and hash(cfg_a) == hash(cfg_b)
    assert cfg_a.read_rate > 0 and cfg_a.num_replicas > 1
    assert cfg_a.acceptors_per_group == 4
