"""Tests of the batched Faster Paxos backend
(tpu/fasterpaxos_batched.py): delegate slot-partitioning
(fasterpaxos/Server.scala:315-340), dead-delegate leader changes with
seating rotation (Server.scala:497-530), hole noop-fills, stale-round
rejection, and the choose-once ledger."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu import fasterpaxos_batched as fp


def run_random(cfg, seed, ticks):
    key = jax.random.PRNGKey(seed)
    state, t = fp.run_ticks(cfg, fp.init_state(cfg), jnp.int32(0), ticks, key)
    return state, t


def test_delegates_partition_and_progress():
    cfg = fp.BatchedFasterPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2,
        lat_min=1, lat_max=3,
    )
    state, t = run_random(cfg, seed=0, ticks=200)
    s = fp.stats(cfg, state, t)
    # Both seats of every group commit: ~K * D * G per tick sustained.
    assert s["committed_real"] > 8 * 2 * 150
    assert s["leader_changes"] == 0
    assert s["choose_violations"] == 0
    assert s["executed_global"] > 0
    inv = fp.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_dead_delegate_triggers_leader_change_and_recovery():
    """Kill the server seating delegate 0 of every group: the stripe
    stalls, detection fires, the leader change rotates the seating, and
    the log flows again with holes noop-filled."""
    cfg = fp.BatchedFasterPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, detect_timeout=4, revive_rate=0.0,
    )
    key = jax.random.PRNGKey(1)
    state = fp.init_state(cfg)
    t = 0
    for _ in range(30):
        state = fp.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    wm_before = int(jax.device_get(state.group_wm).sum())
    # Server 0 serves seat 0 (seat_epoch 0) in every group: kill it.
    state = dataclasses.replace(
        state, server_alive=state.server_alive.at[0, :].set(False)
    )
    for _ in range(120):
        state = fp.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    s = fp.stats(cfg, state, jnp.int32(t))
    assert s["leader_changes"] >= 4  # every group changed leaders
    assert s["noop_fills"] > 0  # the dead seat's holes were filled
    assert s["executed_global"] > wm_before + 100  # the log flows again
    assert s["choose_violations"] == 0
    inv = fp.check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv
    # The new seating avoids the dead server.
    seat_server = np.asarray(fp._seat_server(cfg, state.seat_epoch))
    assert (seat_server != 0).all()


def test_stale_round_phase2a_rejected():
    """An acceptor that promised round 1 must reject a straggling
    round-0 Phase2a (no vote recorded)."""
    cfg = fp.BatchedFasterPaxosConfig(
        f=1, num_groups=2, window=8, slots_per_tick=1,
        lat_min=1, lat_max=1,
    )
    state = fp.init_state(cfg)
    state = dataclasses.replace(
        state,
        status=state.status.at[0, 0, 0].set(fp.PROPOSED),
        slot_value=state.slot_value.at[0, 0, 0].set(7),
        next_ord=state.next_ord.at[0, 0].set(1),
        acc_round=state.acc_round.at[0, 0].set(1),  # promised round 1
        p2a_arrival=state.p2a_arrival.at[0, 0, 0, 0].set(5),
        p2a_round=state.p2a_round.at[0, 0, 0, 0].set(0),  # stale round
    )
    state = fp.tick(cfg, state, jnp.int32(5), jax.random.PRNGKey(2))
    assert int(state.vote_round[0, 0, 0, 0]) == -1  # rejected
    assert int(state.p2a_arrival[0, 0, 0, 0]) == fp.INF  # consumed


def test_churn_invariants_random():
    """Continuous server churn: leader changes fire, seatings rotate,
    safety holds, progress continues."""
    cfg = fp.BatchedFasterPaxosConfig(
        f=1, num_groups=16, window=16, slots_per_tick=2,
        lat_min=1, lat_max=3, fail_rate=0.01, revive_rate=0.15,
        detect_timeout=4, drop_rate=0.05,
    )
    state, t = run_random(cfg, seed=3, ticks=400)
    s = fp.stats(cfg, state, t)
    assert s["deaths"] > 0
    assert s["leader_changes"] > 0
    assert s["committed_real"] > 2000
    assert s["choose_violations"] == 0
    inv = fp.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_throughput_dip_during_leader_change():
    """Per-tick committed counts around an injected death show the
    stall-detect-recover timeline."""
    cfg = fp.BatchedFasterPaxosConfig(
        f=1, num_groups=32, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, detect_timeout=6, revive_rate=0.0,
    )
    key = jax.random.PRNGKey(4)
    state = fp.init_state(cfg)
    t = 0
    per_tick = []
    for _ in range(40):
        before = int(state.committed)
        state = fp.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        per_tick.append(int(state.committed) - before)
        t += 1
    steady = sorted(per_tick[20:])[10]
    state = dataclasses.replace(
        state, server_alive=state.server_alive.at[0, :].set(False)
    )
    dip = []
    for _ in range(60):
        before = int(state.committed)
        state = fp.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        dip.append(int(state.committed) - before)
        t += 1
    # The dead seats halve throughput until recovery; afterwards the
    # rate returns to ~steady.
    assert min(dip[:10]) < steady
    assert sorted(dip[-20:])[10] >= steady // 2
    inv = fp.check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv
