import random

import pytest

from frankenpaxos_tpu.clienttable import ClientTable, Executed, NotExecuted
from frankenpaxos_tpu.thrifty import Closest, NotThrifty, RandomThrifty, from_name


def test_client_table_in_order():
    t = ClientTable()
    assert t.executed("c", 0) == NotExecuted()
    t.execute("c", 0, b"out0")
    assert t.executed("c", 0) == Executed(b"out0")
    t.execute("c", 1, b"out1")
    assert t.executed("c", 1) == Executed(b"out1")
    assert t.executed("c", 0) == Executed(None)  # old id: executed, no cache
    assert t.executed("c", 2) == NotExecuted()


def test_client_table_out_of_order():
    # The EPaxos scenario from ClientTable.scala:44-60: replica executes
    # id 1 before id 0.
    t = ClientTable()
    t.execute("c", 1, b"y")
    assert t.executed("c", 1) == Executed(b"y")
    assert t.executed("c", 0) == NotExecuted()  # still executable!
    t.execute("c", 0, b"x")
    assert t.executed("c", 0) == Executed(None)  # not the largest -> no cache
    assert t.executed("c", 1) == Executed(b"y")


def test_client_table_double_execute_rejected():
    t = ClientTable()
    t.execute("c", 0, b"x")
    with pytest.raises(ValueError):
        t.execute("c", 0, b"x")


def test_client_table_proto_roundtrip():
    t = ClientTable()
    t.execute("alice", 0, b"a")
    t.execute("alice", 1, b"b")
    t.execute("bob", 5, b"c")
    proto = t.to_proto(lambda a: a.encode(), lambda o: o)
    t2 = ClientTable.from_proto(proto, lambda b: b.decode(), lambda b: b)
    assert t2.executed("alice", 1) == Executed(b"b")
    assert t2.executed("alice", 0) == Executed(None)
    assert t2.executed("bob", 5) == Executed(b"c")
    assert t2.executed("bob", 4) == NotExecuted()


def test_thrifty():
    rng = random.Random(0)
    delays = {"a": 3.0, "b": 1.0, "c": 2.0, "d": float("inf")}
    assert NotThrifty().choose(delays, 2, rng) == {"a", "b", "c", "d"}
    picked = RandomThrifty().choose(delays, 2, rng)
    assert len(picked) == 2 and picked <= set(delays)
    assert Closest().choose(delays, 2, rng) == {"b", "c"}
    assert isinstance(from_name("Closest"), Closest)
    with pytest.raises(ValueError):
        from_name("nope")


def test_closest_thrifty_end_to_end_with_live_ewma_delays():
    """VERDICT gap: thrifty Closest exercised against LIVE heartbeat EWMA
    delays (ThriftySystem.scala:29-80 + Heartbeat network_delay), end to
    end on a SimTransport with per-peer delivery delays controlled via
    the fake clock: the observer pings its acceptors, pongs return after
    different simulated one-way delays, and Closest.choose over
    unsafe_network_delay() must pick the actually-nearest quorum — then
    ADAPT when the topology changes and the EWMA re-converges."""
    from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
    from frankenpaxos_tpu.heartbeat import (
        HeartbeatOptions,
        Participant as HeartbeatParticipant,
    )
    from frankenpaxos_tpu.thrifty import Closest

    class FakeClock:
        def __init__(self):
            self.now = 0.0

        def __call__(self):
            return self.now

    t = SimTransport(FakeLogger())
    observer = SimAddress("leader")
    acceptors = [SimAddress(f"acc{i}") for i in range(3)]
    clock = FakeClock()
    # Every node participates so pings AND pongs flow both ways; only
    # the observer's delay table is read.
    parts = {
        a: HeartbeatParticipant(
            a, t, FakeLogger(), [observer] + acceptors,
            HeartbeatOptions(network_delay_alpha=0.5), clock,
        )
        for a in [observer] + acceptors
    }

    def exchange(delays_by_peer):
        """One ping/pong round from the observer with per-peer one-way
        delays: deliver each peer's traffic only once the clock has
        advanced 2 * delay past the ping send."""
        # Restart the observer's heartbeat cycle toward every acceptor:
        # after a pong, successTimer is the one running — firing it
        # sends the next ping (and arms the failTimer, which we leave
        # alone so each round is exactly one ping/pong exchange).
        for a in acceptors:
            t.trigger_timer(observer, f"successTimer{a}")
        send_time = clock.now
        for a in sorted(acceptors, key=lambda x: delays_by_peer[x]):
            clock.now = send_time + 2 * delays_by_peer[a]
            # Deliver everything addressed to or from this peer that is
            # queued right now (ping out, pong back).
            for _ in range(200):
                pending = [
                    m for m in list(t.messages)
                    if m.dst == a or (m.src == a and m.dst == observer)
                ]
                if not pending:
                    break
                for m in pending:
                    t.deliver_message(m)

    rng = random.Random(0)
    # Establish the heartbeat mesh first (instant delivery, delay 0).
    for _ in range(400):
        if not t.messages:
            break
        t.deliver_message(t.messages[0])
    # Initial topology: acc0 is closest, acc2 farthest.
    topo = {acceptors[0]: 1.0, acceptors[1]: 5.0, acceptors[2]: 9.0}
    for _ in range(4):
        exchange(topo)
    delays = {
        a: d
        for a, d in parts[observer].unsafe_network_delay().items()
        if a in acceptors  # the quorum domain is the acceptor set
    }
    assert all(d < float("inf") for d in delays.values())
    chosen = Closest().choose(delays, 2, rng)
    assert chosen == {acceptors[0], acceptors[1]}, (chosen, delays)

    # Topology flips: acc2 becomes nearest. The EWMA (alpha=0.5) must
    # re-converge within a few rounds and Closest must follow.
    topo = {acceptors[0]: 9.0, acceptors[1]: 5.0, acceptors[2]: 1.0}
    for _ in range(6):
        exchange(topo)
    delays = {
        a: d
        for a, d in parts[observer].unsafe_network_delay().items()
        if a in acceptors
    }
    chosen = Closest().choose(delays, 2, rng)
    assert chosen == {acceptors[2], acceptors[1]}, (chosen, delays)
