import random

import pytest

from frankenpaxos_tpu.clienttable import ClientTable, Executed, NotExecuted
from frankenpaxos_tpu.thrifty import Closest, NotThrifty, RandomThrifty, from_name


def test_client_table_in_order():
    t = ClientTable()
    assert t.executed("c", 0) == NotExecuted()
    t.execute("c", 0, b"out0")
    assert t.executed("c", 0) == Executed(b"out0")
    t.execute("c", 1, b"out1")
    assert t.executed("c", 1) == Executed(b"out1")
    assert t.executed("c", 0) == Executed(None)  # old id: executed, no cache
    assert t.executed("c", 2) == NotExecuted()


def test_client_table_out_of_order():
    # The EPaxos scenario from ClientTable.scala:44-60: replica executes
    # id 1 before id 0.
    t = ClientTable()
    t.execute("c", 1, b"y")
    assert t.executed("c", 1) == Executed(b"y")
    assert t.executed("c", 0) == NotExecuted()  # still executable!
    t.execute("c", 0, b"x")
    assert t.executed("c", 0) == Executed(None)  # not the largest -> no cache
    assert t.executed("c", 1) == Executed(b"y")


def test_client_table_double_execute_rejected():
    t = ClientTable()
    t.execute("c", 0, b"x")
    with pytest.raises(ValueError):
        t.execute("c", 0, b"x")


def test_client_table_proto_roundtrip():
    t = ClientTable()
    t.execute("alice", 0, b"a")
    t.execute("alice", 1, b"b")
    t.execute("bob", 5, b"c")
    proto = t.to_proto(lambda a: a.encode(), lambda o: o)
    t2 = ClientTable.from_proto(proto, lambda b: b.decode(), lambda b: b)
    assert t2.executed("alice", 1) == Executed(b"b")
    assert t2.executed("alice", 0) == Executed(None)
    assert t2.executed("bob", 5) == Executed(b"c")
    assert t2.executed("bob", 4) == NotExecuted()


def test_thrifty():
    rng = random.Random(0)
    delays = {"a": 3.0, "b": 1.0, "c": 2.0, "d": float("inf")}
    assert NotThrifty().choose(delays, 2, rng) == {"a", "b", "c", "d"}
    picked = RandomThrifty().choose(delays, 2, rng)
    assert len(picked) == 2 and picked <= set(delays)
    assert Closest().choose(delays, 2, rng) == {"b", "c"}
    assert isinstance(from_name("Closest"), Closest)
    with pytest.raises(ValueError):
        from_name("nope")
