"""Cross-check the dependency-graph implementations (the analog of
depgraph/DependencyGraphTest.scala): Tarjan, Zigzag (GC'd,
leader-striped), and the Kosaraju/Kahn-based Naive oracle must execute
the same vertex sets in dependency-respecting orders."""

import random

import pytest

from frankenpaxos_tpu.depgraph import (
    IncrementalTarjanDependencyGraph,
    NaiveDependencyGraph,
    TarjanDependencyGraph,
    ZigzagTarjanDependencyGraph,
)


def check_order(executed_order, committed):
    """Every executed vertex's committed dependencies must appear before
    it unless they share a strongly connected component (approximated:
    mutual reachability isn't rechecked here — instead we only require
    deps that were executed EARLIER OR in the same component; for
    cross-checking we verify deps are not executed AFTER unless there is
    a cycle between them)."""
    position = {k: i for i, k in enumerate(executed_order)}
    for key in executed_order:
        _, deps = committed[key]
        for dep in deps:
            if dep in position:
                # A dependency executed strictly later implies a cycle
                # (same SCC); verify mutual reachability via committed
                # edges restricted to the executed set.
                if position[dep] > position[key]:
                    assert _reaches(dep, key, committed), (
                        f"{key} executed before its dependency {dep} "
                        f"without a cycle"
                    )


def _reaches(a, b, committed, limit=10000):
    seen = {a}
    frontier = [a]
    steps = 0
    while frontier and steps < limit:
        node = frontier.pop()
        steps += 1
        if node == b:
            return True
        for dep in committed.get(node, (None, ()))[1]:
            if dep in committed and dep not in seen:
                seen.add(dep)
                frontier.append(dep)
    return a == b or b in seen


@pytest.mark.parametrize("seed", range(8))
def test_depgraph_implementations_agree(seed):
    rng = random.Random(seed)
    num_leaders = 3
    graphs = {
        "tarjan": TarjanDependencyGraph(),
        "incremental": IncrementalTarjanDependencyGraph(),
        "naive": NaiveDependencyGraph(),
        "zigzag": ZigzagTarjanDependencyGraph(
            num_leaders, garbage_collect_every_n_commands=20
        ),
    }
    executed = {name: [] for name in graphs}
    committed = {}
    next_id = [0] * num_leaders
    in_flight = []

    for step in range(200):
        action = rng.random()
        if action < 0.6 or not in_flight:
            # Commit a fresh vertex with deps on existing (and sometimes
            # not-yet-committed) vertices.
            leader = rng.randrange(num_leaders)
            key = (leader, next_id[leader])
            next_id[leader] += 1
            deps = set()
            pool = list(committed) + in_flight
            for _ in range(rng.randrange(4)):
                if pool:
                    deps.add(rng.choice(pool))
            if rng.random() < 0.3:
                # A dependency on a vertex that does not exist yet. Claim
                # its id NOW so no later fresh commit reuses the key.
                future_leader = rng.randrange(num_leaders)
                future = (future_leader, next_id[future_leader])
                next_id[future_leader] += 1
                deps.add(future)
                in_flight.append(future)
            committed[key] = (step, deps)
            if key in in_flight:
                in_flight.remove(key)
            for g in graphs.values():
                g.commit(key, step, deps)
        else:
            # Commit a previously promised in-flight vertex.
            key = in_flight.pop(rng.randrange(len(in_flight)))
            deps = set()
            for _ in range(rng.randrange(3)):
                if committed:
                    deps.add(rng.choice(list(committed)))
            committed[key] = (step, deps)
            for g in graphs.values():
                g.commit(key, step, deps)
        if rng.random() < 0.5:
            for name, g in graphs.items():
                keys, _ = g.execute()
                executed[name].extend(keys)

    # Fill every promised hole: zigzag executes columns in id order, so
    # a PERMANENTLY uncommitted vertex parks the rest of its column (by
    # design — EPaxos-family ids are contiguous and holes get recovered).
    for key in list(in_flight):
        committed[key] = (10 ** 6 + key[1], set())
        for g in graphs.values():
            g.commit(key, 10 ** 6 + key[1], set())
    in_flight.clear()
    # Final drain. Zigzag's frontier walk may defer vertices unblocked
    # by a LATER column to the next invocation (the protocols call
    # execute() per commit, so this self-heals there) — loop until
    # quiescent.
    for name, g in graphs.items():
        for _ in range(1000):
            keys, blockers = g.execute()
            executed[name].extend(keys)
            if not keys:
                break
        else:
            pytest.fail(f"{name} never quiesced")

    sets = {name: set(keys) for name, keys in executed.items()}
    assert (
        sets["tarjan"] == sets["incremental"] == sets["naive"] == sets["zigzag"]
    ), {name: len(s) for name, s in sets.items()}
    for name in graphs:
        assert len(executed[name]) == len(sets[name]), (
            f"{name} executed a vertex twice"
        )
        check_order(executed[name], committed)
    # After hole-filling, EVERY committed vertex must have executed.
    assert sets["tarjan"] == set(committed)


def test_zigzag_garbage_collects():
    g = ZigzagTarjanDependencyGraph(
        2, vertices_grow_size=8, garbage_collect_every_n_commands=10
    )
    for i in range(50):
        for leader in (0, 1):
            deps = {(1 - leader, i - 1)} if i > 0 else set()
            g.commit((leader, i), i, deps)
        keys, blockers = g.execute()
    assert g.num_vertices == 0
    # The per-leader vertex buffers have been GC'd up to the watermark.
    for leader in (0, 1):
        assert g.vertices[leader].watermark > 0
        assert g.executed[leader].watermark == 50
    # And the graph still works after GC.
    g.commit((0, 50), 50, {(1, 49)})
    keys, _ = g.execute()
    assert keys == [(0, 50)]


def test_zigzag_blockers_and_update_executed():
    g = ZigzagTarjanDependencyGraph(2)
    g.commit((0, 0), 0, {(1, 0)})
    keys, blockers = g.execute()
    assert keys == []
    assert blockers == {(1, 0)}
    # Learn that (1, 0) was executed externally (e.g. via snapshot).
    g.update_executed({(1, 0)})
    keys, blockers = g.execute()
    assert keys == [(0, 0)]
    # Zigzag reports each column's NEXT frontier hole as a blocker (the
    # reference does the same): ids are contiguous, so the hole is the
    # next thing to recover.
    assert blockers == {(0, 1), (1, 1)}
    # Regression: snapshot-executing an already-committed vertex must
    # evict it (num_vertices would otherwise over-report forever).
    g.commit((0, 1), 1, set())
    assert g.num_vertices == 1
    g.update_executed({(0, 1)})
    assert g.num_vertices == 0


def test_naive_matches_tarjan_on_cycles():
    a, b, c = ("a", 1), ("b", 2), ("c", 3)
    for graph in (TarjanDependencyGraph(), NaiveDependencyGraph()):
        graph.commit(a, 1, {b})
        graph.commit(b, 2, {a, c})
        keys, blockers = graph.execute()
        assert keys == []
        assert blockers == {c}
        graph.commit(c, 3, set())
        keys, blockers = graph.execute()
        # c first (dependency), then the {a, b} component sorted by seq.
        assert keys == [c, a, b]


def test_incremental_tarjan_pauses_and_resumes():
    """The incremental variant suspends on an uncommitted dependency,
    reports exactly that blocker, and resumes mid-pass once it commits
    (IncrementalTarjanDependencyGraph.scala: Paused/Success)."""
    g = IncrementalTarjanDependencyGraph()
    # a -> b -> c(uncommitted); d independent.
    g.commit("a", 0, {"b"})
    g.commit("b", 1, {"c"})
    g.commit("d", 2, set())
    components, blockers = g.execute_by_component()
    executed = {k for comp in components for k in comp}
    assert blockers == {"c"}
    assert "a" not in executed and "b" not in executed
    # The pass is suspended: metadata persists between calls.
    assert g.callstack, "expected a suspended pass"
    # Committing c unblocks the suspended chain; the resumed pass
    # executes c, b, a in dependency order.
    g.commit("c", 3, set())
    components, blockers = g.execute_by_component()
    order = [k for comp in components for k in comp]
    assert blockers == set()
    for k in ("a", "b", "c"):
        assert k in order
    assert order.index("c") < order.index("b") < order.index("a")
    # Everything executed exactly once across both calls.
    all_executed = [k for comp in components for k in comp] + sorted(executed)
    assert sorted(all_executed) == ["a", "b", "c", "d"]
    assert g.num_vertices == 0


def test_incremental_tarjan_cycle_executes_together():
    g = IncrementalTarjanDependencyGraph()
    g.commit("x", 0, {"y"})
    g.commit("y", 1, {"x"})
    components, blockers = g.execute_by_component()
    assert blockers == set()
    assert [sorted(c) for c in components] == [["x", "y"]]
    # Sequence-number order within the component.
    assert components[0] == ["x", "y"]


def test_incremental_tarjan_update_executed_guard():
    g = IncrementalTarjanDependencyGraph()
    g.commit("a", 0, {"missing"})
    g.execute_by_component()  # pauses
    with pytest.raises(NotImplementedError):
        g.update_executed({"other"})
