"""EPaxos sim tests (the analog of shared/src/test/scala/epaxos): replicas
may execute non-conflicting commands in different orders, but conflicting
commands must execute in the same relative order everywhere."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport, wire
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import epaxos as ep
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set


class RecordingKv(KeyValueStore):
    """KeyValueStore that records executed commands for invariants."""

    def __init__(self):
        super().__init__()
        self.executed_commands = []

    def run(self, input: bytes) -> bytes:
        self.executed_commands.append(input)
        return super().run(input)


def make(f=1, num_clients=2, seed=0,
         options=ep.EPaxosReplicaOptions()):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    config = ep.EPaxosConfig(
        f=f,
        replica_addresses=tuple(
            SimAddress(f"replica{i}") for i in range(2 * f + 1)
        ),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    replicas = [
        ep.EpReplica(a, t, log(), config, RecordingKv(), options,
                     seed=seed + i)
        for i, a in enumerate(config.replica_addresses)
    ]
    clients = [
        ep.EpClient(SimAddress(f"client{i}"), t, log(), config, seed=seed + 20 + i)
        for i in range(num_clients)
    ]
    return t, config, replicas, clients


def drain(t, max_steps=100000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def test_epaxos_single_command():
    t, config, replicas, clients = make()
    p = clients[0].propose(0, kv_set(("x", "1")))
    drain(t)
    assert p.done
    # Every replica executed it (commit broadcast + graph execution).
    for r in replicas:
        assert r.state_machine.get() == {"x": "1"}


def test_epaxos_fast_path_uncontended():
    """An uncontended command commits without any Accept messages."""
    t, config, replicas, clients = make()
    clients[0].propose(0, kv_set(("x", "1")))
    accepts_seen = []
    while t.messages:
        m = t.messages[0]
        decoded = wire.decode(m.data)
        if isinstance(decoded, ep.EpAccept):
            accepts_seen.append(decoded)
        t.deliver_message(m)
    assert accepts_seen == []


def test_epaxos_sequential_conflicting_commands():
    t, config, replicas, clients = make()
    for i in range(5):
        p = clients[0].propose(0, kv_set(("x", f"{i}")))
        drain(t)
        assert p.done
    for r in replicas:
        assert r.state_machine.get() == {"x": "4"}


def test_epaxos_concurrent_conflicting_commands_converge():
    t, config, replicas, clients = make(seed=5)
    p1 = clients[0].propose(0, kv_set(("x", "a")))
    p2 = clients[1].propose(0, kv_set(("x", "b")))
    rng = random.Random(3)
    for _ in range(4000):
        cmd = t.generate_command(rng)
        if cmd is None:
            break
        t.run_command(cmd, record=False)
    drain(t)
    assert p1.done and p2.done
    finals = {tuple(sorted(r.state_machine.get().items())) for r in replicas}
    assert len(finals) == 1, f"replicas diverged: {finals}"


def _conflicting_order_violation(replicas, conflicts):
    """Check every pair of replicas executed conflicting commands in the
    same relative order; returns an explanation or None."""
    logs = [r.state_machine.executed_commands for r in replicas]
    for i in range(len(logs)):
        for j in range(i + 1, len(logs)):
            a, b = logs[i], logs[j]
            both = [c for c in a if c in b]
            pos_b = {}
            for idx, c in enumerate(b):
                pos_b.setdefault(c, idx)
            for x_idx in range(len(both)):
                for y_idx in range(x_idx + 1, len(both)):
                    x, y = both[x_idx], both[y_idx]
                    if not conflicts(x, y):
                        continue
                    if pos_b[x] > pos_b[y]:
                        return (
                            f"replicas {i} and {j} executed conflicting "
                            f"commands in different orders: {x!r} vs {y!r}"
                        )
    return None


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    key: str
    value: str


class SimulatedEPaxos(SimulatedSystem):
    def __init__(self, f=1, top_k=0):
        self.f = f
        self.top_k = top_k
        self._kv = KeyValueStore()

    def new_system(self, seed):
        return make(self.f, seed=seed, options=ep.EPaxosReplicaOptions(
            top_k_dependencies=self.top_k
        ))

    def get_state(self, system):
        t, config, replicas, clients = system
        return tuple(
            tuple(r.state_machine.executed_commands) for r in replicas
        )

    def generate_command(self, system, rng):
        t, config, replicas, clients = system
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    # Single- AND multi-key commands: multi-key writes
                    # conflict with instances that don't conflict with
                    # each other, the case that breaks naive top-k deps.
                    keys = "k0" if rng.random() < 0.5 else "k0,k1"
                    ops.append(
                        (1, Propose(i, pseudonym, keys,
                                    f"v{rng.randrange(50)}"))
                    )
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, config, replicas, clients = system
        if isinstance(command, Propose):
            clients[command.client_index].propose(
                command.pseudonym,
                kv_set(*[(k, command.value) for k in command.key.split(",")]),
            )
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        class _Fake:
            executed_commands: list

        fakes = []
        for log in state:
            fake = _Fake()
            sm = _Fake()
            sm.executed_commands = list(log)
            fake.state_machine = sm
            fakes.append(fake)
        return _conflicting_order_violation(fakes, self._kv.conflicts)


@pytest.mark.parametrize("f", [1, 2])
def test_epaxos_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedEPaxos(f), run_length=120, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


@pytest.mark.parametrize("top_k", [1, 2])
def test_epaxos_safety_randomized_top_k_dependencies(top_k):
    """Top-k dependency compression (only the k most recent conflicting
    instances per replica column) preserves execution-order agreement:
    the dropped older conflicts are transitively covered by the newer
    ones."""
    bad = simulate_and_minimize(
        SimulatedEPaxos(1, top_k=top_k), run_length=150, num_runs=10,
        seed=60 + top_k,
    )
    assert bad is None, f"\n{bad}"


def test_epaxos_prefix_deps_algebra():
    """EpPrefixDeps union/normalize agree with materialized-set semantics,
    and equal sets have equal canonical forms (fast-path equality)."""
    import itertools
    import random as _random

    rng = _random.Random(7)
    instance = (1, 2)
    for _ in range(200):
        wm_a = [rng.randrange(0, 5) for _ in range(3)]
        wm_b = [rng.randrange(0, 5) for _ in range(3)]
        a = ep._normalize_prefix_deps(
            list(wm_a), instance if instance[1] < wm_a[instance[0]] else None
        )
        b = ep._normalize_prefix_deps(
            list(wm_b), instance if instance[1] < wm_b[instance[0]] else None
        )
        u = ep._deps_union(a, b)
        assert isinstance(u, ep.EpPrefixDeps)
        assert ep._deps_materialize(u) == (
            ep._deps_materialize(a) | ep._deps_materialize(b)
        )
        assert instance not in ep._deps_materialize(u)
    # Canonicalization: top-of-column exclusion folds into the watermark.
    folded = ep._normalize_prefix_deps([3, 0, 0], (0, 2))
    plain = ep._normalize_prefix_deps([2, 0, 0], None)
    assert folded == plain


def test_epaxos_top_k_deps_are_prefix_shaped():
    """With top_k=1, dependency sets are contiguous per-column prefixes
    (compressible to one watermark per replica) and cover EVERY
    conflicting instance, not just the newest per column."""
    t, config, replicas, clients = make(
        seed=71, options=ep.EPaxosReplicaOptions(top_k_dependencies=1)
    )
    for i in range(12):
        p = clients[i % 2].propose(i // 2, kv_set(("hot", f"v{i}")))
        drain(t)
        assert p.done
    _, deps = replicas[0]._compute_seq_deps(
        (0, 999), ep.EpCommand(b"x", 0, 0, kv_set(("hot", "probe")))
    )
    # State/wire form is the compact O(columns) watermark vector, not a
    # materialized set (ADVICE r1: deps must not be O(instance history)).
    assert isinstance(deps, ep.EpPrefixDeps)
    assert len(deps.watermarks) == config.n
    materialized = ep._deps_materialize(deps)
    assert materialized
    by_col = {}
    for col, id in materialized:
        by_col.setdefault(col, set()).add(id)
    for col, ids in by_col.items():
        assert ids == set(range(max(ids) + 1)), (col, sorted(ids))


def test_epaxos_recovery_after_leader_failure():
    """A replica pre-accepts then its leader dies; the recover timer on a
    blocking instance runs Prepare and the instance eventually commits."""
    t, config, replicas, clients = make(seed=9)
    # Client proposes to replica 0.
    class _R0:
        def randrange(self, n):
            return 0

    clients[0].rng = _R0()
    p = clients[0].propose(0, kv_set(("x", "1")))
    # Deliver the request and the PreAccepts, but DROP all PreAcceptOks and
    # kill replica 0 (the instance leader).
    while t.messages:
        m = t.messages[0]
        if isinstance(wire.decode(m.data), ep.EpPreAcceptOk):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    t.partition_actor(config.replica_addresses[0])
    # A second, conflicting command from another client commits and depends
    # on the stuck instance, making it a blocker.
    class _R1:
        def randrange(self, n):
            return 1

    clients[1].rng = _R1()
    p2 = clients[1].propose(0, kv_set(("x", "2")))
    drain(t)
    # Let time pass: fire every running timer on the surviving replicas
    # (PreAccept resends reach the live replica; the dep graph blocks on
    # replica 0's instance; recover timers then run Prepare).
    recover_fired = 0
    alive = {r.address for r in replicas[1:]}
    for _ in range(8):
        for timer in list(t.running_timers()):
            if timer.address in alive:
                if timer.name().startswith("recoverInstance"):
                    recover_fired += 1
                t.trigger_timer(timer.address, timer.name())
        drain(t)
    assert recover_fired > 0, "no recover timer ever armed"
    assert p2.done, "recovery did not unblock the dependent command"
    # Replicas 1 and 2 agree.
    finals = {
        tuple(sorted(r.state_machine.get().items())) for r in replicas[1:]
    }
    assert len(finals) == 1


def test_execute_graph_flush_timer():
    """Regression: with execute_graph_batch_size > 1, a single commit (a
    partial batch) must still execute via the flush timer."""
    t, config, replicas, clients = make()
    # Rebuild replicas with batching enabled.
    for r in replicas:
        del t.actors[r.address]
    log = lambda: FakeLogger(LogLevel.FATAL)
    replicas = [
        ep.EpReplica(
            a, t, log(), config, RecordingKv(),
            ep.EPaxosReplicaOptions(execute_graph_batch_size=4),
            seed=100 + i,
        )
        for i, a in enumerate(config.replica_addresses)
    ]
    p = clients[0].propose(0, kv_set(("x", "1")))
    drain(t)
    assert not p.done  # committed but batched: not yet executed
    for r in replicas:
        t.trigger_timer(r.address, "executeGraphTimer")
    drain(t)
    assert p.done
    for r in replicas:
        assert r.state_machine.get() == {"x": "1"}
