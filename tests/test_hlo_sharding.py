"""HLO-inspection tests enforcing the sharding claims of
``frankenpaxos_tpu.parallel``: the grouped backend's write path compiles
with NO inter-device communication beyond small stat/read reductions
(the slot % G partitioning is group-local), while the grid backend's
global quorum system genuinely requires cross-device reductions. These
pin the claims as compile-time facts, not comments (8 virtual CPU
devices via conftest)."""

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from frankenpaxos_tpu.parallel import make_mesh, shard_state
from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, init_state, run_ticks

# Collective ops XLA SPMD emits, as they appear in optimized HLO text.
_BIG_COLLECTIVES = ("all-gather", "collective-permute", "all-to-all")
# Shapes like "s32[]", "pred[2,8]{1,0}", "s32[64]{0}" -> dtype + dims.
_SHAPE_RE = re.compile(r"=\s*\(?([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _elements(shape_dims: str) -> int:
    if not shape_dims:
        return 1
    n = 1
    for d in shape_dims.split(","):
        n *= int(d)
    return n


def _compiled_text(cfg, mesh, num_ticks=4):
    state = shard_state(init_state(cfg), mesh)
    lowered = jax.jit(
        run_ticks.__wrapped__, static_argnums=(0, 3)
    ).lower(cfg, state, jnp.zeros((), jnp.int32), num_ticks, jax.random.PRNGKey(0))
    return lowered.compile().as_text()


def _all_reduce_sizes(txt):
    """Element counts of the STATE all-reduces. XLA's SPMD partitioner
    also assembles the per-tick threefry random sweep from per-device
    partial writes via an add-combine all-reduce (u32, op_name
    ".../concatenate" inside jax.random.bits) — a PRNG-derivation
    artifact that moves no sharded simulation state, so unsigned
    all-reduces are accounted separately (bounded by the largest
    per-tick random sweep) and excluded here. Simulation-state
    reductions are signed (s32 stats/watermarks) or pred."""
    sizes = []
    for line in txt.splitlines():
        if "all-reduce(" in line or "all-reduce-start(" in line:
            m = _SHAPE_RE.search(line)
            if m and not m.group(1).startswith("u"):
                sizes.append(_elements(m.group(2)))
    return sizes


def _state_collectives(txt, ops):
    """Lines applying one of ``ops`` to SIGNED/pred (i.e. simulation
    state) operands. XLA's partitioner moves slices of the u32 threefry
    sweep between devices while assembling per-tick random bits
    (op_name ".../slice" / ".../concatenate" under jax.random.bits);
    those carry no sharded simulation state and are accounted
    separately by :func:`_prng_collective_sizes` — the claims under
    test are about state movement."""
    offenders = []
    for line in txt.splitlines():
        if not any(op + "(" in line or op + "-start(" in line for op in ops):
            continue
        m = _SHAPE_RE.search(line)
        if m and m.group(1).startswith("u"):
            continue
        offenders.append(line.strip()[:160])
    return offenders


def _prng_collective_sizes(txt):
    """Element counts of EVERY unsigned (threefry-sweep) collective —
    all-reduce, all-gather, all-to-all, collective-permute. Unsigned
    ops are exempt from the state checks above, so they must be bounded
    here: if XLA ever gathered the full replicated random sweep (or a
    u32 state array grew), these sizes would blow past the per-tick
    sweep bound and the tests fail instead of silently passing."""
    ops = ("all-reduce", "all-gather", "all-to-all", "collective-permute")
    sizes = []
    for line in txt.splitlines():
        if not any(op + "(" in line or op + "-start(" in line for op in ops):
            continue
        m = _SHAPE_RE.search(line)
        if m and m.group(1).startswith("u"):
            sizes.append(_elements(m.group(2)))
    return sizes


def test_grouped_write_path_compiles_with_no_collectives():
    """Pure write path, reads off: the compiled sharded program must
    contain NO inter-device communication on [G/n, ...]-sized data —
    only scalar/histogram stat reductions (<= LAT_BINS elements)."""
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, drop_rate=0.1,
        retry_timeout=8,
    )
    txt = _compiled_text(cfg, make_mesh())
    offenders = _state_collectives(txt, _BIG_COLLECTIVES)
    assert not offenders, f"grouped write path moved state: {offenders}"
    sizes = _all_reduce_sizes(txt)
    assert all(s <= 64 for s in sizes), (
        f"grouped write path all-reduces large data: sizes={sizes}"
    )
    # The PRNG sweep assembly stays bounded by one tick's random draws
    # (every unsigned collective, not just all-reduces).
    A, G, W = cfg.group_size, cfg.num_groups, cfg.window
    assert all(s <= A * G * W for s in _prng_collective_sizes(txt))


def test_grouped_backend_with_reads_reduces_only_read_state():
    """Linearizable reads add the one legitimate cross-group pattern —
    reductions landing on replicated [RW]/scalar read arrays. Still no
    all-gather of sharded state."""
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2,
        read_rate=2, read_window=8, read_mode="linearizable",
    )
    txt = _compiled_text(cfg, make_mesh())
    offenders = _state_collectives(txt, ("all-gather", "all-to-all"))
    assert not offenders, f"read path moved sharded state: {offenders}"
    sizes = _all_reduce_sizes(txt)
    assert sizes, "read path must reduce (watermark/bind/floor)"
    # RW=8 ring reductions, LAT_BINS=64 hist, scalars — nothing larger.
    assert all(s <= 64 for s in sizes), sizes
    A, G = cfg.group_size, cfg.num_groups
    bound = A * G * max(cfg.window, cfg.read_window)
    assert all(s <= bound for s in _prng_collective_sizes(txt))


def test_grid_backend_requires_cross_device_reductions():
    """The grid/majority quorum system spans ALL acceptors: sharding the
    acceptor rows over the mesh MUST produce cross-device reductions —
    the communication cost the flexible-quorum sweep measures."""
    from frankenpaxos_tpu.tpu import grid_batched as gb

    cfg = gb.GridBatchedConfig(rows=8, cols=4, mode="majority", window=8,
                               slots_per_tick=2)
    mesh = make_mesh()
    state = gb.init_state(cfg)
    specs = {
        # Shard the acceptor-row axis of the [W, R, C] arrays.
        "p2a_arrival": P(None, "groups", None),
        "p2b_arrival": P(None, "groups", None),
    }
    import dataclasses as dc

    placed = {}
    for f_ in dc.fields(state):
        arr = getattr(state, f_.name)
        spec = specs.get(f_.name, P())
        placed[f_.name] = jax.device_put(arr, NamedSharding(mesh, spec))
    state = type(state)(**placed)
    lowered = jax.jit(
        gb.run_ticks.__wrapped__, static_argnums=(0, 3)
    ).lower(cfg, state, jnp.zeros((), jnp.int32), 4, jax.random.PRNGKey(0))
    txt = lowered.compile().as_text()
    assert (
        "all-reduce" in txt
        or "all-gather" in txt
        or "reduce-scatter" in txt
    ), "grid backend compiled without any cross-device communication"
