"""Tests of the streaming observability stack: the serve loop's
chunked dispatch + double-buffered drain (harness/serve.py), the
telemetry drain cursor and span sampler (tpu/telemetry.py), the SLO
engine (monitoring/slo.py), and the Perfetto trace export
(monitoring/traceviz.py)."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.harness.serve import ServeConfig, ServeLoop
from frankenpaxos_tpu.monitoring import traceviz
from frankenpaxos_tpu.monitoring.slo import SloEngine, SloPolicy
from frankenpaxos_tpu.tpu import multipaxos_batched as mp
from frankenpaxos_tpu.tpu import telemetry as T
from frankenpaxos_tpu.tpu import workload as wl_mod
from frankenpaxos_tpu.tpu.workload import WorkloadPlan


def _cfg(**kw):
    return mp.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, retry_timeout=8,
        **kw
    )


def _with_telemetry(state, window, spans=0):
    return dataclasses.replace(
        state, telemetry=T.make_telemetry(window, spans=spans)
    )


# ---------------------------------------------------------------------------
# Drain-cursor exactness
# ---------------------------------------------------------------------------


def _run_chunks(cfg, chunks, chunk_ticks, window, spans=0):
    """The serve dispatch shape: per-chunk run_ticks with per-chunk
    fold_in keys — deterministic, replayable."""
    key = jax.random.PRNGKey(7)
    state = _with_telemetry(mp.init_state(cfg), window, spans)
    t = jnp.zeros((), jnp.int32)
    for i in range(chunks):
        state, t = mp.run_ticks(
            cfg, state, t, chunk_ticks, jax.random.fold_in(key, i)
        )
        yield state


def test_drain_cursor_chunked_equals_one_shot():
    """Partial drains across chunk boundaries are EXACT: the per-chunk
    rows concatenate to the full per-tick history, their sums equal the
    cumulative totals, and an identical run drained once at the end
    reports bit-identical totals — nothing lost, nothing
    double-counted."""
    cfg = _cfg()
    CH, N, W = 13, 5, 32  # chunk < window; boundaries never align

    cur = T.DrainCursor()
    rows = {name: [] for name in T.COUNTER_FIELDS}
    ticks_seen = []
    for state in _run_chunks(cfg, N, CH, W):
        d = cur.drain(state.telemetry)
        assert d["dropped_ticks"] == 0
        ticks_seen.extend(d["tick"].tolist())
        for name in T.COUNTER_FIELDS:
            rows[name].extend(d[name].tolist())
    chunked_totals = d["totals"]

    assert ticks_seen == list(range(N * CH))  # every tick exactly once

    # One-shot capture of the IDENTICAL run (same chunked dispatch,
    # drained once): bit-identical cumulative totals.
    for state2 in _run_chunks(cfg, N, CH, W):
        pass
    one_shot = T.DrainCursor().drain(state2.telemetry)
    assert one_shot["totals"] == chunked_totals
    # And the drained per-tick rows SUM to the cumulative totals for
    # every counter column (queue_depth is a gauge, not a counter).
    for name in T.COUNTER_FIELDS:
        if name == "queue_depth":
            continue
        assert sum(rows[name]) == chunked_totals[name], name


def test_drain_cursor_reports_overrun_instead_of_double_count():
    """A drain slower than the ring period reports the overrun in
    dropped_ticks and returns only the retained rows — never a
    double-count, never a silent gap."""
    cfg = _cfg()
    W = 16
    key = jax.random.PRNGKey(0)
    state = _with_telemetry(mp.init_state(cfg), W)
    t = jnp.zeros((), jnp.int32)
    state, t = mp.run_ticks(cfg, state, t, 40, key)  # 40 > W
    d = T.DrainCursor().drain(state.telemetry)
    assert d["ticks_total"] == 40
    assert d["dropped_ticks"] == 40 - W
    assert d["tick"].tolist() == list(range(40 - W, 40))


def test_span_sampler_lifecycle_stamps_ordered():
    """Sampled spans carry ordered stage stamps (proposed < voted <=
    committed < executed), cover multiple groups, and drain exactly
    once through the span cursor."""
    cfg = _cfg()
    seen = []
    cur = T.DrainCursor()
    for state in _run_chunks(cfg, 4, 20, 64, spans=8):
        d = cur.drain(state.telemetry)
        seen.extend(d["spans"])
        assert d["dropped_spans"] == 0
    assert len(seen) >= 10
    assert len({s["seq"] for s in seen}) == len(seen)  # no double-drain
    for s in seen:
        assert 0 <= s["proposed"] <= s["committed"] < s["executed"], s
        if s["phase2_voted"] >= 0:
            assert s["proposed"] < s["phase2_voted"] <= s["committed"], s
    assert len({s["group"] for s in seen}) > 1  # samples across groups


def test_spans_disabled_is_structural_noop():
    """spans=0 (every backend's default) adds nothing: the protocol
    state replays bit-identically with and without a sized reservoir
    (the sampler only observes), and the zero-sized leaves survive the
    scan carry."""
    cfg = _cfg()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)
    on, _ = mp.run_ticks(
        cfg, _with_telemetry(mp.init_state(cfg), 32, spans=8), t0, 30, key
    )
    off, _ = mp.run_ticks(
        cfg, _with_telemetry(mp.init_state(cfg), 32, spans=0), t0, 30, key
    )
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(on, f.name)),
            jax.tree_util.tree_leaves(getattr(off, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    # The observer halves agree too (counters are span-independent).
    np.testing.assert_array_equal(
        np.asarray(on.telemetry.totals), np.asarray(off.telemetry.totals)
    )


# ---------------------------------------------------------------------------
# The serve loop
# ---------------------------------------------------------------------------


def test_serve_loop_matches_manual_chunked_run():
    """The serve loop is OBSERVABILITY only: its chunked dispatch
    replays the exact same program as manual run_ticks segments with
    the same keys — final committed/retired and telemetry totals are
    bit-identical."""
    cfg = _cfg()
    serve = ServeConfig(chunk_ticks=10, telemetry_window=32, spans=4,
                        max_chunks=4)
    loop = ServeLoop(mp, cfg, serve, seed=5)
    report = loop.run()
    assert report["clean_shutdown"] and report["ticks"] == 40

    key = jax.random.PRNGKey(5)
    state = _with_telemetry(mp.init_state(cfg), 32, spans=4)
    t = jnp.zeros((), jnp.int32)
    for i in range(4):
        state, t = mp.run_ticks(
            cfg, state, t, 10, jax.random.fold_in(key, i)
        )
    assert int(state.committed) == int(loop.state.committed)
    assert int(state.retired) == int(loop.state.retired)
    np.testing.assert_array_equal(
        np.asarray(state.telemetry.totals),
        np.asarray(loop.state.telemetry.totals),
    )
    # The drains saw every tick and every completed span exactly once.
    assert report["dropped_ticks"] == 0
    assert report["spans_exported"] == int(state.telemetry.spans_done)


def test_serve_hot_path_never_blocks_on_state(monkeypatch):
    """The no-blocking-transfer spy: during the loop, device_get only
    ever touches tiny snapshot pytrees (never the protocol state), and
    block_until_ready runs exactly once — at shutdown, after the last
    chunk was dispatched."""
    gets, waits, dispatched = [], [], []

    real_get = jax.device_get
    real_wait = jax.block_until_ready

    def spy_get(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        nbytes = sum(getattr(a, "nbytes", 0) for a in leaves)
        assert not isinstance(tree, mp.BatchedMultiPaxosState), (
            "serve loop pulled the full protocol state"
        )
        gets.append(nbytes)
        return real_get(tree)

    def spy_wait(tree):
        assert isinstance(tree, mp.BatchedMultiPaxosState)
        waits.append(len(dispatched))
        return real_wait(tree)

    real_run_ticks = mp.run_ticks

    def spy_run_ticks(*a, **kw):
        dispatched.append(1)
        return real_run_ticks(*a, **kw)

    monkeypatch.setattr(jax, "device_get", spy_get)
    monkeypatch.setattr(jax, "block_until_ready", spy_wait)
    monkeypatch.setattr(mp, "run_ticks", spy_run_ticks)

    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=64, window=16, slots_per_tick=2,
        retry_timeout=8,
    )
    serve = ServeConfig(chunk_ticks=8, telemetry_window=32, spans=4,
                        max_chunks=5)
    loop = ServeLoop(mp, cfg, serve, seed=0)
    report = loop.run()
    assert report["clean_shutdown"]
    assert len(dispatched) == 5
    # Exactly one wait, at shutdown — after every chunk went out.
    assert waits == [5]
    # Every hot-path transfer is snapshot-sized — a fixed few KB that
    # does NOT scale with the protocol state (already ~25x here at
    # G=64; ~10^4x at the flagship shape).
    state_bytes = sum(
        a.nbytes for a in jax.tree_util.tree_leaves(loop.state)
    )
    assert gets and max(gets) < state_bytes / 10


def test_serve_slo_alarm_clamps_and_p99_recovers():
    """The control-plane loop: offered load ~2x saturation backs the
    queue up, the windowed queue-wait p99 breaches the target, the
    alarm fires, admission clamps through the traced rate (no
    recompile — the jit cache stays flat), the backlog drains, and the
    windowed p99 recovers to the target."""
    cfg = _cfg(
        workload=WorkloadPlan(
            arrival="constant", rate=2.0 * 2, backlog_cap=64
        )
    )
    serve = ServeConfig(
        chunk_ticks=16, telemetry_window=64,
        slo=SloPolicy(
            p99_target_ticks=4, source="queue_wait",
            window_chunks=2, clear_after=2, clamp_factor=0.4,
        ),
        max_chunks=30,
    )
    loop = ServeLoop(mp, cfg, serve, seed=1)
    cache0 = None
    report = loop.run()
    hist = loop.slo.history
    assert loop.slo.alarms_fired >= 1
    fired_at = next(i for i, h in enumerate(hist) if h["fired"])
    assert hist[fired_at]["p99"] > 4
    # The clamp engaged (scale dropped) ...
    assert min(h["scale"] for h in hist) < 1.0
    # ... and after it, the windowed p99 recovered to the target and
    # the alarm cleared (p99 == -1 means the queue fully drained; the
    # controller may probe upward again afterwards).
    assert any(
        h["cleared"] and h["p99"] <= 4 for h in hist[fired_at + 1:]
    ), [(h["p99"], h["scale"]) for h in hist]
    assert report["slo"]["clamps_applied"] >= 1
    del cache0


def test_serve_live_fault_plan_swap_recovers():
    """Live FaultPlan swaps through the serve control plane (the PR 10
    follow-up): a FaultPlan(traced=True) config serves healthy, the
    set_fault_rates verb drives the drop rate UP mid-run (per-chunk
    commit throughput collapses), then back DOWN — throughput recovers
    to the healthy band, with zero recompiles across both swaps."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    cfg = _cfg(faults=FaultPlan(traced=True))
    serve = ServeConfig(chunk_ticks=20, telemetry_window=64,
                        max_chunks=100)
    loop = ServeLoop(mp, cfg, serve, seed=4)

    def commits_over(chunks):
        c0 = int(jax.device_get(loop.state.committed))
        snaps = [loop._dispatch_chunk() for _ in range(chunks)]
        for s in snaps:
            loop._drain(s)
        return int(jax.device_get(loop.state.committed)) - c0

    healthy = commits_over(4)
    cache = mp.run_ticks._cache_size()
    # Fault leg ON: heavy drops eat the vote/quorum planes.
    loop.set_fault_rates(drop=0.6)
    degraded = commits_over(4)
    # Fault leg OFF: the same compiled program recovers.
    loop.set_fault_rates(drop=0.0)
    commits_over(1)  # flush in-flight retries
    recovered = commits_over(4)
    assert mp.run_ticks._cache_size() == cache, "fault swap recompiled"
    assert degraded < 0.7 * healthy, (healthy, degraded)
    assert recovered > 0.9 * healthy, (healthy, recovered)
    # The verb landed in the host span stream (trace-visible).
    assert any(
        s["name"] == "verb:set_fault_rates" for s in loop.host_spans
    )


def test_serve_rate_clamp_does_not_recompile():
    """set_rate between chunks rides the traced scalar: the whole SLO
    serve run compiles run_ticks exactly once for its chunk length."""
    cfg = _cfg(
        workload=WorkloadPlan(arrival="constant", rate=4.0,
                              backlog_cap=64)
    )
    serve = ServeConfig(
        chunk_ticks=12, telemetry_window=32,
        slo=SloPolicy(p99_target_ticks=2, source="queue_wait",
                      window_chunks=1),
        max_chunks=3,
    )
    loop = ServeLoop(mp, cfg, serve, seed=2)
    loop._dispatch_chunk()  # first compile
    before = mp.run_ticks._cache_size()
    loop2 = ServeLoop(mp, cfg, serve, seed=3)
    loop2.run()
    assert mp.run_ticks._cache_size() == before


# ---------------------------------------------------------------------------
# SLO engine edge cases
# ---------------------------------------------------------------------------


def test_slo_empty_histogram_never_alarms():
    eng = SloEngine(SloPolicy(p99_target_ticks=0))
    for _ in range(5):
        s = eng.observe(wait_hist_delta=np.zeros(8, np.int64))
        assert not s["alarm"] and s["p99"] == -1
    assert eng.scale == 1.0 and eng.alarms_fired == 0


def test_slo_exactly_at_target_is_in_slo():
    """p99 == target must NOT alarm (strictly-above fires)."""
    eng = SloEngine(SloPolicy(p99_target_ticks=5))
    h = np.zeros(8, np.int64)
    h[5] = 100  # every sample at exactly 5 ticks -> p99 == 5
    s = eng.observe(wait_hist_delta=h)
    assert s["p99"] == 5 and not s["alarm"]
    h2 = np.zeros(8, np.int64)
    h2[6] = 100  # one bin above -> breach
    s = eng.observe(wait_hist_delta=h2)
    assert s["alarm"] and s["fired"]


def test_slo_hysteresis_and_scale_recovery():
    pol = SloPolicy(
        p99_target_ticks=3, window_chunks=1, clear_after=2,
        clamp_factor=0.5, recover_factor=2.0,
    )
    eng = SloEngine(pol)
    bad = np.zeros(8, np.int64)
    bad[7] = 10
    good = np.zeros(8, np.int64)
    good[1] = 10
    s = eng.observe(wait_hist_delta=bad)
    assert s["fired"] and eng.scale == 0.5
    s = eng.observe(wait_hist_delta=bad)
    assert s["alarm"] and not s["fired"] and eng.scale == 0.25
    s = eng.observe(wait_hist_delta=good)
    assert s["alarm"]  # one clean drain < clear_after: still latched
    s = eng.observe(wait_hist_delta=good)
    assert s["cleared"] and not s["alarm"]
    assert eng.scale == 0.5  # recovery starts the drain it clears
    s = eng.observe(wait_hist_delta=good)
    assert eng.scale == 1.0  # multiplicative recovery, capped
    s = eng.observe(wait_hist_delta=good)
    assert eng.scale == 1.0  # stays at the plan rate
    assert eng.alarms_fired == 1


def test_slo_shed_rate_alarm():
    eng = SloEngine(
        SloPolicy(p99_target_ticks=100, shed_rate_target=0.1,
                  window_chunks=1)
    )
    s = eng.observe(offered_delta=90, shed_delta=10)  # exactly 0.1
    assert not s["alarm"]
    s = eng.observe(offered_delta=80, shed_delta=20)  # 0.2 > 0.1
    assert s["alarm"] and s["shed_breach"] and not s["p99_breach"]


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------


def test_trace_export_loads_and_carries_both_halves(tmp_path):
    cfg = _cfg()
    out = tmp_path / "trace.json"
    serve = ServeConfig(
        chunk_ticks=16, telemetry_window=64, spans=8,
        trace_path=str(out), max_chunks=4,
    )
    loop = ServeLoop(mp, cfg, serve, seed=0)
    report = loop.run()
    assert report["spans_exported"] > 0
    payload = traceviz.load_chrome_trace(str(out))
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    device = [e for e in xs if e["pid"] == traceviz.DEVICE_PID]
    host = [e for e in xs if e["pid"] == traceviz.HOST_PID]
    assert device and host
    # Device lifecycle slices carry the stage stamps as args and map
    # onto the host wall clock (ts within the run's span envelope).
    lifecycles = [e for e in device if e.get("cat") == "lifecycle"]
    assert lifecycles
    assert all("committed" in e["args"] for e in lifecycles)
    host_lo = min(e["ts"] for e in host)
    host_hi = max(e["ts"] + e["dur"] for e in host)
    for e in lifecycles:
        assert host_lo - 5e6 <= e["ts"] <= host_hi + 5e6
    # Host spans include the dispatch/drain pair of the serve loop.
    assert {e["name"] for e in host} >= {"dispatch", "drain"}
    # The whole file is plain JSON — Perfetto's loader needs no more.
    json.loads(out.read_text())


def test_tick_clock_interpolates_and_extrapolates():
    clock = traceviz.TickClock([(0, 100.0), (100, 101.0)])
    assert clock.to_us(50) == pytest.approx(100.5e6)
    assert clock.to_us(200) == pytest.approx(102.0e6)
    assert clock.to_us(-100) == pytest.approx(99.0e6)


def test_dashboard_live_tails_serve_csv(tmp_path):
    """The dashboard's --live mode: a scrape CSV that a serve loop fed
    renders (device counters become rate panels) and the tail exits on
    idle — watching a run without waiting for a finished capture."""
    from frankenpaxos_tpu.monitoring import dashboard, scrape

    cfg = _cfg()
    csv_path = str(tmp_path / "serve_metrics.csv")
    serve = ServeConfig(chunk_ticks=8, telemetry_window=32,
                        scrape_csv=csv_path, max_chunks=3)
    loop = ServeLoop(mp, cfg, serve, seed=0)
    loop.run()
    # Host spans land in the CSV EXACTLY once each — including the
    # compile-marked first dispatch, with no double-write at shutdown.
    import csv as _csv

    with open(csv_path) as f:
        span_rows = [
            r for r in _csv.DictReader(f)
            if r["name"] == "fpx_host_span_seconds"
        ]
    assert len(span_rows) == len(loop.host_spans)
    assert sum("compile=true" in r["labels"] for r in span_rows) == 1
    out = str(tmp_path / "live.png")
    renders = dashboard.tail_live(
        csv_path, out, interval_s=0.1, max_seconds=5.0, idle_exit_s=0.5
    )
    assert renders >= 1
    assert os.path.getsize(out) > 0
    del scrape


# ---------------------------------------------------------------------------
# CI wiring
# ---------------------------------------------------------------------------


def test_serve_smoke_script_and_bench_mode_exist():
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    script = repo / "scripts" / "serve_smoke.sh"
    assert script.exists() and os.access(script, os.X_OK)
    src = script.read_text()
    assert "harness.serve" in src and "trace-serve-nosync" in src
    bench_src = (repo / "bench.py").read_text()
    assert '"--serve"' in bench_src and "--inner-serve" in bench_src


# ---------------------------------------------------------------------------
# Fleet serving (FleetServeLoop)
# ---------------------------------------------------------------------------


def _fleet_cfg(num_groups=8, rate=2.0, **kw):
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    return mp.BatchedMultiPaxosConfig(
        f=1, num_groups=num_groups, window=16, slots_per_tick=2,
        retry_timeout=8,
        workload=WorkloadPlan(
            arrival="constant", rate=rate, backlog_cap=256
        ),
        faults=FaultPlan(traced=True),
        **kw,
    )


def test_fleet_serve_loop_matches_manual_chunked_run():
    """The fleet serve loop is OBSERVABILITY only: its chunked
    dispatch replays the exact same fleet program as manual
    run_ticks_fleet segments with the same vmapped fold_in keys —
    per-instance committed totals and telemetry are bit-identical, and
    the drains saw every tick of every instance exactly once."""
    from frankenpaxos_tpu.harness.serve import (
        FleetServeConfig, FleetServeLoop,
    )
    from frankenpaxos_tpu.parallel import sharding as sh

    cfg = _fleet_cfg()
    n, CH, NCH = 4, 10, 4
    rates = [2.0] * n
    frates = [[0.0] * 4] * n
    loop = FleetServeLoop(
        "multipaxos", cfg,
        FleetServeConfig(chunk_ticks=CH, telemetry_window=32,
                         max_chunks=NCH),
        n, seeds=[5 + i for i in range(n)], rates=rates,
        fault_rates=frates,
    )
    report = loop.run()
    assert report["clean_shutdown"] and report["ticks"] == NCH * CH
    assert report["dropped_ticks"] == 0

    base = dataclasses.replace(
        mp.init_state(cfg), telemetry=T.make_telemetry(32)
    )
    states = sh.fleet_states(
        "multipaxos", cfg, n, rates=rates, fault_rates=frates,
        base=base,
    )
    keys = sh.fleet_keys([5 + i for i in range(n)])
    t = jnp.zeros((), jnp.int32)
    for e in range(NCH):
        kk = jax.vmap(jax.random.fold_in, in_axes=(0, None))(keys, e)
        states, t = sh.run_ticks_fleet(
            "multipaxos", cfg, None, states, t, CH, kk
        )
    np.testing.assert_array_equal(
        np.asarray(states.committed), np.asarray(loop.states.committed)
    )
    np.testing.assert_array_equal(
        np.asarray(states.telemetry.totals),
        np.asarray(loop.states.telemetry.totals),
    )


def test_fleet_serve_hot_path_single_wait(monkeypatch):
    """The fleet no-blocking spy: block_until_ready runs exactly once
    (at shutdown, on the fleet state), and every hot-path device_get
    touches only snapshot-sized pytrees, never the protocol state."""
    from frankenpaxos_tpu.harness.serve import (
        FleetServeConfig, FleetServeLoop,
    )

    gets, waits = [], []
    real_get = jax.device_get
    real_wait = jax.block_until_ready

    def spy_get(tree):
        assert not isinstance(tree, mp.BatchedMultiPaxosState), (
            "fleet loop pulled the full protocol state"
        )
        gets.append(
            sum(
                getattr(a, "nbytes", 0)
                for a in jax.tree_util.tree_leaves(tree)
            )
        )
        return real_get(tree)

    def spy_wait(tree):
        assert isinstance(tree, mp.BatchedMultiPaxosState)
        waits.append(1)
        return real_wait(tree)

    monkeypatch.setattr(jax, "device_get", spy_get)
    monkeypatch.setattr(jax, "block_until_ready", spy_wait)

    cfg = _fleet_cfg(num_groups=32)
    n = 4
    loop = FleetServeLoop(
        "multipaxos", cfg,
        FleetServeConfig(chunk_ticks=8, telemetry_window=32,
                         max_chunks=5),
        n, rates=[2.0] * n, fault_rates=[[0.0] * 4] * n,
    )
    report = loop.run()
    assert report["clean_shutdown"]
    assert waits == [1], "hot path must wait exactly once, at shutdown"
    state_bytes = sum(
        a.nbytes for a in jax.tree_util.tree_leaves(loop.states)
    )
    assert gets and max(gets) < state_bytes / 4


def test_fleet_serve_hostile_instance_flagged_clamped_siblings_flat():
    """The differential-failure loop end to end: a homogeneous fleet
    below saturation, ONE instance on a hostile traced drop rate — the
    in-graph summary flags it (and only it), its per-instance SLO
    alarm clamps it (and only it) through the fleet-sharded traced
    rate with the jit cache FLAT, and every sibling's p99 stays within
    target."""
    from frankenpaxos_tpu.harness.serve import (
        FleetServeConfig, FleetServeLoop,
    )
    from frankenpaxos_tpu.parallel import sharding as sh

    cfg = _fleet_cfg(num_groups=16, rate=1.8)
    n, HOSTILE = 4, 2
    frates = [[0.0] * 4 for _ in range(n)]
    frates[HOSTILE][0] = 0.6
    loop = FleetServeLoop(
        "multipaxos", cfg,
        FleetServeConfig(
            chunk_ticks=16, telemetry_window=32,
            slo=SloPolicy(p99_target_ticks=8, source="queue_wait"),
            max_chunks=10,
        ),
        n, rates=[1.8] * n, fault_rates=frates,
    )
    runner = sh._fleet_runner(
        "multipaxos", None,
        sh._fleet_wrap_mesh("multipaxos", cfg, None),
    )
    # Delta-based: the runner is lru-cached per (backend, mesh), so
    # other tests in this process may already hold entries; this run
    # may add AT MOST its own one compile (chunk length), and the SLO
    # clamps inside it must add none.
    cache0 = runner._cache_size()
    report = loop.run()
    assert report["stragglers_flagged"] == [HOSTILE], report["summary"]
    scales = report["slo"]["scales"]
    assert scales[HOSTILE] < 1.0
    assert all(
        s == 1.0 for i, s in enumerate(scales) if i != HOSTILE
    ), scales
    for i, row in enumerate(report["summary"]):
        if i != HOSTILE:
            assert row["p99_queue_wait"] <= 8, (i, row)
    # Alarm + clamp markers landed on the hostile instance's lane only.
    kinds = {(m["instance"], m["kind"]) for m in report["markers"]}
    assert (HOSTILE, "alarm") in kinds and (HOSTILE, "clamp") in kinds
    assert all(m["instance"] == HOSTILE for m in report["markers"])
    assert runner._cache_size() <= cache0 + 1, (
        "control plane recompiled"
    )


def test_fleet_trace_and_csv_carry_per_instance_lanes(tmp_path):
    """Presentation plumbing: the Perfetto export carries one track
    group per instance with the control plane's instant markers, and
    the scrape CSV carries per-instance summary rows (straggler lane
    included) that the --fleet dashboard pivots."""
    import csv as _csv

    from frankenpaxos_tpu.harness.serve import (
        FleetServeConfig, FleetServeLoop,
    )
    from frankenpaxos_tpu.monitoring import dashboard
    from frankenpaxos_tpu.monitoring.scrape import MetricsCapture

    cfg = _fleet_cfg(num_groups=16, rate=1.8)
    n, HOSTILE = 4, 1
    frates = [[0.0] * 4 for _ in range(n)]
    frates[HOSTILE][0] = 0.6
    csv_path = str(tmp_path / "fleet_metrics.csv")
    trace_path = str(tmp_path / "fleet_trace.json")
    loop = FleetServeLoop(
        "multipaxos", cfg,
        FleetServeConfig(
            chunk_ticks=16, telemetry_window=32,
            slo=SloPolicy(p99_target_ticks=8, source="queue_wait"),
            scrape_csv=csv_path, trace_path=trace_path, max_chunks=8,
        ),
        n, rates=[1.8] * n, fault_rates=frates,
    )
    loop.run()
    payload = traceviz.load_chrome_trace(trace_path)
    events = payload["traceEvents"]
    group_pids = {
        e["pid"] for e in events
        if e.get("ph") == "M"
        and str(e["args"].get("name", "")).startswith("fleet instance")
    }
    assert group_pids == {traceviz.FLEET_PID0 + i for i in range(n)}
    marks = [e for e in events if e.get("cat") == "fleet-control"]
    assert marks and all(
        e["pid"] == traceviz.FLEET_PID0 + HOSTILE for e in marks
    )
    with open(csv_path) as f:
        rows = list(_csv.DictReader(f))
    strag = [r for r in rows if r["name"] == "fpx_fleet_straggler"]
    assert {r["instance"] for r in strag} == {str(i) for i in range(n)}
    assert any(
        float(r["value"]) == 1.0 and r["instance"] == str(HOSTILE)
        for r in strag
    )
    # Per-instance device counter rows (the exact-drain CSV half).
    assert {
        r["instance"] for r in rows
        if r["name"] == "fpx_device_commits_total"
    } == {str(i) for i in range(n)}
    out = str(tmp_path / "fleet.png")
    assert dashboard.render_fleet_dashboard(
        MetricsCapture(csv_path), out
    ) == out
    assert os.path.getsize(out) > 0


# ---------------------------------------------------------------------------
# Span sampler on craq (the third spans backend)
# ---------------------------------------------------------------------------


def test_craq_span_sampler_stamps_and_structural_noop():
    """craq records spans through the generic telemetry plumbing:
    ordered stage stamps (proposed < tail-apply commit < head-ack
    execute), spans=0 stays a structural no-op (bit-identical protocol
    state), and the counter halves agree across both modes."""
    from frankenpaxos_tpu.tpu import craq_batched as cq

    cfg = cq.analysis_config()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)

    def run(spans):
        st = dataclasses.replace(
            cq.init_state(cfg), telemetry=T.make_telemetry(64, spans=spans)
        )
        st, _ = cq.run_ticks(cfg, st, t0, 50, key)
        return st

    on, off = run(8), run(0)
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(on, f.name)),
            jax.tree_util.tree_leaves(getattr(off, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    np.testing.assert_array_equal(
        np.asarray(on.telemetry.totals), np.asarray(off.telemetry.totals)
    )
    spans, dropped, _ = T.completed_spans(on.telemetry)
    assert spans and dropped == 0
    for s in spans:
        assert 0 <= s["proposed"] < s["committed"] <= s["executed"], s
        assert s["executed"] > s["committed"], s  # head ack >= 1 hop
        assert s["phase2_voted"] == s["committed"], s  # tail apply
        assert s["phase1_promised"] == -1, s  # no phase-1 on a chain
    assert len({s["group"] for s in spans}) > 1


def test_craq_serve_perfetto_round_trip(tmp_path):
    """The serve loop over craq with the span sampler on: the Perfetto
    export round-trips with DEVICE lifecycle slices (craq spans) and
    host dispatch spans in one timeline."""
    from frankenpaxos_tpu.tpu import craq_batched as cq

    cfg = cq.analysis_config()
    out = tmp_path / "craq_trace.json"
    serve = ServeConfig(
        chunk_ticks=16, telemetry_window=64, spans=8,
        trace_path=str(out), max_chunks=4,
    )
    loop = ServeLoop(cq, cfg, serve, seed=0)
    report = loop.run()
    assert report["clean_shutdown"] and report["spans_exported"] > 0
    payload = traceviz.load_chrome_trace(str(out))
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    device = [e for e in xs if e["pid"] == traceviz.DEVICE_PID]
    host = [e for e in xs if e["pid"] == traceviz.HOST_PID]
    assert device and host
    lifecycles = [e for e in device if e.get("cat") == "lifecycle"]
    assert lifecycles
    assert all("committed" in e["args"] for e in lifecycles)


# ---------------------------------------------------------------------------
# Span sampler on mencius (the fourth spans backend)
# ---------------------------------------------------------------------------


def test_mencius_span_sampler_stamps_and_structural_noop():
    """mencius records spans through the generic telemetry plumbing:
    ordered stage stamps on the striped log (proposed < quorum vote <=
    chosen <= global-watermark retire), spans=0 stays a structural
    no-op (bit-identical protocol state), no phase-1 stamps (each
    leader owns its stripe), and every stripe gets sampled."""
    from frankenpaxos_tpu.tpu import mencius_batched as mc

    cfg = mc.analysis_config()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)

    def run(spans):
        st = dataclasses.replace(
            mc.init_state(cfg), telemetry=T.make_telemetry(64, spans=spans)
        )
        st, _ = mc.run_ticks(cfg, st, t0, 50, key)
        return st

    on, off = run(8), run(0)
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(on, f.name)),
            jax.tree_util.tree_leaves(getattr(off, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    np.testing.assert_array_equal(
        np.asarray(on.telemetry.totals), np.asarray(off.telemetry.totals)
    )
    spans, dropped, _ = T.completed_spans(on.telemetry)
    assert spans and dropped == 0
    for s in spans:
        assert 0 <= s["proposed"] < s["committed"] <= s["executed"], s
        assert s["proposed"] < s["phase2_voted"] <= s["committed"], s
        assert s["phase1_promised"] == -1, s  # no phase-1 on a stripe
    # The round-robin stripes all commit, so the reservoir sees all of
    # them (slot ids are owned ordinals: distinct mod num_leaders).
    assert {s["group"] for s in spans} == set(range(cfg.num_leaders))


def test_mencius_serve_perfetto_round_trip(tmp_path):
    """The serve loop over mencius with the span sampler on: the
    Perfetto export round-trips with DEVICE lifecycle slices (mencius
    striped-log spans) and host dispatch spans in one timeline."""
    from frankenpaxos_tpu.tpu import mencius_batched as mc

    cfg = mc.analysis_config()
    out = tmp_path / "mencius_trace.json"
    serve = ServeConfig(
        chunk_ticks=16, telemetry_window=64, spans=8,
        trace_path=str(out), max_chunks=4,
    )
    loop = ServeLoop(mc, cfg, serve, seed=0)
    report = loop.run()
    assert report["clean_shutdown"] and report["spans_exported"] > 0
    payload = traceviz.load_chrome_trace(str(out))
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    device = [e for e in xs if e["pid"] == traceviz.DEVICE_PID]
    host = [e for e in xs if e["pid"] == traceviz.HOST_PID]
    assert device and host
    lifecycles = [e for e in device if e.get("cat") == "lifecycle"]
    assert lifecycles
    assert all("committed" in e["args"] for e in lifecycles)


# ---------------------------------------------------------------------------
# Span sampler on epaxos (the sixth spans backend)
# ---------------------------------------------------------------------------


def test_epaxos_span_sampler_stamps_and_structural_noop():
    """epaxos records instance lifecycles through the generic telemetry
    plumbing: group = column, slot id = the instance ordinal, the
    PreAccept quorum and the commit are one modeled event (vote ==
    chosen stamp), and the "executed" stamp is the snapshot-barrier GC
    prune — strictly downstream of the commit. spans=0 stays a
    structural no-op (bit-identical protocol state), and there are no
    phase-1 stamps (EPaxos is leaderless)."""
    from frankenpaxos_tpu.tpu import epaxos_batched as ep

    cfg = ep.analysis_config()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)

    def run(spans):
        st = dataclasses.replace(
            ep.init_state(cfg), telemetry=T.make_telemetry(64, spans=spans)
        )
        st, _ = ep.run_ticks(cfg, st, t0, 100, key)
        return st

    on, off = run(8), run(0)
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(on, f.name)),
            jax.tree_util.tree_leaves(getattr(off, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    np.testing.assert_array_equal(
        np.asarray(on.telemetry.totals), np.asarray(off.telemetry.totals)
    )
    spans, dropped, _ = T.completed_spans(on.telemetry)
    assert spans and dropped == 0
    for s in spans:
        # The commit round is >= 2 one-way hops of lat_min >= 1 each,
        # so the commit strictly follows the proposal; the GC prune
        # waits for the quorum watermark's snapshot barrier, so
        # retirement never precedes the commit.
        assert 0 <= s["proposed"] < s["committed"] <= s["executed"], s
        assert s["phase2_voted"] == s["committed"], s
        assert s["phase1_promised"] == -1, s  # leaderless: no phase 1
        assert 0 <= s["group"] < cfg.num_columns, s


def test_epaxos_serve_perfetto_round_trip(tmp_path):
    """The serve loop over epaxos with the span sampler on: the
    Perfetto export round-trips with DEVICE lifecycle slices (epaxos
    instance spans) and host dispatch spans in one timeline."""
    from frankenpaxos_tpu.tpu import epaxos_batched as ep

    cfg = ep.analysis_config()
    out = tmp_path / "epaxos_trace.json"
    serve = ServeConfig(
        chunk_ticks=32, telemetry_window=64, spans=8,
        trace_path=str(out), max_chunks=4,
    )
    loop = ServeLoop(ep, cfg, serve, seed=0)
    report = loop.run()
    assert report["clean_shutdown"] and report["spans_exported"] > 0
    payload = traceviz.load_chrome_trace(str(out))
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    device = [e for e in xs if e["pid"] == traceviz.DEVICE_PID]
    host = [e for e in xs if e["pid"] == traceviz.HOST_PID]
    assert device and host
    lifecycles = [e for e in device if e.get("cat") == "lifecycle"]
    assert lifecycles
    assert all("committed" in e["args"] for e in lifecycles)


# ---------------------------------------------------------------------------
# Span sampler on scalog (the fifth spans backend)
# ---------------------------------------------------------------------------


def test_scalog_span_sampler_stamps_and_structural_noop():
    """scalog records CUT lifecycles through the generic telemetry
    plumbing: one pseudo-group (the aggregator), slot id = the monotone
    cut number, proposed = the cut snapshot, and commit == execute ==
    phase2 (the Paxos decision lands and the global log extends in the
    same in-order scan — one tick, by construction). spans=0 stays a
    structural no-op (bit-identical protocol state) and the counter
    halves agree across both modes."""
    from frankenpaxos_tpu.tpu import scalog_batched as sb

    cfg = sb.analysis_config()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)

    def run(spans):
        st = dataclasses.replace(
            sb.init_state(cfg), telemetry=T.make_telemetry(64, spans=spans)
        )
        st, _ = sb.run_ticks(cfg, st, t0, 50, key)
        return st

    on, off = run(8), run(0)
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(on, f.name)),
            jax.tree_util.tree_leaves(getattr(off, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    np.testing.assert_array_equal(
        np.asarray(on.telemetry.totals), np.asarray(off.telemetry.totals)
    )
    spans, dropped, _ = T.completed_spans(on.telemetry)
    assert spans and dropped == 0
    for s in spans:
        # The ordering round is >= 2*lat_min >= 2 ticks, so the commit
        # strictly follows the snapshot; commit and the global-log
        # extension are the same scan, so the three late stamps agree.
        assert 0 <= s["proposed"] < s["committed"], s
        assert s["phase2_voted"] == s["committed"] == s["executed"], s
        assert s["phase1_promised"] == -1, s  # no phase-1 on the cut log
        assert s["group"] == 0, s  # the single aggregator
    # Distinct cut numbers (the reservoir never double-adopts a cut;
    # completion order can swap within a tick — reservoir-slot order —
    # so only uniqueness is ordering-stable).
    ids = [s["slot_id"] for s in spans]
    assert len(set(ids)) == len(ids)


def test_scalog_serve_perfetto_round_trip(tmp_path):
    """The serve loop over scalog with the span sampler on: the
    Perfetto export round-trips with DEVICE lifecycle slices (scalog
    cut spans) and host dispatch spans in one timeline."""
    from frankenpaxos_tpu.tpu import scalog_batched as sb

    cfg = sb.analysis_config()
    out = tmp_path / "scalog_trace.json"
    serve = ServeConfig(
        chunk_ticks=16, telemetry_window=64, spans=8,
        trace_path=str(out), max_chunks=4,
    )
    loop = ServeLoop(sb, cfg, serve, seed=0)
    report = loop.run()
    assert report["clean_shutdown"] and report["spans_exported"] > 0
    payload = traceviz.load_chrome_trace(str(out))
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    device = [e for e in xs if e["pid"] == traceviz.DEVICE_PID]
    host = [e for e in xs if e["pid"] == traceviz.HOST_PID]
    assert device and host
    lifecycles = [e for e in device if e.get("cat") == "lifecycle"]
    assert lifecycles
    assert all("committed" in e["args"] for e in lifecycles)


def test_bpaxos_span_sampler_stamps_and_structural_noop():
    """bpaxos records vertex lifecycles through the generic telemetry
    plumbing: group = leader lane, slot id = the lane's command number,
    consensus choice is one event (vote == chosen), "executed" is ring
    retirement (all replicas ran the vertex), and there is no phase-1
    plane at all — BPaxos proposers are leaderless. spans=0 stays a
    structural no-op (bit-identical protocol state) and the counter
    halves agree across both modes."""
    from frankenpaxos_tpu.tpu import bpaxos_batched as bp

    cfg = bp.analysis_config()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)

    def run(spans):
        st = dataclasses.replace(
            bp.init_state(cfg), telemetry=T.make_telemetry(64, spans=spans)
        )
        st, _ = bp.run_ticks(cfg, st, t0, 60, key)
        return st

    on, off = run(8), run(0)
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(on, f.name)),
            jax.tree_util.tree_leaves(getattr(off, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    np.testing.assert_array_equal(
        np.asarray(on.telemetry.totals), np.asarray(off.telemetry.totals)
    )
    spans, dropped, _ = T.completed_spans(on.telemetry)
    assert spans and dropped == 0
    for s in spans:
        # Acceptor round-trip >= lat_min >= 1, replica visibility adds
        # at least one more hop before retirement can fire.
        assert 0 <= s["proposed"] < s["committed"] < s["executed"], s
        assert s["phase2_voted"] == s["committed"], s  # one event
        assert s["phase1_promised"] == -1, s  # leaderless
        assert 0 <= s["group"] < cfg.num_leaders, s
    # The rotating reservoir samples across the leader-lane axis.
    assert len({s["group"] for s in spans}) > 1


def test_bpaxos_serve_perfetto_round_trip(tmp_path):
    """The serve loop over bpaxos with the span sampler on: the
    Perfetto export round-trips with DEVICE lifecycle slices (vertex
    spans) and host dispatch spans in one timeline."""
    from frankenpaxos_tpu.tpu import bpaxos_batched as bp

    cfg = bp.analysis_config()
    out = tmp_path / "bpaxos_trace.json"
    serve = ServeConfig(
        chunk_ticks=16, telemetry_window=64, spans=8,
        trace_path=str(out), max_chunks=4,
    )
    loop = ServeLoop(bp, cfg, serve, seed=0)
    report = loop.run()
    assert report["clean_shutdown"] and report["spans_exported"] > 0
    payload = traceviz.load_chrome_trace(str(out))
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    device = [e for e in xs if e["pid"] == traceviz.DEVICE_PID]
    host = [e for e in xs if e["pid"] == traceviz.HOST_PID]
    assert device and host
    lifecycles = [e for e in device if e.get("cat") == "lifecycle"]
    assert lifecycles
    assert all("committed" in e["args"] for e in lifecycles)


def test_caspaxos_span_sampler_stamps_and_structural_noop():
    """caspaxos records register-BIT lifecycles through the generic
    telemetry plumbing: group = register, slot id = bit index (bits
    issue once, ids never recycle), "voted" = an acceptor vote value
    carries the bit, and choice == execution (a bit first visible in
    the chosen value — no separate dispatch plane). spans=0 stays a
    structural no-op (bit-identical protocol state) and the counter
    halves agree across both modes."""
    from frankenpaxos_tpu.tpu import caspaxos_batched as cp

    cfg = cp.analysis_config()
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)

    def run(spans):
        st = dataclasses.replace(
            cp.init_state(cfg), telemetry=T.make_telemetry(64, spans=spans)
        )
        st, _ = cp.run_ticks(cfg, st, t0, 40, key)
        return st

    on, off = run(8), run(0)
    for f in dataclasses.fields(on):
        if f.name == "telemetry":
            continue
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(on, f.name)),
            jax.tree_util.tree_leaves(getattr(off, f.name)),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f.name
            )
    np.testing.assert_array_equal(
        np.asarray(on.telemetry.totals), np.asarray(off.telemetry.totals)
    )
    spans, dropped, _ = T.completed_spans(on.telemetry)
    assert spans and dropped == 0
    for s in spans:
        # A CAS round trip (phase 1 + phase 2 quorums) separates issue
        # from visibility; choice and execution are ONE event.
        assert 0 <= s["proposed"] < s["committed"] == s["executed"], s
        if s["phase2_voted"] != -1:
            # The acceptor vote lands before the leader learns quorum.
            assert s["proposed"] < s["phase2_voted"] < s["committed"], s
        if s["phase1_promised"] != -1:
            assert s["phase1_promised"] > s["proposed"], s
        assert 0 <= s["group"] < cfg.num_registers, s
    # The rotating reservoir samples across the register axis.
    assert len({s["group"] for s in spans}) > 1


def test_caspaxos_serve_perfetto_round_trip(tmp_path):
    """The serve loop over caspaxos with the span sampler on: the
    Perfetto export round-trips with DEVICE lifecycle slices (register-
    bit spans) and host dispatch spans in one timeline."""
    from frankenpaxos_tpu.tpu import caspaxos_batched as cp

    cfg = cp.analysis_config()
    out = tmp_path / "caspaxos_trace.json"
    serve = ServeConfig(
        chunk_ticks=16, telemetry_window=64, spans=8,
        trace_path=str(out), max_chunks=4,
    )
    loop = ServeLoop(cp, cfg, serve, seed=0)
    report = loop.run()
    assert report["clean_shutdown"] and report["spans_exported"] > 0
    payload = traceviz.load_chrome_trace(str(out))
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    device = [e for e in xs if e["pid"] == traceviz.DEVICE_PID]
    host = [e for e in xs if e["pid"] == traceviz.HOST_PID]
    assert device and host
    lifecycles = [e for e in device if e.get("cat") == "lifecycle"]
    assert lifecycles
    assert all("committed" in e["args"] for e in lifecycles)
