"""Pedagogical simulator examples (bankaccount + diehard, the analog of
``shared/src/test/scala/{bankaccount,diehard}``): the harness both
verifies invariants that hold and FINDS states that violate falsifiable
ones — the Die Hard water-jug solution drops out as a minimized
counterexample history."""

from frankenpaxos_tpu.examples import (
    DieHard,
    SimulatedBankAccount,
    SimulatedBuggyBankAccount,
    SimulatedDieHard,
)
from frankenpaxos_tpu.sim import simulate, simulate_and_minimize


def test_bank_account_always_positive():
    """BankAccountTest.scala: the guarded account never goes negative."""
    bad = simulate_and_minimize(
        SimulatedBankAccount(), run_length=100, num_runs=100, seed=0
    )
    assert bad is None, f"\n{bad}"


def test_buggy_bank_account_caught_and_shrunk():
    """Removing the withdraw guard must be caught, and the minimized
    counterexample is a single unfunded withdrawal."""
    bad = simulate_and_minimize(
        SimulatedBuggyBankAccount(), run_length=100, num_runs=100, seed=0
    )
    assert bad is not None
    assert "negative" in bad.error
    # Shrinking should reduce the history to just one withdraw (possibly
    # preceded by deposits smaller than it — but a lone withdraw suffices
    # and ddmin finds it).
    assert len(bad.history) == 1, bad
    assert type(bad.history[0]).__name__ == "Withdraw"


def test_diehard_finds_the_solution():
    """The simulator solves the water-jug puzzle: the minimized violating
    history of the "big != 4" invariant is a valid pouring sequence
    ending with exactly 4 gallons in the 5-gallon jug (DieHard.scala,
    Lamport's TLA+ example)."""
    sim = SimulatedDieHard()
    bad = simulate(sim, run_length=60, num_runs=200, seed=0)
    assert bad is not None, "simulator never measured 4 gallons"

    from frankenpaxos_tpu.sim import minimize

    shrunk = minimize(sim, bad.seed, bad.history)
    assert "4 gallons" in shrunk.error

    # Replay the minimized history on a fresh puzzle: it must genuinely
    # end with big == 4, and the classic solution takes 6 steps, so the
    # shrunk history can't beat that.
    jugs = DieHard()
    for command in shrunk.history:
        getattr(jugs, command)()
    assert jugs.big == 4
    assert 6 <= len(shrunk.history) <= 12, shrunk
