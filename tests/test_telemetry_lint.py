"""Telemetry contract (thin wrapper): every batched *State threads the
Telemetry carry, every tick records into it, and no host-sync primitive
is reachable from any compiled tick/run_ticks/step body — TRANSITIVELY,
through helpers in ``tpu/`` and ``ops/`` (the old ad-hoc lint only saw
syncs written inline in the tick body itself).

The checkers are the ``telemetry-*`` and ``host-sync-purity`` rules in
``frankenpaxos_tpu/analysis``; synthetic positive/negative fixtures for
them live in ``test_analysis_engine.py``. Intentional exceptions go in
``analysis/allowlists.py`` with a reason.
"""

import pytest

from frankenpaxos_tpu import analysis

pytestmark = pytest.mark.lint


@pytest.mark.parametrize(
    "rule_id",
    ["telemetry-state-carry", "telemetry-tick-records", "host-sync-purity"],
)
def test_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()
