"""AST lint: the telemetry contract across every batched backend
(the tpu/telemetry.py repo-wide contract, sibling of the donation lint
in test_donation_lint.py).

Three clauses, enforced for every ``tpu/*_batched.py``:

 1. The backend's ``*State`` dataclass carries a ``telemetry`` field
    (annotated ``Telemetry``), so the ring threads through every
    ``run_ticks`` scan carry, donation, sharding, and vmap for free.
 2. Its ``tick`` function actually records — a ``record(...)`` call —
    so new backends can't silently ship a dead ring.
 3. NO host-sync primitive appears inside any tick/step/run_ticks body
    in ``tpu/``: ``block_until_ready``, ``device_get``, ``np.asarray``
    / ``numpy.asarray``, or ``.item()`` would serialize the compiled
    loop against the host — exactly what the device-side ring exists to
    avoid. (Top-level helpers like ``stats()``/``sweep()`` may sync;
    only the in-graph functions are constrained.)

Intentional exceptions go in the ALLOWLISTs with a reason.
"""

import ast
import pathlib

TPU_DIR = (
    pathlib.Path(__file__).resolve().parent.parent
    / "frankenpaxos_tpu"
    / "tpu"
)

# Files exempt from the State-carries-telemetry clause, with reasons.
STATE_ALLOWLIST = {
    # Nothing is currently exempt.
}

# (filename, function) -> reason a host-sync primitive is intentional.
HOST_SYNC_ALLOWLIST = {
    # Nothing is currently exempt.
}

# Function names whose bodies run INSIDE the compiled scan and are
# therefore subject to the no-host-sync clause.
IN_GRAPH_FUNCS = ("tick", "run_ticks", "step")

HOST_SYNC_ATTRS = ("block_until_ready", "device_get", "asarray", "item")


def _batched_files():
    files = sorted(TPU_DIR.glob("*_batched.py"))
    assert len(files) >= 13, [f.name for f in files]
    return files


def _state_classes(tree):
    """ClassDef nodes that look like registered *State dataclasses."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name.endswith("State"):
            out.append(node)
    return out


def test_every_backend_state_threads_the_telemetry_carry():
    offenders = []
    for path in _batched_files():
        if path.name in STATE_ALLOWLIST:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        classes = _state_classes(tree)
        assert classes, f"{path.name}: no *State dataclass found"
        for cls in classes:
            fields = {
                stmt.target.id: ast.unparse(stmt.annotation)
                for stmt in cls.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
            ann = fields.get("telemetry")
            if ann is None or "Telemetry" not in ann:
                offenders.append((path.name, cls.name))
    assert not offenders, (
        "batched *State dataclasses without a `telemetry: Telemetry` "
        f"field (the tpu/telemetry.py carry contract): {offenders}"
    )


def test_every_backend_tick_records_telemetry():
    offenders = []
    for path in _batched_files():
        tree = ast.parse(path.read_text(), filename=str(path))
        tick_funcs = [
            n
            for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name == "tick"
        ]
        assert tick_funcs, f"{path.name}: no tick function"
        for func in tick_funcs:
            calls_record = any(
                isinstance(n, ast.Call)
                and (
                    (isinstance(n.func, ast.Name) and n.func.id == "record")
                    or (
                        isinstance(n.func, ast.Attribute)
                        and n.func.attr == "record"
                    )
                )
                for n in ast.walk(func)
            )
            if not calls_record:
                offenders.append(path.name)
    assert not offenders, (
        "tick functions that never call telemetry.record() — a dead "
        f"ring ships no observability: {offenders}"
    )


def _host_sync_offenses(func: ast.FunctionDef, fname: str):
    """Host-sync attribute/name references anywhere in ``func``'s body
    (including nested ``step`` closures)."""
    offenders = []
    for node in ast.walk(func):
        attr = None
        if isinstance(node, ast.Attribute) and node.attr in HOST_SYNC_ATTRS:
            attr = node.attr
        elif (
            isinstance(node, ast.Name) and node.id in HOST_SYNC_ATTRS
        ):
            attr = node.id
        if attr is None:
            continue
        if (fname, func.name) in HOST_SYNC_ALLOWLIST:
            continue
        offenders.append((fname, func.name, attr, node.lineno))
    return offenders


def test_no_host_sync_inside_tick_bodies():
    offenders = []
    for path in sorted(TPU_DIR.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.FunctionDef)
                and node.name in IN_GRAPH_FUNCS
            ):
                offenders.extend(_host_sync_offenses(node, path.name))
    assert not offenders, (
        "host-sync primitives inside compiled tick/run_ticks bodies "
        "(they serialize the scan against the host — use the telemetry "
        f"ring instead): {offenders}"
    )


def test_lint_detects_a_violation():
    """The host-sync matcher has teeth: a synthetic tick body using
    jax.device_get must be flagged."""
    src = (
        "def tick(cfg, state, t, key):\n"
        "    x = jax.device_get(state.committed)\n"
        "    return state\n"
    )
    func = ast.parse(src).body[0]
    assert _host_sync_offenses(func, "synthetic.py")


def test_allowlists_reference_existing_code():
    for fname in STATE_ALLOWLIST:
        assert (TPU_DIR / fname).exists(), f"stale allowlist file {fname}"
    for fname, func in HOST_SYNC_ALLOWLIST:
        path = TPU_DIR / fname
        assert path.exists(), f"stale allowlist file {fname}"
        tree = ast.parse(path.read_text())
        names = {
            n.name
            for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef)
        }
        assert func in names, f"stale allowlist entry {fname}:{func}"
