"""Harness self-tests (the analog of benchmarks/cluster_test.py and
proc_test.py)."""

import csv
import dataclasses
import os
import random
import sys

import pytest

from frankenpaxos_tpu.harness import (
    Cluster,
    PopenProc,
    Reaped,
    Suite,
    workload_from_dict,
)
from frankenpaxos_tpu.harness.benchmark import (
    flatten,
    summarize_latency_throughput,
)
from frankenpaxos_tpu.harness.workload import (
    BernoulliSingleKeyWorkload,
    ReadWriteWorkload,
    StringWorkload,
    UniformSingleKeyWorkload,
)


def test_cluster_json(tmp_path):
    path = tmp_path / "cluster.json"
    path.write_text(
        '{"leaders": {"1": ["a", "b"], "2": ["a", "b", "c"]},'
        ' "acceptors": {"1": ["x", "y", "z"]}}'
    )
    cluster = Cluster.from_json_file(str(path))
    assert cluster.roles() == ["acceptors", "leaders"]
    sub = cluster.f(1)
    assert sub["leaders"] == ["a", "b"]
    assert sub["acceptors"] == ["x", "y", "z"]
    assert cluster.f(2)["leaders"] == ["a", "b", "c"]
    assert cluster.f(2).get("acceptors") is None


def test_popen_proc_capture(tmp_path):
    out = tmp_path / "out.txt"
    proc = PopenProc(
        [sys.executable, "-c", "print('hello from proc')"], stdout=str(out)
    )
    assert proc.wait(timeout=30) == 0
    proc.kill()
    assert "hello from proc" in out.read_text()


def test_reaped_kills_on_exception(tmp_path):
    with pytest.raises(RuntimeError):
        with Reaped() as reaped:
            proc = reaped.register(
                PopenProc([sys.executable, "-c", "import time; time.sleep(60)"])
            )
            raise RuntimeError("boom")
    assert proc.wait(timeout=10) is not None  # killed, not still sleeping


def test_flatten():
    @dataclasses.dataclass
    class Inner:
        x: int

    @dataclasses.dataclass
    class Outer:
        inner: Inner
        name: str

    assert flatten(Outer(Inner(3), "n"), "input") == {
        "input.inner.x": 3,
        "input.name": "n",
    }
    assert flatten(5, "v") == {"v": 5}


def test_workloads_roundtrip_and_generate():
    rng = random.Random(0)
    for workload in [
        StringWorkload(size_mean=6),
        UniformSingleKeyWorkload(num_keys=3),
        BernoulliSingleKeyWorkload(conflict_rate=0.5),
        ReadWriteWorkload(read_fraction=0.5, num_keys=4),
    ]:
        again = workload_from_dict(workload.to_dict())
        assert type(again) is type(workload)
        for _ in range(10):
            assert isinstance(workload.get(rng), bytes)
    rw = ReadWriteWorkload(read_fraction=1.0)
    assert rw.is_read(rw.get(rng))
    rw0 = ReadWriteWorkload(read_fraction=0.0)
    assert not rw0.is_read(rw0.get(rng))
    with pytest.raises(ValueError):
        workload_from_dict({"type": "nope"})


def test_percentiles_nearest_rank():
    rows = [
        {"start": float(i), "latency_nanos": 2e6} for i in range(99)
    ] + [{"start": 99.0, "latency_nanos": 5000e6}]
    s = summarize_latency_throughput(rows)
    assert s.p99_ms == 2.0  # rank 99 of 100, NOT the outlier max
    assert s.p90_ms == 2.0


def test_suite_widening_schema(tmp_path):
    class WideningSuite(Suite):
        def inputs(self):
            return [1, 2]

        def run_benchmark(self, bench, args, input):
            return {"ok": 1} if input == 1 else {"ok": 0, "error": "boom"}

    suite_dir = WideningSuite().run_suite(str(tmp_path), "widening")
    with open(os.path.join(suite_dir.path, "results.csv")) as f:
        rows = list(csv.DictReader(f))
    assert rows[0]["output.error"] == ""
    assert rows[1]["output.error"] == "boom"


def test_summarize():
    rows = [
        {"start": float(i), "latency_nanos": (i + 1) * 1e6} for i in range(10)
    ]
    s = summarize_latency_throughput(rows)
    assert s.count == 10
    assert s.median_ms == 5.0  # nearest-rank: ceil(0.5*10)-1 = index 4
    assert round(s.throughput_per_s, 2) == round(10 / 9.0, 2)
    assert summarize_latency_throughput([]) is None


@dataclasses.dataclass(frozen=True)
class DoubleInput:
    x: int


class DoublingSuite(Suite):
    def inputs(self):
        return [DoubleInput(1), DoubleInput(2), DoubleInput(3)]

    def run_benchmark(self, bench, args, input):
        bench.write_string("scratch.txt", "hi")
        return {"doubled": input.x * 2}


def test_suite_run(tmp_path):
    suite_dir = DoublingSuite().run_suite(str(tmp_path), "doubling")
    assert os.path.exists(os.path.join(suite_dir.path, "args.json"))
    with open(os.path.join(suite_dir.path, "results.csv")) as f:
        rows = list(csv.DictReader(f))
    assert [r["input.x"] for r in rows] == ["1", "2", "3"]
    assert [r["output.doubled"] for r in rows] == ["2", "4", "6"]
    for i in (1, 2, 3):
        bench = os.path.join(suite_dir.path, f"{i:03}")
        assert os.path.exists(os.path.join(bench, "input.json"))
        assert os.path.exists(os.path.join(bench, "scratch.txt"))


def test_in_process_smokes():
    from frankenpaxos_tpu.harness import smoke

    for name in [
        "echo", "unreplicated", "batchedunreplicated", "paxos",
        "fastpaxos", "caspaxos", "craq", "epaxos",
    ]:
        result = smoke.SMOKES[name](None)
        assert result["requests"] > 0, name


def test_deployment_registry_consistent():
    """Every protocol spec's generated local config parses into a valid
    Config whose role counts are well-formed (each role constructible)."""
    from frankenpaxos_tpu.mains.registry import REGISTRY

    assert len(REGISTRY) == 19  # all protocols except multipaxos (own main)
    for name, spec in REGISTRY.items():
        hp = lambda i: f"127.0.0.1:{19000 + i}"
        data = spec.local_config(hp)
        config = spec.parse_config(data)
        for role_name, role in spec.roles.items():
            cnt = role.count(config)
            if role.grouped:
                groups, per_group = cnt
                assert groups > 0 and per_group > 0, (name, role_name)
            else:
                assert cnt > 0, (name, role_name)
        assert spec.make_client is not None, name


def test_deploy_smokes_sample(tmp_path):
    """Real multi-process TCP deployments of a leader-based and a
    leaderless protocol (the full set runs via
    ``python -m frankenpaxos_tpu.harness.smoke --deploy``)."""
    from frankenpaxos_tpu.harness.benchmark import BenchmarkDirectory
    from frankenpaxos_tpu.harness import smoke

    for name in ["paxos", "epaxos"]:
        bench = BenchmarkDirectory(str(tmp_path / name))
        with bench:
            result = smoke.deploy_smoke(name, bench, duration=2.0)
        assert result["requests"] > 0, name


def test_microbench_smoke():
    """Every microbenchmark runs and reports sane rows (the scalameter
    suite analog, jvm/src/bench/scala)."""
    from frankenpaxos_tpu.harness import microbench

    rows = []
    rows += microbench.bench_depgraph(
        num_commands=300, batch=16, window=16, rounds=1, closure_iters=4
    )
    rows += microbench.bench_int_prefix_set(num_ops=2000)
    rows += microbench.bench_buffer_map(num_ops=2000)
    rows += microbench.bench_conflict_index(num_ops=500)
    assert {r["name"] for r in rows} == {
        "depgraph", "int_prefix_set", "buffer_map", "conflict_index",
    }
    assert {r["case"] for r in rows if r["name"] == "depgraph"} == {
        "Tarjan", "IncrementalTarjan", "Naive", "Zigzag",
        "bitmask_closure", "pointer_walk",
    }
    assert all(r["ops_per_sec"] > 0 for r in rows)


def test_microbench_hbm_smoke():
    """The HBM-bandwidth device bench at toy size, before/after pair
    only (each variant costs two XLA compiles; the two intermediate
    variants are CLI-only). The shipped variant (narrow+donate) must
    measure a strictly smaller state footprint than the int32 reference
    AND a nonzero aliased (donated) size; the non-donating baseline
    aliases nothing."""
    from frankenpaxos_tpu.harness import microbench

    rows = microbench.bench_hbm(
        num_groups=8, window=16, slots_per_tick=2, ticks=10,
        cases=("int32_nodonate", "narrow_donate"),
    )
    by_case = {r["case"]: r for r in rows}
    assert set(by_case) == {"int32_nodonate", "narrow_donate"}
    before = by_case["int32_nodonate"]
    after = by_case["narrow_donate"]
    assert after["state_bytes"] < before["state_bytes"]
    assert before["alias_bytes"] == 0
    assert after["alias_bytes"] > 0
    assert after["peak_bytes"] < before["peak_bytes"]
    assert all(r["ops_per_sec"] > 0 for r in rows)


def test_microbench_faults_smoke():
    """The degraded-mode bench at toy size (guards `bench.py --faults`):
    healthy vs faulty runs complete, the faulty plan injects REAL faults
    (drops + retries + leader changes land in the telemetry ring), both
    sides commit, and invariants hold under the degraded plan."""
    from frankenpaxos_tpu.harness import microbench
    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig
    from frankenpaxos_tpu.tpu.telemetry import COL

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, retry_timeout=8,
    )
    measured = microbench.measure_fault_overhead(cfg, ticks=50, rounds=1)
    assert measured["rates"]["healthy"] > 0
    assert measured["rates"]["faulty"] > 0
    assert measured["committed"]["healthy"] > 0
    assert 0 < measured["committed"]["faulty"] <= measured["committed"][
        "healthy"
    ]
    tel = measured["sim_faulty"].telemetry()
    assert int(tel.totals[COL["drops"]]) > 0, "plan injected no drops"
    assert all(measured["sim_faulty"].check_invariants().values())
    # The plan in the result is the documented degraded plan, JSON-ready.
    assert measured["plan"]["drop_rate"] == (
        microbench.DEGRADED_PLAN_KW["drop_rate"]
    )

    # bench.py forwards the flag to the inner measurement process.
    import pathlib

    bench_src = (
        pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    ).read_text()
    assert '"--faults"' in bench_src and '"faults"' in bench_src


def test_microbench_workload_smoke():
    """The workload-engine bench at toy size (guards ``microbench
    workload`` and ``bench.py --workload``): every shaping tier runs,
    shaped runs commit less than saturation (the load really shapes),
    the closed tier stays window-bound, and the overhead ratios come
    back finite."""
    from frankenpaxos_tpu.harness import microbench
    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, retry_timeout=8,
    )
    measured = microbench.measure_workload_overhead(
        cfg, ticks=50, rounds=1
    )
    assert set(measured["ratios"]) == {"constant", "poisson", "closed"}
    assert all(r > 0 for r in measured["ratios"].values())
    c = measured["committed"]
    assert c["none"] > 0
    # rate == slots_per_tick but backlog warm-up + Zipf skew keep the
    # shaped tiers at or under saturation throughput.
    assert 0 < c["constant"] <= c["none"]
    assert 0 < c["poisson"] <= c["none"]
    assert 0 < c["closed"] < c["none"]
    for case in ("constant", "poisson", "closed"):
        sim = measured["sims"][case]
        assert all(sim.check_invariants().values()), case

    # bench.py exposes the separate --workload mode + its inner half.
    import pathlib

    bench_src = (
        pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    ).read_text()
    assert '"--workload"' in bench_src
    assert "--inner-workload" in bench_src


def test_simtest_joint_randomization_smoke():
    """The joint [workload x fault] schedule axis (guards the simtest
    sweep): a randomized workload + fault pair runs green with the
    workload invariant merged into the per-segment checks."""
    import random as _random

    from frankenpaxos_tpu.harness import simtest
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    spec = simtest.SPECS["unreplicated"]
    rng = _random.Random(42)
    # Draw until a genuinely active workload comes up (deterministic).
    wplan = simtest.random_workload(rng, spec, 80)
    while not wplan.active:
        wplan = simtest.random_workload(rng, spec, 80)
    res = simtest.run_schedule(
        spec, FaultPlan(drop_rate=0.05), seed=1, ticks=80, segment=40,
        workload=wplan,
    )
    assert res["ok"], res["violations"]
    assert res["progress"][-1] > 0
    assert res["workload"]["type"] == "device_plan"


def test_simtest_fleet_brick_smoke():
    """Tier-1 smoke for the fleet axis (guards ``bench.py --fleet`` +
    ``simtest --fleet``): a tiny [2 schedules x 2 seeds] brick runs as
    ONE compiled executable on a 2x2 product mesh carved from the
    conftest's 8 virtual devices — per-instance traced rates, invariants
    reduced in-graph, verdicts identical to the default-device brick."""
    import jax

    from frankenpaxos_tpu.harness import simtest
    from frankenpaxos_tpu.parallel import sharding as sh

    mesh = sh.make_fleet_mesh(fleet=2, devices=jax.devices()[:4])
    res = simtest.run_fleet(
        simtest.SPECS["multipaxos"], schedules=2, seeds_per_schedule=2,
        ticks=40, mesh=mesh,
    )
    assert res["ok"], res["failures"]
    assert res["instances"] == 4 and res["mesh"] == [2, 2]
    assert all(p > 0 for p in res["progress"])
    # The whole brick is one executable for this mesh.
    assert simtest._fleet_program(
        "multipaxos", mesh, None
    )._cache_size() == 1


def test_microbench_fleet_smoke():
    """The fleet brick-vs-sequential race at toy size (guards
    ``microbench fleet``): both sides run green, verdicts agree, and
    the timing fields are populated."""
    from frankenpaxos_tpu.harness import microbench

    rows = microbench.bench_fleet(
        ticks=20, schedules=2, seeds_per_schedule=2, rounds=1
    )
    summary = next(r for r in rows if r["case"] == "summary")
    assert summary["fleet_ok"] and summary["sequential_ok"]
    assert summary["cold_fleet_seconds"] > 0
    assert summary["cold_sequential_seconds"] > 0


def test_microbench_kernels_smoke():
    """The kernel-layer bench at toy size (guards ``microbench
    kernels``): every registered plane reports a reference timing and —
    off-TPU — interpret-mode BIT-PARITY with its reference twin; and
    bench.py surfaces the kernel_policy/coverage fields."""
    from frankenpaxos_tpu.harness import microbench
    from frankenpaxos_tpu.ops import registry

    rows = microbench.bench_kernels(
        iters=2, A=3, G=32, W=16, N=32, L=3, KV=4, CW=8
    )
    cases = {r["case"] for r in rows}
    for name in registry.PLANES:
        assert f"{name}:reference" in cases
    assert all(r["ops_per_sec"] > 0 for r in rows)

    import pathlib

    bench_src = (
        pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    ).read_text()
    assert '"kernel_policy"' in bench_src and '"kernel_coverage"' in bench_src
    assert '"fused_tick"' in bench_src


def test_microbench_costmodel_smoke(capsys):
    """The cost-model observatory pass at toy size (guards
    ``microbench costmodel``): byte terms exact against live arrays,
    every registered plane covered, the committed captures replay
    clean through the drift engine, and the COSTMODEL_JSON line
    carries the envelope-artifact payload."""
    import json as _json

    from frankenpaxos_tpu.harness import microbench
    from frankenpaxos_tpu.ops import costmodel, registry

    rows = microbench.bench_costmodel(A=3, G=32, W=16, N=32, L=3, KV=4, CW=8)
    assert rows and all(r["ops_per_sec"] > 0 for r in rows)
    line = next(
        ln for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("COSTMODEL_JSON ")
    )
    payload = _json.loads(line[len("COSTMODEL_JSON "):])
    assert payload["bytes_exact"] is True
    assert payload["uncovered_planes"] == []
    assert payload["drift_findings"] == []
    assert payload["constants_version"] == costmodel.CONSTANTS_VERSION
    assert set(registry.PLANES) <= set(payload["planes"])
    assert "costmodel" in microbench.DEVICE_BENCHES


def test_microbench_fused_tick_smoke():
    """The megakernel-vs-multiplane race at toy size (guards
    ``microbench fused_tick``): both sides sweep blocks, outputs are
    bit-identical, and the summary row carries the speedup."""
    from frankenpaxos_tpu.harness import microbench

    rows = microbench.bench_fused_tick(
        iters=1, rounds=1, A=3, G=32, W=16, N=32, L=3, KV=4, CW=8
    )
    summary = next(r for r in rows if r["case"] == "summary")
    assert summary["bit_identical"] is True
    assert summary["speedup"] > 0
    assert {r["case"] for r in rows} == {"fused", "multiplane", "summary"}


def test_microbench_grid_vote_smoke():
    """The grid-vote fused-vs-unfused race at toy size (guards
    ``microbench grid_vote``): the interleaved (side x block) matrix
    runs, outputs are bit-identical, and the summary carries both the
    dispatch-block and best-vs-best ratios plus the sweep table."""
    from frankenpaxos_tpu.harness import microbench

    rows = microbench.bench_grid_vote(
        iters=1, rounds=1, A=3, G=32, W=16, N=32, L=3, KV=4, CW=8
    )
    summary = next(r for r in rows if r["case"] == "summary")
    assert summary["bit_identical"] is True
    assert summary["speedup"] > 0
    assert summary["speedup_best_vs_best"] > 0
    assert set(summary["block_sweep_seconds"]) == {"fused", "unfused"}
    assert summary["shape"][:2] == [2, 2]  # [R, C, G, W]


def test_microbench_mesh_kernels_smoke():
    """The sharded kernels-vs-reference race at toy size (guards
    ``microbench mesh_kernels``): compiles on the conftest mesh, the
    two sharded programs replay each other bit for bit, and the
    off-TPU row is flagged pending_tpu_remeasure."""
    from frankenpaxos_tpu.harness import microbench

    rows = microbench.bench_mesh_kernels(
        ticks=6, rounds=1, groups_per_device=8
    )
    summary = next(r for r in rows if r["case"] == "summary")
    assert summary["bit_identical"] is True
    assert summary["committed"] > 0
    assert summary["pending_tpu_remeasure"] is True


def test_deploy_smoke_profiles_a_role(tmp_path):
    """profile_role wraps one role with cProfile and the pstats dump
    lands in the bench dir (perf_util.py capability)."""
    import pstats

    from frankenpaxos_tpu.harness.benchmark import BenchmarkDirectory
    from frankenpaxos_tpu.harness import smoke

    bench = BenchmarkDirectory(str(tmp_path / "prof"))
    with bench:
        result = smoke.deploy_smoke(
            "unreplicated", bench, duration=1.5, profile_role="server"
        )
    assert result["requests"] > 0
    stats = pstats.Stats(bench.abspath("profile_server.pstats"))
    assert len(stats.stats) > 50

    with pytest.raises(ValueError):
        smoke.deploy_smoke("unreplicated", bench, profile_role="bogus")


def test_serve_smoke(tmp_path):
    """The serve-mode smoke (guards scripts/serve_smoke.sh + bench.py
    --serve): a bounded serve run of the flagship backend through the
    chunked-dispatch loop shuts down cleanly with zero drop, exports a
    Perfetto-loadable trace carrying BOTH device lifecycle spans and
    host dispatch spans, and feeds the live scrape CSV the dashboard's
    --live mode tails."""
    from frankenpaxos_tpu.harness.serve import serve_flagship
    from frankenpaxos_tpu.monitoring import traceviz

    report = serve_flagship(
        seconds=120.0, out_dir=str(tmp_path), num_groups=32,
        chunk_ticks=16, spans=8, rate_x=1.1, slo_p99=24,
        max_chunks=8,
    )
    assert report["clean_shutdown"]
    assert report["ticks"] == 8 * 16
    assert report["dropped_ticks"] == 0
    assert report["spans_exported"] > 0
    tr = traceviz.load_chrome_trace(str(tmp_path / "serve_trace.json"))
    xs = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
    assert any(e["pid"] == traceviz.DEVICE_PID for e in xs)
    assert any(e["pid"] == traceviz.HOST_PID for e in xs)
    assert (tmp_path / "serve_metrics.csv").stat().st_size > 0
    assert report["slo"]["observations"] == 8
