"""Trace-layer rules over every batched backend: what XLA actually
compiles must honor the dtype policy (no unallowlisted narrow->wide
conversions in the tick jaxpr), donation must actually alias the State
buffers in the compiled HLO, and an equal config must hit the jit
cache. One test per backend so a regression localizes immediately.

These are the checks the AST lints structurally cannot make — a silent
``int16 -> int32`` upcast, a donation that fails to alias, or a
config-hashability retrace all pass every syntax lint while eating the
HBM/throughput wins. Compile cost is bounded by the tiny
``analysis_config()`` shapes plus the persistent XLA compilation cache
(conftest.py / rules_trace._jax_cache_setup).
"""

import pytest

from frankenpaxos_tpu.analysis import allowlists, core, rules_trace

pytestmark = pytest.mark.lint

TRACE_RULES = [
    "trace-dtype-policy",
    "trace-donation-alias",
    "trace-retrace-guard",
    # Kernels x mesh: sharded wrappers with the policy engaged must
    # shard_map-lower the Pallas planes (no silent reference fallback,
    # no signed-state collectives beyond the stat reductions); no-op
    # for backends outside the sharding registry.
    "trace-shardmap-kernel",
    # Serve hot path: run_ticks + the telemetry snapshot compile free
    # of host callbacks, and the snapshot copies (aliases nothing);
    # no-op for every backend except the flagship serve target.
    "trace-serve-nosync",
    # Fleet axis: a [seeds x workload x fault] brick is one compiled
    # executable per product mesh (flat jit cache across traced-rate
    # re-sweeps) and no signed collective crosses the fleet axis;
    # no-op for backends outside the sharding registry.
    "trace-fleet-onecompile",
    # Fleet serve hot path: run_ticks_fleet + the fleet snapshot (with
    # the in-graph summary) compile callback-free, the snapshot
    # aliases nothing, summary collectives stay summary-sized, and a
    # per-instance SLO clamp re-entry keeps the runner's jit cache
    # flat; no-op for every backend except the flagship serve target.
    "trace-fleet-drain-nosync",
]


@pytest.mark.parametrize("backend", rules_trace.BACKENDS)
def test_trace_rules_clean(backend):
    ctx = core.Context(backends=(backend,))
    report = core.run(rule_ids=TRACE_RULES, ctx=ctx)
    assert not report.findings, "\n" + report.format()


def test_all_backends_registered():
    """The trace layer covers every batched backend module."""
    import pathlib

    from frankenpaxos_tpu.analysis import astutil

    stems = {
        p.name[: -len("_batched.py")]
        for p in astutil.batched_files(astutil.PKG_ROOT)
    }
    assert stems == set(rules_trace.BACKENDS)
    assert len(rules_trace.BACKENDS) >= 13
    del pathlib


def test_dtype_pin_has_teeth(monkeypatch):
    """A DTYPE_WIDENING pin that the jaxpr does not satisfy (here: a
    conversion that never happens) must produce a mismatch finding —
    the exact-count pin rejects drift in BOTH directions."""
    monkeypatch.setitem(
        allowlists.DTYPE_WIDENING,
        ("unreplicated", "int8->int32"),
        (3, "synthetic pin for the teeth test"),
    )
    ctx = core.Context(backends=("unreplicated",))
    report = core.run(rule_ids=["trace-dtype-policy"], ctx=ctx)
    assert [f.key for f in report.findings] == ["unreplicated:int8->int32"]
    assert "pins 3" in report.findings[0].message


def test_shardmap_kernel_rule_has_teeth(monkeypatch):
    """Simulate the silent-fallback regression the rule exists for: if
    every plane resolves to the reference under a sharded trace (here:
    resolve_mode forced), the kernels-engaged wrapper traces zero
    pallas_calls and the rule must flag it."""
    from frankenpaxos_tpu.ops import registry

    monkeypatch.setattr(
        registry, "resolve_mode", lambda name, cfg: "reference"
    )
    ctx = core.Context(backends=("compartmentalized",))
    report = core.run(rule_ids=["trace-shardmap-kernel"], ctx=ctx)
    assert any(
        "fell back" in f.message for f in report.findings
    ), report.format()


def test_fleet_onecompile_rule_has_teeth(monkeypatch):
    """Simulate the cross-fleet regression the census exists for: with
    the fleet-row map deliberately wrong (columns instead of rows), the
    brick's in-row stat reductions no longer fit any row and the rule
    must flag them — proving it actually reads every collective's
    replica groups."""
    def wrong_rows(n_fleet, n_group):
        return [
            {i + j * n_group for j in range(n_fleet)}
            for i in range(n_group)
        ]

    monkeypatch.setattr(rules_trace, "_fleet_rows", wrong_rows)
    ctx = core.Context(backends=("multipaxos",))
    report = core.run(rule_ids=["trace-fleet-onecompile"], ctx=ctx)
    assert any(
        "crossing the fleet axis" in f.message for f in report.findings
    ), report.format()


def test_fleet_replica_group_parser():
    """The replica-group scraper handles the explicit brace format, the
    iota format, and the transposed-iota format."""
    assert rules_trace._collective_groups(
        "x = s32[2] all-reduce(y), replica_groups={{0,1,2,3},{4,5,6,7}}"
    ) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert rules_trace._collective_groups(
        "x = s32[2] all-reduce(y), replica_groups=[2,4]<=[8], to_apply=%r"
    ) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert rules_trace._collective_groups(
        "x = s32[2] all-reduce(y), replica_groups=[4,2]<=[2,4]T(1,0)"
    ) == [[0, 4], [1, 5], [2, 6], [3, 7]]
    assert rules_trace._collective_groups(
        "x = s32[2] all-reduce(y), replica_groups=<unknown-fmt>"
    ) is None


def test_alias_table_parser():
    """The HLO input_output_alias scraper handles the nested-brace
    table format (balanced-brace scan, not a fragile regex)."""
    hlo = (
        "HloModule jit_run_ticks, is_scheduled=true, "
        "input_output_alias={ {0}: (0, {}, may-alias), "
        "{1}: (2, {}, must-alias), {12}: (11, {}, may-alias) }, "
        "entry_computation_layout={(s8[4,16]{1,0})->(s8[4,16]{1,0})}"
    )
    assert rules_trace._alias_param_indices(hlo) == {0, 2, 11}
    assert rules_trace._alias_param_indices("HloModule bare") == set()


def test_unknown_backend_raises():
    ctx = core.Context(backends=("no-such-backend",))
    with pytest.raises(KeyError, match="no-such-backend"):
        core.run(rule_ids=["trace-dtype-policy"], ctx=ctx)


def test_fused_tick_rule_clean():
    """The flagship tick with the kernel policy engaged traces exactly
    ONE pallas_call — the whole-tick megakernel, no per-plane HBM round
    trips — and the reference-mode trace is pallas-free."""
    report = core.run(rule_ids=["trace-fused-tick"])
    assert not report.findings, "\n" + report.format()


def test_serve_nosync_rule_clean():
    """The serve chunk path (run_ticks + the jitted telemetry
    snapshot, with and without the span sampler) compiles free of
    host callbacks, and the snapshot aliases nothing."""
    report = core.run(rule_ids=["trace-serve-nosync"])
    assert not report.findings, "\n" + report.format()


def test_serve_nosync_rule_has_teeth(monkeypatch):
    """Simulate the regression the alias check exists for: a snapshot
    that DONATES its input aliases the output to the donated buffer —
    draining it after the next chunk would read reused memory — and
    the rule must flag it."""
    import jax

    from frankenpaxos_tpu.harness import serve as serve_mod

    monkeypatch.setattr(
        serve_mod,
        "_SNAP",
        jax.jit(serve_mod._copy_tree, donate_argnums=(0,)),
    )
    report = core.run(rule_ids=["trace-serve-nosync"])
    assert any("ALIASES" in f.message for f in report.findings), (
        report.format()
    )


def test_fused_tick_rule_has_teeth():
    """Disabling the fused-tick plane (per-plane dispatch: two
    pallas_calls) must trip the single-pallas_call pin."""
    from frankenpaxos_tpu.ops.registry import KernelPolicy
    from frankenpaxos_tpu.tpu import multipaxos_batched as mb

    cfg = mb.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2,
        kernels=KernelPolicy(
            mode="interpret", disable=("multipaxos_fused_tick",)
        ),
    )
    eqns = rules_trace._tick_eqns("multipaxos", cfg)
    assert rules_trace._count_pallas_calls(eqns) == 2


def test_fleet_drain_nosync_rule_clean():
    """The fleet serve chunk path (run_ticks_fleet + the jitted fleet
    snapshot with the in-graph summary) compiles free of host
    callbacks, the snapshot aliases nothing, the summary reduction
    moves nothing state-sized across the fleet axis, and a per-
    instance clamp re-entry keeps the fleet runner's jit cache flat."""
    report = core.run(rule_ids=["trace-fleet-drain-nosync"])
    assert not report.findings, "\n" + report.format()


def test_fleet_drain_nosync_rule_has_teeth(monkeypatch):
    """Simulate the regression the alias check exists for: a fleet
    snapshot that DONATES its input aliases the output buffers — the
    drain would read memory the next chunk's donation reused — and the
    rule must flag it."""
    import functools

    import jax

    from frankenpaxos_tpu.harness import serve as serve_mod
    from frankenpaxos_tpu.tpu import telemetry as telemetry_mod

    def donated_snap_fn(k_mad, expected_x1000, rings):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def snap(leaves):
            tel = leaves["telemetry"]
            return {
                "summary": telemetry_mod.fleet_summary(
                    tel,
                    wait_hist=leaves["wait_hist"],
                    shed=leaves["shed"],
                ),
                "telemetry": tel,
            }

        return snap

    monkeypatch.setattr(serve_mod, "_fleet_snap_fn", donated_snap_fn)
    report = core.run(
        rule_ids=["trace-fleet-drain-nosync"],
        ctx=core.Context(backends=("multipaxos",)),
    )
    assert any("ALIASES" in f.message for f in report.findings), (
        report.format()
    )
