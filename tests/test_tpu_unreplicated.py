"""Tests of the batched unreplicated ceiling baseline
(unreplicated_batched.py; the eurosys-fig1 framing: consensus throughput
as a fraction of the no-replication ceiling)."""

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import unreplicated_batched as ub


def test_ceiling_progress_and_latency():
    cfg = ub.BatchedUnreplicatedConfig(
        num_servers=8, window=32, ops_per_tick=4, lat_min=1, lat_max=3
    )
    state, t = ub.run_ticks(
        cfg, ub.init_state(cfg), jnp.int32(0), 200, jax.random.PRNGKey(0)
    )
    inv = ub.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    s = ub.stats(cfg, state, t)
    # Steady state completes ~K per server per tick.
    assert s["done"] > 8 * 4 * 200 * 0.8
    # An op is exactly two hops.
    assert s["latency_p50_ticks"] >= 2
    assert s["latency_mean_ticks"] <= 2 * 3 + 1


def test_ceiling_is_cheaper_than_consensus_per_tick():
    """The whole point of the baseline: at identical (G, W, K, latency)
    settings the unreplicated tick does strictly less work than the
    MultiPaxos tick, so its wall-clock ops/sec bounds any consensus
    backend from above on the same hardware."""
    import time

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

    G, W, K = 256, 32, 4
    ucfg = ub.BatchedUnreplicatedConfig(
        num_servers=G, window=W, ops_per_tick=K, lat_min=1, lat_max=3
    )
    ustate, ut = ub.run_ticks(
        ucfg, ub.init_state(ucfg), jnp.int32(0), 200, jax.random.PRNGKey(0)
    )
    jax.block_until_ready(ustate)
    u0 = int(ustate.done)
    t0 = time.perf_counter()
    ustate, ut = ub.run_ticks(ucfg, ustate, ut, 200, jax.random.PRNGKey(1))
    jax.block_until_ready(ustate)
    u_rate = (int(ustate.done) - u0) / (time.perf_counter() - t0)

    sim = TpuSimTransport(
        BatchedMultiPaxosConfig(
            f=1, num_groups=G, window=W, slots_per_tick=K,
            lat_min=1, lat_max=3,
        ),
        seed=0,
    )
    sim.run(200)
    sim.block_until_ready()
    c0 = sim.committed()
    t0 = time.perf_counter()
    sim.run(200)
    sim.block_until_ready()
    m_rate = (sim.committed() - c0) / (time.perf_counter() - t0)
    # The ceiling holds with comfortable margin (2 hops vs 4+ and a
    # fraction of the arrays); avoid flaky tight bounds.
    assert u_rate > m_rate, (u_rate, m_rate)
