"""Tests of the device-side replica state machine + client table in the
batched MultiPaxos backend (Replica.executeCommand, Replica.scala:305-344:
client-table dedup then stateMachine.run; ClientTable.scala;
KeyValueStore.scala). CPU backend, 8 virtual devices via conftest."""

import dataclasses

import jax
import numpy as np

from frankenpaxos_tpu.parallel import make_mesh, run_ticks_sharded, shard_state
from frankenpaxos_tpu.tpu import (
    BatchedMultiPaxosConfig,
    TpuSimTransport,
    init_state,
    run_ticks,
)


def make(**kw):
    defaults = dict(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=3, retry_timeout=4,
        state_machine="kv", kv_keys=8, num_clients=4, dup_rate=0.4,
    )
    defaults.update(kw)
    return BatchedMultiPaxosConfig(**defaults)


def test_sm_counters_conserve_and_dups_filtered():
    sim = TpuSimTransport(make(), seed=0)
    sim.run(100)
    stats = sim.stats()
    inv = sim.check_invariants()
    assert all(inv.values()), inv
    # With no failovers there are no noops: every retired slot is a real
    # command, so it either applied to the SM or was filtered as a dup.
    assert stats["sm_applied"] + stats["dups_filtered"] == stats["executed"]
    assert stats["dups_filtered"] > 0  # dup_rate=0.4 must actually inject
    assert stats["sm_applied"] > 0
    assert 0 < stats["kv_keys_set"] <= 4 * 8


def test_sm_off_is_inert_and_asserts_dup_rate():
    sim = TpuSimTransport(
        BatchedMultiPaxosConfig(
            f=1, num_groups=4, window=16, slots_per_tick=2
        ),
        seed=0,
    )
    sim.run(30)
    assert int(sim.state.sm_applied) == 0
    assert sim.state.kv_val.shape == (4, 0)
    assert all(sim.check_invariants().values())
    try:
        BatchedMultiPaxosConfig(
            f=1, num_groups=4, window=16, slots_per_tick=2, dup_rate=0.1
        )
        assert False, "dup_rate without state_machine must be rejected"
    except AssertionError:
        pass


def test_sm_host_replay_is_exact():
    """Reconstruct every retired command from tick-by-tick snapshots and
    replay the client-table + KV semantics in plain Python (an independent
    implementation of ClientTable.executed + KeyValueStore.run); the
    device state must match field-for-field."""
    cfg = make(num_groups=3, window=16, slots_per_tick=2, kv_keys=8,
               num_clients=4, dup_rate=0.4)
    G, W, NC, KV = 3, 16, 4, 8
    sim = TpuSimTransport(cfg, seed=3)

    ct = np.full((G, NC), -1, np.int64)
    kv = np.full((G, KV), -1, np.int64)
    applied = 0
    filtered = 0
    for _ in range(80):
        head_b = np.asarray(jax.device_get(sim.state.head), np.int64)
        chosen_b = np.asarray(jax.device_get(sim.state.chosen_value), np.int64)
        sim.run(1)
        head_a = np.asarray(jax.device_get(sim.state.head), np.int64)
        for g in range(G):
            for s in range(head_b[g], head_a[g]):
                cmd = chosen_b[g, s % W]
                assert cmd >= 0, "no noops in a failure-free run"
                client = (cmd // G) % NC
                if cmd > ct[g, client]:
                    ct[g, client] = cmd
                    kv[g, cmd % KV] = cmd  # log-order last-writer-wins
                    applied += 1
                else:
                    filtered += 1

    assert applied == int(sim.state.sm_applied)
    assert filtered == int(sim.state.dups_filtered)
    # kv stores NO_VALUE=-2 for never-written keys; the replay used -1.
    dev_kv = np.asarray(jax.device_get(sim.state.kv_val), np.int64)
    assert np.array_equal(np.where(dev_kv < 0, -1, dev_kv), kv)
    assert np.array_equal(
        np.asarray(jax.device_get(sim.state.ct_last), np.int64), ct
    )
    assert filtered > 0  # the scenario actually exercised dedup
    assert all(sim.check_invariants().values())


def test_sm_host_replay_with_failovers_is_exact():
    """The adversarial version of the replay test: repeated failovers
    noop-repair unvoted slots, so a client's retry can EXECUTE (its
    original was lost) and chained retries of the same id can retire in
    one batch. The sequential Python replay is the ground truth for
    exactly-once under all of it."""
    cfg = make(num_groups=3, window=16, slots_per_tick=2, kv_keys=8,
               num_clients=4, dup_rate=0.5, drop_rate=0.15,
               retry_timeout=12, lat_min=2, lat_max=4)
    G, W, NC, KV = 3, 16, 4, 8
    sim = TpuSimTransport(cfg, seed=11)

    ct = np.full((G, NC), -1, np.int64)
    kv = np.full((G, KV), -1, np.int64)
    applied = 0
    filtered = 0
    noops = 0
    for step in range(140):
        head_b = np.asarray(jax.device_get(sim.state.head), np.int64)
        chosen_b = np.asarray(jax.device_get(sim.state.chosen_value), np.int64)
        if step % 20 == 19:
            sim.leader_change()
        sim.run(1)
        head_a = np.asarray(jax.device_get(sim.state.head), np.int64)
        for g in range(G):
            for s in range(head_b[g], head_a[g]):
                cmd = chosen_b[g, s % W]
                if cmd < 0:  # noop-repaired slot: the SM skips it
                    noops += 1
                    continue
                client = (cmd // G) % NC
                if cmd > ct[g, client]:
                    ct[g, client] = cmd
                    kv[g, cmd % KV] = cmd  # log-order last-writer-wins
                    applied += 1
                else:
                    filtered += 1

    assert applied == int(sim.state.sm_applied)
    assert filtered == int(sim.state.dups_filtered)
    dev_kv = np.asarray(jax.device_get(sim.state.kv_val), np.int64)
    assert np.array_equal(np.where(dev_kv < 0, -1, dev_kv), kv)
    assert np.array_equal(
        np.asarray(jax.device_get(sim.state.ct_last), np.int64), ct
    )
    assert noops > 0, "the scenario must actually produce noop repairs"
    assert filtered > 0
    assert all(sim.check_invariants().values())


def test_sm_survives_failover_noops():
    """Leader failover repairs unvoted slots to noops; the SM must skip
    them (noops don't touch the KV store) and exactly-once bookkeeping
    must still balance."""
    sim = TpuSimTransport(make(drop_rate=0.05), seed=5)
    sim.run(25)
    sim.leader_change()
    sim.run(25)
    sim.leader_change()
    sim.run(40)
    stats = sim.stats()
    inv = sim.check_invariants()
    assert all(inv.values()), inv
    # Noops retire without applying, so applied + filtered <= executed.
    assert stats["sm_applied"] + stats["dups_filtered"] <= stats["executed"]
    assert stats["sm_applied"] > 0


def test_sm_sharded_matches_unsharded():
    cfg = make(num_groups=8, window=16, slots_per_tick=2)
    key = jax.random.PRNGKey(7)
    t0 = jax.numpy.zeros((), jax.numpy.int32)
    plain_state, plain_t = run_ticks(cfg, init_state(cfg), t0, 100, key)
    mesh = make_mesh()
    sharded0 = shard_state(init_state(cfg), mesh)
    sharded_state, sharded_t = run_ticks_sharded(
        cfg, mesh, sharded0, t0, 100, key
    )
    assert int(plain_t) == int(sharded_t)
    for field in dataclasses.fields(plain_state):
        la = jax.tree_util.tree_leaves(
            jax.device_get(getattr(plain_state, field.name))
        )
        lb = jax.tree_util.tree_leaves(
            jax.device_get(getattr(sharded_state, field.name))
        )
        assert len(la) == len(lb), field.name
        assert all(
            np.array_equal(a, b) for a, b in zip(la, lb)
        ), field.name


def test_sm_kv_is_log_order_not_id_max():
    """Crafted divergence (ADVICE r03): two clients write the SAME key in
    one retiring batch, and the LATER-in-log command carries the SMALLER
    id (a chained re-issue executing after its original slot was
    noop-repaired). Sequential log-order execution keeps the later value;
    a scatter-max on raw id would keep the earlier one."""
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import tick
    from frankenpaxos_tpu.tpu.multipaxos_batched import CHOSEN

    # G=1, NC=2, KV=3: client = cmd % 2, key = cmd % 3 — decoupled.
    cfg = make(num_groups=1, window=8, slots_per_tick=1,
               kv_keys=3, num_clients=2, dup_rate=0.0)
    state = init_state(cfg)
    # Slot 0 (client 0): cmd 8, key 2. Slot 1 (client 1): cmd 5, key 2.
    # Both execute (fresh client table); log order says key 2 ends at 5.
    status = np.asarray(state.status).copy()
    status[0, 0] = CHOSEN
    status[0, 1] = CHOSEN
    chosen_value = np.asarray(state.chosen_value).copy()
    chosen_value[0, 0] = 8
    chosen_value[0, 1] = 5
    replica_arrival = np.asarray(state.replica_arrival).copy()
    replica_arrival[0, 0] = 0
    replica_arrival[0, 1] = 0
    next_slot = np.asarray(state.next_slot).copy()
    next_slot[0] = 2
    state = dataclasses.replace(
        state,
        status=jnp.asarray(status),
        chosen_value=jnp.asarray(chosen_value),
        replica_arrival=jnp.asarray(replica_arrival),
        next_slot=jnp.asarray(next_slot),
    )
    state = tick(cfg, state, jnp.int32(0), jax.random.PRNGKey(9))
    assert int(state.sm_applied) == 2
    assert int(np.asarray(state.kv_val)[0, 2]) == 5, (
        "KV must follow log order (last writer), not id-max"
    )
