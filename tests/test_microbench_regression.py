"""Regression teeth for the microbenchmark suite: every hot path must
stay within a generous factor of the baselines pinned in
``results/microbench_baseline.json`` (the jvm/src/bench scalameter
culture: committed numbers, not just a runnable harness). The 5x margin
absorbs CI noise; a real algorithmic regression (e.g. the round-1
O(history) dependency-set bug) blows far past it."""

import json
import os

import pytest

from frankenpaxos_tpu.harness import microbench

_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "results", "microbench_baseline.json",
)
MARGIN = 5.0


@pytest.fixture(scope="module")
def baseline():
    with open(_BASELINE_PATH) as f:
        return json.load(f)["ops_per_sec"]


@pytest.mark.parametrize("bench", sorted(microbench.BENCHES))
def test_hot_paths_within_margin_of_pinned_baseline(bench, baseline):
    rows = microbench.BENCHES[bench]()
    assert rows, f"bench {bench} produced no rows"
    for row in rows:
        key = f"{row['name']}.{row['case']}"
        assert key in baseline, f"unpinned microbench case {key}"
        floor = baseline[key] / MARGIN
        assert row["ops_per_sec"] >= floor, (
            f"{key}: {row['ops_per_sec']:.0f} ops/s is below the "
            f"regression floor {floor:.0f} (pinned {baseline[key]})"
        )
