"""Metrics capture tests: actor-boundary instrumentation
(Actor.enable_metrics), exposition parsing, the scraper, and post-hoc
pandas queries (the benchmarks/prometheus.py capability)."""

import time

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.monitoring import FakeCollectors, PrometheusCollectors
from frankenpaxos_tpu.monitoring.scrape import (
    MetricsCapture,
    MetricsScraper,
    parse_exposition,
    scrape_config,
)
from frankenpaxos_tpu.protocols.echo import EchoClient, EchoServer


def test_enable_metrics_counts_and_times():
    t = SimTransport(FakeLogger())
    server_addr = SimAddress("server")
    server = EchoServer(server_addr, t, FakeLogger())
    collectors = FakeCollectors()
    server.enable_metrics(collectors, "echo_server")
    client = EchoClient(SimAddress("client"), t, FakeLogger(), server_addr)
    for _ in range(5):
        client.echo("hi")
    while t.messages:
        t.deliver_message(t.messages[0])
    counter = collectors.counter("echo_server_requests_total", labels=("type",))
    assert counter.labels("EchoRequest").value == 5
    summary = collectors.summary(
        "echo_server_handler_latency_seconds", labels=("type",)
    )
    assert summary.labels("EchoRequest").count == 5
    assert summary.labels("EchoRequest").sum >= 0


def test_parse_exposition():
    text = (
        "# HELP x_total help\n"
        "# TYPE x_total counter\n"
        'x_total{type="A"} 3\n'
        'x_total{type="B"} 4\n'
        "plain_gauge 1.5\n"
        "garbage line without value x\n"
    )
    samples = parse_exposition(text)
    assert ("x_total", (("type", "A"),), 3.0) in samples
    assert ("plain_gauge", (), 1.5) in samples
    assert len(samples) == 3


def test_scrape_config_shape():
    cfg = scrape_config(200, {"acceptor": ["127.0.0.1:1", "127.0.0.1:2"]})
    assert cfg["global"]["scrape_interval"] == "200ms"
    assert cfg["scrape_configs"][0]["job_name"] == "acceptor"
    assert cfg["scrape_configs"][0]["static_configs"][0]["targets"] == [
        "127.0.0.1:1", "127.0.0.1:2",
    ]


def test_scraper_and_capture_roundtrip(tmp_path):
    collectors = PrometheusCollectors()
    counter = collectors.counter("demo_total", "d", labels=("kind",))
    port = 23987
    server = collectors.start_http_server(port, host="127.0.0.1")
    try:
        path = str(tmp_path / "metrics.csv")
        with MetricsScraper(
            {"demo": [f"127.0.0.1:{port}"]}, path, scrape_interval_ms=50
        ):
            counter.labels("a").inc(3)
            time.sleep(0.15)
            counter.labels("a").inc(7)
            time.sleep(0.15)
        cap = MetricsCapture(path)
        assert "demo_total" in cap.names()
        assert cap.total("demo_total", kind="a") == 10.0
        wide = cap.query("demo_total")
        assert wide.shape[1] == 1  # one labelset series
        assert float(wide.ffill().iloc[-1].iloc[0]) == 10.0
    finally:
        server.shutdown()


def test_dashboard_renders(tmp_path):
    """metrics.csv -> multi-panel dashboard figure (the Grafana-dashboard
    capability, grafana/dashboards/)."""
    import time as _time

    from frankenpaxos_tpu.monitoring.dashboard import render_dashboard

    collectors = PrometheusCollectors()
    counter = collectors.counter("demo_requests_total", "d", labels=("type",))
    lat = collectors.summary(
        "demo_handler_latency_seconds", "d", labels=("type",)
    )
    port = 23991
    server = collectors.start_http_server(port, host="127.0.0.1")
    try:
        path = str(tmp_path / "metrics.csv")
        with MetricsScraper(
            {"demo": [f"127.0.0.1:{port}"]}, path, scrape_interval_ms=50
        ):
            for i in range(4):
                counter.labels("A").inc(5)
                lat.labels("A").observe(0.001 * (i + 1))
                _time.sleep(0.08)
        out = render_dashboard(MetricsCapture(path), str(tmp_path / "dash.png"))
        assert out is not None
        import os

        assert os.path.getsize(out) > 1000
    finally:
        server.shutdown()
