"""Metrics capture tests: actor-boundary instrumentation
(Actor.enable_metrics), exposition parsing, the scraper, and post-hoc
pandas queries (the benchmarks/prometheus.py capability)."""

import time

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.monitoring import FakeCollectors, PrometheusCollectors
from frankenpaxos_tpu.monitoring.scrape import (
    MetricsCapture,
    MetricsScraper,
    parse_exposition,
    scrape_config,
)
from frankenpaxos_tpu.protocols.echo import EchoClient, EchoServer


def test_enable_metrics_counts_and_times():
    t = SimTransport(FakeLogger())
    server_addr = SimAddress("server")
    server = EchoServer(server_addr, t, FakeLogger())
    collectors = FakeCollectors()
    server.enable_metrics(collectors, "echo_server")
    client = EchoClient(SimAddress("client"), t, FakeLogger(), server_addr)
    for _ in range(5):
        client.echo("hi")
    while t.messages:
        t.deliver_message(t.messages[0])
    counter = collectors.counter("echo_server_requests_total", labels=("type",))
    assert counter.labels("EchoRequest").value == 5
    summary = collectors.summary(
        "echo_server_handler_latency_seconds", labels=("type",)
    )
    assert summary.labels("EchoRequest").count == 5
    assert summary.labels("EchoRequest").sum >= 0


def test_parse_exposition():
    text = (
        "# HELP x_total help\n"
        "# TYPE x_total counter\n"
        'x_total{type="A"} 3\n'
        'x_total{type="B"} 4\n'
        "plain_gauge 1.5\n"
        "garbage line without value x\n"
    )
    samples = parse_exposition(text)
    assert ("x_total", (("type", "A"),), 3.0) in samples
    assert ("plain_gauge", (), 1.5) in samples
    assert len(samples) == 3


def test_scrape_config_shape():
    cfg = scrape_config(200, {"acceptor": ["127.0.0.1:1", "127.0.0.1:2"]})
    assert cfg["global"]["scrape_interval"] == "200ms"
    assert cfg["scrape_configs"][0]["job_name"] == "acceptor"
    assert cfg["scrape_configs"][0]["static_configs"][0]["targets"] == [
        "127.0.0.1:1", "127.0.0.1:2",
    ]


def test_scraper_and_capture_roundtrip(tmp_path):
    collectors = PrometheusCollectors()
    counter = collectors.counter("demo_total", "d", labels=("kind",))
    port = 23987
    server = collectors.start_http_server(port, host="127.0.0.1")
    try:
        path = str(tmp_path / "metrics.csv")
        with MetricsScraper(
            {"demo": [f"127.0.0.1:{port}"]}, path, scrape_interval_ms=50
        ):
            counter.labels("a").inc(3)
            time.sleep(0.15)
            counter.labels("a").inc(7)
            time.sleep(0.15)
        cap = MetricsCapture(path)
        assert "demo_total" in cap.names()
        assert cap.total("demo_total", kind="a") == 10.0
        wide = cap.query("demo_total")
        assert wide.shape[1] == 1  # one labelset series
        assert float(wide.ffill().iloc[-1].iloc[0]) == 10.0
    finally:
        server.shutdown()


def test_dashboard_renders(tmp_path):
    """metrics.csv -> multi-panel dashboard figure (the Grafana-dashboard
    capability, grafana/dashboards/)."""
    import time as _time

    from frankenpaxos_tpu.monitoring.dashboard import render_dashboard

    collectors = PrometheusCollectors()
    counter = collectors.counter("demo_requests_total", "d", labels=("type",))
    lat = collectors.summary(
        "demo_handler_latency_seconds", "d", labels=("type",)
    )
    port = 23991
    server = collectors.start_http_server(port, host="127.0.0.1")
    try:
        path = str(tmp_path / "metrics.csv")
        with MetricsScraper(
            {"demo": [f"127.0.0.1:{port}"]}, path, scrape_interval_ms=50
        ):
            for i in range(4):
                counter.labels("A").inc(5)
                lat.labels("A").observe(0.001 * (i + 1))
                _time.sleep(0.08)
        out = render_dashboard(MetricsCapture(path), str(tmp_path / "dash.png"))
        assert out is not None
        import os

        assert os.path.getsize(out) > 1000
    finally:
        server.shutdown()


def test_v1_capture_without_instance_column_parses_as_instance_0(tmp_path):
    """CSV schema versioning (scrape.CSV_SCHEMA_VERSION): a v1 capture
    — no ``instance`` column — round-trips through MetricsCapture with
    every sample on instance 0, so pre-fleet captures keep answering
    queries (and ``dashboard --live`` keeps rendering) unchanged."""
    from frankenpaxos_tpu.monitoring.scrape import (
        MetricsCapture,
        instance_index,
    )

    path = tmp_path / "old_metrics.csv"
    path.write_text(
        "ts,job,name,labels,value\n"
        "1000.0,device,fpx_device_commits_total,,5\n"
        "1001.0,device,fpx_device_commits_total,,11\n"
    )
    cap = MetricsCapture(str(path))
    assert set(cap.df["instance"]) == {"0"}
    wide = cap.query("fpx_device_commits_total")
    assert len(wide) == 2
    assert cap.total("fpx_device_commits_total") == 11.0
    # The fleet dashboard's instance mapping: numeric strings are fleet
    # rows, every legacy name is instance 0.
    assert instance_index("3") == 3
    assert instance_index("serve") == 0
    assert instance_index("127.0.0.1:9090") == 0
    assert instance_index(None) == 0


def test_v2_fleet_summary_rows_round_trip(tmp_path):
    """append_fleet_summary writes the v2 schema (instance = fleet row
    index) and MetricsCapture pivots it back per instance."""
    from frankenpaxos_tpu.monitoring.scrape import (
        CSV_COLUMNS,
        MetricsCapture,
        append_fleet_summary,
    )

    path = str(tmp_path / "fleet.csv")
    rows = [
        {
            "commit_rate_x1000": 1000 * (i + 1),
            "p50_commit_latency": 2,
            "p99_commit_latency": 4 + i,
            "p50_queue_wait": 0,
            "p99_queue_wait": i,
            "shed": 0,
            "rotations": 0,
            "straggler": int(i == 2),
        }
        for i in range(3)
    ]
    n = append_fleet_summary(path, rows, ts=1000.0, scales=[1.0, 1.0, 0.5])
    assert n == 3 * 9
    with open(path) as f:
        header = f.readline().strip().split(",")
    assert header == CSV_COLUMNS
    cap = MetricsCapture(path)
    strag = cap.query("fpx_fleet_straggler")
    assert set(strag.columns) == {"0{}", "1{}", "2{}"}
    assert float(strag["2{}"].iloc[0]) == 1.0
    scale = cap.query("fpx_fleet_admission_scale")
    assert float(scale["2{}"].iloc[0]) == 500.0


def test_v2_efficiency_rows_round_trip(tmp_path):
    """append_efficiency_samples writes the three fpx_efficiency_*
    gauges (x1000 fixed point, params label) under schema v2 and
    MetricsCapture pivots them back — the serve/fleet drain path the
    dashboard's efficiency panel reads."""
    from frankenpaxos_tpu.monitoring.scrape import (
        CSV_COLUMNS,
        EFFICIENCY_METRICS,
        MetricsCapture,
        append_efficiency_samples,
    )

    path = str(tmp_path / "eff.csv")
    n = append_efficiency_samples(
        path,
        observed_per_tick=12.0,
        predicted_per_tick=16.0,
        params="cpu_jit",
        ts=1000.0,
    )
    n += append_efficiency_samples(
        path,
        observed_per_tick=15.0,
        predicted_per_tick=16.0,
        params="cpu_jit",
        ts=2000.0,
    )
    assert n == 2 * len(EFFICIENCY_METRICS)
    with open(path) as f:
        header = f.readline().strip().split(",")
    assert header == CSV_COLUMNS
    cap = MetricsCapture(path)
    assert set(EFFICIENCY_METRICS) <= set(cap.names())
    obs = cap.query("fpx_efficiency_observed_commits_per_tick_x1000")
    col = obs.columns[0]
    assert "params=cpu_jit" in col
    assert list(obs[col]) == [12000.0, 15000.0]
    ratio = cap.query("fpx_efficiency_ratio_x1000")
    assert list(ratio[ratio.columns[0]]) == [750.0, 938.0]
