"""Batched unreplicated SM sim test."""

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import batchedunreplicated as bu
from frankenpaxos_tpu.statemachine import AppendLog


def test_batched_unreplicated_end_to_end():
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    config = bu.BatchedUnreplicatedConfig(
        batcher_addresses=(SimAddress("batcher0"), SimAddress("batcher1")),
        server_address=SimAddress("server"),
        proxy_server_addresses=(SimAddress("proxy0"), SimAddress("proxy1")),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    batchers = [
        bu.BuBatcher(a, t, log(), config, bu.BuBatcherOptions(batch_size=2))
        for a in config.batcher_addresses
    ]
    sm = AppendLog()
    bu.BuServer(config.server_address, t, log(), config, sm)
    proxies = [bu.BuProxyServer(a, t, log(), config) for a in config.proxy_server_addresses]
    clients = [
        bu.BuClient(SimAddress(f"client{i}"), t, log(), config, seed=i)
        for i in range(2)
    ]
    promises = []
    for i, c in enumerate(clients):
        for pseudonym in (0, 1):
            promises.append(c.propose(pseudonym, f"c{i}p{pseudonym}".encode()))
    steps = 0
    while t.messages and steps < 10000:
        t.deliver_message(t.messages[0])
        steps += 1
    # Batch size 2 with 4 commands spread over 2 batchers: batches may be
    # partial; flush stragglers via resend timers.
    for _ in range(4):
        for timer in list(t.running_timers()):
            t.trigger_timer(timer.address, timer.name())
        while t.messages and steps < 10000:
            t.deliver_message(t.messages[0])
            steps += 1
    assert all(p.done for p in promises)
    assert len(sm.log) >= 4  # resends may duplicate; server has no dedup
