"""Matchmaker MultiPaxos sim tests: normal-case MultiPaxos, i/i+1
acceptor reconfiguration, matchmaker reconfiguration via reconfigurers,
the GC pipeline, driver-injected chaos, and randomized safety."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import matchmakermultipaxos as mmm
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import ReadableAppendLog


class Cluster:
    def __init__(self, seed=0, f=1, num_clients=2, num_acceptors=None,
                 num_matchmakers=None, watermark_every=100):
        self.transport = SimTransport(FakeLogger(LogLevel.FATAL))
        t = self.transport
        n = 2 * f + 1
        num_acceptors = num_acceptors or n + 1  # spares for reconfiguration
        num_matchmakers = num_matchmakers or n + 1
        self.config = mmm.MatchmakerMultiPaxosConfig(
            f=f,
            leader_addresses=tuple(
                SimAddress(f"leader{i}") for i in range(f + 1)
            ),
            leader_election_addresses=tuple(
                SimAddress(f"election{i}") for i in range(f + 1)
            ),
            reconfigurer_addresses=tuple(
                SimAddress(f"reconfigurer{i}") for i in range(f + 1)
            ),
            matchmaker_addresses=tuple(
                SimAddress(f"matchmaker{i}") for i in range(num_matchmakers)
            ),
            acceptor_addresses=tuple(
                SimAddress(f"acceptor{i}") for i in range(num_acceptors)
            ),
            replica_addresses=tuple(
                SimAddress(f"replica{i}") for i in range(f + 1)
            ),
        )
        log = lambda: FakeLogger(LogLevel.FATAL)
        options = mmm.MmmLeaderOptions(
            send_chosen_watermark_every_n=watermark_every
        )
        self.leaders = [
            mmm.MmmLeader(a, t, log(), self.config, options, seed=seed + i)
            for i, a in enumerate(self.config.leader_addresses)
        ]
        self.reconfigurers = [
            mmm.MmmReconfigurer(a, t, log(), self.config, seed=seed + 10 + i)
            for i, a in enumerate(self.config.reconfigurer_addresses)
        ]
        self.matchmakers = [
            mmm.MmmMatchmaker(a, t, log(), self.config)
            for a in self.config.matchmaker_addresses
        ]
        self.acceptors = [
            mmm.MmmAcceptor(a, t, log(), self.config)
            for a in self.config.acceptor_addresses
        ]
        self.replicas = [
            mmm.MmmReplica(a, t, log(), self.config, ReadableAppendLog(),
                           seed=seed + 30 + i)
            for i, a in enumerate(self.config.replica_addresses)
        ]
        self.clients = [
            mmm.MmmClient(SimAddress(f"client{i}"), t, log(), self.config,
                          seed=seed + 50 + i)
            for i in range(num_clients)
        ]
        self.driver = mmm.MmmDriver(
            SimAddress("driver"), t, log(), self.config, mmm.DoNothing(),
            seed=seed + 99,
        )

    def drain(self, max_steps=300000):
        steps = 0
        t = self.transport
        while t.messages and steps < max_steps:
            t.deliver_message(t.messages[0])
            steps += 1
        assert steps < max_steps

    def pump(self, rounds=8, skip=lambda timer: False):
        infra = set(self.config.leader_election_addresses)
        self.drain()
        for _ in range(rounds):
            for timer in list(self.transport.running_timers()):
                if timer.address not in infra and not skip(timer):
                    self.transport.trigger_timer(timer.address, timer.name())
            self.drain()


def test_mmm_single_command():
    cluster = Cluster()
    cluster.drain()  # leader 0's matchmaking + phase 1
    p = cluster.clients[0].propose(0, b"hello")
    cluster.drain()
    assert p.done
    for r in cluster.replicas:
        assert r.state_machine.log == [b"hello"]


def test_mmm_sequential_commands():
    cluster = Cluster(seed=3)
    cluster.drain()
    for i in range(10):
        p = cluster.clients[i % 2].propose(i // 2, f"c{i}".encode())
        cluster.drain()
        assert p.done, i
    for r in cluster.replicas:
        assert r.state_machine.log == [f"c{i}".encode() for i in range(10)]


def test_mmm_acceptor_reconfiguration_mid_stream():
    """ForceReconfiguration mid-stream swaps the acceptor set via the
    i/i+1 pipeline; commands before, during, and after all commit."""
    cluster = Cluster(seed=5)
    cluster.drain()
    p1 = cluster.clients[0].propose(0, b"before")
    cluster.drain()
    assert p1.done
    old_round = cluster.leaders[0]._get_round(cluster.leaders[0].state)
    # Swap to acceptors {1, 2, 3} (dropping 0, adding the spare 3).
    cluster.driver.force_reconfiguration(members=(1, 2, 3))
    p2 = cluster.clients[1].propose(0, b"during")
    cluster.pump()
    assert p2.done
    leader = cluster.leaders[0]
    assert isinstance(leader.state, mmm._Phase2)
    assert leader.state.round == old_round + 1
    assert leader.state.quorum.nodes() == frozenset({1, 2, 3})
    p3 = cluster.clients[0].propose(1, b"after")
    cluster.drain()
    assert p3.done
    for r in cluster.replicas:
        assert r.state_machine.log == [b"before", b"during", b"after"]
    # The new round's phase 2 must not involve acceptor 0 at all: every
    # vote it holds is from the old round.
    assert all(
        v[0] <= old_round for v in cluster.acceptors[0].states.values()
    )


def test_mmm_repeated_reconfigurations():
    cluster = Cluster(seed=7)
    cluster.drain()
    rng = random.Random(11)
    for i in range(6):
        members = tuple(rng.sample(range(4), 3))
        cluster.driver.force_reconfiguration(members=members)
        p = cluster.clients[0].propose(0, f"r{i}".encode())
        cluster.pump(rounds=6)
        assert p.done, (i, members)
    for r in cluster.replicas:
        assert r.state_machine.log == [f"r{i}".encode() for i in range(6)]


def test_mmm_matchmaker_reconfiguration():
    """Reconfigurers stop the old epoch, bootstrap new matchmakers, and
    choose the new configuration; the leader picks it up and future
    leader changes matchmake against the NEW epoch."""
    cluster = Cluster(seed=9)
    cluster.drain()
    p1 = cluster.clients[0].propose(0, b"epoch0")
    cluster.drain()
    assert p1.done
    cluster.driver.force_matchmaker_reconfiguration(members=(1, 2, 3))
    cluster.pump()
    assert all(
        leader.matchmaker_configuration.epoch == 1
        for leader in cluster.leaders
    )
    assert cluster.leaders[0].matchmaker_configuration.matchmaker_indices \
        == (1, 2, 3)
    # A reconfiguration (requiring fresh matchmaking in epoch 1) works.
    cluster.driver.force_reconfiguration(members=(0, 1, 2))
    p2 = cluster.clients[1].propose(0, b"epoch1")
    cluster.pump()
    assert p2.done
    for r in cluster.replicas:
        assert r.state_machine.log == [b"epoch0", b"epoch1"]


def test_mmm_leader_failover_intersects_prior_configs():
    """Leader 1 takes over after a reconfiguration history: matchmakers
    report every prior configuration and phase 1 reads a quorum of each,
    so chosen values survive the failover."""
    cluster = Cluster(seed=13)
    cluster.drain()
    p1 = cluster.clients[0].propose(0, b"one")
    cluster.drain()
    assert p1.done
    cluster.driver.force_reconfiguration(members=(1, 2, 3))
    cluster.pump()
    p2 = cluster.clients[0].propose(1, b"two")
    cluster.drain()
    assert p2.done
    # Kill leader 0; leader 1 must matchmake and see BOTH configurations.
    dead = cluster.config.leader_addresses[0]
    cluster.transport.partition_actor(dead)
    cluster.transport.partition_actor(
        cluster.config.leader_election_addresses[0]
    )
    cluster.leaders[1]._on_election(1)
    cluster.pump(skip=lambda tm: tm.address == dead)
    p3 = cluster.clients[1].propose(0, b"three")
    cluster.pump(skip=lambda tm: tm.address == dead)
    assert p3.done
    assert cluster.replicas[0].state_machine.log == [b"one", b"two", b"three"]


def test_mmm_client_routes_to_stuttered_round_leader():
    """Regression: leaders own STUTTERED round runs (leader 1 starts at
    round 1000). After a leadership change the client must map the
    learned round to the right leader immediately — with a plain
    round-robin mapping, leader(1000) = 0 and every request would stall
    on the inactive leader until the 10s resend broadcast."""
    cluster = Cluster(seed=25)
    cluster.drain()
    cluster.leaders[0]._on_election(1)  # leader 0 steps down
    cluster.leaders[1]._on_election(1)  # leader 1 takes over (round 1000)
    cluster.pump()
    assert cluster.leaders[1]._get_round(cluster.leaders[1].state) == 1000
    # NO timer pumps below: the commit must flow purely through
    # NotLeader -> LeaderInfoRequest -> LeaderInfoReply rerouting.
    p = cluster.clients[0].propose(0, b"routed")
    cluster.drain()
    assert p.done
    assert cluster.clients[0].round == 1000


def test_mmm_gc_pipeline_persists_and_prunes():
    """The full GC pipeline: replicas report execution, acceptors learn
    the persisted watermark (pruning their vote state), and matchmakers
    drop configurations below the leader's round."""
    cluster = Cluster(seed=17)
    cluster.drain()
    for i in range(5):
        p = cluster.clients[0].propose(0, f"c{i}".encode())
        cluster.drain()
        assert p.done
    # Reconfigure so a SECOND configuration lands at the matchmakers,
    # then let the new round's GC pipeline run via timer pumps.
    cluster.driver.force_reconfiguration(members=(1, 2, 3))
    cluster.pump(rounds=10)
    leader = cluster.leaders[0]
    assert isinstance(leader.state, mmm._Phase2)
    assert leader.state.gc in (mmm._GC_DONE,) or isinstance(
        leader.state.gc, mmm._GarbageCollecting
    ), leader.state.gc
    cluster.pump(rounds=4)
    assert leader.state.gc == mmm._GC_DONE
    # Acceptors in the new quorum pruned persisted slots.
    assert any(a.persisted_watermark > 0 for a in cluster.acceptors)
    for a in cluster.acceptors:
        for slot in a.states:
            assert slot >= a.persisted_watermark
    # Matchmakers GC'd configurations below the leader's round.
    round = leader.state.round
    for m in cluster.matchmakers:
        state = m.states.get(0)
        if isinstance(state, mmm._MmNormal):
            assert all(r >= state.gc_watermark for r in state.configurations)
            assert state.gc_watermark == round
    # And the system still works.
    p = cluster.clients[1].propose(0, b"post-gc")
    cluster.drain()
    assert p.done


def test_mmm_driver_chaos_converges():
    """Chaos: random acceptor + matchmaker reconfigurations interleaved
    with writes and message loss; after repair everything commits and
    replicas agree."""
    cluster = Cluster(seed=19, num_clients=3)
    cluster.drain()
    rng = random.Random(23)
    promises = []
    for burst in range(5):
        if burst % 2 == 0:
            cluster.driver.force_reconfiguration()
        else:
            cluster.driver.force_matchmaker_reconfiguration()
        for i, client in enumerate(cluster.clients):
            promises.append(client.propose(burst, f"b{burst}c{i}".encode()))
        steps = 0
        t = cluster.transport
        while t.messages and steps < 8000:
            m = t.messages[0]
            r = rng.random()
            if r < 0.05:
                t.drop_message(m)
            else:
                t.deliver_message(m)
            steps += 1
    cluster.pump(rounds=40)
    assert all(p.done for p in promises), (
        f"{sum(p.done for p in promises)}/{len(promises)}"
    )
    logs = {tuple(r.state_machine.log) for r in cluster.replicas}
    shortest = min(logs, key=len)
    for log in logs:
        assert log[: len(shortest)] == shortest


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    value: str


@dataclasses.dataclass(frozen=True)
class Reconfigure:
    members: tuple


@dataclasses.dataclass(frozen=True)
class MatchmakerReconfigure:
    members: tuple


class SimulatedMmm(SimulatedSystem):
    def __init__(self, f=1, reconfigure=True):
        self.f = f
        self.reconfigure = reconfigure

    def new_system(self, seed):
        cluster = Cluster(seed=seed, f=self.f)
        cluster.drain()
        return cluster

    def get_state(self, system):
        return tuple(
            tuple(r.state_machine.log) for r in system.replicas
        )

    def generate_command(self, system, rng):
        ops = []
        for i, c in enumerate(system.clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (2, Propose(i, pseudonym, f"v{rng.randrange(100)}"))
                    )
        if self.reconfigure:
            n_acc = len(system.config.acceptor_addresses)
            n_mm = len(system.config.matchmaker_addresses)
            ops.append((1, Reconfigure(
                tuple(rng.sample(range(n_acc), 2 * self.f + 1))
            )))
            ops.append((1, MatchmakerReconfigure(
                tuple(rng.sample(range(n_mm), 2 * self.f + 1))
            )))
        return mixed_command(rng, system.transport, ops)

    def run_command(self, system, command):
        if isinstance(command, Propose):
            system.clients[command.client_index].propose(
                command.pseudonym, command.value.encode()
            )
        elif isinstance(command, Reconfigure):
            system.driver.force_reconfiguration(members=command.members)
        elif isinstance(command, MatchmakerReconfigure):
            system.driver.force_matchmaker_reconfiguration(
                members=command.members
            )
        else:
            system.transport.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        for i in range(len(state)):
            for j in range(i + 1, len(state)):
                a, b = state[i], state[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return f"replica logs diverge: {a!r} vs {b!r}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if n[: len(o)] != o:
                return f"replica log rewrote history: {o!r} -> {n!r}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_mmm_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedMmm(f), run_length=150, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_mmm_safety_randomized_no_reconfig():
    bad = simulate_and_minimize(
        SimulatedMmm(1, reconfigure=False), run_length=120, num_runs=5,
        seed=55,
    )
    assert bad is None, f"\n{bad}"
