"""Tests of the batched Vanilla Mencius backend
(tpu/vanillamencius_batched.py): revocation of dead servers' stripes
(vanillamencius/Server.scala), the choose-once safety ledger, phase-1
discovery of a dead owner's possibly-chosen value, and promise-based
rejection of owner stragglers."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.tpu import vanillamencius_batched as vm


def run_random(cfg, seed, ticks):
    key = jax.random.PRNGKey(seed)
    state, t = vm.run_ticks(cfg, vm.init_state(cfg), jnp.int32(0), ticks, key)
    return state, t


def test_progress_without_failures():
    cfg = vm.BatchedVanillaMenciusConfig(
        f=1, num_servers=8, window=32, slots_per_tick=2,
        lat_min=1, lat_max=3,
    )
    state, t = run_random(cfg, seed=0, ticks=200)
    s = vm.stats(cfg, state, t)
    assert s["committed_real"] > 8 * 150
    assert s["revocations"] == 0
    assert s["choose_violations"] == 0
    inv = vm.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv


def test_dead_stripe_stalls_without_revocation():
    """Kill one server with revocation effectively disabled (huge
    threshold): the global watermark pins at its stripe."""
    cfg = vm.BatchedVanillaMenciusConfig(
        f=1, num_servers=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, revoke_threshold=10**6, revive_rate=0.0,
    )
    key = jax.random.PRNGKey(1)
    state = vm.init_state(cfg)
    state = dataclasses.replace(state, alive=state.alive.at[0].set(False))
    t = 0
    for _ in range(120):
        state = vm.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    # Stripe 0 never proposes; global watermark stuck at 0 (slot 0
    # belongs to server 0 and is never chosen).
    assert int(state.executed_global) == 0
    assert int(state.revocations) == 0


def test_revocation_unsticks_the_global_watermark():
    """Same dead server, revocation enabled: live peers claim its slots
    as noops and the global log flows past the dead stripe."""
    cfg = vm.BatchedVanillaMenciusConfig(
        f=1, num_servers=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, revoke_threshold=4, revive_rate=0.0,
    )
    key = jax.random.PRNGKey(2)
    state = vm.init_state(cfg)
    state = dataclasses.replace(state, alive=state.alive.at[0].set(False))
    t = 0
    for _ in range(200):
        state = vm.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    s = vm.stats(cfg, state, jnp.int32(t))
    assert s["revocations"] > 0
    assert s["executed_global"] > 100  # the log flows past stripe 0
    assert s["choose_violations"] == 0
    inv = vm.check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv


def test_revocation_discovers_dead_owners_choice():
    """The safety case revocation exists for: the owner proposed, a full
    round-0 vote quorum formed at the acceptors, but the owner died
    before counting the Phase2bs. Revocation's phase 1 must DISCOVER the
    vote and re-propose the owner's value — not a noop — and the
    choose-once ledger stays clean."""
    cfg = vm.BatchedVanillaMenciusConfig(
        f=1, num_servers=2, window=8, slots_per_tick=1,
        lat_min=1, lat_max=1, revoke_threshold=2, revive_rate=0.0,
    )
    key = jax.random.PRNGKey(3)
    state = vm.init_state(cfg)
    t = 0
    # Tick 0: both servers propose slot ordinal 0; Phase2as land at t=1
    # (lat=1), votes cast, Phase2bs due t=2.
    state = vm.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
    t += 1
    state = vm.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
    t += 1
    # Votes exist at server 0's acceptors for ordinal 0; kill server 0
    # BEFORE it can count the Phase2bs arriving this tick.
    assert bool(np.asarray(state.voted)[0].any())
    owner_val = int(np.asarray(state.slot_value)[0, 0])
    assert owner_val >= 0
    state = dataclasses.replace(state, alive=state.alive.at[0].set(False))
    # Run on: server 1 races ahead, triggers revocation of stripe 0;
    # phase 1 must discover the round-0 votes.
    for _ in range(80):
        state = vm.tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        t += 1
    s = vm.stats(cfg, state, jnp.int32(t))
    assert s["revocations"] > 0
    assert s["revoked_discovered"] > 0, "phase 1 never discovered a vote"
    assert s["choose_violations"] == 0
    assert s["executed_global"] > 0
    inv = vm.check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv


def test_promise_rejects_owner_straggler():
    """After a revocation promise (round 1), a dead owner's straggling
    round-0 Phase2a must NOT produce a vote."""
    cfg = vm.BatchedVanillaMenciusConfig(
        f=1, num_servers=2, window=8, slots_per_tick=1,
        lat_min=1, lat_max=1,
    )
    state = vm.init_state(cfg)
    # Hand-craft: slot (0,0) PROPOSED, acceptor 0 already promised round
    # 1, owner Phase2a arriving now.
    state = dataclasses.replace(
        state,
        status=state.status.at[0, 0].set(vm.PROPOSED),
        slot_value=state.slot_value.at[0, 0].set(0),
        next_slot=state.next_slot.at[0].set(1),
        acc_round=state.acc_round.at[0, 0, 0].set(1),
        p2a_arrival=state.p2a_arrival.at[0, 0, 0].set(5),
    )
    state = vm.tick(cfg, state, jnp.int32(5), jax.random.PRNGKey(4))
    assert not bool(state.voted[0, 0, 0])  # rejected
    assert int(state.p2a_arrival[0, 0, 0]) == vm.INF  # consumed


def test_churn_invariants_random():
    """Continuous die/revive churn with revocation: safety ledger clean,
    watermark monotone, books balanced."""
    cfg = vm.BatchedVanillaMenciusConfig(
        f=1, num_servers=16, window=32, slots_per_tick=2,
        lat_min=1, lat_max=3, fail_rate=0.01, revive_rate=0.1,
        revoke_threshold=6, drop_rate=0.05,
    )
    state, t = run_random(cfg, seed=5, ticks=400)
    s = vm.stats(cfg, state, t)
    assert s["deaths"] > 0
    assert s["committed_real"] > 1000
    assert s["choose_violations"] == 0
    inv = vm.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
