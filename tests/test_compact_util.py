import random

import pytest

from frankenpaxos_tpu.compact import FakeCompactSet, IntPrefixSet
from frankenpaxos_tpu.util import (
    BufferMap,
    QuorumWatermark,
    QuorumWatermarkVector,
    TopK,
    TopOne,
    TupleVertexIdLike,
    histogram,
    merge_maps_with,
    popular_items,
)


class TestIntPrefixSet:
    def test_add_and_compact(self):
        s = IntPrefixSet()
        assert s.add(1)
        assert s.add(0)
        assert s.watermark == 2 and s.values == set()
        assert not s.add(1)
        assert s.add(5)
        assert s.watermark == 2 and s.values == {5}
        s.add(2)
        s.add(3)
        s.add(4)
        assert s.watermark == 6 and s.values == set()

    def test_contains_size(self):
        s = IntPrefixSet(3, {5, 7})
        assert all(s.contains(x) for x in [0, 1, 2, 5, 7])
        assert not s.contains(3) and not s.contains(6)
        assert s.size == 5
        assert s.uncompacted_size == 2
        assert s.materialize() == {0, 1, 2, 5, 7}

    def test_constructor_compacts(self):
        s = IntPrefixSet(2, {2, 3, 5})
        assert s.watermark == 4 and s.values == {5}

    def test_union_diff(self):
        a = IntPrefixSet(3, {5})
        b = IntPrefixSet(1, {2, 7})
        u = a.union(b)
        assert u.materialize() == {0, 1, 2, 5, 7}
        d = a.diff(b)
        assert d.materialize() == {1, 5}  # a={0,1,2,5}; b={0,2,7}
        assert d.contains(5)
        assert list(a.diff_iterator(b)) == [1, 5]

    def test_add_subtract_all(self):
        a = IntPrefixSet(2, {4})
        a.add_all(IntPrefixSet(4, {6}))
        # {0,1,4} ∪ {0,1,2,3,6} = {0..4, 6}; prefix compacts to watermark 5.
        assert a.materialize() == {0, 1, 2, 3, 4, 6}
        assert a.watermark == 5
        a.subtract_all(IntPrefixSet(0, {6}))
        assert a.materialize() == {0, 1, 2, 3, 4}
        assert a.watermark == 5

    def test_subtract_one(self):
        a = IntPrefixSet(3, {5})
        a.subtract_one(5)
        assert a.materialize() == {0, 1, 2}
        a.subtract_one(1)
        assert a.materialize() == {0, 2}
        assert a.watermark == 1 and a.values == {2}

    def test_subset_monotone(self):
        a = IntPrefixSet(3, {5})
        sub = a.subset()
        assert sub.materialize() <= a.materialize()
        a.add(3)
        a.add(4)  # now watermark 6
        assert sub.materialize() <= a.subset().materialize()

    def test_proto_roundtrip(self):
        a = IntPrefixSet(3, {5, 9})
        assert IntPrefixSet.from_proto(a.to_proto()) == a

    def test_randomized_against_model(self):
        rng = random.Random(0)
        s = IntPrefixSet()
        model = set()
        for _ in range(500):
            x = rng.randrange(40)
            assert s.add(x) == (x not in model)
            model.add(x)
            assert s.materialize() == model
            assert s.size == len(model)


def test_fake_compact_set():
    s = FakeCompactSet([1, 2])
    assert s.add(3) and not s.add(1)
    assert s.contains(2)
    assert s.union(FakeCompactSet([9])).materialize() == {1, 2, 3, 9}
    assert s.diff(FakeCompactSet([1])).materialize() == {2, 3}
    assert s.size == 3


class TestBufferMap:
    def test_put_get(self):
        m = BufferMap(grow_size=4)
        m.put(0, "a")
        m.put(10, "b")  # forces growth
        assert m.get(0) == "a" and m.get(10) == "b"
        assert m.get(5) is None
        assert m.contains(10) and not m.contains(3)

    def test_gc(self):
        m = BufferMap(grow_size=4)
        for i in range(8):
            m.put(i, f"v{i}")
        m.garbage_collect(5)
        assert m.get(4) is None  # below watermark
        assert m.get(5) == "v5"
        m.put(3, "stale")  # put below watermark ignored
        assert m.get(3) is None
        m.garbage_collect(3)  # lower watermark ignored
        assert m.watermark == 5

    def test_iterate(self):
        m = BufferMap(grow_size=2)
        m.put(1, "a")
        m.put(4, "b")
        assert list(m.items()) == [(1, "a"), (4, "b")]
        assert list(m.items_from(2)) == [(4, "b")]
        assert m.to_map() == {1: "a", 4: "b"}
        m.garbage_collect(2)
        assert m.to_map() == {4: "b"}


def test_quorum_watermark():
    # Example from QuorumWatermark.scala doc: 4, 3, 6, 2.
    qw = QuorumWatermark(4)
    for i, w in enumerate([4, 3, 6, 2]):
        qw.update(i, w)
    assert qw.watermark(4) == 2
    assert qw.watermark(3) == 3
    assert qw.watermark(2) == 4
    assert qw.watermark(1) == 6
    qw.update(3, 1)  # watermarks never decrease
    assert qw.watermark(4) == 2
    with pytest.raises(ValueError):
        qw.watermark(5)


def test_quorum_watermark_vector():
    qwv = QuorumWatermarkVector(n=4, depth=3)
    qwv.update(0, [1, 2, 3])
    qwv.update(1, [3, 2, 1])
    qwv.update(2, [2, 4, 6])
    qwv.update(3, [7, 5, 3])
    assert qwv.watermark(2) == [3, 4, 3]
    assert qwv.watermark(4) == [1, 2, 1]


def test_top_one():
    like = TupleVertexIdLike()
    t = TopOne(3, like)
    t.put((0, 4))
    t.put((0, 2))
    t.put((2, 0))
    assert t.get() == [5, 0, 1]
    other = TopOne(3, like)
    other.put((1, 9))
    t.merge_equals(other)
    assert t.get() == [5, 10, 1]


def test_top_k():
    like = TupleVertexIdLike()
    t = TopK(2, 2, like)
    for id_ in [1, 5, 3, 4]:
        t.put((0, id_))
    assert t.get()[0] == {4, 5}
    other = TopK(2, 2, like)
    other.put((0, 9))
    other.put((1, 1))
    t.merge_equals(other)
    assert t.get()[0] == {5, 9}
    assert t.get()[1] == {1}


def test_util_helpers():
    assert histogram("abca") == {"a": 2, "b": 1, "c": 1}
    assert popular_items("aaabbc", 2) == {"a", "b"}  # count >= n
    assert popular_items("aabbc", 1) == {"a", "b", "c"}
    assert popular_items([], 2) == set()
    assert merge_maps_with({"a": 1}, {"a": 2, "b": 3}, lambda x, y: x + y) == {
        "a": 3,
        "b": 3,
    }
