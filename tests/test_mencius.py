"""Compartmentalized Mencius sim tests (the analog of
shared/src/test/scala/mencius), reusing the MultiPaxos ProxyLeader,
Replica, and ProxyReplica roles."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import mencius as mn
from frankenpaxos_tpu.protocols import multipaxos as mp
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import ReadableAppendLog


class _PickGroup:
    """rng stub: first randrange picks the leader group, second the member
    (always the initially-active member 0)."""

    def __init__(self, group):
        self.group = group
        self._calls = 0

    def randrange(self, n):
        self._calls += 1
        return self.group if self._calls % 2 == 1 else 0


def make(f=1, num_leaders=3, num_clients=2, seed=0):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    config = mn.MenciusConfig(
        f=f,
        batcher_addresses=(),
        leader_groups=tuple(
            tuple(SimAddress(f"leader_{g}_{m}") for m in range(f + 1))
            for g in range(num_leaders)
        ),
        leader_election_groups=tuple(
            tuple(SimAddress(f"election_{g}_{m}") for m in range(f + 1))
            for g in range(num_leaders)
        ),
        proxy_leader_addresses=tuple(
            SimAddress(f"proxy_leader{i}") for i in range(f + 1)
        ),
        acceptor_addresses=tuple(
            tuple(SimAddress(f"acceptor_{g}_{i}") for i in range(2 * f + 1))
            for g in range(2)
        ),
        replica_addresses=tuple(SimAddress(f"replica{i}") for i in range(f + 1)),
        proxy_replica_addresses=(),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [
        mn.MenciusLeader(a, t, log(), config, seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    # leaders[2 * g] is group g's initially-active member; leaders[2*g+1]
    # its standby (f=1 -> group size 2).
    active = [leaders[i] for i in range(0, len(leaders), f + 1)]
    proxy_leaders = [
        mp.ProxyLeader(a, t, log(), config, seed=seed + 10 + i)
        for i, a in enumerate(config.proxy_leader_addresses)
    ]
    acceptors = [
        mn.MenciusAcceptor(a, t, log(), config)
        for group in config.acceptor_addresses
        for a in group
    ]
    replicas = [
        mp.Replica(
            a, t, log(), ReadableAppendLog(), config,
            mp.ReplicaOptions(send_chosen_watermark_every_n_entries=5),
            seed=seed + 30 + i,
        )
        for i, a in enumerate(config.replica_addresses)
    ]
    clients = [
        mn.MenciusClient(
            SimAddress(f"client{i}"), t, log(), config, seed=seed + 50 + i
        )
        for i in range(num_clients)
    ]
    return t, config, active, proxy_leaders, acceptors, replicas, clients


def drain(t, max_steps=100000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def test_mencius_single_write():
    t, config, leaders, proxy_leaders, acceptors, replicas, clients = make()
    p = clients[0].write(0, b"hello")
    drain(t)
    # The write is chosen at some leader's first owned slot; replicas may
    # need earlier residues noop-filled before executing. The proposing
    # leader broadcasts watermarks only every N proposals, so nudge via
    # another write if needed.
    if not p.done:
        p2 = clients[1].write(0, b"second")
        drain(t)
    assert p.done


def test_mencius_multi_leader_interleaving_converges():
    t, config, leaders, proxy_leaders, acceptors, replicas, clients = make(seed=2)
    promises = []
    for round_ in range(6):
        for i, c in enumerate(clients):
            promises.append(c.write(round_, f"r{round_}c{i}".encode()))
        drain(t)
    # Force watermark broadcasts + skips so stragglers fill.
    for leader in leaders:
        leader._broadcast_watermark()
    drain(t)
    done = sum(p.done for p in promises)
    assert done == len(promises), f"{done}/{len(promises)}"
    logs = {tuple(r.state_machine.get()) for r in replicas}
    assert len(logs) == 1, "replica logs diverged"
    assert len([e for e in next(iter(logs))]) == len(promises)


def test_mencius_skips_unblock_lagging_leaders():
    """All writes via leader 0: its watermarks make leaders 1 and 2 skip,
    so the global log executes."""
    t, config, leaders, proxy_leaders, acceptors, replicas, clients = make(seed=3)

    clients[0].rng = _PickGroup(0)
    promises = [clients[0].write(i, f"w{i}".encode()) for i in range(8)]
    drain(t)
    assert all(p.done for p in promises)
    logs = {tuple(r.state_machine.get()) for r in replicas}
    assert len(logs) == 1


def test_mencius_leader_failover_phase1_repairs_owned_slots():
    """Leader 1 dies mid-stream; a Recover drives its round bump + phase 1
    repair of its residue, and other leaders' round-0 path is unaffected."""
    t, config, leaders, proxy_leaders, acceptors, replicas, clients = make(seed=4)

    clients[0].rng = _PickGroup(1)
    p1 = clients[0].write(0, b"doomed?")
    # Deliver the request + phase2as, drop the 2bs so the slot hangs.
    t.deliver_message(t.messages[0])  # request -> leader1
    while t.messages:
        m = t.messages[0]
        from frankenpaxos_tpu.core import wire
        from frankenpaxos_tpu.protocols.multipaxos.messages import Phase2b

        if isinstance(wire.decode(m.data), Phase2b):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    # Recovery is driven end-to-end: the client resends (leader 1 proposes
    # the command again at a later slot), replicas now see a hole and their
    # recover timers fire Recover at the executed watermark; non-owner
    # leaders skip past it and the owner re-runs phase 1, repairing the
    # stuck slot with its original vote. Repeat until unblocked.
    t.trigger_timer(clients[0].address, "resendMencius[0;0]")
    drain(t)
    for _ in range(8):
        if p1.done:
            break
        for r in replicas:
            t.trigger_timer(r.address, "recover")
        drain(t)
    assert p1.done  # repaired with the original value
    # Other leaders still work in round 0.
    clients[1].rng = _PickGroup(2)
    p2 = clients[1].write(0, b"unaffected")
    drain(t)
    for leader in leaders:
        leader._broadcast_watermark()
    drain(t)
    assert p2.done
    logs = {tuple(r.state_machine.get()) for r in replicas}
    assert len(logs) == 1
    final = next(iter(logs))
    assert b"doomed?" in final and b"unaffected" in final


@dataclasses.dataclass(frozen=True)
class Write:
    client_index: int
    pseudonym: int
    value: bytes


class SimulatedCompartmentalizedMencius(SimulatedSystem):
    def __init__(self, f=1):
        self.f = f

    def new_system(self, seed):
        return make(self.f, seed=seed)

    def get_state(self, system):
        replicas = system[5]
        return tuple(tuple(r.state_machine.get()) for r in replicas)

    def generate_command(self, system, rng):
        t = system[0]
        clients = system[6]
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (1, Write(i, pseudonym, f"v{rng.randrange(50)}".encode()))
                    )
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t = system[0]
        clients = system[6]
        if isinstance(command, Write):
            clients[command.client_index].write(
                command.pseudonym, command.value
            )
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        for i in range(len(state)):
            for j in range(i + 1, len(state)):
                a, b = state[i], state[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return f"replica logs not prefix-compatible: {a!r} vs {b!r}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if n[: len(o)] != o:
                return f"replica log shrank or changed"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_mencius_compartmentalized_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedCompartmentalizedMencius(f), run_length=120, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_mencius_standby_takes_over_dead_stripe():
    """The active member of group 2 dies entirely; its standby wins the
    group election, phase-1-repairs the stripe, and the cluster keeps
    committing (the reference's per-group leaderChange)."""
    t, config, leaders, proxy_leaders, acceptors, replicas, clients = make(seed=6)
    # Warm up: one write through each stripe, then converge.
    for g in range(3):
        clients[0].rng = _PickGroup(g)
        clients[0].write(g, f"warm{g}".encode())
        drain(t)
    for leader in leaders:
        leader._broadcast_watermark()
    drain(t)

    # Kill group 2's ACTIVE member and its election participant.
    dead_leader = config.leader_groups[2][0]
    dead_election = config.leader_election_groups[2][0]
    t.partition_actor(dead_leader)
    t.partition_actor(dead_election)

    # The standby's election times out and it becomes the stripe leader.
    standby_election = config.leader_election_groups[2][1]
    t.trigger_timer(standby_election, "noPingTimer")
    drain(t)

    # New writes through a live group land in slots AFTER stripe 2's
    # holes; execution requires the standby to keep its stripe moving
    # (repair + skips on watermarks).
    clients[1].rng = _PickGroup(0)
    p = clients[1].write(0, b"takeover")
    drain(t)
    for _ in range(8):
        if p.done:
            break
        for leader in leaders[:2] + [t.actors[config.leader_groups[2][1]]]:
            leader._broadcast_watermark()
        for timer in list(t.running_timers()):
            if timer.address not in (dead_leader, dead_election):
                t.trigger_timer(timer.address, timer.name())
        drain(t)
    assert p.done, "log stalled: standby did not keep stripe 2 moving"
    live_logs = {tuple(r.state_machine.get()) for r in replicas}
    assert len(live_logs) == 1
    assert b"takeover" in next(iter(live_logs))


def test_mencius_phase1_preserves_slot_residue():
    """Regression: a phase-1 repair with no prior votes must not drift
    next_slot off the stripe's residue (it drifted to max_slot+n = 2 for
    stripe 1, making it propose into stripe 2's slots)."""
    t, config, leaders, proxy_leaders, acceptors, replicas, clients = make(seed=8)
    g1 = leaders[1]
    assert g1.next_slot % 3 == 1
    # Force a fresh phase 1 with no votes anywhere.
    g1.round = g1._next_owned_round(g1.round)
    g1._start_phase1()
    drain(t)
    assert g1.state == "phase2"
    assert g1.next_slot % 3 == 1, f"next_slot {g1.next_slot} off residue"
    # And every subsequent proposal stays on the stripe.
    clients[0].rng = _PickGroup(1)
    clients[0].write(0, b"x")
    drain(t)
    assert g1.next_slot % 3 == 1


def test_mencius_no_vote_phase1_leaves_no_hole_and_no_timer_leak():
    """Regressions: (a) a no-vote repair resumes at the FIRST owned slot —
    no permanent hole; (b) a nack-driven phase-1 restart stops the old
    resend timer."""
    t, config, leaders, proxy_leaders, acceptors, replicas, clients = make(seed=9)
    g1 = leaders[1]
    g1.round = g1._next_owned_round(g1.round)
    g1._start_phase1()
    # Restart phase 1 again before the first completes (nack-style).
    g1.round = g1._next_owned_round(g1.round)
    g1._start_phase1()
    resends = [
        x for x in t.running_timers() if x.name() == "resendPhase1as"
    ]
    assert len(resends) == 1, "stale phase-1 resend timer leaked"
    drain(t)
    assert g1.next_slot == 1  # first owned slot, not a stride past it
    # A write through stripe 1 lands at slot 1 and executes once stripes
    # 0/2 fill slot 0 and 2 (watermarks drive the skips).
    clients[0].rng = _PickGroup(1)
    p = clients[0].write(0, b"no-hole")
    drain(t)
    for leader in leaders:
        leader._broadcast_watermark()
    drain(t)
    assert p.done


def test_mencius_batcher_spreads_across_groups():
    """MenciusBatcher round-robins full batches over leader GROUPS (the
    multipaxos Batcher would pin everything to one leader's round)."""
    t, config0, leaders, proxy_leaders, acceptors, replicas, clients = make(seed=10)
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel

    batcher_addr = SimAddress("mencius_batcher0")
    config = dataclasses.replace(config0, batcher_addresses=(batcher_addr,))
    batcher = mn.MenciusBatcher(
        batcher_addr, t, FakeLogger(LogLevel.FATAL), config,
        mn.MenciusBatcherOptions(batch_size=2), seed=3,
    )
    # New clients bound to the batched config.
    bclients = [
        mn.MenciusClient(SimAddress(f"bclient{i}"), t,
                         FakeLogger(LogLevel.FATAL), config, seed=60 + i)
        for i in range(2)
    ]
    promises = []
    for r in range(4):
        for i, c in enumerate(bclients):
            promises.append(c.write(r, f"b{r}c{i}".encode()))
        drain(t)
    for leader in leaders:
        leader._broadcast_watermark()
    drain(t)
    assert all(p.done for p in promises)
    # Batches landed on more than one stripe.
    used_stripes = {
        slot % 3
        for rep in replicas
        for slot, entry in rep.log.to_map().items()
        if not entry.is_noop
    }
    assert len(used_stripes) > 1, f"all batches pinned to {used_stripes}"
