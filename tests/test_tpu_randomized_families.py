"""Randomized cross-validation families: a scenario generator drives BOTH
the batched TPU model and the per-actor sim from the same randomly drawn
scenario, asserting identical logs — the batched analog of the
reference's ``Simulator.simulate(runs=500)`` sweeps (Simulator.scala:
28-41). Four families: MultiPaxos repair (random per-slot fate +
failover), Mencius skips (random active stripe + write count), Scalog
cuts (random append schedules), Fast Paxos O4 recovery (random vote
splits + random phase-1 quorums; the per-actor leader fallback is the
ground truth)."""

import dataclasses
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.core import wire
from frankenpaxos_tpu.protocols.multipaxos.messages import Phase2a, Phase2b
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    INF,
    INF16,
    NOOP_VALUE,
    BatchedMultiPaxosConfig,
    check_invariants,
    init_state,
    leader_change,
    tick,
)
from multipaxos_testbed import SimulatedMultiPaxos, Write
from test_tpu_cross_validation import (
    NOOP,
    batched_symbols,
    drain,
    run_batched_collecting,
    sim_symbols,
)

jit_tick = jax.jit(tick, static_argnums=0)


# -- Family 1: MultiPaxos repair ----------------------------------------------


def _multipaxos_scenario(seed):
    """Random scenario: f, slots-per-group, and a fate for every global
    slot — committed (quorum formed before failover), voted (votes at
    <= f acceptors, no quorum), or empty (Phase2as all lost)."""
    rng = random.Random(seed)
    f = rng.choice([1, 2])
    spg = rng.choice([2, 3])
    n = 2 * spg  # the per-actor testbed always has 2 acceptor groups
    fates = {s: rng.choice(["committed", "voted", "empty"]) for s in range(n)}
    # The per-actor new leader's phase-1 repair range ends at the max slot
    # any acceptor knows about; trailing all-empty slots are not noopified
    # (their clients would re-propose into FRESH slots instead). Keep the
    # last slot known so both executions cover the same range.
    fates[n - 1] = rng.choice(["committed", "voted"])
    vote_counts = {
        s: rng.randint(1, f) for s in range(n) if fates[s] == "voted"
    }
    return f, spg, n, fates, vote_counts


def _expected_symbols(n, fates):
    return [NOOP if fates[s] == "empty" else s for s in range(n)]


@pytest.mark.parametrize("seed", range(50))
def test_multipaxos_repair_family(seed):
    f, spg, n, fates, vote_counts = _multipaxos_scenario(seed)
    expected = _expected_symbols(n, fates)

    # ---- Per-actor side: n concurrent writes; deliver Phase2as only for
    # non-empty slots, Phase2bs only for committed slots; then failover.
    sim_ = SimulatedMultiPaxos(f=f, batched=False, flexible=False)
    system = sim_.new_system(seed=seed)
    t = system.transport
    config = system.config
    acceptor_addrs = {a for group in config.acceptor_addresses for a in group}
    for k in range(n):
        sim_.run_command(system, Write(0, k, f"c{k}".encode()))
    steps = 0
    while t.messages and steps < 20_000:
        steps += 1
        m = t.messages[0]
        decoded = wire.decode(m.data)
        if isinstance(decoded, Phase2a) and m.dst in acceptor_addrs:
            if fates.get(decoded.slot) == "empty":
                t.drop_message(m)
            else:
                t.deliver_message(m)
        elif isinstance(decoded, Phase2b):
            if fates.get(decoded.slot) == "committed":
                t.deliver_message(m)
            else:
                t.drop_message(m)
        else:
            t.deliver_message(m)
    assert steps < 20_000
    # Failover: kill leader 0, elect leader 1.
    t.partition_actor(config.leader_addresses[0])
    t.partition_actor(config.leader_election_addresses[0])
    t.trigger_timer(config.leader_election_addresses[1], "noPingTimer")
    drain(system)
    assert sim_symbols(system, n) == expected

    # ---- Batched side: same fates via Phase2a arrival masks.
    cfg = BatchedMultiPaxosConfig(
        f=f, num_groups=2, window=2 * spg, slots_per_tick=spg,
        lat_min=1, lat_max=1, thrifty=False, retry_timeout=100,
        max_slots_per_group=spg,
    )
    key = jax.random.PRNGKey(seed)
    state = jit_tick(cfg, init_state(cfg), jnp.int32(0), jax.random.fold_in(key, 0))
    p2a = np.asarray(state.p2a_arrival).copy()  # [A, 2, W]
    for s in range(n):
        g, w = s % 2, s // 2
        if fates[s] == "empty":
            p2a[:, g, w] = INF16
        elif fates[s] == "voted":
            p2a[vote_counts[s]:, g, w] = INF16
    state = dataclasses.replace(state, p2a_arrival=jnp.asarray(p2a))
    log = {}
    state, t_ = run_batched_collecting(cfg, state, 1, 3, key, log)
    # Only committed-fate slots may be chosen before the failover.
    pre = set(log)
    assert pre == {s for s in range(n) if fates[s] == "committed"}, (pre, fates)
    state = leader_change(cfg, state, jnp.int32(t_), jax.random.fold_in(key, 999))
    state, t_ = run_batched_collecting(cfg, state, t_, 12, key, log)
    inv = check_invariants(cfg, state, jnp.int32(t_))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.retired) == n
    assert batched_symbols(log, n) == expected


# -- Family 2: Mencius skips --------------------------------------------------


@pytest.mark.parametrize("seed", range(25))
def test_mencius_skip_family(seed):
    """Random active stripe and write count: the active server's writes
    land on its owned slots; every other stripe noop-fills — identical
    global logs in both executions."""
    import frankenpaxos_tpu.tpu.mencius_batched as mb
    from test_vanillamencius import drain as vm_drain, make as vm_make

    rng = random.Random(1000 + seed)
    active = rng.randrange(3)
    n_writes = rng.randint(2, 6)
    L = 3

    # Per-actor.
    t, config, servers, clients = vm_make(f=1, num_clients=1, seed=seed)

    class _Pick:
        def randrange(self, n, _v=active):
            return _v

    clients[0].rng = _Pick()
    for k in range(n_writes):
        p = clients[0].propose(k, f"w{k}".encode())
        vm_drain(t)
        assert p.done
    total = n_writes * L - (L - 1 - active)  # trailing idle slots unfilled
    sim_log = []
    for slot in range(total):
        entry = servers[0].log.get(slot)
        if entry is None:
            break
        (value,) = entry
        sim_log.append(NOOP if value is None else int(value.command[1:]))

    # Batched: permanently-idle stripes are 0..k-1, so ROTATE the
    # per-actor layout: per-actor active index `active` corresponds to
    # batched stripe L-1 (idle stripes first). The global logs then match
    # up to the stripe rotation r -> (r - active - 1) % L, which
    # preserves ownership order; compare symbol multisets per global
    # position after rotating.
    cfg = mb.BatchedMenciusConfig(
        f=1, num_leaders=L, window=16, slots_per_tick=1,
        num_idle_leaders=L - 1, skip_threshold=1, lat_min=1, lat_max=1,
        max_slots_per_leader=n_writes,
    )
    key = jax.random.PRNGKey(seed)
    state = mb.init_state(cfg)
    blog = {}
    t_ = 0
    for _ in range(n_writes * 3 + 15):
        state = mb.tick(cfg, state, jnp.int32(t_), jax.random.fold_in(key, t_))
        ct = np.asarray(state.chosen_tick)
        head = np.asarray(state.head)
        sv = np.asarray(state.slot_value)
        for l in range(L):
            for pos in range(cfg.window):
                if ct[l, pos] == t_:
                    o = int(head[l]) + ((pos - int(head[l])) % cfg.window)
                    blog[o * L + l] = int(sv[l, pos])
        t_ += 1
    inv = mb.check_invariants(cfg, state, jnp.int32(t_))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.committed_real) == n_writes

    # Translate the batched log (active stripe = L-1) into the per-actor
    # layout (active stripe = `active`): ordinal o of the active stripe
    # is global slot o*L + active per-actor, o*L + (L-1) batched; idle
    # stripes fill with noops in both.
    translated = []
    for s in range(total):
        o, stripe = s // L, s % L
        if stripe == active:
            v = blog.get(o * L + (L - 1))
            translated.append(
                NOOP if v is None or v == mb.NOOP_VALUE else v // L
            )
        else:
            # an idle stripe's slot below the active watermark: noop
            translated.append(NOOP)
    assert translated[: len(sim_log)] == sim_log, (translated, sim_log)


# -- Family 3: Scalog cuts ----------------------------------------------------


@pytest.mark.parametrize("seed", range(25))
def test_scalog_cut_family(seed):
    """Random monotone append schedules for two shards: identical cut
    sequences, and the batched prefix-sum projection reproduces the real
    system's global log exactly."""
    import frankenpaxos_tpu.tpu.scalog_batched as sb
    from test_scalog import ScalogCluster

    rng = random.Random(2000 + seed)
    rounds = rng.randint(2, 4)
    cum = []
    a = b = 0
    for _ in range(rounds):
        # Each interval appends >= 1 record in total (else no cut).
        da, db = rng.randint(0, 3), rng.randint(0, 3)
        if da + db == 0:
            da = 1
        a, b = a + da, b + db
        cum.append((a, b))

    cluster = ScalogCluster(
        seed=seed, num_clients=2, push_size=10**6, cuts_per_proposal=4
    )

    class _PickFlat:
        def __init__(self, flat):
            self.flat = flat

        def randrange(self, n):
            return self.flat

    cluster.clients[0].rng = _PickFlat(0)
    cluster.clients[1].rng = _PickFlat(2)
    seqs = [0, 0]
    prev = (0, 0)
    for target in cum:
        for shard in (0, 1):
            for _ in range(target[shard] - prev[shard]):
                cluster.clients[shard].write(
                    seqs[shard], f"s{shard}-{seqs[shard]}".encode()
                )
                seqs[shard] += 1
        cluster.drain()
        for server in cluster.servers:
            server.push()
        cluster.drain()
        prev = target
    cuts = [tuple(c) for c in cluster.aggregator.cuts]
    assert [(c[0], c[2]) for c in cuts] == cum, (cuts, cum)
    replica_log = [bytes(v) for v in cluster.replicas[0].state_machine.log]
    assert len(replica_log) == sum(cum[-1])

    # Batched projection must reproduce the real global log.
    predicted = [None] * sum(cum[-1])
    prev_vec = jnp.zeros((2,), jnp.int32)
    for cut in cum:
        cut_vec = jnp.asarray(cut, jnp.int32)
        starts, ends = sb.global_indices_of_cut(prev_vec, cut_vec)
        starts, ends = np.asarray(starts), np.asarray(ends)
        base = np.asarray(prev_vec)
        for shard in (0, 1):
            for j in range(ends[shard] - starts[shard]):
                predicted[starts[shard] + j] = (
                    f"s{shard}-{base[shard] + j}".encode()
                )
        prev_vec = cut_vec
    assert predicted == replica_log, (predicted, replica_log)


# -- Family 4: Fast Paxos O4 recovery -----------------------------------------

from frankenpaxos_tpu.tpu import fastpaxos_batched as fb

fb_jit_tick = jax.jit(fb.tick, static_argnums=0)


def _fastpaxos_scenario(seed):
    """Random scenario: f, a round-0 vote split over the 2f+1 acceptors
    (proposer 0 / proposer 1 / unvoted), and a random classic-quorum
    subset whose Phase1bs the recovery observes."""
    rng = random.Random(1000 + seed)
    f = rng.choice([1, 2])
    n = 2 * f + 1
    votes = [rng.choice([0, 1, None]) for _ in range(n)]
    quorum = sorted(rng.sample(range(n), f + 1))
    return f, n, votes, quorum


@pytest.mark.parametrize("seed", range(25))
def test_fastpaxos_o4_family(seed):
    """Drive the SAME vote split + phase-1 quorum through the per-actor
    protocol's leader fallback (ground truth) and the batched model's
    timeout recovery; both must choose the same value — including when
    the split holds an unobserved fast quorum (the O4 safety case)."""
    from test_fastpaxos_craq import make_fp
    from test_tpu_fastpaxos import _inject_instance

    f, n, votes, quorum = _fastpaxos_scenario(seed)

    # ---- Per-actor side.
    t, config, leaders, acceptors, clients = make_fp(f=f)
    clients[0].propose("a")
    clients[1].propose("b")
    acc = config.acceptor_addresses
    c0, c1 = clients[0].address, clients[1].address

    def deliver_where(pred):
        for m in [m for m in t.messages if pred(m)]:
            t.deliver_message(m)

    for i, v in enumerate(votes):
        if v == 0:
            deliver_where(lambda m, i=i: m.src == c0 and m.dst == acc[i])
        elif v == 1:
            deliver_where(lambda m, i=i: m.src == c1 and m.dst == acc[i])
    assert [a.vote_value for a in acceptors] == [
        {0: "a", 1: "b", None: None}[v] for v in votes
    ]
    # No Phase2bs reach the clients: the fast path stalls and client 0
    # falls back through leader 0 (the batched model's proposer-0-default
    # alignment).
    t.trigger_timer(c0, "reproposeTimer")
    deliver_where(lambda m: m.dst == leaders[0].address)
    deliver_where(lambda m: m.src == leaders[0].address and m.dst in acc)
    for i in quorum:
        deliver_where(
            lambda m, i=i: m.src == acc[i] and m.dst == leaders[0].address
        )
    deliver_where(lambda m: m.src == leaders[0].address and m.dst in acc)
    deliver_where(lambda m: m.dst == leaders[0].address)
    expected = leaders[0].chosen_value
    assert expected in ("a", "b")
    # Test-guard: a fast-committed value must win (quorum intersection).
    fb_cfg_probe = fb.BatchedFastPaxosConfig(f=f, num_groups=1)
    for val, name in ((0, "a"), (1, "b")):
        if votes.count(val) >= fb_cfg_probe.fast_quorum:
            assert expected == name

    # ---- Batched side: same votes in the acceptor arrays (replies too
    # slow to observe), timeout recovery, and the same phase-1 quorum
    # (non-quorum Phase1bs delayed past the horizon).
    cfg = fb.BatchedFastPaxosConfig(
        f=f, num_groups=1, window=4, instances_per_tick=0,
        conflict_rate=0.0, lat_min=1, lat_max=1, recovery_timeout=4,
    )
    v0, v1 = 10, 11  # _values_of(5), the id _inject_instance uses
    state = _inject_instance(cfg, fb.init_state(cfg), votes, t=0)
    key = jax.random.PRNGKey(seed)
    tt = 0
    overrode = False
    chosen_seen = None
    for _ in range(40):
        state = fb_jit_tick(
            cfg, state, jnp.int32(tt), jax.random.fold_in(key, tt)
        )
        tt += 1
        st = int(state.status[0, 0])
        if st == fb.I_REC1 and not overrode:
            up = np.asarray(state.up_arrival[:, 0, 0])
            if np.all(up < int(INF)):  # every Phase1b reply scheduled
                for a in range(n):
                    if a not in quorum:
                        state = dataclasses.replace(
                            state,
                            up_arrival=state.up_arrival.at[a, 0, 0].set(1000),
                        )
                overrode = True
        if st == fb.I_CHOSEN and chosen_seen is None:
            chosen_seen = int(state.chosen_value[0, 0])
    assert overrode, "recovery never scheduled its phase-1 replies"
    assert chosen_seen is not None, "batched instance never chose"
    inv = fb.check_invariants(cfg, state, jnp.int32(tt))
    assert all(bool(x) for x in inv.values()), inv
    assert chosen_seen == {"a": v0, "b": v1}[expected], (
        seed, f, votes, quorum, expected, chosen_seen
    )


# -- Family 5: CRAQ apportioned-read routing ----------------------------------


def _craq_scenario(seed):
    """Random op schedule over a 3-node chain with 3 keys: full writes,
    one optional stalled write (delivered to the head only), and reads
    at random nodes. Every read's routing decision (clean-local vs
    dirty-via-tail) and returned version must agree across executions."""
    rng = random.Random(2000 + seed)
    n_ops = rng.randint(5, 9)
    ops = []
    stalled_at = rng.randrange(n_ops) if rng.random() < 0.7 else None
    for i in range(n_ops):
        if rng.random() < 0.5:
            ops.append(("write", rng.randrange(3), i == stalled_at))
        else:
            ops.append(("read", rng.randrange(3), rng.randrange(3)))
    return ops


@pytest.mark.parametrize("seed", range(20))
def test_craq_routing_family(seed):
    import frankenpaxos_tpu.tpu.craq_batched as cb
    from frankenpaxos_tpu.protocols import craq as cq
    from test_fastpaxos_craq import make_craq
    from test_tpu_craq import _inject_read, _inject_write

    ops = _craq_scenario(seed)

    # ---- Per-actor side. Writes use increasing values "v0", "v1", ...;
    # a stalled write is delivered to the head only and released at the
    # end. Reads route deterministically; record (was_dirty, value).
    t, config, nodes, clients = make_craq(n=3, num_clients=2)
    acc = config.chain_node_addresses
    stalled_msgs = []
    stalled_key = None
    wseq = 0
    actor_reads = []

    def drain_except_stalled():
        for _ in range(2000):
            pend = [m for m in t.messages if m not in stalled_msgs]
            if not pend:
                return
            t.deliver_message(pend[0])
        raise AssertionError("no quiesce")

    class _Pick:
        def __init__(self, n):
            self.n = n

        def randrange(self, _):
            return self.n

    pseud = 0
    for op in ops:
        if op[0] == "write":
            _, key, stall = op
            clients[0].write(pseud, f"k{key}", f"v{wseq}")
            pseud += 1
            if stall and stalled_key is None:
                # Deliver to the head only; hold the forward to node 1.
                for m in [m for m in t.messages if m.dst == acc[0]]:
                    t.deliver_message(m)
                stalled_msgs = [m for m in t.messages if m.dst == acc[1]]
                stalled_key = key
            else:
                drain_except_stalled()
            wseq += 1
        else:
            _, node, key = op
            clients[1].rng = _Pick(node)
            r = clients[1].read(pseud, f"k{key}")
            pseud += 1
            # OBSERVE the routing decision: deliver the read to its node,
            # then check whether the node forwarded a CraqTailRead.
            for m in [m for m in t.messages
                      if m.dst == acc[node] and m not in stalled_msgs]:
                t.deliver_message(m)
            from frankenpaxos_tpu.core import wire as _wire
            was_dirty = any(
                isinstance(_wire.decode(m.data), cq.CraqTailRead)
                for m in t.messages
                if m not in stalled_msgs
            )
            drain_except_stalled()
            assert r.done
            actor_reads.append((was_dirty, r.result()))
    # Release the stalled write and quiesce.
    for m in list(stalled_msgs):
        t.deliver_message(m)
    stalled_msgs = []
    drain_except_stalled()

    # ---- Batched side: same schedule by injection; versions are the
    # write sequence numbers. Record (routed_dirty, version).
    cfg = cb.BatchedCraqConfig(
        num_chains=1, chain_len=3, num_keys=3, window=16,
        writes_per_tick=0, reads_per_tick=0, read_window=16,
        lat_min=1, lat_max=1,
    )
    key_ = jax.random.PRNGKey(seed)
    state = cb.init_state(cfg)
    tt = 0

    def run(state, tt, n):
        for _ in range(n):
            state = cb.tick(
                cfg, state, jnp.int32(tt), jax.random.fold_in(key_, tt)
            )
            tt += 1
        return state, tt

    wslot = 0
    rslot = 0
    b_stalled_slot = None
    bseq = 0
    batched_reads = []
    for op in ops:
        if op[0] == "write":
            _, key, stall = op
            assert wslot < 16, 'scenario exceeds the write ring'
            state = _inject_write(state, wslot, key, bseq, tt)
            if stall and b_stalled_slot is None:
                state, tt = run(state, tt, 2)  # reaches the head: dirty
                assert int(state.node_dirty[0, 0, key]) >= 1
                state = dataclasses.replace(
                    state,
                    w_arrival=state.w_arrival.at[0, wslot].set(tt + 5000),
                )
                b_stalled_slot = wslot
            else:
                state, tt = run(state, tt, 10)  # fully acked
            wslot += 1
            bseq += 1
        else:
            _, node, key = op
            floor = int(state.node_version[0, 2, key])
            dirty0 = int(state.reads_dirty)
            assert rslot < 16, 'scenario exceeds the read ring'
            state = _inject_read(state, rslot, key, node, tt, floor)
            state, tt = run(state, tt, 5)
            routed_dirty = int(state.reads_dirty) > dirty0
            batched_reads.append(
                (routed_dirty, int(state.r_version[0, rslot]))
            )
            rslot += 1
    if b_stalled_slot is not None:
        state = dataclasses.replace(
            state,
            w_arrival=state.w_arrival.at[0, b_stalled_slot].set(tt + 1),
        )
        state, tt = run(state, tt, 10)
    inv = cb.check_invariants(cfg, state, jnp.int32(tt))
    assert all(bool(v) for v in inv.values()), inv

    # ---- Alignment: same routing decisions; values map version k <->
    # "v<k>" (unwritten keys: batched -1 <-> per-actor DEFAULT).
    assert len(actor_reads) == len(batched_reads)
    for (a_dirty, a_val), (b_dirty, b_ver) in zip(actor_reads, batched_reads):
        assert a_dirty == b_dirty, (seed, ops, actor_reads, batched_reads)
        expect = cq.DEFAULT if b_ver < 0 else f"v{b_ver}"
        assert a_val == expect, (seed, ops, actor_reads, batched_reads)
