"""Teeth tests for the jaxpr dataflow layer (ANALYSIS_VERSION 2.4).

Each dataflow rule is proven against a pair of toy fixture backends
under ``tests/fixtures/analysis/dataflow/``:

* ``clean_toy.py`` — a model citizen: zero findings from every rule.
* ``dirty_toy.py`` — one seeded violation per rule family, each of
  which must surface under its expected stable finding key.

The rules are invoked DIRECTLY (``core.RULES[rid].check(ctx)``) rather
than through ``core.run``: the engine's stale-allowlist hygiene walk
rightly reports real-tree SUPPRESS entries as stale when the rule is
pointed at fixtures instead of the backend registry, and that is the
engine's contract under test in test_analysis_engine.py — here we want
the raw rule verdicts.
"""

from __future__ import annotations

import importlib.util
import pathlib
import sys

import pytest

from frankenpaxos_tpu.analysis import core, rules_dataflow

pytestmark = pytest.mark.lint

FIXTURES = (
    pathlib.Path(__file__).parent / "fixtures" / "analysis" / "dataflow"
)

DATAFLOW_RULES = (
    "prng-stream-lineage",
    "prng-salt-disjoint",
    "state-dead-write-reachable",
    "donation-hazard",
)


def _load(name: str):
    spec = importlib.util.spec_from_file_location(
        name, FIXTURES / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations via sys.modules.
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def clean_ctx():
    mod = _load("clean_toy")
    return core.Context(dataflow_targets=[("clean_toy", mod)])


@pytest.fixture(scope="module")
def dirty_ctx():
    mod = _load("dirty_toy")
    return core.Context(dataflow_targets=[("dirty_toy", mod)])


def _keys(rule_id: str, ctx) -> list:
    return [f.key for f in core.RULES[rule_id].check(ctx)]


# ---------------------------------------------------------------------------
# Clean fixture: every rule silent
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule_id", DATAFLOW_RULES)
def test_clean_fixture_has_no_findings(clean_ctx, rule_id):
    assert _keys(rule_id, clean_ctx) == []


# ---------------------------------------------------------------------------
# Dirty fixture: each seeded violation surfaces under its stable key
# ---------------------------------------------------------------------------


def test_stream_reuse_is_detected(dirty_ctx):
    """The same split child feeding two draws is stream reuse."""
    keys = _keys("prng-stream-lineage", dirty_ctx)
    reuse = [k for k in keys if k.startswith("dirty_toy:reuse:")]
    assert len(reuse) == 1
    # The offending stream is the first split child of the tick key.
    assert "split[0]" in reuse[0]


def test_foreign_key_root_is_detected(dirty_ctx):
    """A key minted from PRNGKey(0) inside the tick has no lineage to
    the tick key argument."""
    keys = _keys("prng-stream-lineage", dirty_ctx)
    assert "dirty_toy:foreign:0" in keys


def test_mixed_family_lineage_is_detected(dirty_ctx):
    """Folding both the fault and workload salts onto one key mixes
    two declared stream families."""
    keys = _keys("prng-stream-lineage", dirty_ctx)
    mixed = [k for k in keys if k.startswith("dirty_toy:mixed:")]
    assert len(mixed) == 1
    assert "0x5eed" in mixed[0] and "0x10ad" in mixed[0]


def test_salt_escape_is_detected_in_both_rules(dirty_ctx):
    """WORKLOAD_SALT + 300 lands past the workload family span
    (span = 256): the lineage rule flags the undeclared stream and the
    salt rule flags the escaping fold constant."""
    lineage = _keys("prng-stream-lineage", dirty_ctx)
    assert "dirty_toy:undeclared:0x11d9" in lineage
    salt = _keys("prng-salt-disjoint", dirty_ctx)
    assert "dirty_toy:escape:0x11d9" in salt


def test_declared_salt_intervals_stay_disjoint(clean_ctx):
    """The declared family bases themselves must never overlap — the
    rule asserts this from the traced constants on every run."""
    assert not [
        k for k in _keys("prng-salt-disjoint", clean_ctx)
        if k.startswith("declared:")
    ]


def test_alias_fed_dead_write_is_detected(dirty_ctx):
    """``ghost`` is rewritten each tick through a local alias
    (``g = state.ghost + 1``) — invisible to the retired AST
    ``state.replace``-pattern rule — and read by no invariant,
    telemetry field, or host roll-up: a reachability-level dead write."""
    keys = _keys("state-dead-write-reachable", dirty_ctx)
    assert keys == ["dirty_toy:ghost"]


def test_live_leaves_are_not_flagged_dead(dirty_ctx):
    """big/echo/count all reach check_invariants: never dead."""
    keys = _keys("state-dead-write-reachable", dirty_ctx)
    for leaf in ("big", "echo", "count"):
        assert f"dirty_toy:{leaf}" not in keys


def test_post_alias_read_is_a_donation_hazard(dirty_ctx):
    """Reading the OLD value of ``big`` after its replacement is
    produced would read a clobbered buffer under donate_argnums."""
    keys = _keys("donation-hazard", dirty_ctx)
    assert keys == ["dirty_toy:big"]


# ---------------------------------------------------------------------------
# Real-tree invariants the layer asserts as machine-checked facts
# ---------------------------------------------------------------------------


def test_declared_families_match_source_constants():
    from frankenpaxos_tpu.analysis import dataflow
    from frankenpaxos_tpu.tpu.faults import FAULT_SALT
    from frankenpaxos_tpu.tpu.lifecycle import LIFECYCLE_SALT
    from frankenpaxos_tpu.tpu.workload import WORKLOAD_SALT

    fams = rules_dataflow.declared_families()
    assert fams["fault"] == FAULT_SALT
    assert fams["workload"] == WORKLOAD_SALT
    assert fams["lifecycle"] == LIFECYCLE_SALT
    # Pairwise-disjoint intervals of span FAMILY_SPAN each.
    bases = sorted(fams.values())
    for a, b in zip(bases, bases[1:]):
        assert a + dataflow.FAMILY_SPAN <= b


def test_salt_disjointness_holds_on_a_real_backend():
    """Acceptance pin: salt disjointness is asserted from the traced
    jaxpr of a real backend, not just from the Python constants."""
    from frankenpaxos_tpu.analysis import rules_trace

    ctx = core.Context(backends=("multipaxos",))
    findings = core.RULES["prng-salt-disjoint"].check(ctx)
    assert findings == []
    # The trace really saw fold_in constants from the declared bands:
    # the multipaxos analysis trace folds the fault + lifecycle family
    # salts (the constant-arrival workload plan derives no key).
    t = rules_dataflow._traced(
        "multipaxos", rules_trace._module("multipaxos")
    )
    from frankenpaxos_tpu.analysis import dataflow

    folds = set()
    for node in t.graph.nodes:
        if node.prim == "random_fold_in" and len(node.invars) >= 2:
            lit = t.graph.literals.get(node.invars[1])
            if lit is not None:
                folds.add(int(lit))
    fams = rules_dataflow.declared_families()
    hit = {
        fam for fam, base in fams.items()
        if any(base <= c < base + dataflow.FAMILY_SPAN for c in folds)
    }
    assert {"fault", "lifecycle"} <= hit
