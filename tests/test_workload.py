"""The in-graph workload engine (tpu/workload.py): traffic shaping,
closed-loop window conservation, Zipf skew, traced [workload x
fault-rate] sweeps, and the WorkloadPlan.none() structural no-op.

The load-bearing guarantee first: ``WorkloadPlan.none()`` (the default
on every batched config) is a STRUCTURAL no-op. The golden values below
are the ``tests/test_faults.py`` pre-fault-subsystem captures (PR 2
head, commit f899c3f) — the same fixed configs/seeds, now constructed
with an EXPLICIT none plan — so any workload-threading change that
perturbs a default run by even one bit fails here against the true
pre-PR behavior."""

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.harness import simtest
from frankenpaxos_tpu.tpu import (
    craq_batched,
    multipaxos_batched,
    unreplicated_batched,
    vanillamencius_batched,
)
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as wl
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan


def _hash(state, fields):
    m = hashlib.sha256()
    for f in fields:
        m.update(np.asarray(jax.device_get(getattr(state, f))).tobytes())
    return m.hexdigest()[:16]


def _full_hash(state):
    m = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state)):
        m.update(np.asarray(leaf).tobytes())
    return m.hexdigest()[:16]


# ---------------------------------------------------------------------------
# none() bit-identity against the pre-PR goldens (4 backends x 3 seeds;
# values identical to tests/test_faults.py — the workload default must
# not move them by a bit)
# ---------------------------------------------------------------------------

GOLDEN_MULTIPAXOS = {
    0: (582, 562, 3426, "dd70eeb17ab45de2"),
    1: (581, 530, 3487, "c665a10d449618ae"),
    2: (583, 551, 3340, "ec2d56f23217dda9"),
}
GOLDEN_CRAQ = {
    0: (374, 743, 251, "b6fe4b6285011bda"),
    1: (368, 747, 231, "0025adf193587ca4"),
    2: (370, 750, 219, "d9c0363c64b1db0c"),
}
GOLDEN_UNREPLICATED = {
    0: (929, 3663, "589abaf0933332b2"),
    1: (929, 3705, "bbd795f9ce1b7c01"),
    2: (928, 3692, "f8fe3872c1751c1a"),
}


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_multipaxos(seed):
    mp = multipaxos_batched
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2, lat_min=1,
        lat_max=3, drop_rate=0.05, retry_timeout=8,
        workload=WorkloadPlan.none(),
    )
    assert cfg.workload == WorkloadPlan.none()
    # The default IS the none plan (an implicit default must be the
    # same structural no-op as the explicit one).
    assert mp.BatchedMultiPaxosConfig().workload == cfg.workload
    st, _ = mp.run_ticks(
        cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), 120,
        jax.random.PRNGKey(seed),
    )
    got = (
        int(st.committed), int(st.retired), int(st.lat_sum),
        _hash(st, ("status", "slot_value", "chosen_round", "head",
                   "next_slot", "acc_round", "vote_round", "vote_value")),
    )
    assert got == GOLDEN_MULTIPAXOS[seed]
    # And the carried shaping state is structurally EMPTY.
    assert all(
        leaf.size == 0
        for leaf in jax.tree_util.tree_leaves(st.workload)
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_craq(seed):
    cr = craq_batched
    cfg = cr.BatchedCraqConfig(
        num_chains=4, chain_len=3, num_keys=8, window=8,
        writes_per_tick=2, reads_per_tick=2, read_window=8,
        workload=WorkloadPlan.none(),
    )
    st, _ = cr.run_ticks(
        cfg, cr.init_state(cfg), jnp.zeros((), jnp.int32), 120,
        jax.random.PRNGKey(seed),
    )
    got = (
        int(st.writes_done), int(st.reads_done), int(st.reads_dirty),
        _hash(st, ("w_status", "w_version", "node_version", "node_dirty",
                   "r_status")),
    )
    assert got == GOLDEN_CRAQ[seed]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_none_plan_bit_identical_unreplicated(seed):
    ur = unreplicated_batched
    cfg = ur.BatchedUnreplicatedConfig(
        num_servers=4, window=16, ops_per_tick=2,
        workload=WorkloadPlan.none(),
    )
    st, _ = ur.run_ticks(
        cfg, ur.init_state(cfg), jnp.zeros((), jnp.int32), 120,
        jax.random.PRNGKey(seed),
    )
    got = (
        int(st.done), int(st.lat_sum),
        _hash(st, ("status", "issue", "arrival", "executed")),
    )
    assert got == GOLDEN_UNREPLICATED[seed]


def test_none_plan_bit_identical_vanillamencius():
    """4th backend for the >=4-backend pin: the none plan replays the
    exact same history as a default config (self-consistency across
    two separately-traced programs on a churn-heavy backend)."""
    vm = vanillamencius_batched
    base = vm.analysis_config()
    explicit = vm.analysis_config(workload=WorkloadPlan.none())
    key = jax.random.PRNGKey(4)
    a, _ = vm.run_ticks(
        base, vm.init_state(base), jnp.zeros((), jnp.int32), 120, key
    )
    b, _ = vm.run_ticks(
        explicit, vm.init_state(explicit), jnp.zeros((), jnp.int32),
        120, key,
    )
    assert _full_hash(a) == _full_hash(b)
    assert int(a.committed) > 0


# ---------------------------------------------------------------------------
# Plan semantics
# ---------------------------------------------------------------------------


def test_plan_validation_rejects_malformed_plans():
    with pytest.raises(AssertionError):
        WorkloadPlan(arrival="weibull").validate()
    with pytest.raises(AssertionError):
        WorkloadPlan(arrival="poisson", rate=0.0).validate()
    with pytest.raises(AssertionError):
        WorkloadPlan(arrival="poisson", rate=1.0, read_fraction=0.3
                     ).validate(reads_supported=False)
    with pytest.raises(AssertionError):
        WorkloadPlan(read_fraction=0.3).validate(reads_supported=True)
    with pytest.raises(AssertionError):
        WorkloadPlan(arrival="bursty", rate=1.0, burst_len=0).validate()
    with pytest.raises(AssertionError):
        WorkloadPlan(arrival="diurnal", rate=1.0, phases=()).validate()
    with pytest.raises(AssertionError):
        WorkloadPlan(closed_window=-1).validate()
    WorkloadPlan(
        arrival="diurnal", rate=1.5, phases=(0.5, 2.0), phase_len=8,
        zipf_s=0.9, closed_window=4, think_time=2,
    ).validate()
    # The config path rejects a read mix without a read ring.
    with pytest.raises(AssertionError):
        multipaxos_batched.BatchedMultiPaxosConfig(
            workload=WorkloadPlan(
                arrival="poisson", rate=1.0, read_fraction=0.2
            )
        )


def test_plan_round_trips_through_json_and_host_dispatcher():
    plan = WorkloadPlan(
        arrival="diurnal", rate=2.5, phases=(0.5, 1.5, 3.0),
        phase_len=16, zipf_s=0.8, read_fraction=0.25,
        closed_window=6, think_time=3, backlog_cap=512,
    )
    again = WorkloadPlan.from_dict(json.loads(json.dumps(plan.to_dict())))
    assert again == plan
    # One config surface: the HOST workload dispatcher deserializes the
    # device plan from the same schema, and the host Zipf generator
    # shares the device skew vector.
    from frankenpaxos_tpu.harness.workload import (
        ZipfSingleKeyWorkload,
        workload_from_dict,
    )

    assert workload_from_dict(plan.to_dict()) == plan
    host = ZipfSingleKeyWorkload(num_keys=16, zipf_s=0.8)
    again_host = workload_from_dict(host.to_dict())
    assert again_host == host
    np.testing.assert_allclose(
        host._weights, wl.zipf_weights(16, 0.8), rtol=1e-6
    )


def test_zipf_weights_normalized_and_skewed():
    w = wl.zipf_weights(64, 1.0)
    assert w.shape == (64,)
    assert abs(float(w.mean()) - 1.0) < 1e-5
    assert w[0] > w[10] > w[63] > 0
    u = wl.zipf_weights(64, 0.0)
    np.testing.assert_allclose(u, np.ones(64), rtol=1e-6)


def test_constant_arrivals_are_exact_and_deterministic():
    """The fixed-point accumulator emits the exact long-run rate with
    zero drift: over T ticks each lane emits floor-error < 1."""
    plan = WorkloadPlan(arrival="constant", rate=1.75)
    plan.validate()
    s = wl.make_state(plan, 8)
    key = jax.random.PRNGKey(0)
    total = np.zeros(8, np.int64)
    for t in range(64):
        writes, _, s = wl.begin(
            plan, s, jax.random.fold_in(key, t), jnp.int32(t), 8
        )
        total += np.asarray(writes)
    expected = 1.75 * 64
    assert np.all(np.abs(total - expected) <= 1.0), total


def test_bursty_and_diurnal_modulation():
    bursty = WorkloadPlan(
        arrival="bursty", rate=2.0, burst_every=16, burst_len=4,
        burst_mult=3.0,
    )
    assert float(wl._modulation(bursty, jnp.int32(1))) == 3.0
    assert float(wl._modulation(bursty, jnp.int32(10))) == 1.0
    diurnal = WorkloadPlan(
        arrival="diurnal", rate=1.0, phases=(0.5, 2.0, 1.0), phase_len=8
    )
    assert float(wl._modulation(diurnal, jnp.int32(0))) == 0.5
    assert float(wl._modulation(diurnal, jnp.int32(9))) == 2.0
    assert float(wl._modulation(diurnal, jnp.int32(17))) == 1.0
    assert float(wl._modulation(diurnal, jnp.int32(24))) == 0.5  # wraps


def test_read_split_accumulator_tracks_fraction():
    plan = WorkloadPlan(arrival="constant", rate=4.0, read_fraction=0.25)
    plan.validate(reads_supported=True)
    s = wl.make_state(plan, 4)
    key = jax.random.PRNGKey(1)
    w_tot = r_tot = 0
    for t in range(64):
        writes, reads, s = wl.begin(
            plan, s, jax.random.fold_in(key, t), jnp.int32(t), 4
        )
        w_tot += int(writes.sum())
        r_tot += int(reads.sum())
    total = w_tot + r_tot
    assert abs(total - 4.0 * 4 * 64) <= 4
    assert abs(r_tot / total - 0.25) < 0.02


def test_fifo_wait_histogram_is_exact():
    """Hand-run scenario: 3 arrivals at t=0 on one lane, drained one
    per tick — waits must be exactly {0, 1, 2}."""
    plan = WorkloadPlan(arrival="constant", rate=1.0)
    s = wl.make_state(plan, 1)
    key = jax.random.PRNGKey(0)
    # Tick 0: inject 3 arrivals by hand (bypass begin's draw), admit 1.
    writes = jnp.asarray([3], jnp.int32)
    s = wl.finish(plan, s, jnp.int32(0), writes,
                  jnp.asarray([1], jnp.int32), jnp.zeros((1,), jnp.int32))
    for t in (1, 2):
        s = wl.finish(plan, s, jnp.int32(t), jnp.zeros((1,), jnp.int32),
                      jnp.asarray([1], jnp.int32),
                      jnp.zeros((1,), jnp.int32))
    hist = np.asarray(s.wait_hist)
    assert hist[0] == 1 and hist[1] == 1 and hist[2] == 1
    assert int(s.wait_sum) == 0 + 1 + 2
    assert int(s.backlog[0]) == 0


# ---------------------------------------------------------------------------
# Closed-loop window conservation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("think", [0, 3])
def test_closed_loop_window_conservation(think):
    """in_flight <= closed_window at EVERY segment boundary, the
    in_flight + idle + thinking partition is exact, and the engine's
    own books (admitted - completed == sum in_flight) balance."""
    mp = multipaxos_batched
    cfg = mp.analysis_config(
        workload=WorkloadPlan(closed_window=3, think_time=think)
    )
    st = mp.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)
    for seg in range(6):
        st, t = mp.run_ticks(cfg, st, t, 20, jax.random.fold_in(key, seg))
        inflight = np.asarray(st.workload.in_flight)
        assert np.all(inflight >= 0)
        assert np.all(inflight <= 3)
        inv = mp.check_invariants(cfg, st, t)
        assert all(bool(v) for v in inv.values()), {
            k: bool(v) for k, v in inv.items() if not bool(v)
        }
        assert int(st.workload.admitted) - int(st.workload.completed) == int(
            inflight.sum()
        )
    assert int(st.committed) > 0
    assert int(st.workload.completed) > 0


def test_closed_loop_throughput_is_window_bound():
    """Little's law sanity: halving the window roughly halves the
    committed throughput of an otherwise-saturating run."""
    mp = multipaxos_batched

    def run(window):
        cfg = mp.analysis_config(
            workload=WorkloadPlan(closed_window=window)
        )
        st, _ = mp.run_ticks(
            cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), 120,
            jax.random.PRNGKey(2),
        )
        return int(st.committed)

    c1, c4 = run(1), run(4)
    assert 0 < c1 < c4
    assert c4 > 2 * c1


def test_epaxos_admission_accounts_post_clamp_count():
    """Regression: finish() must see the ACTUAL issue count — with
    max_instances_per_column active, the pre-clamp cap would drain
    phantom entries from the backlog and strand the closed-loop
    window. Every admission must correspond to a real issued
    instance (admitted == sum(next_instance)) and the window must
    fully drain once the columns hit their cap."""
    from frankenpaxos_tpu.tpu import epaxos_batched as ep

    cfg = dataclasses.replace(
        ep.analysis_config(
            workload=WorkloadPlan(closed_window=4, think_time=1)
        ),
        max_instances_per_column=20,
    )
    st, t = ep.run_ticks(
        cfg, ep.init_state(cfg), jnp.zeros((), jnp.int32), 150,
        jax.random.PRNGKey(0),
    )
    inv = ep.check_invariants(cfg, st, t)
    assert all(bool(v) for v in inv.values())
    adm = int(st.workload.admitted)
    assert adm == int(st.next_instance.sum())
    assert adm - int(st.workload.completed) == int(
        st.workload.in_flight.sum()
    )
    assert int(st.workload.in_flight.sum()) == 0  # capped run drains


# ---------------------------------------------------------------------------
# Zipf skew on a live backend (3 seeds)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_zipf_skew_shapes_per_lane_admissions(seed):
    """Empirical per-lane admission frequency tracks the configured
    Zipf weights: the hot lane strictly leads, the ordering follows
    rank, and the hot/cold ratio lands near the analytic weight ratio."""
    ur = unreplicated_batched
    cfg = ur.BatchedUnreplicatedConfig(
        num_servers=8, window=64, ops_per_tick=4,
        workload=WorkloadPlan(
            arrival="poisson", rate=1.0, zipf_s=1.0, backlog_cap=4096
        ),
    )
    st, _ = ur.run_ticks(
        cfg, ur.init_state(cfg), jnp.zeros((), jnp.int32), 400,
        jax.random.PRNGKey(seed),
    )
    # Per-lane admissions = executed + still-in-ring (every admitted op
    # stays counted); with a large window nothing backlogs away.
    per_lane = np.asarray(st.executed) + np.asarray(
        jax.device_get((st.status != 0).sum(axis=1))
    )
    w = wl.zipf_weights(8, 1.0)
    assert per_lane[0] == per_lane.max()
    assert per_lane[0] > per_lane[3] > per_lane[7]
    ratio = per_lane[0] / max(per_lane[7], 1)
    expected = w[0] / w[7]
    assert 0.5 * expected < ratio < 2.0 * expected, (ratio, expected)


# ---------------------------------------------------------------------------
# Determinism + the traced [workload x fault-rate] sweep
# ---------------------------------------------------------------------------


def test_shaped_run_replays_bit_identically_across_seeds():
    mp = multipaxos_batched
    cfg = mp.analysis_config(
        faults=FaultPlan(drop_rate=0.1, jitter=1),
        workload=WorkloadPlan(
            arrival="poisson", rate=1.5, zipf_s=0.6, closed_window=6
        ),
    )
    hashes = {}
    for seed in (0, 1):
        for attempt in range(2):
            st, _ = mp.run_ticks(
                cfg, mp.init_state(cfg), jnp.zeros((), jnp.int32), 100,
                jax.random.PRNGKey(seed),
            )
            hashes.setdefault(seed, set()).add(_full_hash(st))
    assert len(hashes[0]) == 1 and len(hashes[1]) == 1  # replays exact
    assert hashes[0] != hashes[1]  # seeds differ


def test_traced_fault_rates_match_static_plan_results():
    """A traced plan with swept rate r commits exactly what the static
    plan with compile-time rate r commits (same 1/256 quantization,
    same PRNG streams) — and zero traced rates reproduce the none-plan
    VALUES (the program differs; the results must not)."""
    mp = multipaxos_batched
    key = jax.random.PRNGKey(3)
    t0 = jnp.zeros((), jnp.int32)

    def run_static(drop):
        cfg = mp.analysis_config(
            faults=FaultPlan(drop_rate=drop) if drop else FaultPlan.none()
        )
        st, _ = mp.run_ticks(cfg, mp.init_state(cfg), t0, 100, key)
        return int(st.committed)

    def run_traced(drop):
        cfg = mp.analysis_config(faults=FaultPlan(traced=True))
        st = mp.init_state(cfg)
        st = dataclasses.replace(
            st, workload=wl.set_fault_rates(st.workload, drop=drop)
        )
        st, _ = mp.run_ticks(cfg, st, t0, 100, key)
        return int(st.committed)

    assert run_traced(0.0) == run_static(0.0)
    # A traced nonzero drop really degrades (and the cache never grows
    # across the rate sweep — one compile serves the whole grid).
    before = mp.run_ticks._cache_size()
    degraded = run_traced(0.2)
    assert mp.run_ticks._cache_size() == before
    assert degraded < run_traced(0.0)


def test_traced_rate_grid_vmaps_in_one_compile():
    """The device-scale grid: vmap over stacked fault_rates vectors
    fans a whole drop-rate sweep out of one compiled program, and the
    committed counts decrease monotonically with the drop rate."""
    ur = unreplicated_batched
    cfg = ur.analysis_config(faults=FaultPlan(traced=True))
    base = ur.init_state(cfg)
    drops = jnp.asarray([0.0, 0.1, 0.3], jnp.float32)
    rates = jnp.stack(
        [jnp.asarray([d, 0.0, 0.0, 0.0], jnp.float32) for d in drops]
    )

    def one(rate_vec):
        st = dataclasses.replace(
            base,
            workload=dataclasses.replace(
                base.workload, fault_rates=rate_vec
            ),
        )
        out, _ = ur.run_ticks.__wrapped__(
            cfg, st, jnp.zeros((), jnp.int32), 80, jax.random.PRNGKey(0)
        )
        return out.done

    done = jax.jit(jax.vmap(one))(rates)
    done = [int(x) for x in done]
    assert done[0] > done[1] > done[2] > 0, done


def test_traced_plan_without_rate_state_fails_loudly():
    """The enforcement half of the traced contract: helpers reject a
    traced plan whose rates were not threaded."""
    fp = FaultPlan(traced=True)
    with pytest.raises(AssertionError, match="traced"):
        faults_mod.message_faults(
            fp, jax.random.PRNGKey(0), (4,), jnp.zeros((4,), jnp.int32)
        )
    with pytest.raises(AssertionError, match="traced"):
        faults_mod.tcp_latency(
            fp, jax.random.PRNGKey(0), (4,), jnp.zeros((4,), jnp.int32)
        )


def test_offered_rate_sweep_hits_one_compile():
    """The latency-vs-load matrix contract: sweeping the traced
    offered rate replays one compiled program and higher offered load
    commits more (below saturation)."""
    mp = multipaxos_batched
    cfg = mp.analysis_config(
        workload=WorkloadPlan(arrival="constant", rate=0.5)
    )

    def run(rate):
        st = mp.init_state(cfg)
        st = dataclasses.replace(
            st, workload=wl.set_rate(st.workload, rate)
        )
        st, _ = mp.run_ticks(
            cfg, st, jnp.zeros((), jnp.int32), 100, jax.random.PRNGKey(0)
        )
        return int(st.committed)

    lo = run(0.5)
    before = mp.run_ticks._cache_size()
    hi = run(1.5)
    assert mp.run_ticks._cache_size() == before
    assert 0 < lo < hi


# ---------------------------------------------------------------------------
# Joint randomization (simtest)
# ---------------------------------------------------------------------------


def test_random_workload_is_deterministic_and_well_formed():
    import random

    spec = simtest.SPECS["compartmentalized"]
    rng_a, rng_b = random.Random(11), random.Random(11)
    a = [simtest.random_workload(rng_a, spec, 120) for _ in range(16)]
    b = [simtest.random_workload(rng_b, spec, 120) for _ in range(16)]
    assert a == b
    kinds = {p.arrival for p in a} | {
        "closed" for p in a if p.closed
    }
    assert len(kinds) >= 3  # the draw actually diversifies
    for plan in a:
        plan.validate(reads_supported=True)
    # A backend WITHOUT a read path never draws a mix.
    spec_nr = simtest.SPECS["multipaxos"]
    for i in range(16):
        p = simtest.random_workload(random.Random(100 + i), spec_nr, 120)
        assert p.read_fraction == 0.0
        p.validate()


def test_joint_schedule_runs_and_reproducer_round_trips(tmp_path):
    spec = simtest.SPECS["multipaxos"]
    fplan = FaultPlan(drop_rate=0.1)
    wplan = WorkloadPlan(arrival="poisson", rate=1.0, closed_window=5)
    res = simtest.run_schedule(
        spec, fplan, seed=2, ticks=80, segment=40, workload=wplan
    )
    assert res["ok"], res["violations"]
    assert res["progress"][-1] > 0
    assert WorkloadPlan.from_dict(res["workload"]) == wplan
    path = tmp_path / "repro.json"
    simtest.dump_reproducer(
        str(path), spec, fplan, 2, 80, workload=wplan
    )
    loaded = simtest.load_reproducer(str(path))
    assert len(loaded) == 5
    assert loaded[1] == fplan and loaded[4] == wplan


def test_joint_sweep_smoke():
    res = simtest.sweep(
        backends=["unreplicated"], schedules=2, seeds_per_schedule=2,
        ticks=80, base_seed=5, check_liveness=False,
    )
    assert res["ok"], res
