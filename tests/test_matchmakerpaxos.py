"""Matchmaker Paxos sim tests (the analog of
shared/src/test/scala/matchmakerpaxos)."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import matchmakerpaxos as mm
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)


def make(f=1, num_clients=2, num_acceptors=None, seed=0):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    num_acceptors = num_acceptors or (2 * f + 2)  # spare acceptors to rotate
    config = mm.MatchmakerPaxosConfig(
        f=f,
        client_addresses=tuple(
            SimAddress(f"client{i}") for i in range(num_clients)
        ),
        leader_addresses=tuple(SimAddress(f"leader{i}") for i in range(f + 1)),
        matchmaker_addresses=tuple(
            SimAddress(f"matchmaker{i}") for i in range(2 * f + 1)
        ),
        acceptor_addresses=tuple(
            SimAddress(f"acceptor{i}") for i in range(num_acceptors)
        ),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [
        mm.MmLeader(a, t, log(), config, seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    matchmakers = [
        mm.MmMatchmaker(a, t, log(), config)
        for a in config.matchmaker_addresses
    ]
    acceptors = [
        mm.MmAcceptor(a, t, log(), config) for a in config.acceptor_addresses
    ]
    clients = [
        mm.MmClient(a, t, log(), config, seed=seed + 40 + i)
        for i, a in enumerate(config.client_addresses)
    ]
    return t, config, leaders, matchmakers, acceptors, clients


def drain(t, max_steps=100000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def test_matchmaker_single_proposal():
    t, config, leaders, matchmakers, acceptors, clients = make()
    p = clients[0].propose("apple")
    drain(t)
    assert p.done and p.result() == "apple"


def test_matchmaker_contending_leaders_choose_one_value():
    """Two clients through two leaders: matchmaker nacks + acceptor nacks
    retry until one value is chosen, consistently."""
    t, config, leaders, matchmakers, acceptors, clients = make(seed=3)
    p1 = clients[0].propose("a")
    p2 = clients[1].propose("b")
    rng = random.Random(2)
    for _ in range(3000):
        cmd = t.generate_command(rng)
        if cmd is None:
            break
        t.run_command(cmd, record=False)
    drain(t)
    for _ in range(6):
        if p1.done and p2.done:
            break
        for timer in list(t.running_timers()):
            t.trigger_timer(timer.address, timer.name())
        drain(t)
    assert p1.done and p2.done
    assert p1.result() == p2.result()


def test_matchmaker_configs_rotate_across_rounds():
    """Each round registers a fresh quorum system with the matchmakers."""
    t, config, leaders, matchmakers, acceptors, clients = make(seed=5)
    p = clients[0].propose("x")
    drain(t)
    assert p.done
    rounds_registered = {
        r for m in matchmakers for r in m.acceptor_groups.keys()
    }
    assert len(rounds_registered) >= 1


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int


class SimulatedMatchmakerPaxos(SimulatedSystem):
    """Invariant: at most one value ever chosen (consensus), and chosen
    values never change."""

    def __init__(self, f=1):
        self.f = f

    def new_system(self, seed):
        return make(self.f, seed=seed)

    def get_state(self, system):
        t, config, leaders, matchmakers, acceptors, clients = system
        chosen_leaders = tuple(
            l.state.v if isinstance(l.state, mm._MmChosen) else None
            for l in leaders
        )
        return tuple(c.chosen for c in clients) + chosen_leaders

    def generate_command(self, system, rng):
        t, config, leaders, matchmakers, acceptors, clients = system
        ops = [
            (1, Propose(i))
            for i, c in enumerate(clients)
            if c.promise is None and c.chosen is None
        ]
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, config, leaders, matchmakers, acceptors, clients = system
        if isinstance(command, Propose):
            clients[command.client_index].propose(f"v{command.client_index}")
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        chosen = {v for v in state if v is not None}
        if len(chosen) > 1:
            return f"multiple values chosen: {chosen}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if o is not None and n != o:
                return f"chosen value changed: {o!r} -> {n!r}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_matchmaker_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedMatchmakerPaxos(f), run_length=120, num_runs=20, seed=f
    )
    assert bad is None, f"\n{bad}"
