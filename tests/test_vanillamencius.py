"""Vanilla Mencius sim tests (the analog of
shared/src/test/scala/vanillamencius)."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import vanillamencius as vm
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import ReadableAppendLog


def make(f=1, num_clients=2, seed=0):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    n = 2 * f + 1
    config = vm.VanillaMenciusConfig(
        f=f,
        server_addresses=tuple(SimAddress(f"server{i}") for i in range(n)),
        heartbeat_addresses=tuple(SimAddress(f"hb{i}") for i in range(n)),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    servers = [
        vm.VmServer(a, t, log(), config, ReadableAppendLog(), seed=seed + i)
        for i, a in enumerate(config.server_addresses)
    ]
    clients = [
        vm.VmClient(SimAddress(f"client{i}"), t, log(), config, seed=seed + 20 + i)
        for i in range(num_clients)
    ]
    return t, config, servers, clients


def drain(t, max_steps=100000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def test_mencius_single_write():
    t, config, servers, clients = make()
    p = clients[0].propose(0, b"hello")
    drain(t)
    assert p.done


def test_mencius_multi_leader_skips_keep_log_moving():
    """Writes through different servers interleave; skips fill the gaps so
    every server's executed log converges."""
    t, config, servers, clients = make(seed=2)
    promises = []
    for round_ in range(4):
        for i, c in enumerate(clients):
            promises.append(c.propose(round_, f"r{round_}c{i}".encode()))
        drain(t)
    assert all(p.done for p in promises)
    logs = {tuple(s.state_machine.get()) for s in servers}
    assert len(logs) == 1, f"server logs diverged: {logs}"
    assert len(next(iter(logs))) == len(promises)


def test_mencius_revocation_unsticks_dead_leader():
    """Kill a server; another server revokes its slots so the global log
    can execute past them."""
    t, config, servers, clients = make(seed=3)
    # A write through server 0 commits normally.
    class _S0:
        def randrange(self, n):
            return 0

    clients[0].rng = _S0()
    p1 = clients[0].propose(0, b"ok")
    drain(t)
    assert p1.done

    # Server 1 dies. A write through server 2 lands in a slot AFTER server
    # 1's unused slots, so execution stalls waiting for them.
    t.partition_actor(config.server_addresses[1])
    t.partition_actor(config.heartbeat_addresses[1])

    class _S2:
        def randrange(self, n):
            return 2

    clients[1].rng = _S2()
    p2 = clients[1].propose(0, b"after")
    drain(t)
    # The write is chosen but can't execute until server 1's slots fill.
    assert not p2.done
    # Server 2 revokes server 1's slots.
    servers[2].start_revocation(1)
    drain(t)
    assert p2.done, "revocation did not unstick the log"
    live_logs = {
        tuple(s.state_machine.get()) for s in (servers[0], servers[2])
    }
    assert len(live_logs) == 1


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    value: bytes


class SimulatedMencius(SimulatedSystem):
    """Invariant: server executed logs are pairwise prefix-compatible and
    grow monotonically (same as MultiPaxos — Mencius's global log is
    totally ordered)."""

    def __init__(self, f=1):
        self.f = f

    def new_system(self, seed):
        return make(self.f, seed=seed)

    def get_state(self, system):
        t, config, servers, clients = system
        return tuple(tuple(s.state_machine.get()) for s in servers)

    def generate_command(self, system, rng):
        t, config, servers, clients = system
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (1, Propose(i, pseudonym, f"v{rng.randrange(50)}".encode()))
                    )
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t, config, servers, clients = system
        if isinstance(command, Propose):
            clients[command.client_index].propose(
                command.pseudonym, command.value
            )
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        for i in range(len(state)):
            for j in range(i + 1, len(state)):
                a, b = state[i], state[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return f"server logs not prefix-compatible: {a!r} vs {b!r}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if n[: len(o)] != o:
                return f"server log shrank or changed: {o!r} -> {n!r}"
        return None


@pytest.mark.parametrize("f", [1, 2])
def test_mencius_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedMencius(f), run_length=120, num_runs=12, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_mencius_auto_revocation_via_heartbeat():
    """The revocation timer consults the heartbeat and revokes a dead peer
    automatically (no manual start_revocation)."""
    t, config, servers, clients = make(seed=9)

    class _S2:
        def randrange(self, n):
            return 2

    clients[0].rng = _S2()
    p0 = clients[0].propose(0, b"warm")
    drain(t)
    assert p0.done

    # Server 1 dies; make server 2's heartbeat notice (success then fail
    # timers expire num_retries times).
    dead_hb = config.heartbeat_addresses[1]
    t.partition_actor(config.server_addresses[1])
    t.partition_actor(dead_hb)
    hb2 = config.heartbeat_addresses[2]
    t.trigger_timer(hb2, f"successTimer{dead_hb}")
    drain(t)
    for _ in range(servers[2].options.heartbeat_options.num_retries):
        t.trigger_timer(hb2, f"failTimer{dead_hb}")
        drain(t)
    assert dead_hb not in servers[2].heartbeat.unsafe_alive()

    # A new write through server 2 may stall behind server 1's slots.
    p1 = clients[0].propose(1, b"post-death")
    drain(t)
    # Fire server 2's revocation timer for peer 1: heartbeat says dead, so
    # revocation starts and fills the holes.
    for _ in range(3):
        t.trigger_timer(config.server_addresses[2], "revoke1")
        drain(t)
    assert p1.done


def test_mencius_repeated_revocation_uses_fresh_rounds():
    """Re-revoking the same peer must use a strictly larger round
    (review regression: round reuse let stale Phase2bs cross proposals)."""
    t, config, servers, clients = make(seed=11)
    t.partition_actor(config.server_addresses[1])
    servers[2].start_revocation(1)
    r1 = servers[2].recover_round
    drain(t)
    servers[2].start_revocation(1)
    r2 = servers[2].recover_round
    assert r2 > r1
    drain(t)


def test_mencius_false_revocation_does_not_stomp_inflight_writes():
    """A (false) revocation of server 1 proposes ONLY into server 1's
    slots, so server 0's concurrent in-flight write survives with its
    value, and writes through the falsely-suspected server itself advance
    past their noop-filled slots (review regressions)."""
    t, config, servers, clients = make(seed=12)

    # Server 0 has an IN-FLIGHT write: Phase2as delivered, 2bs pending.
    class _S0:
        def randrange(self, n):
            return 0

    clients[0].rng = _S0()
    p0 = clients[0].propose(0, b"precious")
    while t.messages:
        m = t.messages[0]
        if m.dst == config.server_addresses[0] and m.src != clients[0].address:
            break  # hold the 2bs back
        t.deliver_message(m)

    # Concurrent false revocation of server 1 (everyone actually alive).
    servers[2].start_revocation(1)
    drain(t)
    # The in-flight write survives with its VALUE (the revocation never
    # proposed into server 0's slots).
    assert p0.done
    logs = {tuple(s.state_machine.get()) for s in servers}
    assert len(logs) == 1
    assert b"precious" in next(iter(logs))

    # Writes through the falsely-suspected server still work: its own
    # slots were noop-filled up to beta, and the request must advance past
    # them rather than black-holing.
    class _S1:
        def randrange(self, n):
            return 1

    clients[1].rng = _S1()
    p1 = clients[1].propose(0, b"still-alive")
    drain(t)
    assert p1.done
