"""The analytical cost model's contracts (ops/costmodel.py, the
performance observatory): stated byte terms are EXACT against live
dispatch arguments and ``jax.eval_shape`` outputs (including the
bit-packed planes at packed widths), predictions are monotone in every
key axis, the committed microbench captures sit inside the envelope
the drift gate enforces, and the model-ranked block fallback never
predicts worse than the legacy nearest-recorded-G guess it replaced.
"""

import functools
import json
import math
import pathlib

import jax
import pytest

from frankenpaxos_tpu.harness import microbench
from frankenpaxos_tpu.ops import costmodel, registry
from frankenpaxos_tpu.tpu import packing

RESULTS = pathlib.Path(__file__).resolve().parents[1] / "results"

# Small-but-irregular shapes: byte exactness must hold away from the
# flagship key, not just at it (the specs are closed-form in the key).
SMALL = dict(A=3, G=37, W=8, N=29, L=3, KV=4, CW=5)

CASES = microbench._kernel_cases(**SMALL)


@pytest.mark.parametrize("name", sorted(CASES))
def test_plane_bytes_exact(name):
    """Model input bytes == live argument bytes; model output bytes ==
    eval_shape of the reference twin. Zero-cost to keep exact, and it
    pins the spec tables to the dispatch signatures forever."""
    args, statics = CASES[name]
    plane = registry.PLANES[name]
    key = plane.key_of(args)
    assert costmodel.input_bytes(name, key) == sum(
        a.nbytes for a in jax.tree_util.tree_leaves(args)
    )
    outs = jax.eval_shape(
        functools.partial(plane.reference, **statics), *args
    )
    assert costmodel.output_bytes(name, key) == sum(
        math.prod(o.shape) * o.dtype.itemsize
        for o in jax.tree_util.tree_leaves(outs)
    )


def test_unfused_tick_is_the_three_planes():
    """The unfused reference entry prices exactly the three multipaxos
    planes run back to back — same flops total, byte terms the literal
    concatenation (every intermediate round-trips through memory)."""
    key = costmodel.CAPTURE_KEYS["multipaxos_fused_tick"]
    parts = (
        "multipaxos_vote_quorum",
        "multipaxos_p1_promise",
        "multipaxos_dispatch",
    )
    assert costmodel.input_bytes("multipaxos_unfused_tick", key) == sum(
        costmodel.input_bytes(p, key) for p in parts
    )
    assert costmodel.output_bytes("multipaxos_unfused_tick", key) == sum(
        costmodel.output_bytes(p, key) for p in parts
    )
    assert costmodel.flops("multipaxos_unfused_tick", key) == sum(
        costmodel.flops(p, key) for p in parts
    )
    # ...and the fused plane moves strictly fewer bytes at equal or
    # fewer flops: the fusion win the microbench measures is priced in.
    assert costmodel.bytes_moved(
        "multipaxos_fused_tick", key
    ) < costmodel.bytes_moved("multipaxos_unfused_tick", key)


def test_packed_plane_bytes_exact():
    """Packed-plane terms match tpu/packing.py at packed widths: the
    word-count formula is ``words_for`` exactly, and a live
    ``pack_plane`` array stores the predicted bytes."""
    for name, bits in [("status", 2), ("rb_status", 2), ("sess_occ", 1)]:
        pm = costmodel.PACKED_MODELS[name]
        assert pm.bits == bits
        for n in (0, 1, 15, 16, 17, 31, 32, 33, 64, 1000):
            assert pm.packed_bytes(n) == packing.words_for(n, bits) * 4
            assert pm.unpacked_bytes(n) == n
            assert pm.crossing_flops(n) == pm.flops_per_value * n
    # Live array: a [G, W] 2-bit plane packs the last axis.
    import jax.numpy as jnp

    G, W = 7, 37
    x = jax.random.randint(jax.random.PRNGKey(0), (G, W), 0, 3).astype(
        jnp.int8
    )
    packed = packing.pack_plane(x, 2)
    assert packed.nbytes == G * costmodel.PACKED_MODELS[
        "status"
    ].packed_bytes(W)


@pytest.mark.parametrize("name", sorted(costmodel.MODELS))
def test_prediction_monotone_in_every_key_axis(name):
    """Doubling any key extent never shrinks bytes, flops, or
    predicted seconds — the model can rank shapes, not just score
    one."""
    base = costmodel.CAPTURE_KEYS.get(
        name, costmodel.CAPTURE_KEYS["multipaxos_fused_tick"]
    )
    for axis in range(len(base)):
        grown = tuple(
            v * 2 if i == axis else v for i, v in enumerate(base)
        )
        assert costmodel.bytes_moved(name, grown) >= costmodel.bytes_moved(
            name, base
        ), (name, axis)
        assert costmodel.flops(name, grown) >= costmodel.flops(
            name, base
        ), (name, axis)
        for params in costmodel.PARAM_SETS.values():
            assert costmodel.predict_seconds(
                name, grown, params
            ) >= costmodel.predict_seconds(name, base, params), (
                name, axis, params.name,
            )


@pytest.mark.parametrize(
    "capture", ["kernel_microbench_r10.json", "kernel_microbench_r11.json"]
)
def test_recorded_captures_inside_envelope(capture):
    """Every plane rate in the committed microbench rounds lands
    inside the measured/predicted envelope under the CPU-jit
    parameter set — the fit the drift gate freezes."""
    payload = json.loads((RESULTS / capture).read_text())
    rows = costmodel.validate_capture(payload)
    assert rows, "capture carried no modeled plane rates"
    bad = [r for r in rows if not r["ok"]]
    assert not bad, bad
    lo, hi = costmodel.ENVELOPE
    for r in rows:
        assert lo <= r["ratio"] <= hi, r


def test_model_block_beats_or_ties_nearest_g():
    """The model-ranked fallback for unseen shapes: on every recorded
    plane at an off-table key, the model's block choice predicts a
    time <= the legacy nearest-recorded-G guess under the same
    parameter set (it replaced that heuristic and must dominate it)."""
    params = costmodel.CPU_INTERPRET
    checked = 0
    for name, key in costmodel.CAPTURE_KEYS.items():
        if name not in registry.PLANES:
            continue
        m = costmodel.MODELS[name]
        off_table = tuple(
            v * 3 if i == m.batch_axis else v for i, v in enumerate(key)
        )
        legacy = registry.nearest_block(name, off_table)
        if legacy is None:
            continue
        model = costmodel.model_block(name, off_table, params)
        assert model in costmodel.CANDIDATE_BLOCKS
        assert costmodel.predict_seconds(
            name, off_table, params, model
        ) <= costmodel.predict_seconds(name, off_table, params, legacy), (
            name, off_table, model, legacy,
        )
        checked += 1
    assert checked >= 8  # every recorded plane participated


def test_registry_block_for_prefers_table_then_model():
    """Dispatch-time resolution order: an exact table hit wins; an
    unseen key gets the model's ranked choice (never a crash, never
    the bare default when a model exists)."""
    key = (3, 3334, 64)
    table = registry._table()
    assert registry.block_for("multipaxos_fused_tick", key) == table[
        registry.table_key("multipaxos_fused_tick", key)
    ]
    unseen = (3, 500, 64)
    assert registry.table_key("multipaxos_fused_tick", unseen) not in table
    assert registry.block_for(
        "multipaxos_fused_tick", unseen
    ) == costmodel.model_block(
        "multipaxos_fused_tick", unseen, costmodel.params_for_backend()
    )


def test_candidate_blocks_match_autotune_sweep():
    """The model ranks exactly the blocks the autotuner sweeps — a
    drifted candidate list would rank blocks the table can never
    record (or miss ones it does)."""
    assert costmodel.CANDIDATE_BLOCKS == microbench.AUTOTUNE_BLOCKS


def test_rank_blocks_vmem_filter_and_tie_break():
    """TPU ranking excludes VMEM-infeasible blocks; ties resolve to
    the smaller block (less VMEM pressure at equal predicted time)."""
    name, key = "multipaxos_fused_tick", (3, 100000, 64)
    ranked = costmodel.rank_blocks(name, key, costmodel.TPU_V5E)
    assert ranked  # never empty — the smallest block survives
    for blk, _ in ranked:
        assert (
            costmodel.block_bytes(name, key, blk)
            <= costmodel.TPU_V5E.vmem_bytes
            or blk == min(costmodel.CANDIDATE_BLOCKS)
        )
    times = [t for _, t in ranked]
    assert times == sorted(times)


def test_capacity_and_saturation_shapes():
    """The whole-protocol predictions stay self-consistent: capacity
    scales linearly in role counts, saturation in groups (until the
    window caps), and unknown roles raise."""
    one = costmodel.capacity({"leader": 1, "acceptor": 3, "replica": 2})
    two = costmodel.capacity({"leader": 2, "acceptor": 6, "replica": 4})
    assert two["commands_per_sec"] == pytest.approx(
        2 * one["commands_per_sec"]
    )
    assert one["bottleneck_role"] == two["bottleneck_role"]
    with pytest.raises(KeyError):
        costmodel.capacity({"mystery_role": 1})
    s1 = costmodel.predict_saturation(100, 64, 8)
    s2 = costmodel.predict_saturation(200, 64, 8)
    assert s2["committed_per_tick"] == pytest.approx(
        2 * s1["committed_per_tick"]
    )
