"""Crash-tolerant serving tests: the checkpoint/restore subsystem
(tpu/checkpoint.py), bit-exact serve resume (harness/serve.py), the
kill-and-recover harness (harness/recovery.py), the in-graph
kill-restart schedule axis (simtest.run_crash_restart_schedule), and
the PR's satellite features — CRAQ chain-node crash semantics,
membership-aware thrifty quorum sampling, and the session-table expiry
knob."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from frankenpaxos_tpu.harness.serve import ServeConfig, ServeLoop
from frankenpaxos_tpu.monitoring.slo import SloPolicy
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import checkpoint as ck
from frankenpaxos_tpu.tpu import craq_batched as cr
from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod
from frankenpaxos_tpu.tpu import multipaxos_batched as mp
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan


def _cfg(**kw):
    return mp.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, retry_timeout=8,
        **kw
    )


def _serve(max_chunks, ckpt_dir=None, every=0, **kw):
    return ServeConfig(
        chunk_ticks=10, telemetry_window=32, max_chunks=max_chunks,
        checkpoint_dir=ckpt_dir, checkpoint_every=every, **kw
    )


# ---------------------------------------------------------------------------
# On-disk format: roundtrip + torn/corrupt/stale defense
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip_bit_exact(tmp_path):
    """save -> load -> restore reproduces the State sha256-identically
    (every leaf: dtype, shape, bytes) and the manifest carries the
    config fingerprint, tick, and per-leaf checksums."""
    cfg = _cfg()
    state = mp.init_state(cfg)
    state, t = mp.run_ticks(
        cfg, state, jnp.zeros((), jnp.int32), 20, jax.random.PRNGKey(0)
    )
    d = str(tmp_path / "ck")
    ck.save_state(d, mp, cfg, state, t, step=0)
    restored, t_r, man = ck.restore_state(d, mp, cfg, mp.init_state(cfg))
    assert ck.state_digest(restored) == ck.state_digest(state)
    assert int(t_r) == int(t) == man["tick"]
    assert man["config_hash"] == ck.config_fingerprint(mp, cfg)
    assert man["format"] == ck.CHECKPOINT_FORMAT
    # every leaf is manifest-checksummed
    assert set(man["leaves"]) == set(ck.flatten_state(state)) | {"__t__"}


def test_restore_hits_existing_jit_cache(tmp_path):
    """A same-process restore replays the EXISTING compiled run_ticks
    — no recompile (the trace-checkpoint-restore contract, asserted
    directly here too)."""
    cfg = _cfg()
    state = mp.init_state(cfg)
    state, t = mp.run_ticks(
        cfg, state, jnp.zeros((), jnp.int32), 10, jax.random.PRNGKey(0)
    )
    d = str(tmp_path / "ck")
    ck.save_state(d, mp, cfg, state, t, step=0)
    before = mp.run_ticks._cache_size()
    restored, t_r, _ = ck.restore_state(d, mp, cfg, mp.init_state(cfg))
    restored, t_r = mp.run_ticks(
        cfg, restored, t_r, 10, jax.random.PRNGKey(1)
    )
    jax.block_until_ready(t_r)
    assert mp.run_ticks._cache_size() == before


def _corrupt(path, at=0.5):
    blob = bytearray(open(path, "rb").read())
    blob[int(len(blob) * at)] ^= 0xFF
    open(path, "wb").write(bytes(blob))


def test_torn_and_corrupt_checkpoints_rejected(tmp_path):
    """Corruption injection: a truncated npz, a bit-flipped npz, a
    bit-flipped manifest, and a manifest from a different config are
    each REJECTED by the loader — and latest_valid falls back to the
    newest checkpoint that still verifies."""
    cfg = _cfg()
    state = mp.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    d = str(tmp_path / "ck")
    digests = {}
    for step in range(3):
        state, t = mp.run_ticks(cfg, state, t, 10, jax.random.PRNGKey(step))
        ck.save_state(d, mp, cfg, state, t, step=step)
        digests[step] = ck.state_digest(state)
    fp = ck.config_fingerprint(mp, cfg)

    # Newest npz bit-flipped: load raises, latest_valid falls back.
    _corrupt(os.path.join(d, "ckpt_00000002.npz"))
    with pytest.raises(ck.CheckpointError):
        ck.load_checkpoint(d, 2)
    man, arrays = ck.latest_valid(d, config_hash=fp)
    assert man["step"] == 1 and man["skipped"]
    arrays.pop("__t__")
    assert (
        ck.state_digest(ck.restore_leaves(mp.init_state(cfg), arrays))
        == digests[1]
    )

    # Step-1 npz truncated (a torn write): fall back to step 0.
    npz1 = os.path.join(d, "ckpt_00000001.npz")
    blob = open(npz1, "rb").read()
    open(npz1, "wb").write(blob[: len(blob) // 3])
    man, _ = ck.latest_valid(d, config_hash=fp)
    assert man["step"] == 0 and len(man["skipped"]) == 2

    # Step-0 manifest corrupted: nothing valid remains.
    _corrupt(os.path.join(d, "ckpt_00000000.json"), at=0.1)
    assert ck.latest_valid(d, config_hash=fp) is None
    with pytest.raises(ck.CheckpointError):
        ck.restore_state(d, mp, cfg, mp.init_state(cfg))


def test_stale_manifest_rejected(tmp_path):
    """A checkpoint written under a DIFFERENT config (stale manifest)
    never restores: the fingerprint mismatch skips it."""
    cfg = _cfg()
    other = dataclasses.replace(cfg, retry_timeout=4)
    state = mp.init_state(cfg)
    state, t = mp.run_ticks(
        cfg, state, jnp.zeros((), jnp.int32), 10, jax.random.PRNGKey(0)
    )
    d = str(tmp_path / "ck")
    ck.save_state(d, mp, cfg, state, t, step=0)
    assert ck.latest_valid(
        d, config_hash=ck.config_fingerprint(mp, other)
    ) is None
    # ...and a wrong-format version is rejected too.
    man_path = os.path.join(d, "ckpt_00000000.json")
    man = json.load(open(man_path))
    man["format"] = ck.CHECKPOINT_FORMAT + 1
    json.dump(man, open(man_path, "w"))
    with pytest.raises(ck.CheckpointError):
        ck.load_checkpoint(d, 0)


def test_checkpoint_prune_keeps_newest(tmp_path):
    cfg = _cfg()
    state = mp.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    d = str(tmp_path / "ck")
    for step in range(5):
        ck.save_state(d, mp, cfg, state, t, step=step, keep=2)
    assert ck.list_steps(d) == [3, 4]


# ---------------------------------------------------------------------------
# Bit-exact resume twins (the acceptance pin): 3 seeds, flagship +
# compartmentalized, kernels + FaultPlans engaged.
# ---------------------------------------------------------------------------


def _twin_pair(mod, cfg, seed, tmp_path, total=8, cut=5, slo=None):
    """Run the uninterrupted twin, then an interrupted run (stops at
    ``cut`` chunks) resumed to the same total; returns both digests."""
    twin = ServeLoop(mod, cfg, _serve(total, slo=slo), seed=seed)
    twin.run()
    twin_digest = ck.state_digest(twin.state)
    d = str(tmp_path / f"ck{seed}")
    a = ServeLoop(
        mod, cfg, _serve(cut, ckpt_dir=d, every=2, slo=slo), seed=seed
    )
    a.run()
    assert a.checkpoints_written >= 1
    b = ServeLoop.resume(
        mod, cfg, _serve(total, ckpt_dir=d, every=2, slo=slo)
    )
    assert b._chunks < total  # really resumed mid-run
    rep = b.run()
    assert rep["dropped_ticks"] == 0
    return twin_digest, ck.state_digest(b.state), twin, b


def test_resume_bit_exact_flagship_kernels_faults(tmp_path):
    """Flagship: interrupted+resumed == uninterrupted, sha256, 3 seeds,
    with the Pallas kernel planes (interpret mode on CPU) AND an
    active FaultPlan engaged — the full hot path, not a toy."""
    cfg = _cfg(
        kernels=KernelPolicy(mode="interpret"),
        faults=FaultPlan(drop_rate=0.1, dup_rate=0.05, jitter=1),
        workload=WorkloadPlan(arrival="poisson", rate=1.5),
        lifecycle=LifecyclePlan(sessions=4, resubmit_rate=0.1),
    )
    for seed in range(3):
        twin_digest, resumed_digest, twin, b = _twin_pair(
            mp, cfg, seed, tmp_path
        )
        assert resumed_digest == twin_digest, f"seed {seed} diverged"
        # Exactly-once client effects survive the crash: the resumed
        # run's session books equal the twin's.
        inv = mp.check_invariants(cfg, b.state, b.t)
        assert bool(inv["lifecycle_ok"]) and bool(inv["workload_ok"])


def test_resume_bit_exact_compartmentalized(tmp_path):
    """Compartmentalized: the same 3-seed resume==uninterrupted pin on
    the 14th backend (grid kernels in interpret mode + faults)."""
    from frankenpaxos_tpu.tpu import compartmentalized_batched as cz

    cfg = cz.analysis_config(
        faults=FaultPlan(drop_rate=0.1, jitter=1),
        workload=WorkloadPlan(arrival="constant", rate=1.0),
    )
    cfg = dataclasses.replace(cfg, kernels=KernelPolicy(mode="interpret"))
    for seed in range(3):
        twin_digest, resumed_digest, _, _ = _twin_pair(
            cz, cfg, seed, tmp_path, total=6, cut=3
        )
        assert resumed_digest == twin_digest, f"seed {seed} diverged"


def test_resume_restores_slo_and_clamp_context(tmp_path):
    """The SLO engine's full decision state (windows, latch, scale)
    rides the checkpoint: a resumed run's admission-clamp trajectory
    replays the twin's, so even a clamped serve resumes bit-exactly."""
    cfg = _cfg(
        workload=WorkloadPlan(arrival="constant", rate=2.5,
                              backlog_cap=64),
        faults=FaultPlan(drop_rate=0.25, jitter=2),
    )
    slo = SloPolicy(
        p99_target_ticks=4, source="queue_wait", window_chunks=2,
        clear_after=2,
    )
    twin_digest, resumed_digest, twin, b = _twin_pair(
        mp, cfg, 0, tmp_path, total=10, cut=5, slo=slo
    )
    assert resumed_digest == twin_digest
    assert b.slo.scale == pytest.approx(twin.slo.scale)
    assert b.slo.alarm == twin.slo.alarm


def test_resume_report_carries_restart_marker(tmp_path):
    """The resumed loop records a restore marker: the report names the
    checkpoint it resumed from and the Perfetto trace carries a global
    instant event on the host track."""
    from frankenpaxos_tpu.monitoring import traceviz

    cfg = _cfg()
    d = str(tmp_path / "ck")
    a = ServeLoop(mp, cfg, _serve(4, ckpt_dir=d, every=2), seed=0)
    a.run()
    trace_path = str(tmp_path / "trace.json")
    b = ServeLoop.resume(
        mp, cfg,
        dataclasses.replace(
            _serve(6, ckpt_dir=d, every=2), trace_path=trace_path
        ),
    )
    rep = b.run()
    assert rep["resumed_from"]["step"] == a._ckpt_step - 1
    assert rep["checkpoints_written"] >= 1
    tr = traceviz.load_chrome_trace(trace_path)
    markers = [
        e for e in tr["traceEvents"]
        if e["ph"] == "i" and e["name"] == "restore"
    ]
    assert len(markers) == 1
    assert markers[0]["pid"] == traceviz.HOST_PID


def test_serve_checkpoint_leg_is_async(tmp_path):
    """The checkpoint path adds no sync to the hot loop: dispatches
    never block on the snapshot (spy on block_until_ready + device_get
    — the only device_get targets are drains and the post-dispatch
    checkpoint write, never the live state)."""
    cfg = _cfg()
    d = str(tmp_path / "ck")
    loop = ServeLoop(mp, cfg, _serve(6, ckpt_dir=d, every=2), seed=0)
    live_state_pulls = []
    real_get = jax.device_get

    def spy_get(x):
        if x is loop.state:
            live_state_pulls.append(True)
        return real_get(x)

    jax.device_get, orig = spy_get, jax.device_get
    try:
        loop.run()
    finally:
        jax.device_get = orig
    assert not live_state_pulls  # only copies are ever pulled
    assert loop.checkpoints_written >= 2
    # the write span exists and is attributed on the host timeline
    names = {s["name"] for s in loop.host_spans}
    assert "checkpoint:snapshot" in names and "checkpoint:write" in names


# ---------------------------------------------------------------------------
# simtest: the randomized kill-restart schedule axis
# ---------------------------------------------------------------------------


def _crashing_seed(spec_name, plan, **kw):
    """Find a (seed, crash_seed) pair whose schedule actually draws a
    crash — deterministic, so the test never silently passes crash-free."""
    from frankenpaxos_tpu.harness import simtest

    spec = simtest.SPECS[spec_name]
    for crash_seed in range(8):
        res = simtest.run_crash_restart_schedule(
            spec, plan, seed=3, crash_seed=crash_seed, **kw
        )
        if res["crashes"]:
            return res
    raise AssertionError("no crash drawn in 8 crash seeds")


def test_crash_restart_schedule_flagship_exactly_once():
    """Randomized kill-restart schedules on the flagship with the
    session table engaged: invariants (incl. exactly-once lifecycle
    books) hold across every restart and the final state is bit-exact
    vs the never-crashed twin."""
    res = _crashing_seed(
        "multipaxos",
        FaultPlan(drop_rate=0.1),
        ticks=120,
        workload=WorkloadPlan(arrival="constant", rate=1.0),
        lifecycle=LifecyclePlan(sessions=4, resubmit_rate=0.1),
    )
    assert res["ok"], res
    assert res["bit_exact"]
    assert res["progress"][-1] > 0


def test_crash_restart_schedule_craq():
    """The same axis on a chain backend — host kill-restarts compose
    with the in-graph chain-node crash axis."""
    res = _crashing_seed(
        "craq",
        FaultPlan(crash_rate=0.03, revive_rate=0.2),
        ticks=120,
    )
    assert res["ok"], res
    assert res["bit_exact"]


# ---------------------------------------------------------------------------
# Kill-and-recover harness (real subprocess + SIGKILL + watchdog)
# ---------------------------------------------------------------------------


def test_kill_and_recover_subprocess(tmp_path):
    """The full harness: a real serve subprocess SIGKILLed at a
    randomized chunk boundary restarts from the latest checkpoint and
    finishes — liveness, invariants, exactly-once session books, and a
    final digest bit-identical to the uninterrupted in-process twin."""
    from frankenpaxos_tpu.harness import recovery

    out = str(tmp_path / "killed")
    res = recovery.run_kill_recover(
        out, chunks=8, every=2, chunk_ticks=8, seed=0,
        kill_seed=1, max_kills=1, chunk_delay=0.15, poll=0.05,
        backoff_base=0.05,
    )
    assert res.ok, res.to_dict()
    assert res.kills, "no SIGKILL landed"
    assert res.restarts >= 1
    assert res.final["invariants_ok"]
    lc = res.final["lifecycle"]
    assert lc["cache_hits"] <= lc["resubmits"]
    twin = recovery.uninterrupted_digest(
        chunks=8, every=2, chunk_ticks=8, seed=0,
        backend="multipaxos", out_dir=str(tmp_path / "twin"),
    )
    assert res.final["digest"] == twin["digest"]


def test_kill_and_recover_generic_backend(tmp_path):
    """Lifecycle breadth: kill-and-recover beyond the two serve-grade
    worker configs. A GENERIC_BACKENDS worker (epaxos here — leaderless,
    GC-replica churn, no session table) runs the same contract at its
    canonical analysis shape: SIGKILL at a checkpointed boundary,
    resume, liveness + invariants + a digest bit-identical to the
    uninterrupted twin."""
    from frankenpaxos_tpu.harness import recovery

    assert "epaxos" in recovery.GENERIC_BACKENDS
    res = recovery.run_kill_recover(
        str(tmp_path / "killed"), chunks=8, every=2, chunk_ticks=8,
        seed=0, backend="epaxos", kill_seed=1, max_kills=1,
        chunk_delay=0.15, poll=0.05, backoff_base=0.05,
    )
    assert res.ok, res.to_dict()
    assert res.kills and res.restarts >= 1
    assert res.final["invariants_ok"]
    assert res.final["lifecycle"] is None  # no session table here
    twin = recovery.uninterrupted_digest(
        chunks=8, every=2, chunk_ticks=8, seed=0,
        backend="epaxos", out_dir=str(tmp_path / "twin"),
    )
    assert res.final["digest"] == twin["digest"]


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["mencius", "scalog", "craq"])
def test_kill_and_recover_generic_breadth(tmp_path, backend):
    """The rest of the GENERIC_BACKENDS sweep (slow tier): every
    registered generic worker shape recovers bit-exactly."""
    from frankenpaxos_tpu.harness import recovery

    res = recovery.run_kill_recover(
        str(tmp_path / "killed"), chunks=8, every=2, chunk_ticks=8,
        seed=0, backend=backend, kill_seed=1, max_kills=1,
        chunk_delay=0.15, poll=0.05, backoff_base=0.05,
    )
    assert res.ok, res.to_dict()
    assert res.kills, "no SIGKILL landed"
    twin = recovery.uninterrupted_digest(
        chunks=8, every=2, chunk_ticks=8, seed=0,
        backend=backend, out_dir=str(tmp_path / "twin"),
    )
    assert res.final["digest"] == twin["digest"]


@pytest.mark.slow
def test_watchdog_restarts_hung_worker(tmp_path):
    """The watchdog half: a worker whose dispatch hangs (heartbeats
    stop) is SIGKILLed after the hang timeout and restarted with
    backoff; the restarted run completes from the last checkpoint."""
    from frankenpaxos_tpu.harness import recovery

    out = str(tmp_path / "hung")
    res = recovery.run_kill_recover(
        out, chunks=8, every=2, chunk_ticks=8, seed=0,
        max_kills=0, hang_after=4, hang_timeout=12.0,
        chunk_delay=0.1, poll=0.1, backoff_base=0.05,
    )
    assert res.ok, res.to_dict()
    assert res.watchdog_kills == 1
    assert res.restarts >= 1
    assert res.backoffs and res.backoffs[0] <= 5.0


def test_backoff_is_capped():
    """Restart delays grow exponentially but cap (a crash-looping
    worker can't spin the host into ever-longer stalls either way)."""
    base, cap = 0.2, 5.0
    delays = [min(cap, base * (2 ** r)) for r in range(12)]
    assert delays[0] == base
    assert max(delays) == cap
    assert delays[-1] == cap


# ---------------------------------------------------------------------------
# Satellites: CRAQ crash axis, membership-aware thrifty, session TTL
# ---------------------------------------------------------------------------


def test_craq_crash_restitch_liveness_and_conservation():
    """Chain-node crashes: the chain re-stitches around dead middle
    nodes (writes + reads keep completing), pending-set conservation
    holds EXACTLY via the visited bitmask, revived nodes resync from
    the tail, and reads stay linearizable throughout."""
    cfg = cr.analysis_config(
        faults=FaultPlan(crash_rate=0.08, revive_rate=0.3)
    )
    state = cr.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    prev_writes = 0
    for i in range(5):
        state, t = cr.run_ticks(
            cfg, state, t, 30, jax.random.fold_in(jax.random.PRNGKey(5), i)
        )
        inv = {k: bool(v) for k, v in cr.check_invariants(cfg, state, t).items()}
        assert all(inv.values()), inv
        writes = int(state.writes_done)
        assert writes > prev_writes  # liveness through the churn
        prev_writes = writes
    assert int(state.crashes) > 0
    assert int(state.resyncs) > 0
    assert int(state.reads_done) > 0
    assert int(state.read_lin_violations) == 0


def test_craq_crash_axis_off_is_structural_noop():
    """FaultPlan without crash knobs leaves every crash-axis leaf
    zero-sized and replays the pre-crash program bit for bit."""
    cfg = cr.analysis_config()
    st = cr.init_state(cfg)
    assert st.node_alive.size == 0
    assert st.node_suspect.size == 0
    assert st.w_visited.size == 0
    assert st.crashes.size == 0


def test_craq_simtest_crash_axis_enabled():
    """The simtest registry now draws crash/revive for craq (the
    carried PR 3 (b) gap): a crash-bearing random plan runs green with
    liveness after churn."""
    from frankenpaxos_tpu.harness import simtest

    spec = simtest.SPECS["craq"]
    assert spec.crash_ok
    import random as _random

    rng = _random.Random(11)
    saw_crash = False
    for _ in range(20):
        plan = simtest.random_plan(rng, spec, 120)
        saw_crash = saw_crash or plan.has_crash
    assert saw_crash  # the axis is actually drawn
    res = simtest.run_schedule(
        spec, FaultPlan(crash_rate=0.04, revive_rate=0.2, drop_rate=0.1),
        seed=2, ticks=120,
    )
    assert res["ok"], res
    assert res["progress"][-1] > res["progress"][0]


def test_membership_aware_thrifty_no_commit_dip():
    """Membership-aware thrifty sampling: after swapping an acceptor
    out, phase-2 quorums sample only live members — commits/tick never
    dips below the pre-swap floor (a swapped-out acceptor used to cost
    a full retry round for ~1/3 of proposals at f=1)."""
    cfg = mp.analysis_config(lifecycle=LifecyclePlan(reconfig=True))
    key = jax.random.PRNGKey(0)
    state = mp.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    deltas = []
    prev = 0
    for i in range(6):
        if i == 3:
            state = dataclasses.replace(
                state,
                lifecycle=lifecycle_mod.swap_acceptor(state.lifecycle, 0),
            )
        state, t = mp.run_ticks(cfg, state, t, 30, jax.random.fold_in(key, i))
        c = int(jax.device_get(state.committed))
        deltas.append(c - prev)
        prev = c
    inv = {k: bool(v) for k, v in mp.check_invariants(cfg, state, t).items()}
    assert all(inv.values()), inv
    pre_floor = min(deltas[:3])
    # No dip: every post-swap segment commits at least ~90% of the
    # pre-swap floor (the old behavior dropped well below it while
    # sampled-but-departed quorums waited out retry_timeout).
    for post in deltas[3:]:
        assert post >= 0.9 * pre_floor, deltas


def test_membership_masked_quorum_is_exact():
    """sample_quorum(live=...) selects exactly f+1 members, all live
    whenever >= f+1 are live, and degrades to a stalled (masked)
    quorum only when the live set is too small."""
    from frankenpaxos_tpu.tpu.common import sample_quorum

    A, f = 3, 1
    bits = jax.random.bits(jax.random.PRNGKey(0), (A, 64))
    live = jnp.ones((A, 64), bool).at[0].set(False)
    q = sample_quorum(bits, 8, f, A, live=live)
    assert q.sum(axis=0).tolist() == [f + 1] * 64
    assert not bool(jnp.any(q[0]))  # the dead member is never sampled
    # fewer than f+1 alive: selection tops up from the dead (the send
    # mask stalls it) but stays exactly f+1.
    live2 = jnp.zeros((A, 64), bool).at[2].set(True)
    q2 = sample_quorum(bits, 8, f, A, live=live2)
    assert q2.sum(axis=0).tolist() == [f + 1] * 64
    assert bool(jnp.all(q2[2]))  # the one live member is always in


def test_session_ttl_expires_idle_records():
    """LifecyclePlan.session_ttl demotes idle records on a traced tick
    threshold: expiries happen, conservation still reconciles against
    the workload engine's completion totals, and a resubmission that
    finds its record expired is an honest cache MISS."""
    cfg = mp.analysis_config(
        workload=WorkloadPlan(arrival="constant", rate=1.0),
        lifecycle=LifecyclePlan(
            sessions=8, resubmit_rate=0.2, session_ttl=3
        ),
    )
    state = mp.init_state(cfg)
    state, t = mp.run_ticks(
        cfg, state, jnp.zeros((), jnp.int32), 120, jax.random.PRNGKey(2)
    )
    lcs = state.lifecycle
    assert int(lcs.expired) > 0
    assert int(lcs.cache_hits) < int(lcs.resubmits)  # ttl misses exist
    inv = {k: bool(v) for k, v in mp.check_invariants(cfg, state, t).items()}
    assert inv["lifecycle_ok"] and inv["workload_ok"], inv
    s = lifecycle_mod.summary(cfg.lifecycle, lcs)
    assert s["expired"] == int(lcs.expired)
    # Expired entries are fully demoted (id and cached result together).
    np.testing.assert_array_equal(
        np.asarray(lcs.sess_last >= 0), np.asarray(lcs.sess_res >= 0)
    )


def test_session_ttl_validation():
    with pytest.raises(AssertionError):
        LifecyclePlan(session_ttl=8).validate()
    LifecyclePlan(sessions=4, session_ttl=8).validate()


# ---------------------------------------------------------------------------
# CI wiring
# ---------------------------------------------------------------------------


def test_ci_wiring_exists():
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    smoke = (repo / "scripts" / "serve_smoke.sh").read_text()
    assert "harness.recovery" in smoke and "--smoke" in smoke
    assert "checkpoint-alias-free" in smoke
    assert "trace-checkpoint-restore" in smoke
    bench_src = (repo / "bench.py").read_text()
    assert '"--checkpoint"' in bench_src and "--inner-checkpoint" in bench_src
