import pytest

from frankenpaxos_tpu.core import wire
from frankenpaxos_tpu.statemachine import (
    AppendLog,
    KeyValueStore,
    KVGetReply,
    KVGetRequest,
    KVSetReply,
    Noop,
    ReadableAppendLog,
    Register,
    from_name,
    kv_get,
    kv_set,
)
from frankenpaxos_tpu.util import TupleVertexIdLike


def test_noop():
    sm = Noop()
    assert sm.run(b"anything") == b""
    assert not sm.conflicts(b"a", b"b")
    sm.from_bytes(sm.to_bytes())


def test_register():
    sm = Register()
    assert sm.run(b"x") == b"x"
    assert sm.conflicts(b"a", b"b")
    snap = sm.to_bytes()
    sm.run(b"y")
    sm.from_bytes(snap)
    assert sm.x == b"x"


def test_append_log():
    sm = AppendLog()
    assert wire.decode(sm.run(b"a")) == 0
    assert wire.decode(sm.run(b"b")) == 1
    snap = sm.to_bytes()
    sm2 = AppendLog()
    sm2.from_bytes(snap)
    assert sm2.log == [b"a", b"b"]


def test_readable_append_log():
    sm = ReadableAppendLog()
    assert sm.run(b"") == b""  # read of empty log
    assert wire.decode(sm.run(b"a")) == 0
    assert wire.decode(sm.run(b"b")) == 1
    # Empty input is a pure read: returns the latest entry, no mutation.
    assert sm.run(b"") == b"b"
    assert sm.run(b"") == b"b"
    assert sm.get() == [b"a", b"b"]


def test_kv_store_run():
    sm = KeyValueStore()
    assert wire.decode(sm.run(kv_set(("x", "1"), ("y", "2")))) == KVSetReply()
    reply = wire.decode(sm.run(kv_get("x", "z")))
    assert reply == KVGetReply((("x", "1"), ("z", None)))
    assert sm.get() == {"x": "1", "y": "2"}


def test_kv_store_conflicts():
    sm = KeyValueStore()
    get_x, get_y = kv_get("x"), kv_get("y")
    set_x, set_xy = kv_set(("x", "1")), kv_set(("x", "1"), ("y", "2"))
    assert not sm.conflicts(get_x, get_x)  # gets never conflict
    assert sm.conflicts(get_x, set_x)
    assert sm.conflicts(set_x, get_x)
    assert sm.conflicts(set_x, set_xy)
    assert not sm.conflicts(get_x, kv_set(("y", "2")))


def test_kv_store_snapshot():
    sm = KeyValueStore()
    sm.run(kv_set(("a", "1")))
    snap = sm.to_bytes()
    sm.run(kv_set(("a", "2")))
    sm.from_bytes(snap)
    assert sm.get() == {"a": "1"}


def test_kv_conflict_index():
    sm = KeyValueStore()
    ci = sm.conflict_index()
    ci.put(1, kv_get("x", "y"))
    ci.put(2, kv_set(("y", "1"), ("z", "1")))
    # A set of x conflicts with command 1 (gets x).
    assert ci.get_conflicts(kv_set(("x", "0"))) == {1}
    # A get of z conflicts with command 2 (sets z).
    assert ci.get_conflicts(kv_get("z")) == {2}
    # A set of y conflicts with both.
    assert ci.get_conflicts(kv_set(("y", "9"))) == {1, 2}
    # A get of y conflicts only with the setter.
    assert ci.get_conflicts(kv_get("y")) == {2}
    ci.remove(1)
    assert ci.get_conflicts(kv_set(("x", "0"))) == set()
    ci.put_snapshot(77)
    assert ci.get_conflicts(kv_get("q")) == {77}


def test_naive_conflict_index():
    sm = Register()
    ci = sm.conflict_index()
    ci.put("a", b"1")
    ci.put("b", b"2")
    assert ci.get_conflicts(b"x") == {"a", "b"}  # register: all conflict
    ci.remove("a")
    assert ci.get_conflicts(b"x") == {"b"}


def test_top_k_conflict_index():
    sm = KeyValueStore()
    like = TupleVertexIdLike()
    ci = sm.top_k_conflict_index(k=1, num_leaders=2, like=like)
    ci.put((0, 3), kv_set(("x", "1")))
    ci.put((0, 5), kv_set(("x", "2")))
    ci.put((1, 2), kv_get("x"))
    tops = ci.get_top_k_conflicts(kv_set(("x", "9")))
    assert tops[0] == {5}  # only the top-1 per leader
    assert tops[1] == {2}


def test_registry():
    assert isinstance(from_name("KeyValueStore"), KeyValueStore)
    assert isinstance(from_name("Noop"), Noop)
    with pytest.raises(ValueError):
        from_name("Nope")
