"""MultiPaxos simulation tests (the analog of
``shared/src/test/scala/multipaxos/MultiPaxosTest.scala``): sweep
(batched, flexible) x f, run randomized histories, check replica-log
prefix compatibility + monotone growth, and report a liveness signal."""

import pytest

from frankenpaxos_tpu.sim import simulate, simulate_and_minimize
from multipaxos_testbed import MultiPaxosCluster, SimulatedMultiPaxos


@pytest.mark.parametrize("batched", [False, True])
@pytest.mark.parametrize("flexible", [False, True])
@pytest.mark.parametrize("f", [1, 2])
def test_multipaxos_write_safety(batched, flexible, f):
    sim = SimulatedMultiPaxos(f=f, batched=batched, flexible=flexible)
    bad = simulate_and_minimize(sim, run_length=120, num_runs=12, seed=f)
    assert bad is None, f"\n{bad}"


def drain(system, max_steps=50000):
    t = system.transport
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps, "message storm"


def test_multipaxos_liveness_writes_complete():
    """Under a fair (deliver-everything) schedule, writes must finish — the
    valueChosen liveness smoke of MultiPaxosTest.scala:36-40. (Under fully
    adversarial random scheduling liveness is not guaranteed: elections can
    churn forever, which is why the reference only *reports* valueChosen.)"""
    sim = SimulatedMultiPaxos(f=1, batched=False, flexible=False)
    system = sim.new_system(seed=7)
    from multipaxos_testbed import Write

    for i in range(5):
        sim.run_command(system, Write(0, 0, f"w{i}".encode()))
        sim.run_command(system, Write(1, 1, f"x{i}".encode()))
        drain(system)
    assert system.writes_completed == 10
    # All replicas executed all ten commands, identically ordered.
    logs = {tuple(r.state_machine.log) for r in system.replicas}
    assert len(logs) == 1
    assert len(next(iter(logs))) == 10


@pytest.mark.parametrize(
    "workload",
    [("write", "linearizable"), ("write", "sequential"), ("write", "eventual")],
)
def test_multipaxos_reads_safety(workload):
    sim = SimulatedMultiPaxos(
        f=1, batched=False, flexible=False, workload=workload
    )
    bad = simulate_and_minimize(sim, run_length=120, num_runs=8, seed=3)
    assert bad is None, f"\n{bad}"


def test_multipaxos_read_batcher_path():
    sim = SimulatedMultiPaxos(
        f=1,
        batched=True,
        flexible=False,
        read_batched=True,
        workload=("write", "linearizable", "sequential", "eventual"),
    )
    bad = simulate_and_minimize(sim, run_length=150, num_runs=6, seed=11)
    assert bad is None, f"\n{bad}"


def test_multipaxos_liveness_reads_complete():
    sim = SimulatedMultiPaxos(
        f=1, batched=False, flexible=False, workload=("write", "linearizable")
    )
    system = sim.new_system(seed=21)
    from multipaxos_testbed import Read, Write

    sim.run_command(system, Write(0, 0, b"w"))
    drain(system)
    # A linearizable read may defer at slot maxVotedSlot + numGroups - 1,
    # waiting for that slot to execute (Replica.scala:455-529) — in real
    # deployments the leader's noop-flush timer unblocks it; here a
    # subsequent write does.
    for i, kind in enumerate(("linearizable", "sequential", "eventual")):
        sim.run_command(system, Read(0, pseudonym := i % 2, kind))
        drain(system)
        sim.run_command(system, Write(1, 0, f"w{i}".encode()))
        drain(system)
        sim.run_command(system, Write(1, 1, f"x{i}".encode()))
        drain(system)
    assert system.writes_completed == 7
    assert system.reads_completed == 3
    # Every read returned a genuinely-written value (the first, linearizable
    # read was issued after b"w" completed, so it must not be empty).
    assert system.read_results[0] in system.values_written
    for result in system.read_results:
        assert result in system.values_written | {b""}
    assert system.bogus_read is None


def test_multipaxos_leader_failover_and_log_repair():
    """Kill leader 0 mid-stream; leader 1 takes over via election, repairs
    the log with phase 1 (Leader.scala:504-577), and new writes complete."""
    from frankenpaxos_tpu.election.basic import State as ElectionState
    from multipaxos_testbed import Write

    sim = SimulatedMultiPaxos(f=1, batched=False, flexible=False)
    system = sim.new_system(seed=3)
    t = system.transport
    config = system.config

    sim.run_command(system, Write(0, 0, b"before"))
    drain(system)
    assert system.writes_completed == 1

    # Partition leader 0 and its election participant.
    t.partition_actor(config.leader_addresses[0])
    t.partition_actor(config.leader_election_addresses[0])
    # A client writes; request goes to the dead leader and is dropped.
    sim.run_command(system, Write(0, 0, b"after"))
    drain(system)
    assert system.writes_completed == 1

    # Election participant 1 times out and becomes leader; the callback
    # fires leader 1's leaderChange -> phase 1.
    t.trigger_timer(config.leader_election_addresses[1], "noPingTimer")
    drain(system)
    assert system.leaders[1].election.state == ElectionState.LEADER
    from frankenpaxos_tpu.protocols.multipaxos.leader import _Phase2

    assert isinstance(system.leaders[1].state, _Phase2)

    # The client's resend timer redirects the write: leader 0 is dead, so
    # the resend goes to it and is dropped; the client must learn the new
    # round. NotLeaderClient can't arrive (leader 0 is partitioned), so
    # deliver a LeaderInfo poll: fire resend until the new leader replies.
    client = system.clients[0]
    from frankenpaxos_tpu.protocols.multipaxos.messages import (
        LeaderInfoRequestClient,
    )

    for leader in config.leader_addresses:
        client.chan(leader).send(LeaderInfoRequestClient())
    drain(system)
    assert client.round == system.leaders[1].round
    # Now the resend timer sends to the new leader.
    pseudonym_state = client.states[0]
    t.trigger_timer(client.address, f"resendClientRequest[0;{pseudonym_state.id}]")
    drain(system)
    assert system.writes_completed == 2
    logs = {tuple(r.state_machine.log) for r in system.replicas}
    assert len(logs) == 1
    final = next(iter(logs))
    assert final.count(b"before") == 1 and final.count(b"after") == 1
