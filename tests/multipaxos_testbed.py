"""MultiPaxos simulation testbed (the analog of
``shared/src/test/scala/multipaxos/MultiPaxos.scala``): a full cluster on
one SimTransport plus a SimulatedSystem whose invariants check that replica
executed logs are pairwise prefix-compatible and grow monotonically."""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from frankenpaxos_tpu.core import (
    DeliverMessage,
    FakeLogger,
    SimAddress,
    SimTransport,
    TriggerTimer,
)
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import multipaxos as mp
from frankenpaxos_tpu.protocols.multipaxos.read_batcher import SizeScheme
from frankenpaxos_tpu.sim import SimulatedSystem, mixed_command
from frankenpaxos_tpu.statemachine import ReadableAppendLog


@dataclasses.dataclass(frozen=True)
class Write:
    client_index: int
    pseudonym: int
    value: bytes


@dataclasses.dataclass(frozen=True)
class Read:
    client_index: int
    pseudonym: int
    kind: str  # "linearizable" | "sequential" | "eventual"


class MultiPaxosCluster:
    def __init__(self, seed: int, f: int, batched: bool, flexible: bool,
                 read_batched: bool = False, num_clients: int = 2):
        logger = FakeLogger(LogLevel.FATAL)
        self.transport = SimTransport(logger)

        num_leaders = f + 1
        if not flexible:
            acceptors = tuple(
                tuple(SimAddress(f"acceptor_{g}_{i}") for i in range(2 * f + 1))
                for g in range(2)
            )
        else:
            # An (f+1) x (f+1) grid tolerates f failures.
            acceptors = tuple(
                tuple(SimAddress(f"acceptor_{g}_{i}") for i in range(f + 1))
                for g in range(f + 1)
            )
        self.config = mp.Config(
            f=f,
            batcher_addresses=(
                tuple(SimAddress(f"batcher_{i}") for i in range(f + 1))
                if batched
                else ()
            ),
            read_batcher_addresses=(
                tuple(SimAddress(f"read_batcher_{i}") for i in range(f + 1))
                if read_batched
                else ()
            ),
            leader_addresses=tuple(
                SimAddress(f"leader_{i}") for i in range(num_leaders)
            ),
            leader_election_addresses=tuple(
                SimAddress(f"election_{i}") for i in range(num_leaders)
            ),
            proxy_leader_addresses=tuple(
                SimAddress(f"proxy_leader_{i}") for i in range(f + 1)
            ),
            acceptor_addresses=acceptors,
            replica_addresses=tuple(
                SimAddress(f"replica_{i}") for i in range(f + 1)
            ),
            proxy_replica_addresses=tuple(
                SimAddress(f"proxy_replica_{i}") for i in range(f + 1)
            ),
            flexible=flexible,
            distribution_scheme=mp.DistributionScheme.HASH,
        )

        def mklogger():
            return FakeLogger(LogLevel.FATAL)

        seeds = iter(range(seed * 1000, seed * 1000 + 999))
        self.clients = [
            mp.Client(
                SimAddress(f"client_{i}"), self.transport, mklogger(),
                self.config, seed=next(seeds),
            )
            for i in range(num_clients)
        ]
        self.batchers = [
            mp.Batcher(
                a, self.transport, mklogger(), self.config,
                mp.BatcherOptions(batch_size=2), seed=next(seeds),
            )
            for a in self.config.batcher_addresses
        ]
        self.read_batchers = [
            mp.ReadBatcher(
                a, self.transport, mklogger(), self.config,
                mp.ReadBatcherOptions(
                    read_batching_scheme=SizeScheme(batch_size=2, timeout=1.0)
                ),
                seed=next(seeds),
            )
            for a in self.config.read_batcher_addresses
        ]
        self.leaders = [
            mp.Leader(a, self.transport, mklogger(), self.config, seed=next(seeds))
            for a in self.config.leader_addresses
        ]
        self.proxy_leaders = [
            mp.ProxyLeader(
                a, self.transport, mklogger(), self.config, seed=next(seeds)
            )
            for a in self.config.proxy_leader_addresses
        ]
        self.acceptors = [
            mp.Acceptor(a, self.transport, mklogger(), self.config)
            for group in self.config.acceptor_addresses
            for a in group
        ]
        self.replicas = [
            mp.Replica(
                a, self.transport, mklogger(), ReadableAppendLog(), self.config,
                mp.ReplicaOptions(send_chosen_watermark_every_n_entries=5),
                seed=next(seeds),
            )
            for a in self.config.replica_addresses
        ]
        self.proxy_replicas = [
            mp.ProxyReplica(a, self.transport, mklogger(), self.config)
            for a in self.config.proxy_replica_addresses
        ]
        # Liveness signals (the valueChosen flag of MultiPaxosTest.scala:36-40).
        self.writes_completed = 0
        self.reads_completed = 0
        self.read_results = []
        self.values_written = set()
        # Set when a completed read returns a value that was never written —
        # checked by SimulatedMultiPaxos.state_invariant.
        self.bogus_read = None

    def on_write_done(self, promise) -> None:
        if promise.exception is None:
            self.writes_completed += 1

    def on_read_done(self, promise) -> None:
        if promise.exception is None:
            self.reads_completed += 1
            self.read_results.append(promise.value)
            # Reads use the empty command, which ReadableAppendLog answers
            # with its latest entry (or b"" for an empty log). Any other
            # result is fabricated state.
            if promise.value != b"" and promise.value not in self.values_written:
                self.bogus_read = promise.value


class SimulatedMultiPaxos(SimulatedSystem):
    """State = tuple of per-replica executed command tuples (AppendLog)."""

    def __init__(self, f: int, batched: bool, flexible: bool,
                 read_batched: bool = False, workload=("write",)):
        self.f = f
        self.batched = batched
        self.flexible = flexible
        self.read_batched = read_batched
        self.workload = workload
        self._last_system: Optional[MultiPaxosCluster] = None

    def new_system(self, seed: int) -> MultiPaxosCluster:
        self._last_system = MultiPaxosCluster(
            seed, self.f, self.batched, self.flexible, self.read_batched
        )
        return self._last_system

    def get_state(self, system: MultiPaxosCluster):
        return tuple(tuple(r.state_machine.log) for r in system.replicas)

    def generate_command(self, system: MultiPaxosCluster, rng: random.Random):
        ops = []
        for i, client in enumerate(system.clients):
            for pseudonym in (0, 1):
                if pseudonym in client.states:
                    continue
                if "write" in self.workload:
                    ops.append(
                        (1, Write(i, pseudonym, f"v{rng.randrange(100)}".encode()))
                    )
                for kind in ("linearizable", "sequential", "eventual"):
                    if kind in self.workload:
                        ops.append((1, Read(i, pseudonym, kind)))
        return mixed_command(rng, system.transport, ops)

    def run_command(self, system: MultiPaxosCluster, command):
        if isinstance(command, Write):
            system.values_written.add(command.value)
            promise = system.clients[command.client_index].write(
                command.pseudonym, command.value
            )
            promise.on_complete(system.on_write_done)
        elif isinstance(command, Read):
            client = system.clients[command.client_index]
            method = {
                "linearizable": client.read,
                "sequential": client.sequential_read,
                "eventual": client.eventual_read,
            }[command.kind]
            method(command.pseudonym, b"").on_complete(system.on_read_done)
        else:
            system.transport.run_command(command, record=False)
        return system

    # Invariants (multipaxos/MultiPaxos.scala:285-320).

    def state_invariant(self, state):
        if self._last_system is not None and self._last_system.bogus_read:
            return f"read returned a never-written value: {self._last_system.bogus_read!r}"
        for i in range(len(state)):
            for j in range(i + 1, len(state)):
                a, b = state[i], state[j]
                shorter, longer = (a, b) if len(a) <= len(b) else (b, a)
                if longer[: len(shorter)] != shorter:
                    return f"replica logs not prefix-compatible: {a!r} vs {b!r}"
        return None

    def step_invariant(self, old, new):
        for o, n in zip(old, new):
            if n[: len(o)] != o:
                return f"replica log shrank or changed: {o!r} -> {n!r}"
        return None
