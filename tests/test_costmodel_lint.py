"""Performance-observatory gates (thin wrapper + teeth): every
registered plane / packed plane / the unfused reference tick carries
stated cost-model terms (``costmodel-coverage``), every recorded
microbench capture sits inside the model's measured/predicted envelope
with a fresh committed verdict artifact (``costmodel-drift``), and —
the teeth — a deliberately corrupted timing or a round-over-round
ratio regression actually trips the drift engine the rule delegates to
(``costmodel.drift_findings`` is pure data-in/data-out exactly so the
rule and this test share one engine).
"""

import copy
import json
import pathlib

import pytest

from frankenpaxos_tpu import analysis
from frankenpaxos_tpu.ops import costmodel

pytestmark = pytest.mark.lint

RESULTS = pathlib.Path(__file__).resolve().parents[1] / "results"


def _load(name: str) -> dict:
    return json.loads((RESULTS / name).read_text())


@pytest.mark.parametrize(
    "rule_id",
    ["costmodel-coverage", "costmodel-drift"],
)
def test_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()


def test_corrupted_timing_trips_drift():
    """Teeth: multiply one plane's recorded rate by 100 in a copy of
    the committed r11 capture — the drift engine must flag it BOTH as
    outside the absolute envelope and as a regression vs r10, and must
    name the corrupted plane."""
    r10 = _load("kernel_microbench_r10.json")
    r11 = copy.deepcopy(_load("kernel_microbench_r11.json"))
    planes = r11["kernels"]["planes"]
    planes["mencius_vote"]["reference_per_sec"] *= 100.0
    findings = costmodel.drift_findings(
        [("r10.json", r10), ("r11-corrupt.json", r11)]
    )
    kinds = {(f["plane"], f["kind"]) for f in findings}
    assert ("mencius_vote", "envelope") in kinds, findings
    assert ("mencius_vote", "regression") in kinds, findings
    # ...and ONLY the corrupted plane: the committed timings around it
    # stay clean, so the gate points at the culprit, not the capture.
    assert {f["plane"] for f in findings} == {"mencius_vote"}


def test_slow_regression_trips_drift_inside_envelope():
    """Teeth: a ratio move bigger than REGRESSION_FACTOR is a finding
    even when both captures sit inside the absolute envelope — the
    gate catches relative rot, not just absolute corruption."""
    key = list(costmodel.CAPTURE_KEYS["multipaxos_fused_tick"])
    pred = costmodel.predict_per_sec(
        "multipaxos_fused_tick", tuple(key)
    )
    lo, hi = costmodel.ENVELOPE
    mk = lambda ratio: {
        "kernels": {
            "planes": {
                "multipaxos_fused_tick": {
                    "reference_per_sec": ratio * pred
                }
            }
        }
    }
    # both inside the envelope, but the move exceeds the factor
    r_a, r_b = lo * 1.1, lo * 1.1 * costmodel.REGRESSION_FACTOR * 1.2
    assert lo <= r_a <= hi and lo <= r_b <= hi
    findings = costmodel.drift_findings([("a", mk(r_a)), ("b", mk(r_b))])
    assert [f["kind"] for f in findings] == ["regression"], findings


def test_stale_envelope_artifact_is_drift():
    """Teeth for the artifact-freshness half: the committed
    results/costmodel_envelope.json must carry the in-tree constants
    version — the rule flags a refit whose artifact was not
    regenerated. (Checked directly against the committed file so the
    invariant the rule enforces is also pinned here.)"""
    payload = _load("costmodel_envelope.json")
    assert payload["constants_version"] == costmodel.CONSTANTS_VERSION
    assert payload["envelope"] == list(costmodel.ENVELOPE)
    assert payload["regression_factor"] == costmodel.REGRESSION_FACTOR
    assert payload["bytes_exact"] is True
    assert payload["uncovered_planes"] == []
    assert payload["drift_findings"] == []


def test_flag_capture_teeth():
    """The stale-capture plausibility check: the committed pre-kernel-
    layer TPU headline (BENCH_r05 lineage, 4.0M entries/sec) is far
    under the model's TPU saturation prediction and MUST flag; a
    headline near the CPU prediction must NOT."""
    stale = dict(_load("bench_tpu_last_good.json"))
    flagged = costmodel.flag_capture(stale)
    assert flagged["model_flagged"] is True
    assert "re-measured" in flagged["model_flag_reason"]
    sane = costmodel.flag_capture(
        {
            "value": costmodel.predict_saturation(3334, 64, 8)[
                "committed_per_sec"
            ],
            "device": "cpu",
        }
    )
    assert sane["model_flagged"] is False
    assert "model_check" in sane
