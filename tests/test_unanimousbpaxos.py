"""Unanimous BPaxos sim tests (the analog of
shared/src/test/scala/unanimousbpaxos)."""

import dataclasses
import random

import pytest

from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport, wire
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.protocols import unanimousbpaxos as ub
from frankenpaxos_tpu.sim import (
    SimulatedSystem,
    mixed_command,
    simulate_and_minimize,
)
from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set
from test_epaxos import RecordingKv, _conflicting_order_violation


def make(f=1, num_clients=2, seed=0):
    t = SimTransport(FakeLogger(LogLevel.FATAL))
    n = 2 * f + 1
    config = ub.UnanimousBPaxosConfig(
        f=f,
        leader_addresses=tuple(SimAddress(f"leader{i}") for i in range(f + 1)),
        dep_service_node_addresses=tuple(
            SimAddress(f"dep{i}") for i in range(n)
        ),
        acceptor_addresses=tuple(SimAddress(f"acceptor{i}") for i in range(n)),
    )
    log = lambda: FakeLogger(LogLevel.FATAL)
    leaders = [
        ub.UbLeader(a, t, log(), config, RecordingKv(), seed=seed + i)
        for i, a in enumerate(config.leader_addresses)
    ]
    deps = [
        ub.UbDepServiceNode(a, t, log(), config, KeyValueStore())
        for a in config.dep_service_node_addresses
    ]
    acceptors = [
        ub.UbAcceptor(a, t, log(), config) for a in config.acceptor_addresses
    ]
    clients = [
        ub.UbClient(SimAddress(f"client{i}"), t, log(), config, seed=seed + 40 + i)
        for i in range(num_clients)
    ]
    return t, config, leaders, deps, acceptors, clients


def drain(t, max_steps=100000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps


def test_ub_single_command_fast_path():
    """An uncontended command commits via the unanimous fast path — zero
    classic-phase messages on the wire."""
    t, config, leaders, deps, acceptors, clients = make()
    p = clients[0].propose(0, kv_set(("x", "1")))
    classic = 0
    while t.messages:
        m = t.messages[0]
        if isinstance(wire.decode(m.data), (ub.UbPhase1a, ub.UbPhase2a)):
            classic += 1
        t.deliver_message(m)
    assert p.done
    assert classic == 0
    # The proposing leader executed it.
    assert leaders[0].state_machine.get() == {"x": "1"} or \
        leaders[1].state_machine.get() == {"x": "1"}


def test_ub_conflict_falls_back_to_classic_round_1():
    """Interleaved conflicting commands make dep sets diverge; the leader
    proposes the union in classic round 1 and both commit."""
    t, config, leaders, deps, acceptors, clients = make(seed=3)
    p1 = clients[0].propose(0, kv_set(("x", "a")))
    p2 = clients[1].propose(0, kv_set(("x", "b")))
    rng = random.Random(1)
    for _ in range(4000):
        cmd = t.generate_command(rng)
        if cmd is None:
            break
        t.run_command(cmd, record=False)
    drain(t)
    for _ in range(6):
        if p1.done and p2.done:
            break
        for timer in list(t.running_timers()):
            t.trigger_timer(timer.address, timer.name())
        drain(t)
    assert p1.done and p2.done
    finals = {
        tuple(sorted(l.state_machine.get().items())) for l in leaders
    }
    assert len(finals) == 1, finals


def test_ub_recovery_after_leader_death():
    t, config, leaders, deps, acceptors, clients = make(seed=5)

    class _L0:
        def randrange(self, n):
            return 0

    clients[0].rng = _L0()
    p1 = clients[0].propose(0, kv_set(("x", "1")))
    # Deliver dep requests + fast proposals, but kill leader 0 before it
    # sees any Phase2bFast.
    t.deliver_message(t.messages[0])  # request -> leader0
    while t.messages:
        m = t.messages[0]
        if m.dst == config.leader_addresses[0]:
            t.drop_message(m)
        else:
            t.deliver_message(m)
    t.partition_actor(config.leader_addresses[0])

    # A conflicting command through leader 1 depends on the stuck vertex.
    class _L1:
        def randrange(self, n):
            return 1

    clients[1].rng = _L1()
    p2 = clients[1].propose(0, kv_set(("x", "2")))
    drain(t)
    assert not p2.done
    # Leader 1's recover timers run classic rounds on the stuck vertex.
    for _ in range(6):
        if p2.done:
            break
        for timer in list(t.running_timers()):
            if timer.address != config.leader_addresses[0]:
                t.trigger_timer(timer.address, timer.name())
        drain(t)
    assert p2.done, "recovery did not unblock the dependent command"


@dataclasses.dataclass(frozen=True)
class Propose:
    client_index: int
    pseudonym: int
    key: str
    value: str


class SimulatedUbPaxos(SimulatedSystem):
    def __init__(self, f=1):
        self.f = f
        self._kv = KeyValueStore()

    def new_system(self, seed):
        return make(self.f, seed=seed)

    def get_state(self, system):
        leaders = system[2]
        return tuple(
            tuple(l.state_machine.executed_commands) for l in leaders
        )

    def generate_command(self, system, rng):
        t = system[0]
        clients = system[5]
        ops = []
        for i, c in enumerate(clients):
            for pseudonym in (0, 1):
                if pseudonym not in c.pending:
                    ops.append(
                        (1, Propose(i, pseudonym, f"k{rng.randrange(2)}",
                                    f"v{rng.randrange(50)}"))
                    )
        return mixed_command(rng, t, ops)

    def run_command(self, system, command):
        t = system[0]
        clients = system[5]
        if isinstance(command, Propose):
            clients[command.client_index].propose(
                command.pseudonym, kv_set((command.key, command.value))
            )
        else:
            t.run_command(command, record=False)
        return system

    def state_invariant(self, state):
        class _H:
            pass

        fakes = []
        for log in state:
            sm = _H()
            sm.executed_commands = list(log)
            h = _H()
            h.state_machine = sm
            fakes.append(h)
        return _conflicting_order_violation(fakes, self._kv.conflicts)


@pytest.mark.parametrize("f", [1, 2])
def test_ub_safety_randomized(f):
    bad = simulate_and_minimize(
        SimulatedUbPaxos(f), run_length=120, num_runs=10, seed=f
    )
    assert bad is None, f"\n{bad}"


def test_ub_recovery_abstention_recovers_noop():
    """Regression: recovering a round-0 value from a quorum containing an
    ABSTENTION must produce noop — the abstainer's classic promise makes
    unanimity impossible, and adopting the partial voters' value would
    adopt stale dependency sets (observed as divergent execution orders
    of conflicting commands)."""
    t, config, leaders, deps, acceptors, clients = make(seed=19)
    vertex = (0, 0)
    leader = leaders[1]
    # Build a phase-1 state with one round-0 vote and one abstention.
    leader._recover(vertex, nack_round=-1)
    drain_limit = 0
    while t.messages and drain_limit < 1000:
        m = t.messages[0]
        t.drop_message(m)  # discard the real phase1as/bs
        drain_limit += 1
    state = leader.states[vertex]
    assert isinstance(state, ub._UbPhase1)
    cmd = ub.UbCommand(b"addr", 0, 0, kv_set(("x", "1")))
    leader._handle_phase1b(ub.UbPhase1b(
        vertex_id=vertex, acceptor_id=0, round=state.round,
        vote_round=0, vote_value=(cmd, ((1, 7),)),
    ))
    leader._handle_phase1b(ub.UbPhase1b(
        vertex_id=vertex, acceptor_id=1, round=state.round,
        vote_round=-1, vote_value=None,
    ))
    # The leader moved to classic phase 2 proposing NOOP, not the command.
    phase2 = leader.states[vertex]
    assert isinstance(phase2, ub._UbPhase2Classic)
    assert phase2.value == (None, ()), phase2.value
