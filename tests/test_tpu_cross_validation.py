"""Cross-validation of the batched TPU MultiPaxos model against the
per-actor sim (SURVEY.md §4, implication (b)): on aligned scenarios, both
executions must map the same command-arrival sequence to the same per-slot
chosen values — including phase-1 safe-value repair after a leader change
(Leader.scala:314-329, 504-577).

Alignment model: batched value id v corresponds to the v-th command to
arrive at the per-actor leader; group g's per-group slot s is global slot
s*G + g (the ``slot % G`` partitioning of ProxyLeader.scala:190). A slot
repaired to a noop is NOOP in both representations.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.core import wire
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    Phase2a,
    Phase2b,
)
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    CHOSEN,
    INF,
    NOOP_VALUE,
    BatchedMultiPaxosConfig,
    check_invariants,
    init_state,
    leader_change,
    tick,
)
from multipaxos_testbed import SimulatedMultiPaxos, Write

NOOP = "noop"


# -- Batched-side driver ------------------------------------------------------


def run_batched_collecting(cfg, state, t0, num_ticks, key, log):
    """Advance tick-by-tick, recording every chosen slot's value into
    ``log`` (global slot -> value). A chosen slot survives at least one
    tick before retiring (replica_arrival > chosen tick), so per-tick
    observation sees every chosen value exactly."""
    G, W = cfg.num_groups, cfg.window
    t = t0
    for i in range(num_ticks):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        status = np.asarray(state.status)
        chosen_value = np.asarray(state.chosen_value)
        head = np.asarray(state.head)
        next_slot = np.asarray(state.next_slot)
        for g in range(G):
            for s in range(int(head[g]), int(next_slot[g])):
                if status[g, s % W] == CHOSEN:
                    global_slot = s * G + g
                    value = int(chosen_value[g, s % W])
                    if global_slot in log:
                        assert log[global_slot] == value, (
                            f"slot {global_slot} changed value: "
                            f"{log[global_slot]} -> {value}"
                        )
                    log[global_slot] = value
        t += 1
    return state, t


def batched_symbols(log, n):
    assert set(log.keys()) == set(range(n)), sorted(log)
    return [NOOP if log[s] == NOOP_VALUE else log[s] for s in range(n)]


# -- Per-actor-side drivers ---------------------------------------------------


def drain(system, max_steps=50_000):
    t = system.transport
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps, "message storm"


def sim_symbols(system, n):
    """Per-slot values from the replicas' logs, as arrival indices."""
    out = []
    logs = []
    for replica in system.replicas:
        entries = []
        for s in range(n):
            entry = replica.log.get(s)
            assert entry is not None, f"slot {s} missing at {replica.address}"
            if entry.is_noop:
                entries.append(NOOP)
            else:
                (command,) = entry.batch.commands
                # Commands were written as b"c<k>" with k = arrival index.
                entries.append(int(command.command[1:]))
        logs.append(entries)
    assert all(l == logs[0] for l in logs), f"replica logs diverge: {logs}"
    return logs[0]


# -- Tests --------------------------------------------------------------------


def test_cross_validation_happy_path():
    """Same command sequence, no failures: identical per-slot logs."""
    n = 10
    # Per-actor: 10 sequential writes; the leader assigns slot k to the
    # k-th arriving command.
    sim = SimulatedMultiPaxos(f=1, batched=False, flexible=False)
    system = sim.new_system(seed=5)
    for k in range(n):
        sim.run_command(system, Write(0, 0, f"c{k}".encode()))
        drain(system)
    assert system.writes_completed == n

    # Batched: closed workload of 10 commands over G=2 groups.
    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=2,
        window=8,
        slots_per_tick=1,
        lat_min=1,
        lat_max=1,
        drop_rate=0.0,
        retry_timeout=64,
        thrifty=False,
        max_slots_per_group=n // 2,
    )
    state = init_state(cfg)
    log = {}
    state, t = run_batched_collecting(
        cfg, state, 0, 40, jax.random.PRNGKey(0), log
    )
    inv = check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.retired) == n

    assert batched_symbols(log, n) == sim_symbols(system, n) == list(range(n))


def test_cross_validation_leader_change_repair():
    """Aligned failover scenario: six in-flight slots, votes exist for
    slots {0, 2, 5} only, nothing chosen; the leader fails; the new
    leader's phase-1 repair must keep the voted values and noopify slots
    {1, 3, 4} — in BOTH executions, yielding identical logs."""
    n = 6
    voted = {0, 2, 5}

    # ---- Per-actor side.
    sim = SimulatedMultiPaxos(f=1, batched=False, flexible=False)
    system = sim.new_system(seed=7)
    t = system.transport
    config = system.config
    acceptor_addrs = {
        a for group in config.acceptor_addresses for a in group
    }

    # Six concurrent writes (distinct pseudonyms), arriving in order.
    for k in range(n):
        sim.run_command(system, Write(0, k, f"c{k}".encode()))

    # Pump the write path, but: drop acceptor-bound Phase2as for unvoted
    # slots, and drop every Phase2b so nothing is chosen.
    steps = 0
    while t.messages and steps < 10_000:
        steps += 1
        m = t.messages[0]
        decoded = wire.decode(m.data)
        if isinstance(decoded, Phase2a) and m.dst in acceptor_addrs:
            if decoded.slot in voted:
                t.deliver_message(m)
            else:
                t.drop_message(m)
        elif isinstance(decoded, Phase2b):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert system.writes_completed == 0

    # Kill leader 0; leader 1 takes over and repairs the log.
    t.partition_actor(config.leader_addresses[0])
    t.partition_actor(config.leader_election_addresses[0])
    t.trigger_timer(config.leader_election_addresses[1], "noPingTimer")
    drain(system)

    from frankenpaxos_tpu.protocols.multipaxos.leader import _Phase2

    assert isinstance(system.leaders[1].state, _Phase2)

    # ---- Batched side: the same scenario.
    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=2,
        window=8,
        slots_per_tick=3,
        lat_min=1,
        lat_max=1,
        drop_rate=0.0,
        retry_timeout=100,
        thrifty=False,
        max_slots_per_group=3,
    )
    key = jax.random.PRNGKey(1)
    state = init_state(cfg)
    # t=0: propose all six slots; Phase2as arrive at t=1.
    state = tick(cfg, state, jnp.int32(0), jax.random.fold_in(key, 0))
    # Align the vote pattern: unvoted slots lose all their Phase2as;
    # voted slots keep one acceptor's (below quorum, so nothing is
    # chosen — the repair read covers all acceptors, so one voter
    # preserves the value exactly like the per-actor read-quorum
    # intersection does).
    p2a = np.asarray(state.p2a_arrival).copy()  # [A, G, W]
    for global_slot in range(n):
        g, s = global_slot % 2, global_slot // 2
        if global_slot in voted:
            p2a[1:, g, s % cfg.window] = INF
        else:
            p2a[:, g, s % cfg.window] = INF
    state = dataclasses.replace(state, p2a_arrival=jnp.asarray(p2a))
    # t=1: the surviving Phase2as arrive; single votes are recorded.
    state = tick(cfg, state, jnp.int32(1), jax.random.fold_in(key, 1))
    assert int(state.committed) == 0
    # Leader change at t=2: phase-1 repair + re-proposal in round 1.
    state = leader_change(cfg, state, jnp.int32(2), jax.random.fold_in(key, 99))
    log = {}
    state, tend = run_batched_collecting(cfg, state, 2, 10, key, log)
    inv = check_invariants(cfg, state, jnp.int32(tend))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.retired) == n

    expected = [0, NOOP, 2, NOOP, NOOP, 5]
    assert batched_symbols(log, n) == expected
    assert sim_symbols(system, n) == expected


# -- Mencius: batched model vs per-actor vanillamencius -----------------------


def test_cross_validation_mencius_skips():
    """Aligned skip scenario (vanillamencius Server._maybe_skip_to /
    Server.scala skip semantics): one active server, the others idle.
    Both executions must produce the SAME global log — real commands on
    the active stripe's slots, noop skips filling the idle stripes up to
    the watermark — and the same executed watermark."""
    import frankenpaxos_tpu.tpu.mencius_batched as mb
    from frankenpaxos_tpu.protocols import vanillamencius as vm
    from test_vanillamencius import drain as vm_drain, make as vm_make

    n_writes = 4
    L = 3  # stripes / servers; active index 2

    # ---- Per-actor side: all writes routed to server 2.
    t, config, servers, clients = vm_make(f=1, num_clients=1, seed=9)

    class _Pick2:
        def randrange(self, n):
            return 2

    clients[0].rng = _Pick2()
    promises = []
    for k in range(n_writes):
        promises.append(clients[0].propose(k, f"w{k}".encode()))
        vm_drain(t)
    assert all(p.done for p in promises)
    watermark = {s.executed_watermark for s in servers}
    assert watermark == {n_writes * L}, watermark
    sim_log = []
    for slot in range(n_writes * L):
        entry = servers[0].log.get(slot)
        assert entry is not None, f"slot {slot} missing"
        (value,) = entry
        if value is None:
            sim_log.append(NOOP)
        else:
            sim_log.append(int(value.command[1:]))  # b"w<k>" -> k

    # ---- Batched side: stripes 0,1 idle, stripe 2 active, skip fill at
    # threshold 1 (the per-actor skip fires on ANY observed gap).
    cfg = mb.BatchedMenciusConfig(
        f=1, num_leaders=L, window=16, slots_per_tick=1,
        num_idle_leaders=2, skip_threshold=1, lat_min=1, lat_max=1,
        max_slots_per_leader=n_writes,
    )
    key = jax.random.PRNGKey(3)
    state = mb.init_state(cfg)
    blog = {}
    t_ = 0
    for _ in range(30):
        state = mb.tick(cfg, state, jnp.int32(t_), jax.random.fold_in(key, t_))
        ct = np.asarray(state.chosen_tick)
        head = np.asarray(state.head)
        sv = np.asarray(state.slot_value)
        for l in range(L):
            for pos in range(cfg.window):
                if ct[l, pos] == t_:
                    o = int(head[l]) + ((pos - int(head[l])) % cfg.window)
                    blog[o * L + l] = int(sv[l, pos])
        t_ += 1
    inv = mb.check_invariants(cfg, state, jnp.int32(t_))
    assert all(bool(v) for v in inv.values()), inv

    # The batched model idles stripes 0..1 and is active on stripe 2 —
    # the same ownership layout as the per-actor run. Batched real value
    # ids are the global slot numbers themselves; translate to write
    # indices (slot // L) for comparison.
    assert set(blog.keys()) == set(range(n_writes * L)), sorted(blog)
    batched_log = [
        NOOP if blog[s] == mb.NOOP_VALUE else blog[s] // L
        for s in range(n_writes * L)
    ]
    assert batched_log == sim_log, (batched_log, sim_log)
    assert int(state.executed_global) == n_writes * L
    assert int(state.committed_real) == n_writes
    assert int(state.skips) == n_writes * (L - 1)


# -- Scalog: batched model vs per-actor cut projection ------------------------


def test_cross_validation_scalog_cuts():
    """Same append stream -> identical cut sequence and identical
    global-log projection (scalog Server._project / the cut prefix-sum
    doc). The per-actor cluster runs real messages (appends, backups,
    ShardInfo, a Paxos round per cut); the batched model is driven with
    the same per-shard lengths at the same snapshot points."""
    import frankenpaxos_tpu.tpu.scalog_batched as sb
    from test_scalog import ScalogCluster

    # Cumulative per-shard lengths at each of the 3 cut points.
    cum = [(2, 1), (3, 3), (6, 3)]

    # ---- Per-actor side: pinned routing (client k -> shard k's first
    # server), manual pushes per interval, one combined cut per interval.
    # cuts_per_proposal=4: one combined proposal per interval, after ALL
    # four servers (owners AND backups — a cut covers only the
    # fully-replicated prefix, the element-wise MIN of members' views)
    # have pushed their ShardInfo.
    cluster = ScalogCluster(
        seed=21, num_clients=2, push_size=10**6, cuts_per_proposal=4
    )

    class _PickFlat:
        def __init__(self, flat):
            self.flat = flat

        def randrange(self, n):
            return self.flat

    cluster.clients[0].rng = _PickFlat(0)  # shard 0, server 0
    cluster.clients[1].rng = _PickFlat(2)  # shard 1, server 0
    seqs = [0, 0]
    prev = (0, 0)
    for r, target in enumerate(cum):
        for shard in (0, 1):
            for _ in range(target[shard] - prev[shard]):
                cluster.clients[shard].write(
                    seqs[shard], f"s{shard}-{seqs[shard]}".encode()
                )
                seqs[shard] += 1
        cluster.drain()  # appends + backups settle; no cuts yet
        for server in cluster.servers:
            server.push()
        cluster.drain()  # ShardInfo x4 -> one raw cut -> Paxos -> commit
        prev = target
    cuts = [tuple(c) for c in cluster.aggregator.cuts]
    assert [(c[0], c[2]) for c in cuts] == cum, cuts
    assert all(c[1] == 0 and c[3] == 0 for c in cuts)  # backups idle
    replica_log = [
        bytes(v) for v in cluster.replicas[0].state_machine.log
    ]
    assert len(replica_log) == sum(cum[-1])

    # ---- Batched side: inject the same append stream (local_len held to
    # the same cumulative trajectory), snapshot on the same period.
    cfg = sb.BatchedScalogConfig(
        num_shards=2, max_inflight_cuts=4, cut_every=4,
        appends_per_tick=1, append_jitter=0, lat_min=1, lat_max=1,
    )
    key = jax.random.PRNGKey(7)
    state = sb.init_state(cfg)
    committed_cuts_seen = []
    prev_committed = 0
    for t_ in range(20):
        interval = min(t_ // 4, len(cum) - 1)
        want = cum[interval]
        # The tick's own append adds exactly 1 per shard; pre-set so the
        # snapshot (and everything after) sees the planned trajectory.
        state = dataclasses.replace(
            state,
            local_len=jnp.asarray([want[0] - 1, want[1] - 1], jnp.int32),
        )
        state = sb.tick(cfg, state, jnp.int32(t_), jax.random.fold_in(key, t_))
        if int(state.committed_cuts) > prev_committed:
            assert int(state.committed_cuts) == prev_committed + 1
            committed_cuts_seen.append(
                tuple(np.asarray(state.last_committed_cut).tolist())
            )
            prev_committed += 1
        if prev_committed == len(cum):
            break
    assert committed_cuts_seen == cum, committed_cuts_seen
    inv = sb.check_invariants(cfg, state, jnp.int32(t_))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.global_len) == sum(cum[-1])

    # ---- Projection: the batched cut prefix-sum arithmetic must place
    # every per-actor record at exactly the global index the real system
    # executed it at.
    predicted = [None] * sum(cum[-1])
    prev_vec = jnp.zeros((2,), jnp.int32)
    for cut in committed_cuts_seen:
        cut_vec = jnp.asarray(cut, jnp.int32)
        starts, ends = sb.global_indices_of_cut(prev_vec, cut_vec)
        starts, ends = np.asarray(starts), np.asarray(ends)
        base = np.asarray(prev_vec)
        for shard in (0, 1):
            for j in range(ends[shard] - starts[shard]):
                predicted[starts[shard] + j] = (
                    f"s{shard}-{base[shard] + j}".encode()
                )
        prev_vec = cut_vec
    assert predicted == replica_log, (predicted, replica_log)


# -- Dtype policy: narrowed state vs the int32 reference path -----------------
#
# The HBM-bandwidth pass stores status codes in int8 and ballot rounds /
# epochs in int16 (tpu/common.py dtype policy). The tick functions are
# dtype-polymorphic, so running the SAME tick on a widen_state()-upcast
# int32 state replays the pre-narrowing semantics — the narrowed run must
# match it BIT FOR BIT: every state field (after widening), stats(), and
# check_invariants(), across multiple seeds.

import pytest

from frankenpaxos_tpu.tpu.common import widen_state

DTYPE_SEEDS = [0, 1, 2]


def _assert_states_bit_identical(narrow_final, wide_final, what):
    assert type(narrow_final) is type(wide_final)
    widened = widen_state(narrow_final)
    for f in dataclasses.fields(narrow_final):
        a_field = getattr(widened, f.name)
        b_field = getattr(wide_final, f.name)
        if dataclasses.is_dataclass(a_field):
            # Nested pytree field (the Telemetry ring) — recurse.
            _assert_states_bit_identical(
                a_field, b_field, f"{what}.{f.name}"
            )
            continue
        a = np.asarray(a_field)
        b = np.asarray(b_field)
        assert a.dtype == b.dtype, (what, f.name, a.dtype, b.dtype)
        np.testing.assert_array_equal(a, b, err_msg=f"{what}.{f.name}")


@pytest.mark.parametrize("seed", DTYPE_SEEDS)
def test_dtype_narrowing_multipaxos_flagship(seed):
    """Flagship backend, base config: the narrowed run equals the int32
    reference run bit for bit — state, stats(), and invariants."""
    from frankenpaxos_tpu.tpu.multipaxos_batched import run_ticks
    from frankenpaxos_tpu.tpu.transport import TpuSimTransport

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=0.05, retry_timeout=8,
    )
    sim = TpuSimTransport(cfg, seed=seed)
    ref = TpuSimTransport(cfg, seed=seed)
    ref.state = widen_state(ref.state)  # the int32 reference path
    sim.run(120)
    ref.run(120)
    assert sim.stats() == ref.stats()
    inv_n, inv_w = sim.check_invariants(), ref.check_invariants()
    assert inv_n == inv_w
    assert all(inv_n.values()), inv_n
    _assert_states_bit_identical(sim.state, ref.state, "multipaxos")
    # The reference path really is wider: same values, more bytes.
    from frankenpaxos_tpu.tpu.common import state_nbytes

    assert state_nbytes(ref.state) > state_nbytes(sim.state)


@pytest.mark.parametrize("seed", DTYPE_SEEDS)
def test_dtype_narrowing_multipaxos_full_feature(seed):
    """Flagship backend with every optional subsystem live — device
    elections + fault injection, matchmaker reconfiguration, the KV state
    machine with injected duplicates, and linearizable reads — so every
    narrowed field (rounds, epochs, phases, heartbeat counters, read-ring
    statuses) is exercised."""
    from frankenpaxos_tpu.tpu.multipaxos_batched import (
        init_state as mp_init,
        run_ticks as mp_run,
    )

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        lat_min=1, lat_max=2, drop_rate=0.02, retry_timeout=8,
        fail_rate=0.02, revive_rate=0.2, heartbeat_timeout=4,
        reconfigure_every=25,
        state_machine="kv", kv_keys=16, num_clients=4, dup_rate=0.05,
        read_rate=2, read_window=8,
    )
    key = jax.random.PRNGKey(seed)
    t0 = jnp.zeros((), jnp.int32)
    narrow, tn = mp_run(cfg, mp_init(cfg), t0, 100, key)
    wide, tw = mp_run(cfg, widen_state(mp_init(cfg)), t0, 100, key)
    _assert_states_bit_identical(narrow, wide, "multipaxos-full")
    inv = check_invariants(cfg, narrow, tn)
    assert all(bool(v) for v in inv.values()), {
        k: bool(v) for k, v in inv.items()
    }


@pytest.mark.parametrize("seed", DTYPE_SEEDS)
@pytest.mark.parametrize(
    "family",
    # Tier-1 keeps one family per narrowed-dtype class (rounds-heavy
    # caspaxos, phase/seat-epoch fasterpaxos, chunk-epoch horizontal,
    # status-ring craq, and the cheap unreplicated ceiling) plus the two
    # flagship tests above; the rest ride the full suite as slow — each
    # family costs two fresh XLA compiles (narrow + wide reference) and
    # tier-1 has a hard wall-clock budget.
    ["caspaxos", "fasterpaxos", "horizontal", "craq", "unreplicated"]
    + [
        pytest.param(f, marks=pytest.mark.slow)
        for f in ("mencius", "fastpaxos", "fastmultipaxos",
                  "vanillamencius", "grid")
    ],
)
def test_dtype_narrowing_families(seed, family):
    """Every narrowed backend: the run on the narrowed state equals the
    run on the widened (int32) state bit for bit."""
    if family == "mencius":
        import frankenpaxos_tpu.tpu.mencius_batched as m

        cfg = m.BatchedMenciusConfig(
            f=1, num_leaders=4, window=16, slots_per_tick=2,
            idle_rate=0.2, skip_threshold=4, drop_rate=0.05,
        )
    elif family == "caspaxos":
        import frankenpaxos_tpu.tpu.caspaxos_batched as m

        cfg = m.BatchedCasPaxosConfig(num_registers=8, num_leaders=2)
    elif family == "fastpaxos":
        import frankenpaxos_tpu.tpu.fastpaxos_batched as m

        cfg = m.BatchedFastPaxosConfig(
            f=1, num_groups=4, window=8, conflict_rate=0.3
        )
    elif family == "fasterpaxos":
        import frankenpaxos_tpu.tpu.fasterpaxos_batched as m

        cfg = m.BatchedFasterPaxosConfig(
            f=1, num_groups=4, window=16, fail_rate=0.02, revive_rate=0.2
        )
    elif family == "horizontal":
        import frankenpaxos_tpu.tpu.horizontal_batched as m

        cfg = m.BatchedHorizontalConfig(
            f=1, num_groups=4, window=16, reconfigure_every=20
        )
    elif family == "craq":
        import frankenpaxos_tpu.tpu.craq_batched as m

        cfg = m.BatchedCraqConfig(num_chains=4)
    elif family == "fastmultipaxos":
        import frankenpaxos_tpu.tpu.fastmultipaxos_batched as m

        cfg = m.BatchedFastMultiPaxosConfig(f=1, num_groups=4)
    elif family == "vanillamencius":
        import frankenpaxos_tpu.tpu.vanillamencius_batched as m

        cfg = m.BatchedVanillaMenciusConfig(
            f=1, num_servers=3, window=16, fail_rate=0.02, revive_rate=0.2
        )
    elif family == "unreplicated":
        import frankenpaxos_tpu.tpu.unreplicated_batched as m

        cfg = m.BatchedUnreplicatedConfig(num_servers=4)
    else:
        import frankenpaxos_tpu.tpu.grid_batched as m

        cfg = m.GridBatchedConfig(rows=3, cols=3, drop_rate=0.05)

    key = jax.random.PRNGKey(seed)
    t0 = jnp.zeros((), jnp.int32)
    narrow, tn = m.run_ticks(cfg, m.init_state(cfg), t0, 80, key)
    wide, tw = m.run_ticks(cfg, widen_state(m.init_state(cfg)), t0, 80, key)
    _assert_states_bit_identical(narrow, wide, family)
    inv = m.check_invariants(cfg, narrow, tn)
    assert all(bool(v) for v in inv.values()), {
        k: bool(v) for k, v in inv.items()
    }
