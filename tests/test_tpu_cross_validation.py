"""Cross-validation of the batched TPU MultiPaxos model against the
per-actor sim (SURVEY.md §4, implication (b)): on aligned scenarios, both
executions must map the same command-arrival sequence to the same per-slot
chosen values — including phase-1 safe-value repair after a leader change
(Leader.scala:314-329, 504-577).

Alignment model: batched value id v corresponds to the v-th command to
arrive at the per-actor leader; group g's per-group slot s is global slot
s*G + g (the ``slot % G`` partitioning of ProxyLeader.scala:190). A slot
repaired to a noop is NOOP in both representations.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.core import wire
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    Phase2a,
    Phase2b,
)
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    CHOSEN,
    INF,
    NOOP_VALUE,
    BatchedMultiPaxosConfig,
    check_invariants,
    init_state,
    leader_change,
    tick,
)
from multipaxos_testbed import SimulatedMultiPaxos, Write

NOOP = "noop"


# -- Batched-side driver ------------------------------------------------------


def run_batched_collecting(cfg, state, t0, num_ticks, key, log):
    """Advance tick-by-tick, recording every chosen slot's value into
    ``log`` (global slot -> value). A chosen slot survives at least one
    tick before retiring (replica_arrival > chosen tick), so per-tick
    observation sees every chosen value exactly."""
    G, W = cfg.num_groups, cfg.window
    t = t0
    for i in range(num_ticks):
        state = tick(cfg, state, jnp.int32(t), jax.random.fold_in(key, t))
        status = np.asarray(state.status)
        chosen_value = np.asarray(state.chosen_value)
        head = np.asarray(state.head)
        next_slot = np.asarray(state.next_slot)
        for g in range(G):
            for s in range(int(head[g]), int(next_slot[g])):
                if status[g, s % W] == CHOSEN:
                    global_slot = s * G + g
                    value = int(chosen_value[g, s % W])
                    if global_slot in log:
                        assert log[global_slot] == value, (
                            f"slot {global_slot} changed value: "
                            f"{log[global_slot]} -> {value}"
                        )
                    log[global_slot] = value
        t += 1
    return state, t


def batched_symbols(log, n):
    assert set(log.keys()) == set(range(n)), sorted(log)
    return [NOOP if log[s] == NOOP_VALUE else log[s] for s in range(n)]


# -- Per-actor-side drivers ---------------------------------------------------


def drain(system, max_steps=50_000):
    t = system.transport
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1
    assert steps < max_steps, "message storm"


def sim_symbols(system, n):
    """Per-slot values from the replicas' logs, as arrival indices."""
    out = []
    logs = []
    for replica in system.replicas:
        entries = []
        for s in range(n):
            entry = replica.log.get(s)
            assert entry is not None, f"slot {s} missing at {replica.address}"
            if entry.is_noop:
                entries.append(NOOP)
            else:
                (command,) = entry.batch.commands
                # Commands were written as b"c<k>" with k = arrival index.
                entries.append(int(command.command[1:]))
        logs.append(entries)
    assert all(l == logs[0] for l in logs), f"replica logs diverge: {logs}"
    return logs[0]


# -- Tests --------------------------------------------------------------------


def test_cross_validation_happy_path():
    """Same command sequence, no failures: identical per-slot logs."""
    n = 10
    # Per-actor: 10 sequential writes; the leader assigns slot k to the
    # k-th arriving command.
    sim = SimulatedMultiPaxos(f=1, batched=False, flexible=False)
    system = sim.new_system(seed=5)
    for k in range(n):
        sim.run_command(system, Write(0, 0, f"c{k}".encode()))
        drain(system)
    assert system.writes_completed == n

    # Batched: closed workload of 10 commands over G=2 groups.
    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=2,
        window=8,
        slots_per_tick=1,
        lat_min=1,
        lat_max=1,
        drop_rate=0.0,
        retry_timeout=64,
        thrifty=False,
        max_slots_per_group=n // 2,
    )
    state = init_state(cfg)
    log = {}
    state, t = run_batched_collecting(
        cfg, state, 0, 40, jax.random.PRNGKey(0), log
    )
    inv = check_invariants(cfg, state, jnp.int32(t))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.retired) == n

    assert batched_symbols(log, n) == sim_symbols(system, n) == list(range(n))


def test_cross_validation_leader_change_repair():
    """Aligned failover scenario: six in-flight slots, votes exist for
    slots {0, 2, 5} only, nothing chosen; the leader fails; the new
    leader's phase-1 repair must keep the voted values and noopify slots
    {1, 3, 4} — in BOTH executions, yielding identical logs."""
    n = 6
    voted = {0, 2, 5}

    # ---- Per-actor side.
    sim = SimulatedMultiPaxos(f=1, batched=False, flexible=False)
    system = sim.new_system(seed=7)
    t = system.transport
    config = system.config
    acceptor_addrs = {
        a for group in config.acceptor_addresses for a in group
    }

    # Six concurrent writes (distinct pseudonyms), arriving in order.
    for k in range(n):
        sim.run_command(system, Write(0, k, f"c{k}".encode()))

    # Pump the write path, but: drop acceptor-bound Phase2as for unvoted
    # slots, and drop every Phase2b so nothing is chosen.
    steps = 0
    while t.messages and steps < 10_000:
        steps += 1
        m = t.messages[0]
        decoded = wire.decode(m.data)
        if isinstance(decoded, Phase2a) and m.dst in acceptor_addrs:
            if decoded.slot in voted:
                t.deliver_message(m)
            else:
                t.drop_message(m)
        elif isinstance(decoded, Phase2b):
            t.drop_message(m)
        else:
            t.deliver_message(m)
    assert system.writes_completed == 0

    # Kill leader 0; leader 1 takes over and repairs the log.
    t.partition_actor(config.leader_addresses[0])
    t.partition_actor(config.leader_election_addresses[0])
    t.trigger_timer(config.leader_election_addresses[1], "noPingTimer")
    drain(system)

    from frankenpaxos_tpu.protocols.multipaxos.leader import _Phase2

    assert isinstance(system.leaders[1].state, _Phase2)

    # ---- Batched side: the same scenario.
    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=2,
        window=8,
        slots_per_tick=3,
        lat_min=1,
        lat_max=1,
        drop_rate=0.0,
        retry_timeout=100,
        thrifty=False,
        max_slots_per_group=3,
    )
    key = jax.random.PRNGKey(1)
    state = init_state(cfg)
    # t=0: propose all six slots; Phase2as arrive at t=1.
    state = tick(cfg, state, jnp.int32(0), jax.random.fold_in(key, 0))
    # Align the vote pattern: unvoted slots lose all their Phase2as;
    # voted slots keep one acceptor's (below quorum, so nothing is
    # chosen — the repair read covers all acceptors, so one voter
    # preserves the value exactly like the per-actor read-quorum
    # intersection does).
    p2a = np.asarray(state.p2a_arrival).copy()  # [A, G, W]
    for global_slot in range(n):
        g, s = global_slot % 2, global_slot // 2
        if global_slot in voted:
            p2a[1:, g, s % cfg.window] = INF
        else:
            p2a[:, g, s % cfg.window] = INF
    state = dataclasses.replace(state, p2a_arrival=jnp.asarray(p2a))
    # t=1: the surviving Phase2as arrive; single votes are recorded.
    state = tick(cfg, state, jnp.int32(1), jax.random.fold_in(key, 1))
    assert int(state.committed) == 0
    # Leader change at t=2: phase-1 repair + re-proposal in round 1.
    state = leader_change(cfg, state, jnp.int32(2), jax.random.fold_in(key, 99))
    log = {}
    state, tend = run_batched_collecting(cfg, state, 2, 10, key, log)
    inv = check_invariants(cfg, state, jnp.int32(tend))
    assert all(bool(v) for v in inv.values()), inv
    assert int(state.retired) == n

    expected = [0, NOOP, 2, NOOP, NOOP, 5]
    assert batched_symbols(log, n) == expected
    assert sim_symbols(system, n) == expected
