"""Elastic-capacity contract (thin wrapper): under
``ElasticPlan.none()`` the carried role-count state is structurally
empty and feeds no tick equation (default runs stay bit-identical to
the pre-elastic program), and steering the traced membership targets
(the autoscaler's resize verbs) never recompiles — one executable
serves every scale-up and scale-down.

The checkers are the ``elastic-noop`` / ``trace-elastic-retrace``
rules in ``frankenpaxos_tpu/analysis``; the behavioral pins live in
``tests/test_elastic.py``. The teeth tests simulate the two
regressions the rules exist for: a backend that drops the elastic
field, and a resize whose traced signature drifts (the
target-in-a-static-argument failure mode).
"""

import dataclasses

import pytest

from frankenpaxos_tpu import analysis
from frankenpaxos_tpu.analysis import core, rules_trace

pytestmark = pytest.mark.lint


@pytest.mark.parametrize(
    "rule_id",
    ["elastic-noop", "trace-elastic-retrace"],
)
def test_trace_rule_clean(rule_id):
    report = analysis.run(rule_ids=[rule_id])
    assert not report.findings, "\n" + report.format()


def test_elastic_backends_are_traced_backends():
    assert set(rules_trace.ELASTIC_BACKENDS) <= set(rules_trace.BACKENDS)
    # The elastic rollout mirrors the lifecycle rollout: same two
    # serve-grade backends thread both subsystems.
    assert set(rules_trace.ELASTIC_BACKENDS) == set(
        rules_trace.LIFECYCLE_BACKENDS
    )


def test_noop_rule_has_teeth(monkeypatch):
    """Point the rule at a backend that does NOT thread the elastic
    state: the missing-field finding must fire, proving the rule
    actually reads the flattened State tree rather than vacuously
    passing."""
    monkeypatch.setattr(rules_trace, "ELASTIC_BACKENDS", ("epaxos",))
    ctx = core.Context(backends=("epaxos",))
    report = core.run(rule_ids=["elastic-noop"], ctx=ctx)
    assert [f.key for f in report.findings] == ["epaxos:missing"]


def test_retrace_rule_has_teeth(monkeypatch):
    """Simulate the signature-drift regression: a ``set_target`` whose
    result perturbs a carried leaf's dtype (stand-in for a target
    count landing in a static argument) must miss the jit cache, and
    the rule must flag the growth."""
    from frankenpaxos_tpu.tpu import elastic

    import jax.numpy as jnp

    real = elastic.set_target

    def drifting(plan, es, role, n):
        out = real(plan, es, role, n)
        return dataclasses.replace(
            out, scale_ups=out.scale_ups.astype(jnp.float32)
        )

    monkeypatch.setattr(elastic, "set_target", drifting)
    ctx = core.Context(backends=("multipaxos",))
    report = core.run(rule_ids=["trace-elastic-retrace"], ctx=ctx)
    assert any(
        "missed the jit cache" in f.message for f in report.findings
    ), report.format()
